"""Supervised, fail-closed background seal workers.

The legacy store sealed on ONE flusher thread (plus the caller's, at
commit gates): at sustained ingest the npz build + write of every
shard funnels through a single writer — `HOSTPATH_r06.json` measured
it as the slowest host stage by far (19.6 ms/batch vs 4.0 ms
dispatch).  The pool replaces that funnel with N supervised workers
draining a seal queue, so the hot path's whole seal cost is a packed
row copy + an O(1) enqueue, and seal wall time parallelizes across
tenant/device shards.

Semantics carried over from the legacy seal path, unchanged:

- **fail-closed**: a job is retained (queued → in-flight → committed,
  or parked for retry) until its segment is durably published; the
  commit gate's ``flush(sync=True)`` raises while anything is parked,
  so a journal offset can never claim rows that exist nowhere;
- **bounded retry then dead-letter**: a job that keeps failing past
  ``max_seal_retries`` attempts AND ``seal_retry_window_s`` of wall
  clock dead-letters (the durable trace of those rows) instead of
  pinning memory forever — unless the dead-letter sink itself fails,
  in which case the job stays parked (bounded memory loses to silent
  loss);
- **supervision**: each worker runs under a
  :class:`~sitewhere_tpu.runtime.resilience.Supervisor` (restart with
  backoff, terminal escalation), like the egress offload worker.  If
  every worker has escalated, ``drain()`` falls back to sealing
  inline on the caller's thread — correctness over throughput.

Chaos: the write path fires the ``event_store.seal`` fault point and
the ``crash.mid_seal`` SIGKILL crosspoint (the kill-point harness
kills a worker mid-write; boot must quarantine the torn file and
journal replay re-derives the rows).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import List, Optional

import numpy as np

from sitewhere_tpu.runtime import faults
from sitewhere_tpu.runtime.resilience import RetryPolicy, Supervisor, dead_letter
from sitewhere_tpu.store.segment import (
    INT_COLUMNS,
    Segment,
    unpack_cols,
    write_segment_file,
)

logger = logging.getLogger("sitewhere_tpu.store.sealer")

_TS_ROW = INT_COLUMNS.index("ts_s")  # packed-block row carrying ts_s


class SealJob:
    """One shard buffer's worth of rows on its way to disk.

    ``ints``/``flts`` are the packed ``[Ci, n]``/``[Cf, n]`` column
    blocks (views into the shard buffer until the job completes — the
    buffer is only recycled after the write); ``seq`` was assigned when
    the buffer opened, so event ids handed out against buffered rows
    stay valid across the seal.
    """

    __slots__ = ("seq", "shard", "ints", "flts", "n", "buffer",
                 "attempts", "first_failure_t", "committed", "enqueued_t")

    def __init__(self, seq: int, shard: int, ints: np.ndarray,
                 flts: np.ndarray, n: int, buffer=None):
        self.seq = seq
        self.shard = shard
        self.ints = ints
        self.flts = flts
        self.n = n
        self.buffer = buffer
        self.attempts = 0
        self.first_failure_t: Optional[float] = None
        self.committed = False
        self.enqueued_t = time.monotonic()


class SealerPool:
    """The background seal worker pool bound to one SegmentStore.

    Lock order (shared with the store): ``store._lock`` may be held
    while taking ``self._cond`` (queue snapshots for readers, enqueue
    from the append path); the reverse nesting never happens — workers
    release the queue lock before committing under the store lock.
    """

    def __init__(self, store, workers: int = 2,
                 policy: Optional[RetryPolicy] = None):
        self._store = store
        self.n_workers = max(1, int(workers))
        self._cond = threading.Condition()
        self._queue: "deque[SealJob]" = deque()
        self._inflight: List[SealJob] = []
        self._parked: List[SealJob] = []
        self._supervisors: List[Supervisor] = []
        self._stopping = threading.Event()
        self.running = False
        self.sealed_segments = 0
        self._policy = policy or RetryPolicy(initial_s=0.05, max_s=2.0)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self.running:
            return
        self._stopping.clear()
        self.running = True
        self._supervisors = [
            Supervisor(f"store-seal-{i}", self._worker_loop,
                       policy=self._policy, max_restarts=64,
                       min_uptime_s=5.0)
            for i in range(self.n_workers)
        ]
        for sup in self._supervisors:
            sup.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stopping.set()
        self.running = False
        with self._cond:
            self._cond.notify_all()
        for sup in self._supervisors:
            sup.stop(timeout_s=timeout_s)
        self._supervisors = []

    def _workers_alive(self) -> bool:
        return any(sup.alive and not sup.escalated
                   for sup in self._supervisors)

    # -- producer side -------------------------------------------------------

    def enqueue_many(self, jobs) -> None:
        """O(1) hand-off from the append hot path (may run under the
        store lock — consistent with the documented lock order)."""
        if not jobs:
            return
        with self._cond:
            self._queue.extend(jobs)
            self._cond.notify_all()

    def retry_parked(self) -> None:
        """Re-queue parked (failed) jobs — called from flush ticks so a
        transient disk fault heals on the next interval."""
        with self._cond:
            if self._parked:
                self._queue.extend(self._parked)
                del self._parked[:]
                self._cond.notify_all()

    # -- introspection (callable under the store lock) -----------------------

    def snapshot_jobs(self) -> List[SealJob]:
        """Every job whose rows are not yet published to the catalog —
        the read paths' virtual-segment source.  Deduped by identity:
        a failing job sits on BOTH _inflight and _parked for a moment
        (_on_seal_failure parks it before _run_job delists it), and a
        double-listed job would double-count its rows in queries."""
        with self._cond:
            jobs = list(self._queue) + list(self._inflight) \
                + list(self._parked)
        seen: set = set()
        out: List[SealJob] = []
        for j in jobs:
            if not j.committed and id(j) not in seen:
                seen.add(id(j))
                out.append(j)
        return out

    def pending_rows(self) -> int:
        return sum(j.n for j in self.snapshot_jobs())

    def parked_count(self) -> int:
        with self._cond:
            return len(self._parked)

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue) + len(self._inflight)

    # -- drain (the commit gate's durability point) --------------------------

    def drain(self, pump_inline: bool = True) -> None:
        """Block until every queued/in-flight job committed or parked.

        With no live workers (unstarted store, or every supervisor
        escalated) the caller's thread seals the queue inline — the
        sync-flush contract must hold even when the pool is down."""
        while True:
            if pump_inline and not self._workers_alive():
                self._pump_inline()
            with self._cond:
                if not self._queue and not self._inflight:
                    return
                if self._workers_alive() or not pump_inline:
                    # with live workers (or inline pumping disabled)
                    # there is nothing to do but wait — never busy-spin
                    self._cond.wait(timeout=0.05)

    def _pump_inline(self) -> None:
        while self.pump_one():
            pass

    def pump_one(self) -> bool:
        """Seal ONE queued job on the caller's thread.  Returns False
        when the queue is empty.  Used by the drain fallback (no live
        workers) and by the writer's backpressure valve (see
        ``SegmentStore.append_columns``)."""
        with self._cond:
            if not self._queue:
                return False
            job = self._queue.popleft()
            self._inflight.append(job)
        self._run_job(job)
        return True

    def _run_job(self, job: SealJob) -> None:
        """Process one claimed job, fail-closed: whatever raises, an
        uncommitted job is PARKED (never dropped) before the exception
        propagates — a lost job would let a later sync flush report
        durable-success for rows that exist nowhere."""
        try:
            self._process(job)
        except BaseException:
            with self._cond:
                if job in self._inflight:
                    self._inflight.remove(job)
                if not job.committed and job not in self._parked:
                    self._parked.append(job)
                self._cond.notify_all()
            raise
        with self._cond:
            if job in self._inflight:
                self._inflight.remove(job)
            self._cond.notify_all()

    # -- worker side ---------------------------------------------------------

    def _worker_loop(self) -> None:
        while not self._stopping.is_set():
            with self._cond:
                while not self._queue and not self._stopping.is_set():
                    self._cond.wait(timeout=0.2)
                if self._stopping.is_set() and not self._queue:
                    return
                job = self._queue.popleft()
                self._inflight.append(job)
            # a raise parks the job (fail-closed), then the Supervisor
            # restarts this loop
            self._run_job(job)

    def _process(self, job: SealJob) -> None:
        """Seal one job: build the segment (zone maps + Blooms), write
        the file, publish to the catalog, hand the packed block to the
        hot tier, recycle the buffer.  Failure semantics mirror the
        legacy phase-2 seal loop."""
        store = self._store
        if job.committed:
            return
        cols = unpack_cols(job.ints, job.flts)
        t0 = time.perf_counter()
        try:
            seg = Segment(job.seq, cols, shard=job.shard,
                          shard_count=store.n_shards)
            path = store._segment_path(job.seq)
            faults.fire("event_store.seal")
            # chaos kill point: death mid-seal leaves a partial segment
            # file; boot quarantines it and journal replay re-derives
            # the rows (they are below no committed offset — the commit
            # gate's sync flush had not passed this job)
            faults.crosspoint("crash.mid_seal")
            write_segment_file(path, cols, seg, sync=False)
        except OSError as e:
            self._on_seal_failure(job, e)
            return
        store._commit_sealed(job, seg, path,
                             seal_s=time.perf_counter() - t0)
        self.sealed_segments += 1

    def _on_seal_failure(self, job: SealJob, exc: OSError) -> None:
        store = self._store
        now = time.monotonic()
        job.attempts += 1
        if job.first_failure_t is None:
            job.first_failure_t = now
        store.metrics.counter("store.seal_failures").inc()
        from sitewhere_tpu.runtime.metrics import global_registry
        global_registry().counter(
            "resilience.retries.event_store.seal").inc()
        terminal = (job.attempts > store.max_seal_retries
                    and now - job.first_failure_t
                    >= store.seal_retry_window_s)
        if terminal:
            logger.error(
                "segment %d seal failed %d times; dead-lettering %d "
                "rows: %s", job.seq, job.attempts, job.n, exc)
            recorded = dead_letter(store.dead_letters, {
                "kind": "event-flush-failed",
                "seq": int(job.seq),
                "rows": int(job.n),
                "ts_min": int(job.ints[_TS_ROW, :job.n].min())
                if job.n else 0,
                "ts_max": int(job.ints[_TS_ROW, :job.n].max())
                if job.n else 0,
                "error": str(exc),
            })
            if store.dead_letters is None or recorded:
                # the dead-letter record IS the durable trace now.
                # committed flips under the store lock BEFORE the
                # buffer recycles — the reverse order would let a
                # reader snapshot the still-"pending" job while a
                # writer refills its recycled buffer (garbage rows)
                with store._lock:
                    store.sealed_dead_lettered += int(job.n)
                    job.committed = True  # terminal: no longer pending
                store._recycle_buffer(job)
                return
            # the durable trace could not be written (often the same
            # dead disk): dropping now would be SILENT loss — keep the
            # job parked and keep the sync flush failing instead
        else:
            logger.warning("segment %d seal failed (attempt %d); will "
                           "retry: %s", job.seq, job.attempts, exc)
        with self._cond:
            if job not in self._parked:
                self._parked.append(job)
            self._cond.notify_all()


__all__ = ["SealJob", "SealerPool"]
