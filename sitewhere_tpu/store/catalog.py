"""Segment catalog: the queryable manifest over sealed segments.

The legacy store kept a flat chunk list whose consistency was implied
by the single writer.  With parallel seal workers, background
compaction and retention all mutating the segment set concurrently,
the catalog makes the invariants explicit:

- the segment list is kept SORTED by ``order_key`` (scan position —
  seq for freshly sealed segments, the minimum replaced seq for
  compacted ones), so the retrospective lane always streams rows in
  per-shard append order;
- retention goes THROUGH the catalog: only committed segments (ones a
  seal worker has fully written and published) are prunable, so a
  retention pass can never race a background seal into a dangling
  entry — an in-flight job is simply not in the catalog yet;
- compaction swaps are atomic under the store lock with provenance
  recorded both in the merged file (crash recovery) and in the live
  ``remap`` (old event ids keep resolving);
- the whole catalog snapshots as a checkpoint section
  (:func:`catalog_state_provider`) riding PR 12's CRC-framed,
  generation-committed snapshot protocol — restore cross-checks the
  manifest against the directory scan and reports drift instead of
  trusting either side blindly.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, List, Optional, Tuple

from sitewhere_tpu.store.segment import (
    Segment,
    resolve_tombstones,
)

logger = logging.getLogger("sitewhere_tpu.store.catalog")

CATALOG_SECTION = "store-catalog"
CATALOG_VERSION = 1


class SegmentCatalog:
    """Coordinator over the store's segment list.

    The list itself lives on the store (``store._chunks`` — shared with
    the inherited indexed-query machinery); the catalog owns every
    MUTATION of it plus the id remap, all under ``store._lock``.
    """

    def __init__(self, store):
        self._store = store
        # old_seq -> (segment, row_base, rows): event ids inside
        # compacted-away segments resolve through here
        self.remap: Dict[int, Tuple[Segment, int, int]] = {}
        #: manifest restored from the last checkpoint generation (set by
        #: the state provider's restore_fn; verification material)
        self.restored_manifest: Optional[dict] = None
        self.tombstones_resolved = 0

    # -- boot ----------------------------------------------------------------

    def adopt_loaded(self) -> None:
        """Reconcile the freshly scanned segment set: resolve compaction
        tombstones (a crash between the merged write and the input
        unlink leaves both on disk), rebuild the id remap, and restore
        scan order.  Runs once from ``SegmentStore.__init__`` — single
        threaded, no lock needed."""
        store = self._store
        live, dead = resolve_tombstones(store._chunks)
        for seg in dead:
            path = os.path.join(store.dir, f"events-{seg.seq:010d}.npz")
            try:
                os.unlink(path)
            except OSError:
                pass
            store._cache.drop_seq(seg.seq)
            self.tombstones_resolved += 1
            logger.info("segment %d tombstoned by a compacted successor; "
                        "removed", seg.seq)
        live.sort(key=lambda c: (c.order_key, c.seq))
        store._chunks[:] = live
        self._rebuild_remap_locked()

    def _rebuild_remap_locked(self) -> None:
        self.remap.clear()
        for seg in self._store._chunks:
            if seg.replaces:
                for src_seq, base, rows in seg.replaces:
                    self.remap[int(src_seq)] = (seg, int(base), int(rows))

    # -- mutation (all under store._lock) ------------------------------------

    def add_locked(self, seg: Segment) -> None:
        """Publish one sealed segment at its scan position (binary
        search over the already-sorted list — this runs under the
        contended store lock on every seal-worker commit)."""
        chunks = self._store._chunks
        key = (seg.order_key, seg.seq)
        lo, hi = 0, len(chunks)
        while lo < hi:
            mid = (lo + hi) // 2
            if (chunks[mid].order_key, chunks[mid].seq) < key:
                lo = mid + 1
            else:
                hi = mid
        chunks.insert(lo, seg)

    def swap_compacted_locked(self, inputs: List[Segment],
                              merged: Segment) -> bool:
        """Atomically replace ``inputs`` with ``merged``.  Returns False
        (caller discards the merged file) when any input is no longer
        listed — retention won the race, and resurrecting pruned rows
        through a merge would violate the retention contract."""
        chunks = self._store._chunks
        ids = {id(c) for c in inputs}
        if sum(1 for c in chunks if id(c) in ids) != len(inputs):
            return False
        chunks[:] = [c for c in chunks if id(c) not in ids]
        self.add_locked(merged)
        # re-point ids: merged.replaces carries the TRANSITIVE
        # provenance (the compactor folds each input's own replaces
        # in), so this single pass re-points every remap entry that
        # pointed at an input — direct or through an earlier merge
        if merged.replaces:
            for src_seq, base, rows in merged.replaces:
                self.remap[int(src_seq)] = (merged, int(base), int(rows))
        return True

    def prune_locked(self, cutoff_s: int) -> List[Segment]:
        """Select + delist whole segments whose NEWEST row predates
        ``cutoff_s``.  Only COMMITTED segments are candidates: a seal
        job still queued or mid-write is not in the catalog, so
        retention can never leave a worker publishing into a pruned
        entry or a catalog entry pointing at an unlinked file.
        Segments that are inputs of an in-flight compaction merge are
        skipped too — pruning one mid-merge and then crashing before
        the swap aborts would resurrect its rows through the merged
        file's provenance at the next boot.  The caller (the store)
        handles marker durability and file unlinking."""
        store = self._store
        compacting = getattr(store, "_compacting", ())
        doomed = [c for c in store._chunks
                  if c.n and c.max_ts < cutoff_s
                  and id(c) not in compacting]
        if not doomed:
            return []
        dead = {id(c) for c in doomed}
        store._chunks[:] = [c for c in store._chunks if id(c) not in dead]
        for seq in [s for s, (seg, _, _) in self.remap.items()
                    if id(seg) in dead]:
            del self.remap[seq]
        return doomed

    # -- lookup --------------------------------------------------------------

    def resolve_remapped(self, seq: int
                         ) -> Optional[Tuple[Segment, int, int]]:
        """(segment, row_base, rows) for a compacted-away seq."""
        with self._store._lock:
            return self.remap.get(int(seq))

    def rows(self) -> int:
        with self._store._lock:
            return sum(c.n for c in self._store._chunks)

    # -- consistency ---------------------------------------------------------

    def verify(self) -> List[str]:
        """Catalog/filesystem consistency check (crash harness + tests).

        Returns a list of problems (empty = consistent): every listed
        segment's file exists, no duplicate seqs, scan order sorted, no
        live segment is tombstoned by another live segment's
        provenance, and the remap only points at listed segments."""
        store = self._store
        problems: List[str] = []
        with store._lock:
            chunks = list(store._chunks)
            remap = dict(self.remap)
        seqs = [c.seq for c in chunks]
        if len(seqs) != len(set(seqs)):
            problems.append("duplicate segment seqs in the catalog")
        keys = [(c.order_key, c.seq) for c in chunks]
        if keys != sorted(keys):
            problems.append("catalog scan order is not sorted")
        live = set(seqs)
        for c in chunks:
            if c._path is not None and not os.path.exists(c._path):
                problems.append(f"segment {c.seq} file missing: {c._path}")
            if c.replaces:
                ghosts = [int(r[0]) for r in c.replaces if r[0] in live]
                if ghosts:
                    problems.append(
                        f"segment {c.seq} tombstones live segments "
                        f"{ghosts} (unresolved compaction)")
        listed = {id(c) for c in chunks}
        for seq, (seg, base, rows) in remap.items():
            if id(seg) not in listed:
                problems.append(
                    f"remap for old seq {seq} points at an unlisted "
                    "segment")
        return problems

    # -- checkpoint section --------------------------------------------------

    def snapshot(self) -> bytes:
        store = self._store
        with store._lock:
            doc = {
                "next_seq": int(store._next_seq),
                "segments": [
                    {
                        "seq": int(c.seq),
                        "order_key": int(c.order_key),
                        "shard": int(c.shard),
                        "shard_count": int(c.shard_count),
                        "n": int(c.n),
                        "min_ts": int(c.min_ts),
                        "max_ts": int(c.max_ts),
                    }
                    for c in store._chunks
                ],
            }
        return json.dumps(doc, separators=(",", ":")).encode()

    def note_restored(self, doc: dict) -> List[str]:
        """Cross-check a restored manifest against the live (directory-
        scanned) catalog.  The files are authoritative — segments seal
        and compact between checkpoint generations, so drift is
        EXPECTED; what drift must never show is a manifest segment that
        is neither live, tombstoned, nor pruned-by-retention while
        retention is off.  Returns the drift report (logged, exported
        as a gauge)."""
        self.restored_manifest = doc
        store = self._store
        with store._lock:
            live = {int(c.seq) for c in store._chunks}
            remapped = set(self.remap)
            next_seq = int(store._next_seq)
        # a segment retention legitimately pruned between the last
        # checkpoint and this boot is not drift — exempting it keeps
        # the gauge meaningful on retention-enabled stores
        cutoff = (int(time.time()) - store.retention_s
                  if getattr(store, "retention_s", 0) else None)
        drift: List[str] = []
        for ent in doc.get("segments", ()):
            seq = int(ent["seq"])
            if seq not in live and seq not in remapped:
                if (cutoff is not None
                        and int(ent.get("max_ts", 1 << 62)) < cutoff):
                    continue  # retention-expired, not lost
                drift.append(f"manifest segment {seq} not on disk")
        if int(doc.get("next_seq", 0)) > next_seq:
            drift.append(
                f"manifest next_seq {doc.get('next_seq')} leads the "
                f"recovered marker {next_seq}")
        for line in drift:
            logger.warning("store catalog drift: %s", line)
        return drift


def catalog_state_provider(store):
    """The catalog's checkpoint section: rides the CRC-framed,
    generation-committed snapshot protocol (runtime/checkpoint.py), so
    a restored boot can verify its rebuilt catalog against the last
    committed generation's view."""
    from sitewhere_tpu.runtime.checkpoint import StateProvider

    def snapshot_fn():
        return store.catalog.snapshot(), None

    def restore_fn(header, payload):
        doc = json.loads(payload)
        drift = store.catalog.note_restored(doc)
        metrics = getattr(store, "metrics", None)
        if metrics is not None:
            metrics.gauge("store.catalog_drift").set(len(drift))

    return StateProvider(name=CATALOG_SECTION, snapshot_fn=snapshot_fn,
                         restore_fn=restore_fn, version=CATALOG_VERSION)


__all__ = ["SegmentCatalog", "catalog_state_provider", "CATALOG_SECTION",
           "CATALOG_VERSION"]
