"""Log-structured sharded segment store (ROADMAP item 3).

Persistence rebuilt as tenant/device-sharded log-structured columnar
segments so sustained ingest is never gated on seal and history stays
TPU-scannable:

- :mod:`~sitewhere_tpu.store.segment` — the columnar segment format
  (zone maps, Blooms, packed ``[C, n]`` layout, compaction provenance);
- :mod:`~sitewhere_tpu.store.catalog` — the queryable segment manifest
  (prune/lookup/compaction-swap/tombstones + checkpoint section);
- :mod:`~sitewhere_tpu.store.sealer` — supervised, fail-closed
  background seal workers (the parallel replacement for the legacy
  single-writer flush);
- :mod:`~sitewhere_tpu.store.compaction` — background segment merge
  with crash-safe tombstone swap;
- :mod:`~sitewhere_tpu.store.tiering` — the hot tier: recent segments
  retained in packed-column form, H2D-ready;
- :mod:`~sitewhere_tpu.store.scan` — the retrospective scan lane
  streaming sealed segments through the same packed pipeline the live
  path uses;
- :mod:`~sitewhere_tpu.store.segmented` — :class:`SegmentStore`, the
  drop-in store facade wired by :class:`~sitewhere_tpu.instance.
  Instance`.

``SegmentStore`` is exposed lazily: ``segmented`` imports the legacy
:mod:`sitewhere_tpu.services.event_store` (for the shared indexed-query
machinery), which itself imports :mod:`sitewhere_tpu.store.segment` —
an eager import here would be circular.
"""

from __future__ import annotations


def __getattr__(name):
    if name == "SegmentStore":
        from sitewhere_tpu.store.segmented import SegmentStore
        return SegmentStore
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["SegmentStore"]
