"""Retrospective scan lane: stream sealed segments at device speed.

The successor of ``EventStore.iter_chunks``: the same oldest-first,
zone-map/Bloom-pruned, row-filtered column stream the analytics
runner's retrospective mode consumes — but served from the segment
catalog, with three upgrades:

- **hot-tier fast path** — a segment resident in the hot tier yields
  its column dict as ZERO-COPY views over the packed block (no npz
  open, no column-cache lock traffic, no pivot);
- **promote-on-scan** — a demoted segment a scan had to materialize is
  re-packed into the tier (budget permitting), so repeatedly queried
  history heats up;
- **packed scan** (:func:`scan_packed`) — yields the raw
  ``([Ci, n] int32, [Cf, n] float32)`` block pairs, the H2D-staging
  form: a retrospective query can ``device_put`` a sealed segment
  exactly like the live dispatcher stages a batch (H-STREAM's "one
  system for streams and histories", arXiv:2108.03485).

Ordering: segments stream in catalog scan order (``order_key`` —
append order, compaction-stable), so per-device row order matches
what live evaluation saw and the golden live≡retro equivalence holds
through seal, compaction and tiering.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from sitewhere_tpu.store.segment import (
    BLOOM_COLUMNS,
    Segment,
    SegmentPruned,
    bloom_probe,
    pack_cols,
    segment_pruned,
    unpack_cols,
)


def _segment_cols(store, seg):
    """Materialize ``seg``'s columns, following the compaction remap if
    the file vanished mid-scan.

    A scan snapshots the segment list, and background compaction may
    swap snapshotted inputs for a merged segment (unlinking the input
    files) before the scan reaches them.  The merged segment is NOT in
    this scan's snapshot — treating the vanished input as "expired"
    would silently lose its rows, so they are served from the merged
    segment's recorded row range instead.  Returns ``(cols, remapped)``
    or ``(None, False)`` when the rows are genuinely gone (retention).
    """
    try:
        return seg.materialize(), False
    except SegmentPruned:
        entry = store.catalog.resolve_remapped(seg.seq)
        if entry is None:
            return None, False  # retention: the rows really expired
        merged, base, rows = entry
        try:
            cols = merged.materialize()
        except SegmentPruned:
            return None, False
        return {k: v[base:base + rows] for k, v in cols.items()}, True


def filters_active(event_type, mtype_id, device_id, tenant_id):
    return [
        (name, int(want))
        for name, want in (
            ("event_type", event_type), ("mtype_id", mtype_id),
            ("device_id", device_id), ("tenant_id", tenant_id))
        if want is not None
    ]


def row_mask(seg: Segment, cols: Dict[str, np.ndarray], active,
              start_s, end_s) -> Optional[np.ndarray]:
    """Row-filter mask (None = every row passes) — the legacy scan's
    rule: time masks only when the segment STRADDLES a bound."""
    mask = None
    for name, want in active:
        m = cols[name] == want
        mask = m if mask is None else (mask & m)
    if start_s is not None and seg.min_ts < start_s:
        m = cols["ts_s"] >= start_s
        mask = m if mask is None else (mask & m)
    if end_s is not None and seg.max_ts > end_s:
        m = cols["ts_s"] <= end_s
        mask = m if mask is None else (mask & m)
    return mask


def iter_segment_cols(
    store,
    *,
    event_type: Optional[int] = None,
    mtype_id: Optional[int] = None,
    device_id: Optional[int] = None,
    tenant_id: Optional[int] = None,
    start_s: Optional[int] = None,
    end_s: Optional[int] = None,
    promote: bool = True,
    stats: Optional[Dict[str, int]] = None,
) -> Iterator[Dict[str, np.ndarray]]:
    """Pruned, row-filtered column dicts in scan order (the
    ``iter_chunks`` contract, catalog edition).  The caller has already
    flushed, so every row lives in a committed segment.

    ``stats`` (optional dict) collects THIS scan's accounting —
    ``segments_scanned`` / ``segments_pruned`` / ``hot_tier_hits`` —
    so a caller can report per-query numbers without racing other
    scans on the shared ``store.scan_*`` counters."""
    with store._lock:
        segments = list(store._chunks)
    active = filters_active(event_type, mtype_id, device_id, tenant_id)
    probes = {
        name: bloom_probe(want) for name, want in active
        if name in BLOOM_COLUMNS
    }
    if stats is not None:
        stats.setdefault("segments_scanned", 0)
        stats.setdefault("segments_pruned", 0)
        stats.setdefault("hot_tier_hits", 0)
    m_rows = store.metrics.counter("store.scan_rows")
    m_hot = store.metrics.counter("store.scan_hot_hits")
    m_pruned = store.metrics.counter("store.scan_pruned")
    for seg in segments:
        if segment_pruned(seg, active, probes, start_s, end_s):
            m_pruned.inc()
            if stats is not None:
                stats["segments_pruned"] += 1
            continue
        pair = store.hot.get(seg.seq)
        if pair is not None:
            cols = unpack_cols(pair[0], pair[1])
            m_hot.inc()
            if stats is not None:
                stats["hot_tier_hits"] += 1
        else:
            cols, remapped = _segment_cols(store, seg)
            if cols is None:
                continue  # retention expired it mid-scan
            # promote-on-scan only for SELECTIVE scans: an unfiltered
            # whole-history pass would cycle the byte-bounded LRU and
            # evict the recently sealed live window for blocks no
            # windowed query is likely to re-ask for
            selective = bool(active) or start_s is not None \
                or end_s is not None
            if promote and selective and not remapped:
                store.hot.promote(seg, cols)
        if stats is not None:
            stats["segments_scanned"] += 1
        mask = row_mask(seg, cols, active, start_s, end_s)
        if mask is None or mask.all():
            m_rows.inc(seg.n)
            yield cols
        elif mask.any():
            m_rows.inc(int(mask.sum()))
            yield {k: v[mask] for k, v in cols.items()}


def scan_packed(
    store,
    *,
    event_type: Optional[int] = None,
    mtype_id: Optional[int] = None,
    device_id: Optional[int] = None,
    tenant_id: Optional[int] = None,
    start_s: Optional[int] = None,
    end_s: Optional[int] = None,
) -> Iterator[Tuple[np.ndarray, np.ndarray, Segment]]:
    """Pruned segments as packed ``(ints, flts, segment)`` blocks — the
    H2D-staging form.  Hot segments yield their resident block (zero
    copy, unfiltered segments only); filtered or cold segments pack on
    the fly.  Row filters apply before packing so a staged block holds
    exactly the surviving rows."""
    store.flush()
    with store._lock:
        segments = list(store._chunks)
    active = filters_active(event_type, mtype_id, device_id, tenant_id)
    probes = {
        name: bloom_probe(want) for name, want in active
        if name in BLOOM_COLUMNS
    }
    for seg in segments:
        if segment_pruned(seg, active, probes, start_s, end_s):
            continue
        pair = store.hot.get(seg.seq)
        if pair is not None:
            cols = unpack_cols(pair[0], pair[1])
        else:
            cols, _remapped = _segment_cols(store, seg)
            if cols is None:
                continue
            pair = None
        mask = row_mask(seg, cols, active, start_s, end_s)
        if mask is None or mask.all():
            if pair is not None:
                yield pair[0], pair[1], seg
            else:
                ints, flts = pack_cols(cols)
                yield ints, flts, seg
        elif mask.any():
            ints, flts = pack_cols({k: v[mask] for k, v in cols.items()})
            yield ints, flts, seg


__all__ = ["iter_segment_cols", "scan_packed",
           "filters_active", "row_mask"]
