"""SegmentStore: the log-structured sharded segment store facade.

Drop-in successor of the single-writer :class:`~sitewhere_tpu.services.
event_store.EventStore` (same public API — the indexed query paths are
inherited verbatim), with persistence rebuilt around four cooperating
pieces:

- **sharded packed append buffers** — ``append_columns`` routes rows by
  ``(tenant_id, device_id)`` hash into per-shard ``[C, cap]`` packed
  column buffers.  The hot path's ENTIRE seal cost is that row copy
  plus an O(1) job enqueue when a buffer fills (``@hot_path``-marked,
  allocation-lint-clean): sustained ingest is never gated on file IO.
- **seal worker pool** (:mod:`~sitewhere_tpu.store.sealer`) —
  supervised, fail-closed background workers turn full buffers into
  durable segments in parallel.  ``flush(sync=True)`` (the dispatcher's
  commit gate) drains the queue and settles deferred fsyncs before the
  journal offset may commit — the same at-least-once premise as the
  legacy store, minus the single writer.
- **segment catalog** (:mod:`~sitewhere_tpu.store.catalog`) — the
  zone-map/Bloom prune metadata generalized into a queryable manifest:
  retention and compaction go THROUGH it, so neither can race a seal
  worker into a dangling entry, and old event ids survive compaction
  via the id remap.
- **hot tier + scan lane** (:mod:`~sitewhere_tpu.store.tiering` /
  :mod:`~sitewhere_tpu.store.scan`) — recent segments stay resident in
  the packed-column form the TPU pipeline stages, and retrospective
  queries stream pruned segments through the same compiled operators
  the live path uses.

Event ids stay ``(seq << 24) | row``: a shard buffer is assigned its
segment seq the moment it opens, so an id handed out against a
buffered row is already the id of the sealed row.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Dict, Iterator, List, Optional

import numpy as np

from sitewhere_tpu.analysis.markers import hot_path
from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.runtime.metrics import global_registry
from sitewhere_tpu.services.common import EntityNotFound, ValidationError
from sitewhere_tpu.services.event_store import EventRecord, EventStore
from sitewhere_tpu.store.catalog import SegmentCatalog
from sitewhere_tpu.store.scan import iter_segment_cols
from sitewhere_tpu.store.sealer import SealJob, SealerPool
from sitewhere_tpu.store.segment import (
    COLUMNS,
    FLOAT_COLUMNS,
    INT_COLUMNS,
    _INT_INDEX,
    Segment,
    event_id,
    open_segment,
    split_event_id,
)
from sitewhere_tpu.store.compaction import Compactor
from sitewhere_tpu.store.tiering import HotTier

logger = logging.getLogger("sitewhere_tpu.store")

_MIX_DEV = 2654435761  # Knuth multiplicative hash
_MIX_TEN = 97


class _ShardBuffer:
    """One shard's open packed append buffer.

    ``seq`` is assigned when the buffer opens (first row), making event
    ids stable across the seal; the buffer becomes exactly the segment
    of that seq.  Buffers recycle through a freelist once their seal
    job completes — steady-state appends allocate nothing.

    Storage grows on demand (doubling) toward ``cap`` instead of being
    allocated eagerly: ``cap`` tracks ``flush_rows``, and a large
    flush threshold (the benches use 2^30 for "never auto-seal") must
    not eagerly commit gigabytes per shard.  Growth happens under the
    store lock while the buffer is OPEN — seal jobs only ever hold
    views of a buffer that stopped growing.
    """

    INITIAL_ROWS = 4096

    __slots__ = ("shard", "seq", "ints", "flts", "n", "cap", "alloc")

    def __init__(self, cap: int):
        self.shard = -1
        self.seq = -1
        self.cap = int(cap)
        self.alloc = min(self.cap, self.INITIAL_ROWS)
        self.ints = np.empty((len(INT_COLUMNS), self.alloc), np.int32)
        self.flts = np.empty((len(FLOAT_COLUMNS), self.alloc), np.float32)
        self.n = 0

    def ensure(self, rows: int) -> None:
        """Grow storage so ``rows`` total rows fit (amortized: doubles
        up to ``cap``)."""
        if rows <= self.alloc:
            return
        new_alloc = min(self.cap, max(rows, 2 * self.alloc))
        ints = np.empty((len(INT_COLUMNS), new_alloc), np.int32)
        flts = np.empty((len(FLOAT_COLUMNS), new_alloc), np.float32)
        ints[:, :self.n] = self.ints[:, :self.n]
        flts[:, :self.n] = self.flts[:, :self.n]
        self.ints, self.flts, self.alloc = ints, flts, new_alloc


class SegmentStore(EventStore):
    """Tenant/device-sharded log-structured columnar event store."""

    def __init__(
        self,
        root: str,
        flush_rows: int = 10_000,
        flush_interval_s: float = 0.25,
        retention_s: Optional[int] = None,
        resident_bytes: int = 256 << 20,
        dead_letters=None,
        max_seal_retries: int = 8,
        seal_retry_window_s: float = 30.0,
        name: str = "event-store",
        *,
        n_shards: int = 4,
        shard_key=None,
        seal_workers: int = 2,
        hot_bytes: int = 64 << 20,
        compact_min_rows: int = 0,
        compact_target_rows: int = 1 << 20,
        compact_interval_s: float = 30.0,
        metrics=None,
    ):
        self.metrics = metrics if metrics is not None else global_registry()
        self.n_shards = max(1, int(n_shards))
        # Optional placement override: ``shard_key(device_ids, tenant_ids)
        # -> shard array``.  The instance passes a MESH-aligned key on a
        # multi-chip deployment — store shards keyed to the mesh shard
        # owning each device's registry block — so one egress segment's
        # columns land in ONE shard buffer instead of hash-scattering
        # across all of them host-side.  None keeps the tenant/device
        # hash (best load spread for single-chip).
        self._shard_key = shard_key
        # tenant metering hook: the instance points this at its
        # UsageLedger so sealed bytes bill per tenant (_commit_sealed)
        self.usage_ledger = None
        super().__init__(
            root, flush_rows=flush_rows, flush_interval_s=flush_interval_s,
            retention_s=retention_s, resident_bytes=resident_bytes,
            dead_letters=dead_letters, max_seal_retries=max_seal_retries,
            seal_retry_window_s=seal_retry_window_s, name=name)
        cap = min(max(int(flush_rows), 64), (1 << 24) - 1)
        self._buf_cap = cap
        self._open_bufs: List[Optional[_ShardBuffer]] = \
            [None] * self.n_shards
        self._free_bufs: List[_ShardBuffer] = []
        # hoisted identity-index scratch for the single-shard route (the
        # hot-path allocation lint's np.arange finding): grown on demand,
        # sliced per batch
        self._iota = np.arange(4096, dtype=np.int64)
        # ids of segments currently inputs of an in-flight compaction
        # merge (guarded by _lock): retention skips them, so a crash
        # after the merged write can never resurrect rows a concurrent
        # prune removed — the merged segment simply straddles the
        # cutoff and the NEXT retention pass collects it whole
        self._compacting: set = set()
        self.catalog = SegmentCatalog(self)
        self.hot = HotTier(hot_bytes, metrics=self.metrics)
        self.sealer = SealerPool(self, workers=seal_workers)
        # compact_min_rows defaults to flush_rows // 4: interval flushes
        # of a quiet shard produce sub-quarter-full segments worth
        # folding; 0 keeps the default, negative disables
        if compact_min_rows == 0:
            compact_min_rows = max(2, int(flush_rows) // 4)
        self.compactor = Compactor(
            self, min_rows=max(0, compact_min_rows),
            target_rows=compact_target_rows,
            interval_s=compact_interval_s)
        self.catalog.adopt_loaded()
        # pre-register the store.* family so the OpenMetrics surface
        # (and the dynamic name-lint) sees it even before traffic
        for c in ("rows_sealed", "bytes_written", "seal_failures",
                  "rows_compacted", "segments_compacted", "scan_rows",
                  "scan_hot_hits", "scan_pruned", "tier_promotions",
                  "tier_demotions"):
            self.metrics.counter(f"store.{c}")
        self.metrics.histogram("store.seal_s")
        self.metrics.histogram("store.compact_s")
        self._m_buffered = self.metrics.gauge("store.buffered_rows")
        self._update_gauges()

    # -- layout --------------------------------------------------------------

    def _segment_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"events-{seq:010d}.npz")

    def _open_chunk(self, seq: int, path: str) -> Segment:
        try:
            return open_segment(seq, path, self._cache)
        except KeyError:
            # pre-metadata legacy chunk: the base class rebuilds (and
            # persists) its metadata with a one-time full read
            return super()._open_chunk(seq, path)

    # -- write path ----------------------------------------------------------

    @hot_path
    def append_columns(
        self, cols: Dict[str, np.ndarray], mask: Optional[np.ndarray] = None
    ) -> int:
        """Route a column batch into the shard buffers (optionally
        row-masked).  Returns rows added.

        This IS the seal hand-off the dispatcher's egress pays: packed
        row copies plus an O(1) enqueue when a buffer fills — never an
        npz build, never an fsync.  Those run on the seal workers.

        Backpressure valve (the legacy 4×-flush_rows inline seal, pool
        edition): if the seal queue falls more than a few jobs behind
        the workers, the WRITER seals one job on its own thread —
        bounded memory beats hot-path latency when the disk cannot
        keep up, exactly the legacy safety-valve trade."""
        added = self._route_and_fill(cols, mask)
        if added:
            self._m_buffered.set(self._buffered_rows)
            if self.sealer.queue_depth() > 4 + self.sealer.n_workers:
                self.sealer.pump_one()
        return added

    def _route_and_fill(self, cols, mask) -> int:
        """Validate, shard-route and copy rows into the packed buffers;
        enqueue seal jobs for any buffer that filled."""
        src: Dict[str, np.ndarray] = {}
        n_src = None
        for name, dtype in COLUMNS:
            if name == "received_s":
                continue
            if name not in cols:
                raise ValidationError(f"missing event column {name}")
            arr = np.asarray(cols[name])
            if n_src is None:
                n_src = len(arr)
            elif len(arr) != n_src:
                raise ValidationError(
                    f"column {name} length {len(arr)} != {n_src}")
            src[name] = arr
        idx = None
        if mask is not None:
            mask_arr = np.asarray(mask)
            if len(mask_arr) != n_src:
                raise ValidationError(
                    f"mask length {len(mask_arr)} != {n_src}")
            idx = np.nonzero(mask_arr)[0]
            if not len(idx):
                return 0
        if not n_src:
            return 0
        dev = src["device_id"] if idx is None \
            else src["device_id"].take(idx)
        ten = src["tenant_id"] if idx is None \
            else src["tenant_id"].take(idx)
        shards = self._shard_of(dev, ten)
        received = np.int32(int(time.time()))
        total = len(dev)
        added = 0
        jobs: List[SealJob] = []
        with self._lock:
            # scratch growth must happen under the lock: two racing
            # appenders regrowing it unlocked could leave the slower
            # one slicing a too-short iota (silently dropped rows)
            if len(self._iota) < total:
                self._iota = np.arange(
                    max(total, 2 * len(self._iota)), dtype=np.int64)
            for s in range(self.n_shards):
                rel = np.nonzero(shards == s)[0] if self.n_shards > 1 \
                    else self._iota[:total]
                if not len(rel):
                    continue
                sel = rel if idx is None else idx.take(rel)
                pos = 0
                while pos < len(sel):
                    buf = self._open_buf_locked(s)
                    k = min(buf.cap - buf.n, len(sel) - pos)
                    part = sel[pos:pos + k]
                    lo, hi = buf.n, buf.n + k
                    buf.ensure(hi)
                    for ci, cname in enumerate(INT_COLUMNS):
                        if cname == "received_s":
                            buf.ints[ci, lo:hi] = received
                        else:
                            buf.ints[ci, lo:hi] = src[cname].take(part)
                    for ci, cname in enumerate(FLOAT_COLUMNS):
                        buf.flts[ci, lo:hi] = src[cname].take(part)
                    buf.n = hi
                    pos += k
                    added += k
                    if buf.n >= buf.cap:
                        jobs.append(self._close_buf_locked(s))
            self._recount_buffered_locked()
            if jobs:
                self.sealer.enqueue_many(jobs)
        return added

    def _recount_buffered_locked(self) -> None:
        self._buffered_rows = sum(
            b.n for b in self._open_bufs if b is not None)

    def _shard_of(self, dev: np.ndarray, ten: np.ndarray) -> np.ndarray:
        if self.n_shards <= 1:
            return np.zeros(len(dev), np.int64)
        if self._shard_key is not None:
            # mesh-keyed placement; the modulo keeps an out-of-range key
            # (unregistered NULL_ID rows) a valid shard, never a crash
            return (np.asarray(self._shard_key(dev, ten), np.int64)
                    % self.n_shards)
        d = dev.astype(np.int64)
        t = ten.astype(np.int64)
        return ((d * _MIX_DEV) ^ (t * _MIX_TEN)) % self.n_shards

    def _open_buf_locked(self, shard: int) -> _ShardBuffer:
        buf = self._open_bufs[shard]
        if buf is None:
            buf = self._free_bufs.pop() if self._free_bufs \
                else _ShardBuffer(self._buf_cap)
            buf.shard = shard
            buf.seq = self._next_seq
            self._next_seq += 1
            buf.n = 0
            self._open_bufs[shard] = buf
        return buf

    def _close_buf_locked(self, shard: int) -> SealJob:
        buf = self._open_bufs[shard]
        self._open_bufs[shard] = None
        return SealJob(buf.seq, shard, buf.ints[:, :buf.n],
                       buf.flts[:, :buf.n], buf.n, buffer=buf)

    def _recycle_buffer(self, job: SealJob) -> None:
        with self._lock:
            buf = job.buffer
            job.buffer = None
            if buf is not None and len(self._free_bufs) < 2 * self.n_shards:
                self._free_bufs.append(buf)

    def add_event(self, **fields) -> EventRecord:
        """Append one event (REST create path).  The id is computed
        from the owning shard buffer's assigned seq — stable across the
        background seal."""
        received = np.int32(int(time.time()))
        values: Dict[str, object] = {}
        for name, dtype in COLUMNS:
            if name == "received_s":
                values[name] = int(received)
                continue
            default = NULL_ID if np.issubdtype(dtype, np.integer) else 0.0
            values[name] = fields.get(name, default)
        jobs: List[SealJob] = []
        with self._lock:
            shard = int(self._shard_of(
                np.asarray([values["device_id"]], np.int64),
                np.asarray([values["tenant_id"]], np.int64))[0])
            buf = self._open_buf_locked(shard)
            seq, pos = buf.seq, buf.n
            buf.ensure(pos + 1)
            for ci, cname in enumerate(INT_COLUMNS):
                buf.ints[ci, pos] = int(values[cname])
            for ci, cname in enumerate(FLOAT_COLUMNS):
                buf.flts[ci, pos] = float(values[cname])
            # read back through the buffer so the record reflects the
            # stored dtypes exactly (int32/float32 truncation included)
            for ci, cname in enumerate(INT_COLUMNS):
                values[cname] = int(buf.ints[ci, pos])
            for ci, cname in enumerate(FLOAT_COLUMNS):
                values[cname] = float(buf.flts[ci, pos])
            buf.n += 1
            if buf.n >= buf.cap:
                jobs.append(self._close_buf_locked(shard))
                self.sealer.enqueue_many(jobs)
            self._recount_buffered_locked()
        return EventRecord(event_id=event_id(seq, pos), **values)

    # -- seal completion (worker side) ---------------------------------------

    def _commit_sealed(self, job: SealJob, seg: Segment, path: str,
                       seal_s: float) -> None:
        """Publish one durably written segment (called by a seal
        worker, or inline from a drain with no workers)."""
        with self._lock:
            seg.detach(path, self._cache)
            self.catalog.add_locked(seg)
            self._unsynced_paths.add(path)
            job.committed = True
            # seq high-water marker rides the worker (off the hot
            # path); boot recovers a stale one from the files
            try:
                self._write_marker(sync=False)
            except OSError:
                logger.exception("next-seq marker write failed")
        self.hot.adopt(seg.seq, job.ints, job.flts, job.n)
        self._recycle_buffer(job)
        self.metrics.counter("store.rows_sealed").inc(job.n)
        self.metrics.counter("store.bytes_written").inc(
            int(job.ints.nbytes + job.flts.nbytes))
        self.metrics.histogram("store.seal_s").observe(seal_s)
        # Tenant metering: every sealed row bills its storage-bytes
        # share to its tenant (the tenant column is right there in the
        # job's packed ints; one bincount on the seal WORKER — never
        # the hot path).  Attribute wired by the instance; None = off.
        ledger = getattr(self, "usage_ledger", None)
        if ledger is not None and job.n:
            bytes_per_row = (job.ints.nbytes + job.flts.nbytes) / job.n
            try:
                ledger.charge_rows_host(
                    job.ints[_INT_INDEX["tenant_id"], :job.n],
                    "sealed_bytes",
                    weights=np.full(job.n, bytes_per_row))
            except Exception:
                logger.exception("sealed-bytes usage charge failed")
        self._update_gauges()

    # -- flush / drain -------------------------------------------------------

    def flush(self, sync: bool = True) -> int:
        """Seal every open shard buffer.  ``sync=True`` (commit gate,
        shutdown) additionally drains the seal queue and settles the
        deferred fsyncs, raising while any job is parked failed — the
        durability point journal reclaim is premised on."""
        with self._flush_io:
            jobs: List[SealJob] = []
            with self._lock:
                for s in range(self.n_shards):
                    buf = self._open_bufs[s]
                    if buf is not None and buf.n:
                        jobs.append(self._close_buf_locked(s))
                flushed = sum(j.n for j in jobs)
                self._recount_buffered_locked()
                if jobs:
                    self.sealer.enqueue_many(jobs)
                self._last_flush = time.monotonic()
            self.sealer.retry_parked()
            if sync:
                self.sealer.drain()
                with self._lock:
                    self._sync_durable()
                parked = self.sealer.parked_count()
                if parked:
                    raise OSError(
                        f"{parked} segment(s) not durably sealed")
            elif not self.sealer.running:
                # unstarted store: flush(sync=False) still performs the
                # writes (legacy parity) — on the caller's thread
                self.sealer.drain(pump_inline=True)
        return flushed

    # -- reads ---------------------------------------------------------------

    def _buffer_chunks_locked(self) -> List[Segment]:
        """Virtual segments over every unsealed row: queued/in-flight/
        parked seal jobs plus open shard buffers.  Row data is COPIED
        under the lock — the backing buffers recycle once their job
        commits, and a query result must not read recycled memory."""
        out: List[Segment] = []
        for job in self.sealer.snapshot_jobs():
            out.append(self._virtual_locked(
                job.seq, job.shard, job.ints, job.flts, job.n))
        for buf in self._open_bufs:
            if buf is not None and buf.n:
                out.append(self._virtual_locked(
                    buf.seq, buf.shard, buf.ints, buf.flts, buf.n))
        out.sort(key=lambda c: c.seq)
        return out

    def _virtual_locked(self, seq, shard, ints, flts, n) -> Segment:
        cols: Dict[str, np.ndarray] = {}
        for ci, cname in enumerate(INT_COLUMNS):
            cols[cname] = ints[ci, :n].copy()
        for ci, cname in enumerate(FLOAT_COLUMNS):
            cols[cname] = flts[ci, :n].copy()
        return Segment(seq, cols, light=True, shard=shard)

    @property
    def total_events(self) -> int:
        with self._lock:
            n = sum(c.n for c in self._chunks) + self._buffered_rows
            n += sum(j.n for j in self.sealer.snapshot_jobs())
        return n

    def get_event(self, eid: int) -> EventRecord:
        try:
            return super().get_event(eid)
        except EntityNotFound:
            # compacted away?  old ids keep resolving through the
            # catalog remap (provenance-recorded row bases).  The
            # record carries the REQUESTED id — the caller's handle
            # stays round-trippable, the merged segment's fresh
            # (seq, row) is an internal detail
            seq, row = split_event_id(eid)
            entry = self.catalog.resolve_remapped(seq)
            if entry is not None:
                seg, base, rows = entry
                if row < rows:
                    try:
                        rec = self._record(seg, base + row)
                    except Exception:
                        pass
                    else:
                        return dataclasses.replace(rec, event_id=eid)
            raise

    def iter_chunks(self, **filters) -> Iterator[Dict[str, np.ndarray]]:
        """The retrospective scan lane (see store/scan.py): catalog-
        pruned, hot-tier-served, row-filtered column streams in scan
        order.  Accepts ``stats={}`` to collect THIS scan's
        pruned/hot-hit accounting (race-free, unlike the shared
        ``store.scan_*`` counters)."""
        self.flush()
        return iter_segment_cols(self, **filters)

    # -- retention -----------------------------------------------------------

    def prune_older_than(self, cutoff_s: int) -> int:
        """Retention THROUGH the catalog: only committed segments are
        candidates, so a pass can never race a background seal worker
        into a dangling entry — an in-flight job is simply not in the
        catalog yet (its rows are newer than any honest cutoff anyway;
        if not, the next pass collects the sealed segment)."""
        with self._lock:
            doomed = self.catalog.prune_locked(cutoff_s)
            if not doomed:
                return 0
            paths = []
            for c in doomed:
                path = c._path or self._segment_path(c.seq)
                self._unsynced_paths.discard(path)
                paths.append((c, path))
            # Seqs must never regress: the high-water marker goes
            # durable BEFORE any segment file disappears
            self._write_marker(sync=True)
            removed = 0
            for c, path in paths:
                removed += c.n
                self._cache.drop_seq(c.seq)
                self.hot.drop(c.seq)
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass
        self._update_gauges()
        return removed

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        super().start()          # interval flusher + retention ticks
        self.sealer.start()
        self.compactor.start()

    def stop(self) -> None:
        self.compactor.stop()
        try:
            super().stop()       # joins the flusher, then sync flush
        finally:
            self.sealer.stop()

    # -- observability -------------------------------------------------------

    def _update_gauges(self) -> None:
        m = self.metrics
        with self._lock:
            segs = len(self._chunks)
        m.gauge("store.segments").set(segs)
        m.gauge("store.segments_hot").set(len(self.hot))
        m.gauge("store.hot_bytes").set(self.hot.bytes)
        m.gauge("store.seal_queue_depth").set(self.sealer.queue_depth())
        m.gauge("store.buffered_rows").set(self._buffered_rows)

    def store_stats(self) -> Dict[str, object]:
        with self._lock:
            segs = len(self._chunks)
            shards = sorted({c.shard for c in self._chunks})
        return {
            "segments": segs,
            "shards": shards,
            "buffered_rows": int(self._buffered_rows),
            "queued_rows": self.sealer.pending_rows(),
            "sealed_segments": self.sealer.sealed_segments,
            "compactions": self.compactor.compactions,
            "tombstones_resolved": self.catalog.tombstones_resolved,
            "hot": self.hot.stats(),
            "cache": self.cache_stats(),
        }

    def verify_catalog(self) -> List[str]:
        return self.catalog.verify()


__all__ = ["SegmentStore"]
