"""Columnar segment format — the one on-disk/in-memory event unit.

This module is the canonical home of the storage format that
``services/event_store.py`` introduced as private chunk machinery and
the log-structured segment store (:mod:`sitewhere_tpu.store`)
generalizes: an immutable struct-of-arrays segment persisted as one
``.npz`` file whose zip members carry the column arrays PLUS ~33 KB of
prune metadata (zone-map bounds, Bloom filters, row count/ts range) so
a restart — or a catalog rebuild — reads only the metadata.

Extensions over the legacy chunk format (all backward compatible —
legacy files simply lack the new members):

- ``_meta_shard`` — the tenant/device shard the segment belongs to
  (``NULL_SHARD`` for legacy/unsharded segments);
- ``_meta_replaces`` — compaction provenance: ``[src_seq, row_base,
  rows]`` triplets naming the input segments a merged segment
  replaces.  This makes compaction CRASH-SAFE without a write-ahead
  log: the merged file is self-describing, so a boot that finds both
  the merged output and its inputs knows the inputs are tombstoned
  (see :func:`resolve_tombstones`), and old event ids remap through
  the recorded row bases.

The segment store speaks the SAME packed-column layout the TPU
pipeline computes in: :data:`INT_COLUMNS` / :data:`FLOAT_COLUMNS`
define a ``[Ci, n] int32`` + ``[Cf, n] float32`` pair (`pack_cols` /
`unpack_cols`) that the hot tier keeps resident for direct H2D
staging and the retrospective scan lane streams through the compiled
analytics operators.
"""

from __future__ import annotations

import os
from collections import OrderedDict, deque
import threading
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from sitewhere_tpu.ids import NULL_ID

# Column schema of one stored event row: the EventBatch columns that
# matter post-pipeline, plus the enrichment context (IDeviceEventContext
# analog) and the server-side receive time.
COLUMNS = (
    ("device_id", np.int32),
    ("tenant_id", np.int32),
    ("event_type", np.int32),
    ("ts_s", np.int32),
    ("ts_ns", np.int32),
    ("mtype_id", np.int32),
    ("value", np.float32),
    ("lat", np.float32),
    ("lon", np.float32),
    ("elevation", np.float32),
    ("alert_code", np.int32),
    ("alert_level", np.int32),
    ("command_id", np.int32),
    ("payload_ref", np.int32),
    ("device_type_id", np.int32),
    ("assignment_id", np.int32),
    ("area_id", np.int32),
    ("customer_id", np.int32),
    ("asset_id", np.int32),
    ("received_s", np.int32),  # server-side receive time (receivedDate)
)
COLUMN_NAMES = tuple(name for name, _ in COLUMNS)
COLUMN_DTYPES = dict(COLUMNS)

# packed-column layout: every int32 column stacked [Ci, n], every
# float32 column stacked [Cf, n] — the same struct-of-arrays shape the
# packed pipeline stages to the device, so a hot segment is H2D-ready
# without a pivot.
INT_COLUMNS = tuple(n for n, d in COLUMNS if d is np.int32)
FLOAT_COLUMNS = tuple(n for n, d in COLUMNS if d is np.float32)
_INT_INDEX = {n: i for i, n in enumerate(INT_COLUMNS)}
_FLOAT_INDEX = {n: i for i, n in enumerate(FLOAT_COLUMNS)}

ROW_BITS = 24  # up to 16M rows per segment
NULL_SHARD = -1


def event_id(seq: int, row: int) -> int:
    return (seq << ROW_BITS) | row


def split_event_id(eid: int) -> tuple:
    return eid >> ROW_BITS, eid & ((1 << ROW_BITS) - 1)


def pack_cols(cols: Dict[str, np.ndarray]) -> Tuple[np.ndarray, np.ndarray]:
    """Column dict → packed ``([Ci, n] int32, [Cf, n] float32)`` pair."""
    n = len(cols["ts_s"])
    ints = np.empty((len(INT_COLUMNS), n), np.int32)
    flts = np.empty((len(FLOAT_COLUMNS), n), np.float32)
    for i, name in enumerate(INT_COLUMNS):
        ints[i] = cols[name]
    for i, name in enumerate(FLOAT_COLUMNS):
        flts[i] = cols[name]
    return ints, flts


def unpack_cols(ints: np.ndarray, flts: np.ndarray) -> Dict[str, np.ndarray]:
    """Packed pair → column dict of row VIEWS (zero copy)."""
    out: Dict[str, np.ndarray] = {}
    for i, name in enumerate(INT_COLUMNS):
        out[name] = ints[i]
    for i, name in enumerate(FLOAT_COLUMNS):
        out[name] = flts[i]
    return out


# Filterable columns carrying per-segment min/max zone-maps (the
# Cassandra denormalized-table analog: a segment whose [min, max]
# excludes the wanted key is skipped without touching its rows).
FILTER_COLUMNS = (
    "tenant_id", "device_id", "assignment_id", "customer_id", "area_id",
    "asset_id", "event_type", "mtype_id", "alert_code", "command_id",
)

# High-cardinality exact-match columns get a per-segment Bloom filter on
# top of the min/max bounds: random device ids never prune on range, but
# a 128 Kbit two-hash Bloom (16 KB packed per segment; fill ~22% at 16k
# rows → ~5% false positives) skips almost every non-containing segment.
BLOOM_COLUMNS = ("device_id", "assignment_id")
BLOOM_BITS = 17  # 131072-bit filter
_H1 = 0x9E3779B97F4A7C15
_H2 = 0xC2B2AE3D27D4EB4F
_SHIFT = np.uint64(64 - BLOOM_BITS)


def bloom_probe(want: int) -> tuple:
    """(h1, h2) bit positions for one lookup key (pure-int: the prune
    loop tests these against hundreds of segments per query)."""
    v = want & 0xFFFFFFFFFFFFFFFF
    return (((v * _H1) & 0xFFFFFFFFFFFFFFFF) >> int(_SHIFT),
            ((v * _H2) & 0xFFFFFFFFFFFFFFFF) >> int(_SHIFT))


# npz members carrying prune metadata alongside the column arrays, so a
# restart reads ONLY these (np.load decompresses zip members on demand —
# opening a segment never materializes its columns).
META_CORE = "_meta_core"        # int64 [version, n, min_ts, max_ts]
META_BOUNDS = "_meta_bounds"    # int64 (len(FILTER_COLUMNS), 2)
# int64 [shard, shard_count]: the shard the rows routed to AND the
# shard count in force when they were sealed.  Compaction groups by
# the PAIR — after an events.shards resize, a device may hash to a
# different shard, and merging segments across shard generations
# could reorder its history in scan order.  Legacy 1-element arrays
# read back with shard_count=0 (their own group).
META_SHARD = "_meta_shard"
META_REPLACES = "_meta_replaces"  # int64 (k, 3): [src_seq, row_base, rows]
META_VERSION = 1


def bloom_member(name: str) -> str:
    return f"_bloom_{name}"


class SegmentPruned(Exception):
    """A lazy read found the segment file gone.

    Sealed columns are disk-resident; readers must handle the file
    vanishing mid-read (query retries on a fresh snapshot, scans skip
    the expired segment, id lookups report the id expired).  Carries
    the seq so the store can self-heal when the file vanished OUTSIDE
    retention (manual deletion, disk fault)."""

    def __init__(self, seq: int):
        super().__init__(seq)
        self.seq = seq


class ColumnCache:
    """Byte-bounded LRU over sealed-segment column arrays.

    The store's durability layer (npz segment files) doubles as its
    memory manager: sealed columns load on first touch and evict
    least-recently-used once ``max_bytes`` of materialized columns
    accumulate, so a store holding billions of rows keeps only blooms +
    zone-map bounds (+ whatever the current query touches) resident.
    """

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._od: "OrderedDict[Tuple[int, str], np.ndarray]" = OrderedDict()
        # pruned seqs (never reused: the seq high-water marker only goes
        # up) — rejects a put() racing drop_seq(), which would otherwise
        # park a dead column in the LRU that no reader ever asks for.
        # Bounded: the race window is one in-flight column load, so only
        # RECENT tombstones matter; older ones expire FIFO.
        self._dead: set = set()
        self._dead_order: deque = deque()
        self._lock = threading.Lock()
        self.bytes = 0
        self.loads = 0
        self.hits = 0
        self.evictions = 0

    def get(self, key: Tuple[int, str]) -> Optional[np.ndarray]:
        with self._lock:
            arr = self._od.get(key)
            if arr is not None:
                self._od.move_to_end(key)
                self.hits += 1
            return arr

    def put(self, key: Tuple[int, str], arr: np.ndarray) -> None:
        with self._lock:
            if key[0] in self._dead:
                return
            old = self._od.pop(key, None)
            if old is not None:
                self.bytes -= old.nbytes
            self._od[key] = arr
            self.bytes += arr.nbytes
            while self.bytes > self.max_bytes and len(self._od) > 1:
                _, evicted = self._od.popitem(last=False)
                self.bytes -= evicted.nbytes
                self.evictions += 1

    def drop_seq(self, seq: int) -> None:
        """Forget a pruned segment's columns (and refuse late arrivals)."""
        with self._lock:
            if seq not in self._dead:
                self._dead.add(seq)
                self._dead_order.append(seq)
                while len(self._dead_order) > 1024:
                    self._dead.discard(self._dead_order.popleft())
            for key in [k for k in self._od if k[0] == seq]:
                self.bytes -= self._od.pop(key).nbytes


class Segment:
    """An immutable columnar segment (+ zone-map prune metadata).

    Sealed segments are LAZY: only ``n``/``min_ts``/``max_ts``/
    ``bounds``/``blooms`` stay resident; column arrays load from the
    npz file on demand through the store's :class:`ColumnCache`.
    ``light=True`` marks a VIRTUAL segment over an unsealed buffer —
    fully resident, rebuilt per read call under the append lock, no
    prune metadata (as the newest data it would rarely prune).

    ``shard`` tags the tenant/device shard the rows were routed to
    (``NULL_SHARD`` for legacy/unsharded data); ``replaces`` carries
    compaction provenance (``(src_seq, row_base, rows)`` triplets);
    ``order_key`` is the SCAN position — a compacted segment inherits
    the minimum order key of its inputs so per-device append order
    survives compaction (its fresh seq would otherwise move old rows
    after newer ones).
    """

    __slots__ = ("seq", "n", "min_ts", "max_ts", "bounds", "blooms",
                 "_cols", "_path", "_cache", "shard", "shard_count",
                 "replaces", "order_key")

    def __init__(self, seq: int, cols: Dict[str, np.ndarray],
                 light: bool = False, shard: int = NULL_SHARD,
                 shard_count: int = 0):
        self.seq = seq
        self._cols: Optional[Dict[str, np.ndarray]] = cols
        self._path: Optional[str] = None
        self._cache: Optional[ColumnCache] = None
        self.shard = int(shard)
        self.shard_count = int(shard_count)
        self.replaces: Optional[Tuple[Tuple[int, int, int], ...]] = None
        self.order_key = seq
        self.n = len(cols["ts_s"])
        self.min_ts = int(cols["ts_s"].min()) if self.n else 0
        self.max_ts = int(cols["ts_s"].max()) if self.n else 0
        if light:
            self.bounds = None
            self.blooms = {}
            return
        self.bounds = {
            name: ((int(cols[name].min()), int(cols[name].max()))
                   if self.n else (0, -1))
            for name in FILTER_COLUMNS
        }
        self.blooms = {}
        for name in BLOOM_COLUMNS:
            bits = np.zeros(1 << BLOOM_BITS, np.bool_)
            if self.n:
                v = cols[name].astype(np.int64).astype(np.uint64)
                bits[(v * np.uint64(_H1)) >> _SHIFT] = True
                bits[(v * np.uint64(_H2)) >> _SHIFT] = True
            self.blooms[name] = np.packbits(bits)  # 16 KB, MSB-first

    @classmethod
    def lazy(cls, seq: int, path: str, cache: ColumnCache, n: int,
             min_ts: int, max_ts: int, bounds: Dict[str, tuple],
             blooms: Dict[str, np.ndarray],
             shard: int = NULL_SHARD, shard_count: int = 0,
             replaces: Optional[Tuple[Tuple[int, int, int], ...]] = None,
             ) -> "Segment":
        """A sealed segment from persisted metadata — no columns
        resident."""
        seg = cls.__new__(cls)
        seg.seq = seq
        seg._cols = None
        seg._path = path
        seg._cache = cache
        seg.n = n
        seg.min_ts = min_ts
        seg.max_ts = max_ts
        seg.bounds = bounds
        seg.blooms = blooms
        seg.shard = int(shard)
        seg.shard_count = int(shard_count)
        seg.replaces = replaces
        seg.order_key = (min(r[0] for r in replaces)
                         if replaces else seq)
        return seg

    def detach(self, path: str, cache: ColumnCache) -> None:
        """Release resident columns (post-seal): reads go via the
        cache."""
        self._path = path
        self._cache = cache
        self._cols = None

    def _load_members(self, names: List[str]) -> Dict[str, np.ndarray]:
        """One npz open covering every requested member (a cold segment
        must not pay a zip-directory parse per column)."""
        out: Dict[str, np.ndarray] = {}
        try:
            with np.load(self._path) as data:
                files = set(data.files)
                for name in names:
                    if name in files:
                        out[name] = data[name]
                    else:  # forward-compat: absent column → default
                        out[name] = np.full(self.n, NULL_ID,
                                            COLUMN_DTYPES[name])
        except FileNotFoundError:
            raise SegmentPruned(self.seq) from None
        return out

    def col(self, name: str) -> np.ndarray:
        """One column's array, loading (and caching) it if not
        resident."""
        # local capture: readers run lock-free while the sealer's
        # detach() may null _cols between a check and a use
        cols = self._cols
        if cols is not None:
            return cols[name]
        key = (self.seq, name)
        arr = self._cache.get(key)
        if arr is None:
            self._cache.loads += 1
            arr = self._load_members([name])[name]
            self._cache.put(key, arr)
        return arr

    def materialize(self) -> Dict[str, np.ndarray]:
        """Every column (scan/page API) — via the cache when lazy, with
        ONE file open for all the columns a cold segment is missing."""
        cols = self._cols  # local capture: see col()
        if cols is not None:
            return dict(cols)
        out: Dict[str, np.ndarray] = {}
        missing: List[str] = []
        for name in COLUMN_NAMES:
            arr = self._cache.get((self.seq, name))
            if arr is None:
                missing.append(name)
            else:
                out[name] = arr
        if missing:
            self._cache.loads += 1
            loaded = self._load_members(missing)
            for name, arr in loaded.items():
                self._cache.put((self.seq, name), arr)
                out[name] = arr
        return out

    def may_contain(self, name: str, h1: int, h2: int) -> bool:
        bloom = self.blooms.get(name)
        if bloom is None:
            return True
        return bool(bloom[h1 >> 3] >> (7 - (h1 & 7)) & 1
                    and bloom[h2 >> 3] >> (7 - (h2 & 7)) & 1)


def segment_pruned(c: Segment, active, probes, t0, t1) -> bool:
    """Zone-map + Bloom skip (the hour-bucket/denormalized-table
    analog) — ONE predicate shared by the indexed query path, the
    legacy scan API and the segment catalog's retrospective lane, so
    they can never disagree about what a segment's metadata
    excludes."""
    if c.n == 0:
        return True
    if t0 is not None and c.max_ts < t0:
        return True
    if t1 is not None and c.min_ts > t1:
        return True
    if c.bounds is None:
        return False  # light segment (unsealed buffer): never pruned
    for name, want in active:
        lo, hi = c.bounds[name]
        if want < lo or want > hi:
            return True
        probe = probes.get(name)
        if probe is not None and not c.may_contain(name, *probe):
            return True
    return False


def write_segment_file(path: str, cols: Dict[str, np.ndarray],
                       seg: Segment, sync: bool = True,
                       fsync_dir=None) -> None:
    """Atomically write one sealed segment: columns + prune metadata.

    ``sync=False`` defers the fsyncs: the write stays atomic (tmp +
    rename) but durability is settled later by the store's deferred-
    durability pass.  The at-least-once premise only requires a segment
    to be DURABLE before the journal offset covering its rows is
    committed (the commit gate's explicit sync flush), not at seal
    time."""
    meta = {
        META_CORE: np.asarray(
            [META_VERSION, seg.n, seg.min_ts, seg.max_ts], np.int64),
        META_BOUNDS: np.asarray(
            [seg.bounds[name] for name in FILTER_COLUMNS], np.int64),
        META_SHARD: np.asarray([seg.shard, seg.shard_count], np.int64),
    }
    if seg.replaces:
        meta[META_REPLACES] = np.asarray(seg.replaces, np.int64)
    for bname, bloom in seg.blooms.items():
        meta[bloom_member(bname)] = bloom
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez(f, **cols, **meta)
        if sync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if sync and fsync_dir is not None:
        fsync_dir()


def open_segment(seq: int, path: str, cache: ColumnCache) -> Segment:
    """Open a sealed segment reading ONLY its prune metadata.

    np.load on an npz reads the zip directory, not the members; the
    metadata arrays written at seal time are the only members touched
    here.  A pre-metadata file (older store) raises KeyError — the
    caller falls back to a full column read."""
    with np.load(path) as data:
        files = set(data.files)
        if META_CORE not in files or META_BOUNDS not in files:
            raise KeyError("pre-metadata segment")
        core = data[META_CORE]
        bounds_arr = data[META_BOUNDS]
        if (int(core[0]) != META_VERSION
                or len(bounds_arr) != len(FILTER_COLUMNS)):
            raise KeyError("unknown segment metadata version")
        bounds = {
            name: (int(bounds_arr[i][0]), int(bounds_arr[i][1]))
            for i, name in enumerate(FILTER_COLUMNS)
        }
        blooms = {
            name: data[bloom_member(name)]
            for name in BLOOM_COLUMNS
            if bloom_member(name) in files
        }
        shard, shard_count = NULL_SHARD, 0
        if META_SHARD in files:
            shard_arr = data[META_SHARD]
            shard = int(shard_arr[0])
            if len(shard_arr) > 1:  # legacy files carry only [shard]
                shard_count = int(shard_arr[1])
        replaces = None
        if META_REPLACES in files:
            replaces = tuple(
                (int(r[0]), int(r[1]), int(r[2]))
                for r in data[META_REPLACES])
    return Segment.lazy(seq, path, cache, n=int(core[1]),
                        min_ts=int(core[2]), max_ts=int(core[3]),
                        bounds=bounds, blooms=blooms, shard=shard,
                        shard_count=shard_count, replaces=replaces)


def resolve_tombstones(segments: Iterable[Segment]) -> Tuple[
        List[Segment], List[Segment]]:
    """Apply compaction provenance to a freshly scanned segment set.

    A merged segment's ``replaces`` triplets tombstone its input seqs:
    a crash between the merged file landing and the input files being
    unlinked leaves BOTH on disk, and rebuilding the catalog from the
    directory alone would double every compacted row.  Returns
    ``(live, tombstoned)`` — the caller unlinks the tombstoned files.
    """
    segs = list(segments)
    dead = set()
    for s in segs:
        if s.replaces:
            dead.update(r[0] for r in s.replaces)
    live = [s for s in segs if s.seq not in dead]
    gone = [s for s in segs if s.seq in dead]
    return live, gone


__all__ = [
    "COLUMNS", "COLUMN_NAMES", "COLUMN_DTYPES", "INT_COLUMNS",
    "FLOAT_COLUMNS", "FILTER_COLUMNS", "BLOOM_COLUMNS", "BLOOM_BITS",
    "ROW_BITS", "NULL_SHARD", "META_CORE", "META_BOUNDS", "META_SHARD",
    "META_REPLACES", "META_VERSION", "event_id", "split_event_id",
    "pack_cols", "unpack_cols", "bloom_probe", "bloom_member",
    "SegmentPruned", "ColumnCache", "Segment", "segment_pruned",
    "write_segment_file", "open_segment", "resolve_tombstones",
]
