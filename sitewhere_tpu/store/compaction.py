"""Background segment compaction: merge small segments, crash-safely.

Interval flushes and low-traffic shards produce small segments; every
one costs a catalog entry, an npz open on cold reads, and a Bloom/
zone-map probe per query.  The compactor merges runs of small adjacent
segments (same shard, adjacent in scan order) into one, preserving row
order exactly.

Crash safety WITHOUT a write-ahead log — the merged file is
self-describing:

1. the merged segment is written (fsync'd) under its own fresh seq
   with a ``_meta_replaces`` member naming every input ``(src_seq,
   row_base, rows)``;
2. ``crash.mid_compact`` crosspoint — a kill here leaves BOTH the
   merged output and its inputs on disk; boot's tombstone resolution
   (:func:`~sitewhere_tpu.store.segment.resolve_tombstones`) sees the
   provenance and drops the inputs, so rows are never doubled;
3. the catalog swap publishes the merged segment at the MINIMUM input
   order key (scan order is provenance, not seq) and re-points the id
   remap, then the input files are unlinked.

Compaction is idempotent: once swapped, the inputs are gone and the
candidate scan finds nothing to redo; a crashed swap replays as step 3
at boot.  Event ids minted against input segments keep resolving
through the catalog remap (and, across restarts, through the recorded
provenance).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import List, Optional

import numpy as np

from sitewhere_tpu.runtime import faults
from sitewhere_tpu.runtime.resilience import RetryPolicy, Supervisor
from sitewhere_tpu.store.segment import (
    COLUMN_NAMES,
    Segment,
    SegmentPruned,
    write_segment_file,
)

logger = logging.getLogger("sitewhere_tpu.store.compaction")


class Compactor:
    """Per-shard merge of small adjacent segments, on an interval."""

    def __init__(self, store, min_rows: int = 4096,
                 target_rows: int = 1 << 20,
                 interval_s: float = 30.0):
        self._store = store
        self.min_rows = int(min_rows)
        self.target_rows = min(int(target_rows), (1 << 24) - 1)
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._sup: Optional[Supervisor] = None
        self.compactions = 0
        self.rows_compacted = 0

    def start(self) -> None:
        if self.interval_s <= 0 or self._sup is not None:
            return
        self._stop.clear()
        self._sup = Supervisor(
            "store-compact", self._loop,
            policy=RetryPolicy(initial_s=0.5, max_s=30.0),
            max_restarts=16, min_uptime_s=10.0)
        self._sup.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self._sup is not None:
            self._sup.stop(timeout_s=timeout_s)
            self._sup = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.run_once()

    # -- one compaction round ------------------------------------------------

    def _candidates(self) -> List[Segment]:
        """The first run of ≥2 small, file-backed segments adjacent in
        their SHARD's scan order (snapshot under the store lock).
        Adjacency is per (shard, shard_count-at-seal): a device's rows
        route to exactly one shard WITHIN one shard-count generation,
        so merging inside a generation cannot reorder any device's
        history — but after an ``events.shards`` resize the same
        device may hash to a different shard, and a cross-generation
        merge (whose order_key jumps to the run's minimum) could move
        its newer rows ahead of older ones in scan order."""
        store = self._store
        with store._lock:
            chunks = [c for c in store._chunks]
        by_shard: dict = {}
        for c in chunks:
            by_shard.setdefault((c.shard, c.shard_count), []).append(c)
        for shard_chunks in by_shard.values():
            run: List[Segment] = []
            for c in shard_chunks:
                eligible = (c._path is not None and c.n
                            and c.n < self.min_rows)
                if eligible and (not run
                                 or sum(s.n for s in run) + c.n
                                 <= self.target_rows):
                    run.append(c)
                    continue
                if len(run) >= 2:
                    return run
                run = [c] if eligible else []
            if len(run) >= 2:
                return run
        return []

    def run_once(self) -> int:
        """Compact one candidate run; returns segments merged (0 = no
        work)."""
        store = self._store
        run = self._candidates()
        if not run:
            return 0
        # mark the run as in-flight so retention skips its inputs
        # until the swap lands or aborts: without the marker, a prune
        # between the durable merged write and the swap — followed by
        # a crash (crash.mid_compact) — would resurrect the pruned
        # rows through the merged file's provenance at boot
        with store._lock:
            listed = {id(c) for c in store._chunks}
            if any(id(c) not in listed for c in run):
                return 0  # retention already delisted an input
            if any(id(c) in store._compacting for c in run):
                # another run_once (interval loop vs explicit caller)
                # already claimed part of this run: merging it twice
                # would leave two live merged files tombstoning the
                # same inputs if a crash beats the loser's swap abort
                return 0
            store._compacting.update(id(c) for c in run)
        try:
            return self._merge_marked(run)
        finally:
            with store._lock:
                store._compacting.difference_update(id(c) for c in run)

    def _merge_marked(self, run: List[Segment]) -> int:
        store = self._store
        # materialize OUTSIDE the lock (file IO); a retention race
        # pruning an input mid-read simply aborts this round
        try:
            parts = [c.materialize() for c in run]
        except SegmentPruned:
            return 0
        merged = {
            name: np.concatenate([p[name] for p in parts])
            for name in COLUMN_NAMES
        }
        # provenance: direct inputs, plus the transitive sources of any
        # input that was itself a compacted segment — boot-time
        # tombstone resolution and the id remap both need the ORIGINAL
        # seqs to keep resolving after a restart
        replaces = []
        base = 0
        for c in run:
            replaces.append((int(c.seq), base, int(c.n)))
            if c.replaces:
                for src_seq, src_base, src_rows in c.replaces:
                    replaces.append((int(src_seq), base + int(src_base),
                                     int(src_rows)))
            base += int(c.n)
        with store._lock:
            seq = store._next_seq
            store._next_seq += 1
        seg = Segment(seq, merged, shard=run[0].shard,
                      shard_count=run[0].shard_count)
        seg.replaces = tuple(replaces)
        seg.order_key = min(c.order_key for c in run)
        path = store._segment_path(seq)
        t0 = time.perf_counter()
        # the merged file must be DURABLE before any input is unlinked:
        # the inputs may already be the durable trace of a committed
        # journal offset, and a deferred-fsync merged copy could vanish
        # in a power loss after the originals are gone
        write_segment_file(path, merged, seg, sync=True,
                           fsync_dir=store._fsync_dir)
        # chaos kill point: merged file on disk, inputs still listed +
        # on disk — boot must resolve the tombstones, not double rows
        faults.crosspoint("crash.mid_compact")
        with store._lock:
            store._write_marker(sync=False)
            if not store.catalog.swap_compacted_locked(run, seg):
                # retention delisted an input while we merged: discard
                # the merged file — resurrecting pruned rows would
                # violate the retention contract
                swap_ok = False
            else:
                swap_ok = True
                seg.detach(path, store._cache)
                for c in run:
                    store._cache.drop_seq(c.seq)
                    store._unsynced_paths.discard(c._path)
        if not swap_ok:
            try:
                os.unlink(path)
            except OSError:
                pass
            return 0
        for c in run:
            store.hot.drop(c.seq)
            try:
                os.unlink(c._path)
            except OSError:
                pass
        dt = time.perf_counter() - t0
        self.compactions += 1
        self.rows_compacted += seg.n
        store.metrics.counter("store.rows_compacted").inc(seg.n)
        store.metrics.counter("store.segments_compacted").inc(len(run))
        store.metrics.histogram("store.compact_s").observe(dt)
        store._update_gauges()
        logger.info("compacted %d segments (%d rows, shard %d) -> "
                    "segment %d in %.3fs", len(run), seg.n,
                    seg.shard, seq, dt)
        return len(run)

    def drain(self) -> int:
        """Compact until quiescent (tests/tools)."""
        total = 0
        while True:
            n = self.run_once()
            if not n:
                return total
            total += n


__all__ = ["Compactor"]
