"""sitewhere-tpu: a TPU-native IoT event-processing framework.

Re-implements the capabilities of SiteWhere 2.0 (see SURVEY.md) as a sharded
SPMD program on JAX/XLA/Pallas: multi-protocol ingest and decode, device
registration/assignment validation, context enrichment, rule evaluation
(thresholds + geofencing), last-known-state and presence tracking, durable
event persistence, outbound fan-out, command delivery, batch operations,
scheduling and multi-tenant administration — with the hot pipeline
(reference: service-inbound-processing / service-rule-processing /
service-device-state) compiled to a single jitted step over struct-of-array
event tensors, and inter-stage fan-out riding ICI collectives instead of
Kafka hops.
"""

__version__ = "0.1.0"

from sitewhere_tpu.schema import (  # noqa: F401
    EventBatch,
    EventType,
    Registry,
    DeviceState,
    RuleTable,
    ZoneTable,
    AssignmentStatus,
)

# Composition root (imported lazily to keep bare-schema imports light).
def make_instance(config=None, template=None):
    """Build a fully wired :class:`sitewhere_tpu.instance.Instance`."""
    from sitewhere_tpu.instance import Instance

    return Instance(config, template)
