"""Tenant management + per-tenant engine manager.

Reference: ``service-tenant-management`` (tenant CRUD over Mongo,
``templates/TenantTemplateManager.java`` + ``DatasetTemplateManager.java``
for bootstrap content, ``kafka/TenantModelProducer.java`` broadcasting
tenant-model updates) and the kernel's multitenant engine machinery
(``sitewhere-microservice/.../multitenant/MultitenantMicroservice.java:
242-260`` — one engine per tenant, independently restartable;
``MicroserviceTenantEngine.java`` building each engine from tenant config).

TPU-first reshape: a tenant engine is a *vertical slice of host services*
(identity map, registry mirror, device management…) sharing the one SPMD
pipeline — the tenant axis on device is just the ``tenant_id`` column
(SURVEY.md §2.4 "per-tenant engines" row), so engines are cheap: no
per-tenant Spring context, no per-tenant chips.  Tenant templates are
plain config overlays; dataset templates are Python initializers run
against the new engine (the Groovy-initializer analog).
"""

from __future__ import annotations

import dataclasses
import logging
import contextlib
import threading
from typing import Callable, Dict, List, Optional

from sitewhere_tpu.ids import IdentityMap
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent, LifecycleState
from sitewhere_tpu.services.common import (
    DuplicateToken,
    Entity,
    EntityNotFound,
    InvalidReference,
    SearchCriteria,
    SearchResults,
    ValidationError,
    mint_token,
    paged,
    require,
    update_fields,
)
from sitewhere_tpu.services.assets import AssetManagement
from sitewhere_tpu.services.device_management import DeviceManagement, RegistryMirror

logger = logging.getLogger("sitewhere_tpu.tenants")


@dataclasses.dataclass
class Tenant(Entity):
    """Reference: ``ITenant`` (java-model) — name, auth token for device
    ingest, branding, authorized users, template choices."""

    name: str = ""
    auth_token: str = ""
    logo_url: str = ""
    authorized_user_ids: List[str] = dataclasses.field(default_factory=list)
    tenant_template_id: str = "empty"
    dataset_template_id: str = "empty"


@dataclasses.dataclass(frozen=True)
class TenantTemplate:
    """Engine-configuration template (reference: tenant templates stored in
    Zk, listed by ``TenantTemplateManager``).  ``config`` overlays the
    engine defaults (capacities etc.)."""

    id: str
    name: str
    config: Dict[str, object] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class DatasetTemplate:
    """Bootstrap-content template (reference: dataset templates running
    Groovy initializers, ``DatasetTemplateManager.java``).  ``initialize``
    receives the started :class:`TenantEngine`."""

    id: str
    name: str
    initialize: Optional[Callable[["TenantEngine"], None]] = None


class TenantManagement:
    """The ``ITenantManagement`` SPI as an in-process host service.

    Mutation listeners are the ``tenant-model-updates`` Kafka topic analog:
    the engine manager subscribes and spins engines up/down.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._tenants: Dict[str, Tenant] = {}
        self._listeners: List[Callable[[str, Tenant], None]] = []
        self._templates: Dict[str, TenantTemplate] = {}
        self._datasets: Dict[str, DatasetTemplate] = {}
        self.add_tenant_template(TenantTemplate(id="empty", name="Empty"))
        self.add_dataset_template(DatasetTemplate(id="empty", name="Empty"))

    # -- templates ---------------------------------------------------------

    def add_tenant_template(self, template: TenantTemplate) -> None:
        self._templates[template.id] = template

    def add_dataset_template(self, template: DatasetTemplate) -> None:
        self._datasets[template.id] = template

    def list_tenant_templates(self) -> List[TenantTemplate]:
        return sorted(self._templates.values(), key=lambda t: t.id)

    def list_dataset_templates(self) -> List[DatasetTemplate]:
        return sorted(self._datasets.values(), key=lambda t: t.id)

    def get_tenant_template(self, template_id: str) -> TenantTemplate:
        t = self._templates.get(template_id)
        require(t is not None, EntityNotFound(f"no tenant template {template_id!r}"))
        return t

    def get_dataset_template(self, template_id: str) -> DatasetTemplate:
        t = self._datasets.get(template_id)
        require(t is not None, EntityNotFound(f"no dataset template {template_id!r}"))
        return t

    # -- listeners ---------------------------------------------------------

    def add_listener(self, listener: Callable[[str, Tenant], None]) -> None:
        self._listeners.append(listener)

    def _notify(self, kind: str, tenant: Tenant) -> None:
        for listener in list(self._listeners):
            try:
                listener(kind, tenant)
            except Exception:
                logger.exception("tenant listener failed for %s %s", kind, tenant.token)

    # -- CRUD --------------------------------------------------------------

    def create_tenant(self, token: Optional[str] = None, **fields) -> Tenant:
        with self._lock:
            token = token or mint_token("tenant")
            require(token not in self._tenants, DuplicateToken(f"tenant {token!r} exists"))
            tenant = Tenant(token=token, **fields)
            require(bool(tenant.name), ValidationError("tenant name required"))
            require(
                tenant.tenant_template_id in self._templates,
                InvalidReference(f"unknown tenant template {tenant.tenant_template_id!r}"),
            )
            require(
                tenant.dataset_template_id in self._datasets,
                InvalidReference(f"unknown dataset template {tenant.dataset_template_id!r}"),
            )
            if not tenant.auth_token:
                tenant.auth_token = mint_token("auth")
            self._tenants[token] = tenant
        self._notify("tenant.created", tenant)
        return tenant

    def get_tenant(self, token: str) -> Tenant:
        with self._lock:
            tenant = self._tenants.get(token)
            require(tenant is not None, EntityNotFound(f"no tenant {token!r}"))
            return tenant

    def get_tenant_by_auth_token(self, auth_token: str) -> Optional[Tenant]:
        """Reference: device ingest authenticates with the tenant auth token."""
        with self._lock:
            for tenant in self._tenants.values():
                if tenant.auth_token == auth_token:
                    return tenant
            return None

    def update_tenant(self, token: str, **fields) -> Tenant:
        with self._lock:
            tenant = self.get_tenant(token)
            update_fields(
                tenant,
                fields,
                ("name", "auth_token", "logo_url", "authorized_user_ids", "metadata"),
            )
        self._notify("tenant.updated", tenant)
        return tenant

    def delete_tenant(self, token: str) -> Tenant:
        with self._lock:
            tenant = self.get_tenant(token)
            del self._tenants[token]
        self._notify("tenant.deleted", tenant)
        return tenant

    def list_tenants(self, criteria: Optional[SearchCriteria] = None) -> SearchResults[Tenant]:
        with self._lock:
            return paged(sorted(self._tenants.values(), key=lambda t: t.token), criteria)

    def authorized_for(self, token: str, username: str) -> bool:
        tenant = self.get_tenant(token)
        return not tenant.authorized_user_ids or username in tenant.authorized_user_ids


ENGINE_DEFAULTS: Dict[str, object] = {
    "registry_capacity": 4096,
    "max_zones": 256,
    "max_verts": 32,
}


class TenantEngine(LifecycleComponent):
    """Per-tenant vertical slice of host services.

    Reference: ``MicroserviceTenantEngine`` — but where the reference builds
    a Spring child context per tenant per microservice, this engine is a
    handful of host objects; the heavy state (registry/zone tensors) is
    published into the shared pipeline with the tenant's dense id stamped
    on its rows.

    ``extras`` lets dataset/tenant templates attach additional components
    (command processors, connector managers…); lifecycle-managed children
    when they are :class:`LifecycleComponent`.
    """

    def __init__(self, tenant: Tenant, tenant_id: int, config: Dict[str, object],
                 identity: Optional[IdentityMap] = None,
                 mirror: Optional[RegistryMirror] = None,
                 device_management: Optional[DeviceManagement] = None,
                 asset_management: Optional[AssetManagement] = None):
        """Standalone by default; pass ``identity``/``mirror`` to run the
        engine over the INSTANCE's shared tensors (the TPU-first layout:
        one registry with a tenant column, per-tenant service façades —
        :class:`DeviceManagement` was built for this: global device
        tokens, tenant-scoped other namespaces, cross-tenant creation
        lock)."""
        super().__init__(name=f"tenant-engine:{tenant.token}")
        self.tenant = tenant
        self.tenant_id = tenant_id  # dense id — the device-side tenant column value
        self.config = dict(ENGINE_DEFAULTS)
        self.config.update(config)
        cap = int(self.config["registry_capacity"])
        self.identity = identity or IdentityMap(capacity=cap)
        self.mirror = mirror or RegistryMirror(
            cap,
            max_zones=int(self.config["max_zones"]),
            max_verts=int(self.config["max_verts"]),
        )
        self.device_management = device_management or DeviceManagement(
            tenant.token, self.identity, self.mirror)
        self.asset_management = asset_management or AssetManagement(
            tenant.token, self.identity)
        self.extras: Dict[str, object] = {}

    def attach(self, name: str, component: object) -> object:
        self.extras[name] = component
        if isinstance(component, LifecycleComponent):
            self.add_child(component)
            if self.state == LifecycleState.STARTED:
                component.start()
        return component


class MultitenantEngineManager(LifecycleComponent):
    """Engine-per-tenant lifecycle manager.

    Reference: ``MultitenantMicroservice.initializeTenantEngines:242-260``
    (+ engine add/remove on tenant-model updates, independent restart
    ``:358-380``).  Subscribes to :class:`TenantManagement` mutations and
    keeps one started :class:`TenantEngine` per tenant.
    """

    def __init__(
        self,
        tenants: TenantManagement,
        engine_factory: Optional[Callable[[Tenant, int, Dict[str, object]], TenantEngine]] = None,
        tenant_ids: Optional[IdentityMap] = None,
    ):
        super().__init__(name="tenant-engine-manager")
        self.tenants = tenants
        self.engine_factory = engine_factory or TenantEngine
        self._engines: Dict[str, TenantEngine] = {}
        # Dense tenant ids are global (they key the device-side tenant
        # column) and survive engine restarts.  The instance passes ITS
        # identity map so engine tenant ids match the pipeline's column.
        self._tenant_ids = tenant_ids or IdentityMap(capacity=1 << 16)
        self._lock = threading.RLock()
        # Per-token locks serialize restart vs delete for ONE tenant
        # without holding the global lock across a (slow) stop/start —
        # get_engine for other tenants must never block on a restart.
        # Entries are refcounted: evicted when the last holder releases,
        # so the map stays bounded under tenant churn and a waiter can
        # never be stranded on an evicted lock object.
        self._token_locks: Dict[str, list] = {}  # token → [Lock, refcount]
        tenants.add_listener(self._on_tenant_event)

    @contextlib.contextmanager
    def _token_guard(self, token: str):
        with self._lock:
            entry = self._token_locks.get(token)
            if entry is None:
                entry = self._token_locks[token] = [threading.Lock(), 0]
            entry[1] += 1
        entry[0].acquire()
        try:
            yield
        finally:
            entry[0].release()
            with self._lock:
                entry[1] -= 1
                if entry[1] == 0 \
                        and self._token_locks.get(token) is entry:
                    del self._token_locks[token]

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        super().start()
        # page_size=0 = unpaged: every tenant's engine must come up, not
        # just the first default page
        for tenant in self.tenants.list_tenants(SearchCriteria(page_size=0)):
            self._ensure_engine(tenant)

    def stop(self) -> None:
        with self._lock:
            engines = list(self._engines.values())
        for engine in engines:
            if engine.state == LifecycleState.STARTED:
                engine.stop()
        super().stop()

    # -- engine registry ---------------------------------------------------

    def tenant_dense_id(self, token: str) -> int:
        return self._tenant_ids.tenant.mint(token)

    def get_engine(self, token: str) -> TenantEngine:
        with self._lock:
            engine = self._engines.get(token)
        require(engine is not None, EntityNotFound(f"no engine for tenant {token!r}"))
        return engine

    def list_engines(self) -> List[TenantEngine]:
        with self._lock:
            return list(self._engines.values())

    def restart_engine(self, token: str, rebuild: bool = False) -> TenantEngine:
        """Independent engine restart (reference: restartTenantEngine,
        ``MultitenantMicroservice.java:358-380``) — other tenants keep
        flowing.

        Default: stop→start the SAME engine (its host stores are the
        system of record and must survive; the reference reloads from
        Mongo, which we don't have per-engine).  ``rebuild=True`` tears
        the engine down and builds a fresh one through the factory —
        for engines whose factory rehydrates state externally."""
        # The per-token lock serializes restart against tenant.deleted (a
        # racing delete must not see its engine resurrected) WITHOUT
        # holding the global lock across a slow stop/start — other
        # tenants' get_engine/traffic keeps flowing during the restart.
        with self._token_guard(token):
            if not rebuild:
                with self._lock:
                    engine = self._engines.get(token)
                if engine is None:
                    # recovery lever for a tenant whose engine failed to
                    # start/bootstrap: retry from scratch
                    return self._ensure_engine(self.tenants.get_tenant(token))
                if engine.state == LifecycleState.STARTED:
                    engine.stop()
                engine.start()
                return engine
            with self._lock:
                old = self._engines.pop(token, None)
            if old is not None and old.state == LifecycleState.STARTED:
                old.stop()
            return self._ensure_engine(self.tenants.get_tenant(token))

    def _ensure_engine(self, tenant: Tenant) -> TenantEngine:
        # The whole ensure runs under the lock so a concurrent get_engine
        # never observes a half-started engine, and a failed start leaves
        # nothing registered (retryable on the next event/restart).
        with self._lock:
            engine = self._engines.get(tenant.token)
            if engine is not None:
                # Manager restart path: re-start engines parked by stop().
                if engine.state != LifecycleState.STARTED:
                    engine.start()
                return engine
            template = self.tenants.get_tenant_template(tenant.tenant_template_id)
            engine = self.engine_factory(
                tenant, self.tenant_dense_id(tenant.token), dict(template.config)
            )
            engine.start()
            dataset = self.tenants.get_dataset_template(tenant.dataset_template_id)
            if dataset.initialize is not None:
                # Bootstrap content exactly once (reference: dataset-bootstrapped
                # marker in Zk makes initialization idempotent).
                if not engine.tenant.metadata.get("dataset_bootstrapped"):
                    try:
                        dataset.initialize(engine)
                    except BaseException:
                        # A failed bootstrap must not leak a running engine
                        # nor register it — the tenant stays engine-less and
                        # a later _ensure_engine (event or manager restart)
                        # retries from scratch.
                        engine.stop()
                        raise
                    engine.tenant.metadata["dataset_bootstrapped"] = "true"
            self._engines[tenant.token] = engine
            return engine

    def _on_tenant_event(self, kind: str, tenant: Tenant) -> None:
        if self.state != LifecycleState.STARTED:
            return
        if kind == "tenant.created":
            self._ensure_engine(tenant)
        elif kind == "tenant.deleted":
            with self._token_guard(tenant.token):
                with self._lock:
                    engine = self._engines.pop(tenant.token, None)
                if engine is not None \
                        and engine.state == LifecycleState.STARTED:
                    engine.stop()
