"""Device management — the system-of-record for the device model.

Reference: ``service-device-management`` implements the whole
``IDeviceManagement`` SPI in one Mongo-backed class
(``persistence/mongodb/MongoDeviceManagement.java``; SPI at
``sitewhere-core-api/.../spi/device/IDeviceManagement.java``): device types
with commands + statuses, devices, assignments, areas + area types,
customers + customer types, zones, device groups + elements, alarms.

TPU-first reshape: the authoritative records (strings, hierarchy, metadata)
live in host dicts keyed by dense handles from
:class:`~sitewhere_tpu.ids.IdentityMap`; the *hot-path projection* of those
records — exactly the columns ``InboundPayloadProcessingLogic.
validateAssignment`` (``service-inbound-processing/...:185-219``) needs per
event — is maintained incrementally in a numpy :class:`RegistryMirror` and
published to the device as a fresh :class:`~sitewhere_tpu.schema.Registry`
epoch whenever it is dirty (the double-buffered registry of SURVEY.md §7:
rare writes never stall the streaming step; the dispatcher swaps epochs
between batches).

Zones publish the same way into a :class:`~sitewhere_tpu.schema.ZoneTable`
(reference: ``ZoneTestRuleProcessor`` caches zone polygons per processor).

Mutation triggers: like the reference's ``DeviceManagementTriggers.java:31-73``
(assignment create/update/delete emit StateChange events into the pipeline),
listeners registered via :meth:`DeviceManagement.add_listener` receive
``(kind, entity)`` callbacks.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from sitewhere_tpu.ids import NULL_ID, IdentityMap
from sitewhere_tpu.ops.geo import pad_polygon
from sitewhere_tpu.schema import (
    AlertLevel,
    AssignmentStatus,
    Registry,
    ZoneTable,
    pow2_at_least as _pow2_at_least,
)
from sitewhere_tpu.services.common import (
    DuplicateToken,
    Entity,
    EntityNotFound,
    InvalidReference,
    SearchCriteria,
    SearchResults,
    ValidationError,
    mint_token,
    now_s,
    paged,
    require,
)

# ---------------------------------------------------------------------------
# Entity records (host-authoritative; the java-model analog)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DeviceCommand(Entity):
    """Reference: ``IDeviceCommand`` — namespaced command with typed params."""

    name: str = ""
    namespace: str = ""
    description: str = ""
    # [(name, type, required)] — types: 'string'|'double'|'int32'|'int64'|'bool'|'bytes'
    parameters: List[Tuple[str, str, bool]] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class DeviceStatus(Entity):
    """Reference: ``IDeviceStatus`` — named visual status per device type."""

    code: str = ""
    name: str = ""
    background_color: str = "#ffffff"
    foreground_color: str = "#000000"
    border_color: str = "#000000"
    icon: str = ""


@dataclasses.dataclass
class DeviceType(Entity):
    name: str = ""
    description: str = ""
    image_url: str = ""
    container_policy: str = "Standalone"  # or "Composite" (reference enum)
    commands: Dict[str, DeviceCommand] = dataclasses.field(default_factory=dict)
    statuses: Dict[str, DeviceStatus] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Device(Entity):
    device_type: str = ""
    comments: str = ""
    status: str = ""
    parent_device: Optional[str] = None  # composite containment
    # path within parent's composition schema → child device token
    element_mappings: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class DeviceAssignment(Entity):
    device: str = ""
    customer: Optional[str] = None
    area: Optional[str] = None
    asset: Optional[str] = None
    status: str = "Active"  # Active | Missing | Released
    active_date_s: int = dataclasses.field(default_factory=now_s)
    released_date_s: Optional[int] = None


@dataclasses.dataclass
class AreaType(Entity):
    name: str = ""
    description: str = ""
    icon: str = ""
    contained_area_types: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Area(Entity):
    area_type: str = ""
    name: str = ""
    description: str = ""
    parent_area: Optional[str] = None
    bounds: List[Tuple[float, float]] = dataclasses.field(default_factory=list)  # (lat, lon)


@dataclasses.dataclass
class CustomerType(Entity):
    name: str = ""
    description: str = ""
    icon: str = ""
    contained_customer_types: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Customer(Entity):
    customer_type: str = ""
    name: str = ""
    description: str = ""
    parent_customer: Optional[str] = None


@dataclasses.dataclass
class Zone(Entity):
    area: str = ""
    name: str = ""
    bounds: List[Tuple[float, float]] = dataclasses.field(default_factory=list)  # (lat, lon)
    border_color: str = "#ff0000"
    fill_color: str = "#ff0000"
    opacity: float = 0.3
    # Rule attachment (ZoneTestRuleProcessor config lives on the processor in
    # the reference; here the zone row carries its firing config):
    condition: str = "inside"  # 'inside' | 'outside'
    alert_type: str = "zone.violation"
    alert_level: int = int(AlertLevel.WARNING)


@dataclasses.dataclass
class DeviceGroupElement:
    """Reference: ``IDeviceGroupElement`` — a device or nested group + roles."""

    device: Optional[str] = None
    nested_group: Optional[str] = None
    roles: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class DeviceGroup(Entity):
    name: str = ""
    description: str = ""
    roles: List[str] = dataclasses.field(default_factory=list)
    elements: List[DeviceGroupElement] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class DeviceAlarm(Entity):
    """Reference: ``IDeviceAlarm`` — triggered/acknowledged/resolved alarm."""

    device: str = ""
    assignment: Optional[str] = None
    message: str = ""
    state: str = "Triggered"  # Triggered | Acknowledged | Resolved
    triggered_date_s: int = dataclasses.field(default_factory=now_s)
    acknowledged_date_s: Optional[int] = None
    resolved_date_s: Optional[int] = None
    triggering_event_id: Optional[int] = None


# ---------------------------------------------------------------------------
# Registry mirror — incremental numpy projection, published as epochs
# ---------------------------------------------------------------------------


class RegistryMirror:
    """Host-side numpy mirror of the device-resident Registry + ZoneTable.

    Mutations are O(1) row writes under a lock; :meth:`publish` hands the
    dispatcher a fresh immutable epoch only when something changed.  This is
    the resolution of SURVEY.md §7 "registry mutation vs. pure functional
    updates": the streaming step always reads a consistent epoch, and a new
    epoch becomes visible between batches, never within one.
    """

    def __init__(self, capacity: int, max_zones: int = 256, max_verts: int = 32):
        self.capacity = capacity
        self.max_zones = max_zones
        self.max_verts = max_verts
        self._lock = threading.Lock()
        # Serializes device creation across all tenants' service instances
        # (see DeviceManagement.create_device).  Distinct from _lock, which
        # only guards row writes and is taken inside it.
        self.creation_lock = threading.Lock()
        self.epoch = 0
        self._dirty = True
        self._zones_dirty = True
        self._registry_cache: Optional[Registry] = None
        self._zones_cache: Optional[ZoneTable] = None

        self.active = np.zeros(capacity, np.bool_)
        self.tenant_id = np.full(capacity, NULL_ID, np.int32)
        self.device_type_id = np.full(capacity, NULL_ID, np.int32)
        self.assignment_id = np.full(capacity, NULL_ID, np.int32)
        self.assignment_status = np.full(capacity, AssignmentStatus.NONE, np.int32)
        self.area_id = np.full(capacity, NULL_ID, np.int32)
        self.customer_id = np.full(capacity, NULL_ID, np.int32)
        self.asset_id = np.full(capacity, NULL_ID, np.int32)

        self.z_active = np.zeros(max_zones, np.bool_)
        self.z_tenant = np.full(max_zones, NULL_ID, np.int32)
        self.z_area = np.full(max_zones, NULL_ID, np.int32)
        self.z_verts = np.zeros((max_zones, max_verts, 2), np.float32)
        self.z_nvert = np.zeros(max_zones, np.int32)
        # highest zone slot ever written + 1: the published table trims
        # to the next power of two above this (zone ids mint low-first),
        # so the dense [B, Z, V] geofence never pays for empty capacity
        self.z_hi = 0
        self.z_condition = np.zeros(max_zones, np.int32)
        self.z_alert_code = np.full(max_zones, NULL_ID, np.int32)
        self.z_alert_level = np.full(max_zones, AlertLevel.WARNING, np.int32)

    # -- device rows --------------------------------------------------------

    def set_device_row(
        self,
        device_id: int,
        *,
        active: bool,
        tenant_id: int,
        device_type_id: int,
        assignment_id: int = NULL_ID,
        assignment_status: int = int(AssignmentStatus.NONE),
        area_id: int = NULL_ID,
        customer_id: int = NULL_ID,
        asset_id: int = NULL_ID,
    ) -> None:
        if not 0 <= device_id < self.capacity:
            raise ValidationError(
                f"device handle {device_id} outside registry capacity {self.capacity}"
            )
        with self._lock:
            self.active[device_id] = active
            self.tenant_id[device_id] = tenant_id
            self.device_type_id[device_id] = device_type_id
            self.assignment_id[device_id] = assignment_id
            self.assignment_status[device_id] = assignment_status
            self.area_id[device_id] = area_id
            self.customer_id[device_id] = customer_id
            self.asset_id[device_id] = asset_id
            self._dirty = True

    def clear_device_row(self, device_id: int) -> None:
        self.set_device_row(
            device_id,
            active=False,
            tenant_id=NULL_ID,
            device_type_id=NULL_ID,
        )

    # -- zone rows ----------------------------------------------------------

    def set_zone_row(
        self,
        zone_id: int,
        *,
        active: bool,
        tenant_id: int,
        area_id: int,
        verts_lonlat: Optional[np.ndarray] = None,
        condition: int = 0,
        alert_code: int = NULL_ID,
        alert_level: int = int(AlertLevel.WARNING),
    ) -> None:
        if not 0 <= zone_id < self.max_zones:
            raise ValidationError(f"zone handle {zone_id} outside capacity {self.max_zones}")
        # Validate/pad before mutating anything so a bad polygon can't leave
        # a half-written active row in the geofence table.
        padded = None
        if verts_lonlat is not None:
            try:
                padded = pad_polygon(verts_lonlat, self.max_verts)
            except ValueError as e:
                raise ValidationError(str(e)) from e
        with self._lock:
            self.z_active[zone_id] = active
            self.z_tenant[zone_id] = tenant_id
            self.z_area[zone_id] = area_id
            if padded is not None:
                self.z_verts[zone_id] = padded
                self.z_nvert[zone_id] = len(verts_lonlat)
            self.z_condition[zone_id] = condition
            self.z_alert_code[zone_id] = alert_code
            self.z_alert_level[zone_id] = alert_level
            self.z_hi = max(self.z_hi, zone_id + 1)
            self._zones_dirty = True

    def clear_zone_row(self, zone_id: int) -> None:
        with self._lock:
            self.z_active[zone_id] = False
            self._zones_dirty = True

    # -- publication --------------------------------------------------------

    @property
    def dirty(self) -> bool:
        return self._dirty or self._zones_dirty

    def publish_registry(self) -> Registry:
        """Current device-ready Registry epoch (rebuilt only when dirty, so
        steady-state steps reuse the resident device arrays instead of
        re-transferring the registry every step)."""
        import jax.numpy as jnp

        with self._lock:
            if not self._dirty and self._registry_cache is not None:
                return self._registry_cache
            self.epoch += 1
            self._dirty = False
            self._registry_cache = Registry(
                active=jnp.asarray(self.active),
                tenant_id=jnp.asarray(self.tenant_id),
                device_type_id=jnp.asarray(self.device_type_id),
                assignment_id=jnp.asarray(self.assignment_id),
                assignment_status=jnp.asarray(self.assignment_status),
                area_id=jnp.asarray(self.area_id),
                customer_id=jnp.asarray(self.customer_id),
                asset_id=jnp.asarray(self.asset_id),
                epoch=jnp.asarray(self.epoch, jnp.int32),
            )
            return self._registry_cache

    def publish_zones(self) -> ZoneTable:
        """Current ZoneTable epoch (rebuilt only when dirty)."""
        import jax.numpy as jnp

        with self._lock:
            if not self._zones_dirty and self._zones_cache is not None:
                return self._zones_cache
            self._zones_dirty = False
            # Trim to the smallest power of two covering every written
            # slot (zone ids mint low-first, so the prefix is complete):
            # an empty/small zone set must not make every pipeline step
            # pay the full-capacity dense [B, Z, V] geofence.  Power-of-2
            # sizing bounds recompiles at log2(capacity) shape variants.
            z = _pow2_at_least(self.z_hi, cap=self.max_zones)
            self._zones_cache = ZoneTable(
                active=jnp.asarray(self.z_active[:z]),
                tenant_id=jnp.asarray(self.z_tenant[:z]),
                area_id=jnp.asarray(self.z_area[:z]),
                verts=jnp.asarray(self.z_verts[:z]),
                nvert=jnp.asarray(self.z_nvert[:z]),
                condition=jnp.asarray(self.z_condition[:z]),
                alert_code=jnp.asarray(self.z_alert_code[:z]),
                alert_level=jnp.asarray(self.z_alert_level[:z]),
            )
            return self._zones_cache


# ---------------------------------------------------------------------------
# The management service
# ---------------------------------------------------------------------------

Listener = Callable[[str, object], None]


def _locked(fn):
    """Hold the service RLock for the duration of a read that iterates the
    entity dicts — ingest frontends read concurrently while management
    mutates, and ``sorted(dict.values())`` during an insert raises
    ``RuntimeError: dictionary changed size during iteration``."""

    import functools

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)

    return wrapper

_ASSIGN_STATUS = {
    "Active": AssignmentStatus.ACTIVE,
    "Missing": AssignmentStatus.MISSING,
    "Released": AssignmentStatus.RELEASED,
}



def _check_fields(entity, fields) -> None:
    """Reject unknown field names BEFORE any mutation, so a bad update
    cannot leave an entity half-modified."""
    for k in fields:
        if not hasattr(entity, k):
            raise ValidationError(f"unknown {type(entity).__name__} field {k}")


class DeviceManagement:
    """Per-tenant device model service over a shared mirror + identity map.

    Reference: one ``MongoDeviceManagement`` per tenant engine
    (``MultitenantMicroservice.java:242-260`` spins engines per tenant);
    here tenants share the identity map and registry tensors (tenant id is a
    column), and each ``DeviceManagement`` instance is the scoped API for
    one tenant.
    """

    def __init__(self, tenant: str, identity: IdentityMap, mirror: RegistryMirror):
        self.tenant = tenant
        self.tenant_id = identity.tenant.mint(tenant)
        self.identity = identity
        self.mirror = mirror
        self._lock = threading.RLock()
        self._listeners: List[Listener] = []

        self.device_types: Dict[str, DeviceType] = {}
        self.devices: Dict[str, Device] = {}
        self.assignments: Dict[str, DeviceAssignment] = {}
        self.area_types: Dict[str, AreaType] = {}
        self.areas: Dict[str, Area] = {}
        self.customer_types: Dict[str, CustomerType] = {}
        self.customers: Dict[str, Customer] = {}
        self.zones: Dict[str, Zone] = {}
        self.device_groups: Dict[str, DeviceGroup] = {}
        self.alarms: Dict[str, DeviceAlarm] = {}

    # -- listeners (DeviceManagementTriggers analog) ------------------------

    def add_listener(self, listener: Listener) -> None:
        self._listeners.append(listener)

    def _notify(self, kind: str, entity: object) -> None:
        for listener in self._listeners:
            try:
                listener(kind, entity)
            except Exception:  # listener failures never poison the store
                import logging

                logging.getLogger("sitewhere_tpu.services").exception(
                    "device-management listener failed for %s", kind
                )

    # -- device types -------------------------------------------------------

    def create_device_type(self, token: Optional[str] = None, **fields) -> DeviceType:
        with self._lock:
            token = token or mint_token("type")
            require(token not in self.device_types, DuplicateToken(f"device type {token}"))
            dt = DeviceType(token=token, **fields)
            require(bool(dt.name), ValidationError("device type requires a name"))
            self.device_types[token] = dt
            self.identity.device_type.mint(self._scoped(token))
            self._notify("deviceType.created", dt)
            return dt

    def get_device_type(self, token: str) -> DeviceType:
        dt = self.device_types.get(token)
        require(dt is not None, EntityNotFound(f"device type {token}"))
        return dt

    def update_device_type(self, token: str, **fields) -> DeviceType:
        with self._lock:
            dt = self.get_device_type(token)
            _check_fields(dt, fields)
            for k, v in fields.items():
                setattr(dt, k, v)
            dt.touch()
            self._notify("deviceType.updated", dt)
            return dt

    @_locked
    def list_device_types(self, criteria: Optional[SearchCriteria] = None) -> SearchResults[DeviceType]:
        return paged(sorted(self.device_types.values(), key=lambda d: d.token), criteria)

    def delete_device_type(self, token: str) -> DeviceType:
        with self._lock:
            dt = self.get_device_type(token)
            used = [d for d in self.devices.values() if d.device_type == token]
            require(not used, ValidationError(f"device type {token} in use by {len(used)} devices"))
            del self.device_types[token]
            self._notify("deviceType.deleted", dt)
            return dt

    # commands (reference IDeviceManagement.createDeviceCommand etc.)

    def create_device_command(
        self, type_token: str, token: Optional[str] = None, **fields
    ) -> DeviceCommand:
        with self._lock:
            dt = self.get_device_type(type_token)
            token = token or mint_token("cmd")
            require(token not in dt.commands, DuplicateToken(f"command {token}"))
            cmd = DeviceCommand(token=token, **fields)
            require(bool(cmd.name), ValidationError("command requires a name"))
            dt.commands[token] = cmd
            self.identity.command.mint(self._scoped(token))
            self._notify("deviceCommand.created", cmd)
            return cmd

    def get_device_command(self, type_token: str, token: str) -> DeviceCommand:
        dt = self.get_device_type(type_token)
        cmd = dt.commands.get(token)
        require(cmd is not None, EntityNotFound(f"command {token}"))
        return cmd

    @_locked
    def list_device_commands(self, type_token: str) -> List[DeviceCommand]:
        return sorted(self.get_device_type(type_token).commands.values(), key=lambda c: c.token)

    def delete_device_command(self, type_token: str, token: str) -> DeviceCommand:
        with self._lock:
            dt = self.get_device_type(type_token)
            cmd = dt.commands.pop(token, None)
            require(cmd is not None, EntityNotFound(f"command {token}"))
            return cmd

    # statuses

    def create_device_status(
        self, type_token: str, token: Optional[str] = None, **fields
    ) -> DeviceStatus:
        with self._lock:
            dt = self.get_device_type(type_token)
            token = token or mint_token("status")
            require(token not in dt.statuses, DuplicateToken(f"status {token}"))
            st = DeviceStatus(token=token, **fields)
            dt.statuses[token] = st
            return st

    @_locked
    def list_device_statuses(self, type_token: str) -> List[DeviceStatus]:
        return sorted(self.get_device_type(type_token).statuses.values(), key=lambda s: s.token)

    # -- devices ------------------------------------------------------------

    def create_device(self, token: Optional[str] = None, **fields) -> Device:
        with self._lock:
            token = token or mint_token("dev")
            dev = Device(token=token, **fields)
            require(
                dev.device_type in self.device_types,
                InvalidReference(f"device type {dev.device_type}"),
            )
            if dev.parent_device is not None:
                require(
                    dev.parent_device in self.devices,
                    InvalidReference(f"parent device {dev.parent_device}"),
                )
            # Device tokens are GLOBAL (the ingest edge resolves raw tokens
            # with no tenant context, like Kafka keying on the raw token).
            # All device creations — across every tenant's service instance —
            # serialize on the mirror's creation lock so the uniqueness
            # check, the mint and the liveness write are one atomic step
            # (two tenants racing on one token cannot both claim the
            # handle).  A handle whose mirror row is inactive is a tombstone
            # of a deleted device: recreating that token reuses the handle
            # (same token == same device; tenant-scoped queries keep the old
            # tenant's history invisible to the new owner).
            with self.mirror.creation_lock:
                existing = self.identity.device.lookup(token)
                require(
                    existing == NULL_ID or not self.mirror.active[existing],
                    DuplicateToken(f"device {token}"),
                )
                device_id = self.identity.device.mint(token)
                # Mirror-write before committing to the store so a capacity
                # failure can't leave a device without a registry row.
                self.mirror.set_device_row(
                    device_id,
                    active=True,
                    tenant_id=self.tenant_id,
                    device_type_id=self.identity.device_type.lookup(
                        self._scoped(dev.device_type)
                    ),
                )
            self.devices[token] = dev
            self._notify("device.created", dev)
            return dev

    def get_device(self, token: str) -> Device:
        dev = self.devices.get(token)
        require(dev is not None, EntityNotFound(f"device {token}"))
        return dev

    def get_device_by_id(self, device_id: int) -> Device:
        token = self.identity.device.token_of(device_id)
        require(token is not None, EntityNotFound(f"device handle {device_id}"))
        return self.get_device(token)

    def update_device(self, token: str, **fields) -> Device:
        with self._lock:
            dev = self.get_device(token)
            _check_fields(dev, fields)
            if "device_type" in fields:
                require(
                    fields["device_type"] in self.device_types,
                    InvalidReference(f"device type {fields['device_type']}"),
                )
            for k, v in fields.items():
                setattr(dev, k, v)
            dev.touch()
            device_id = self.identity.device.lookup(token)
            self.mirror.set_device_row(
                device_id,
                active=True,
                tenant_id=self.tenant_id,
                device_type_id=self.identity.device_type.lookup(self._scoped(dev.device_type)),
                **self._assignment_cols(dev),
            )
            self._notify("device.updated", dev)
            return dev

    @_locked
    def list_devices(
        self,
        criteria: Optional[SearchCriteria] = None,
        device_type: Optional[str] = None,
        group: Optional[str] = None,
        excluding_assigned: bool = False,
    ) -> SearchResults[Device]:
        items = sorted(self.devices.values(), key=lambda d: d.token)
        if device_type is not None:
            items = [d for d in items if d.device_type == device_type]
        if group is not None:
            tokens = {t for t in self._group_device_tokens(group)}
            items = [d for d in items if d.token in tokens]
        if excluding_assigned:
            assigned = {
                a.device for a in self.assignments.values() if a.status != "Released"
            }
            items = [d for d in items if d.token not in assigned]
        return paged(items, criteria)

    def delete_device(self, token: str) -> Device:
        with self._lock:
            dev = self.get_device(token)
            active = self._active_assignment(token)
            require(active is None, ValidationError(f"device {token} has an active assignment"))
            del self.devices[token]
            device_id = self.identity.device.lookup(token)
            if device_id != NULL_ID:
                # Tombstone, don't free: the event store holds immutable rows
                # keyed by this handle, so recycling it onto an unrelated
                # token would graft the old device's history onto the new
                # one.  The handle stays bound to this token forever.
                self.mirror.clear_device_row(device_id)
            self._notify("device.deleted", dev)
            return dev

    # -- assignments --------------------------------------------------------

    def _active_assignment(self, device_token: str) -> Optional[DeviceAssignment]:
        for a in self.assignments.values():
            if a.device == device_token and a.status in ("Active", "Missing"):
                return a
        return None

    def create_device_assignment(
        self, token: Optional[str] = None, **fields
    ) -> DeviceAssignment:
        with self._lock:
            token = token or mint_token("asgn")
            require(token not in self.assignments, DuplicateToken(f"assignment {token}"))
            a = DeviceAssignment(token=token, **fields)
            require(a.device in self.devices, InvalidReference(f"device {a.device}"))
            require(
                self._active_assignment(a.device) is None,
                ValidationError(f"device {a.device} already has an active assignment"),
            )
            if a.customer is not None:
                require(a.customer in self.customers, InvalidReference(f"customer {a.customer}"))
            if a.area is not None:
                require(a.area in self.areas, InvalidReference(f"area {a.area}"))
            require(a.status in _ASSIGN_STATUS, ValidationError(f"bad status {a.status}"))
            self.assignments[token] = a
            self.identity.assignment.mint(self._scoped(token))
            self._sync_device_row(a.device)
            # Reference: DeviceManagementTriggers fires a StateChange event
            # into the pipeline on assignment create.
            self._notify("assignment.created", a)
            return a

    def get_device_assignment(self, token: str) -> DeviceAssignment:
        a = self.assignments.get(token)
        require(a is not None, EntityNotFound(f"assignment {token}"))
        return a

    @_locked
    def get_active_assignment(self, device_token: str) -> Optional[DeviceAssignment]:
        self.get_device(device_token)
        return self._active_assignment(device_token)

    def update_device_assignment(self, token: str, **fields) -> DeviceAssignment:
        with self._lock:
            a = self.get_device_assignment(token)
            # An assignment is bound to its device for life (reference
            # invariant: reassignment = release + create).
            require(
                "device" not in fields or fields["device"] == a.device,
                ValidationError("assignment cannot move to another device"),
            )
            if fields.get("customer") is not None:
                require(
                    fields["customer"] in self.customers,
                    InvalidReference(f"customer {fields['customer']}"),
                )
            if fields.get("area") is not None:
                require(fields["area"] in self.areas, InvalidReference(f"area {fields['area']}"))
            _check_fields(a, fields)
            require(
                fields.get("status", a.status) in _ASSIGN_STATUS,
                ValidationError(f"bad status {fields.get('status')}"),
            )
            for k, v in fields.items():
                setattr(a, k, v)
            a.touch()
            self._sync_device_row(a.device)
            self._notify("assignment.updated", a)
            return a

    def release_device_assignment(self, token: str) -> DeviceAssignment:
        """End an assignment (reference: ``endDeviceAssignment``)."""
        with self._lock:
            a = self.get_device_assignment(token)
            a.status = "Released"
            a.released_date_s = now_s()
            a.touch()
            self._sync_device_row(a.device)
            self._notify("assignment.released", a)
            return a

    def mark_missing(self, token: str) -> DeviceAssignment:
        """Presence manager hook (reference: DevicePresenceManager state change)."""
        return self.update_device_assignment(token, status="Missing")

    @_locked
    def list_device_assignments(
        self,
        criteria: Optional[SearchCriteria] = None,
        device: Optional[str] = None,
        customer: Optional[str] = None,
        area: Optional[str] = None,
        asset: Optional[str] = None,
        status: Optional[str] = None,
    ) -> SearchResults[DeviceAssignment]:
        items = sorted(self.assignments.values(), key=lambda a: a.token)
        if device is not None:
            items = [a for a in items if a.device == device]
        if customer is not None:
            items = [a for a in items if a.customer == customer]
        if area is not None:
            items = [a for a in items if a.area == area]
        if asset is not None:
            items = [a for a in items if a.asset == asset]
        if status is not None:
            items = [a for a in items if a.status == status]
        return paged(items, criteria)

    def delete_device_assignment(self, token: str) -> DeviceAssignment:
        with self._lock:
            a = self.get_device_assignment(token)
            del self.assignments[token]
            self._sync_device_row(a.device)
            self._notify("assignment.deleted", a)
            return a

    def _assignment_cols(self, dev: Device) -> dict:
        a = self._active_assignment(dev.token)
        if a is None:
            return dict(
                assignment_id=NULL_ID,
                assignment_status=int(AssignmentStatus.NONE),
                area_id=NULL_ID,
                customer_id=NULL_ID,
                asset_id=NULL_ID,
            )
        return dict(
            assignment_id=self.identity.assignment.lookup(self._scoped(a.token)),
            assignment_status=int(_ASSIGN_STATUS[a.status]),
            area_id=(
                self.identity.area.lookup(self._scoped(a.area)) if a.area else NULL_ID
            ),
            customer_id=(
                self.identity.customer.lookup(self._scoped(a.customer))
                if a.customer
                else NULL_ID
            ),
            asset_id=(
                self.identity.asset.mint(self._scoped(a.asset)) if a.asset else NULL_ID
            ),
        )

    def _sync_device_row(self, device_token: str) -> None:
        dev = self.devices.get(device_token)
        if dev is None:
            return
        device_id = self.identity.device.lookup(device_token)
        if device_id == NULL_ID:
            return
        self.mirror.set_device_row(
            device_id,
            active=True,
            tenant_id=self.tenant_id,
            device_type_id=self.identity.device_type.lookup(self._scoped(dev.device_type)),
            **self._assignment_cols(dev),
        )

    # -- areas + area types -------------------------------------------------

    def create_area_type(self, token: Optional[str] = None, **fields) -> AreaType:
        with self._lock:
            token = token or mint_token("areatype")
            require(token not in self.area_types, DuplicateToken(f"area type {token}"))
            at = AreaType(token=token, **fields)
            self.area_types[token] = at
            self.identity.area_type.mint(self._scoped(token))
            return at

    def get_area_type(self, token: str) -> AreaType:
        at = self.area_types.get(token)
        require(at is not None, EntityNotFound(f"area type {token}"))
        return at

    @_locked
    def list_area_types(self, criteria: Optional[SearchCriteria] = None) -> SearchResults[AreaType]:
        return paged(sorted(self.area_types.values(), key=lambda a: a.token), criteria)

    def create_area(self, token: Optional[str] = None, **fields) -> Area:
        with self._lock:
            token = token or mint_token("area")
            require(token not in self.areas, DuplicateToken(f"area {token}"))
            area = Area(token=token, **fields)
            require(
                area.area_type in self.area_types,
                InvalidReference(f"area type {area.area_type}"),
            )
            if area.parent_area is not None:
                require(
                    area.parent_area in self.areas,
                    InvalidReference(f"parent area {area.parent_area}"),
                )
            self.areas[token] = area
            self.identity.area.mint(self._scoped(token))
            return area

    def get_area(self, token: str) -> Area:
        area = self.areas.get(token)
        require(area is not None, EntityNotFound(f"area {token}"))
        return area

    def update_area(self, token: str, **fields) -> Area:
        with self._lock:
            area = self.get_area(token)
            _check_fields(area, fields)
            for k, v in fields.items():
                setattr(area, k, v)
            area.touch()
            return area

    @_locked
    def list_areas(
        self,
        criteria: Optional[SearchCriteria] = None,
        parent: Optional[str] = None,
        root_only: bool = False,
    ) -> SearchResults[Area]:
        items = sorted(self.areas.values(), key=lambda a: a.token)
        if parent is not None:
            items = [a for a in items if a.parent_area == parent]
        elif root_only:
            items = [a for a in items if a.parent_area is None]
        return paged(items, criteria)

    @_locked
    def area_tree(self) -> List[dict]:
        """Nested area hierarchy (reference: ``getAreasTree`` REST helper)."""

        def node(area: Area) -> dict:
            children = [a for a in self.areas.values() if a.parent_area == area.token]
            return {
                "token": area.token,
                "name": area.name,
                "children": [node(c) for c in sorted(children, key=lambda a: a.token)],
            }

        roots = [a for a in self.areas.values() if a.parent_area is None]
        return [node(a) for a in sorted(roots, key=lambda a: a.token)]

    def delete_area(self, token: str) -> Area:
        with self._lock:
            area = self.get_area(token)
            kids = [a for a in self.areas.values() if a.parent_area == token]
            require(not kids, ValidationError(f"area {token} has child areas"))
            used = [a for a in self.assignments.values() if a.area == token]
            require(not used, ValidationError(f"area {token} referenced by assignments"))
            for z in [z for z in self.zones.values() if z.area == token]:
                self.delete_zone(z.token)
            del self.areas[token]
            return area

    # -- customers + customer types -----------------------------------------

    def create_customer_type(self, token: Optional[str] = None, **fields) -> CustomerType:
        with self._lock:
            token = token or mint_token("custtype")
            require(token not in self.customer_types, DuplicateToken(f"customer type {token}"))
            ct = CustomerType(token=token, **fields)
            self.customer_types[token] = ct
            self.identity.customer_type.mint(self._scoped(token))
            return ct

    def get_customer_type(self, token: str) -> CustomerType:
        ct = self.customer_types.get(token)
        require(ct is not None, EntityNotFound(f"customer type {token}"))
        return ct

    @_locked
    def list_customer_types(
        self, criteria: Optional[SearchCriteria] = None
    ) -> SearchResults[CustomerType]:
        return paged(sorted(self.customer_types.values(), key=lambda c: c.token), criteria)

    def create_customer(self, token: Optional[str] = None, **fields) -> Customer:
        with self._lock:
            token = token or mint_token("cust")
            require(token not in self.customers, DuplicateToken(f"customer {token}"))
            c = Customer(token=token, **fields)
            require(
                c.customer_type in self.customer_types,
                InvalidReference(f"customer type {c.customer_type}"),
            )
            if c.parent_customer is not None:
                require(
                    c.parent_customer in self.customers,
                    InvalidReference(f"parent customer {c.parent_customer}"),
                )
            self.customers[token] = c
            self.identity.customer.mint(self._scoped(token))
            return c

    def get_customer(self, token: str) -> Customer:
        c = self.customers.get(token)
        require(c is not None, EntityNotFound(f"customer {token}"))
        return c

    @_locked
    def list_customers(
        self, criteria: Optional[SearchCriteria] = None, parent: Optional[str] = None
    ) -> SearchResults[Customer]:
        items = sorted(self.customers.values(), key=lambda c: c.token)
        if parent is not None:
            items = [c for c in items if c.parent_customer == parent]
        return paged(items, criteria)

    def delete_customer(self, token: str) -> Customer:
        with self._lock:
            c = self.get_customer(token)
            kids = [x for x in self.customers.values() if x.parent_customer == token]
            require(not kids, ValidationError(f"customer {token} has children"))
            used = [a for a in self.assignments.values() if a.customer == token]
            require(not used, ValidationError(f"customer {token} referenced by assignments"))
            del self.customers[token]
            return c

    # -- zones ---------------------------------------------------------------

    def create_zone(self, token: Optional[str] = None, **fields) -> Zone:
        with self._lock:
            token = token or mint_token("zone")
            require(token not in self.zones, DuplicateToken(f"zone {token}"))
            z = Zone(token=token, **fields)
            require(z.area in self.areas, InvalidReference(f"area {z.area}"))
            self._validate_zone_bounds(z.bounds)
            # Mirror-write before committing to the store (a capacity
            # failure must not leave a zone without a geofence row).
            zone_id = self.identity.zone.mint(self._scoped(token))
            try:
                self._sync_zone_row(zone_id, z)
            except ValidationError:
                self.identity.zone.free(self._scoped(token))
                raise
            self.zones[token] = z
            self._notify("zone.created", z)
            return z

    def get_zone(self, token: str) -> Zone:
        z = self.zones.get(token)
        require(z is not None, EntityNotFound(f"zone {token}"))
        return z

    def update_zone(self, token: str, **fields) -> Zone:
        with self._lock:
            z = self.get_zone(token)
            _check_fields(z, fields)
            if "bounds" in fields:
                self._validate_zone_bounds(fields["bounds"])
            if "area" in fields:
                require(fields["area"] in self.areas, InvalidReference(f"area {fields['area']}"))
            for k, v in fields.items():
                setattr(z, k, v)
            z.touch()
            self._sync_zone_row(self.identity.zone.lookup(self._scoped(token)), z)
            self._notify("zone.updated", z)
            return z

    def _validate_zone_bounds(self, bounds) -> None:
        require(len(bounds) >= 3, ValidationError("zone needs >= 3 bound points"))
        require(
            len(bounds) <= self.mirror.max_verts,
            ValidationError(
                f"zone has {len(bounds)} points > max {self.mirror.max_verts}"
            ),
        )

    @_locked
    def list_zones(
        self, criteria: Optional[SearchCriteria] = None, area: Optional[str] = None
    ) -> SearchResults[Zone]:
        items = sorted(self.zones.values(), key=lambda z: z.token)
        if area is not None:
            items = [z for z in items if z.area == area]
        return paged(items, criteria)

    def delete_zone(self, token: str) -> Zone:
        with self._lock:
            z = self.zones.pop(token, None)
            require(z is not None, EntityNotFound(f"zone {token}"))
            scoped = self._scoped(token)
            zone_id = self.identity.zone.lookup(scoped)
            if zone_id != NULL_ID:
                self.mirror.clear_zone_row(zone_id)
                self.identity.zone.free(scoped)
            self._notify("zone.deleted", z)
            return z

    def _sync_zone_row(self, zone_id: int, z: Zone) -> None:
        # bounds are (lat, lon); device verts are (lon, lat) == (x, y).
        verts = np.asarray([(lon, lat) for (lat, lon) in z.bounds], np.float32)
        self.mirror.set_zone_row(
            zone_id,
            active=True,
            tenant_id=self.tenant_id,
            area_id=self.identity.area.lookup(self._scoped(z.area)),
            verts_lonlat=verts,
            condition=0 if z.condition == "inside" else 1,
            alert_code=self.identity.alert_type.mint(self._scoped(z.alert_type)),
            alert_level=int(z.alert_level),
        )

    # -- device groups -------------------------------------------------------

    def create_device_group(self, token: Optional[str] = None, **fields) -> DeviceGroup:
        with self._lock:
            token = token or mint_token("group")
            require(token not in self.device_groups, DuplicateToken(f"group {token}"))
            g = DeviceGroup(token=token, **fields)
            self.device_groups[token] = g
            self.identity.device_group.mint(self._scoped(token))
            return g

    def get_device_group(self, token: str) -> DeviceGroup:
        g = self.device_groups.get(token)
        require(g is not None, EntityNotFound(f"group {token}"))
        return g

    @_locked
    def list_device_groups(
        self, criteria: Optional[SearchCriteria] = None, role: Optional[str] = None
    ) -> SearchResults[DeviceGroup]:
        items = sorted(self.device_groups.values(), key=lambda g: g.token)
        if role is not None:
            items = [g for g in items if role in g.roles]
        return paged(items, criteria)

    def add_device_group_elements(
        self, token: str, elements: List[DeviceGroupElement]
    ) -> DeviceGroup:
        with self._lock:
            g = self.get_device_group(token)
            for el in elements:
                if el.device is not None:
                    require(el.device in self.devices, InvalidReference(f"device {el.device}"))
                elif el.nested_group is not None:
                    require(
                        el.nested_group in self.device_groups,
                        InvalidReference(f"group {el.nested_group}"),
                    )
                    require(el.nested_group != token, ValidationError("group cannot nest itself"))
                else:
                    raise ValidationError("element needs a device or nested group")
                g.elements.append(el)
            g.touch()
            return g

    def remove_device_group_elements(
        self, token: str, elements: List[DeviceGroupElement]
    ) -> DeviceGroup:
        with self._lock:
            g = self.get_device_group(token)
            keys = {(e.device, e.nested_group) for e in elements}
            g.elements = [e for e in g.elements if (e.device, e.nested_group) not in keys]
            g.touch()
            return g

    def delete_device_group(self, token: str) -> DeviceGroup:
        with self._lock:
            g = self.device_groups.pop(token, None)
            require(g is not None, EntityNotFound(f"group {token}"))
            scoped = self._scoped(token)
            if self.identity.device_group.lookup(scoped) != NULL_ID:
                self.identity.device_group.free(scoped)
            return g

    def _group_device_tokens(self, token: str, _seen=None) -> List[str]:
        """Flatten a group (recursing nested groups) into device tokens.

        Reference: ``BatchUtils.getDevicesFromGroup`` expands groups for
        batch command targeting.
        """
        _seen = _seen if _seen is not None else set()
        if token in _seen:
            return []
        _seen.add(token)
        g = self.get_device_group(token)
        out: List[str] = []
        for el in g.elements:
            if el.device is not None:
                out.append(el.device)
            elif el.nested_group is not None and el.nested_group in self.device_groups:
                out.extend(self._group_device_tokens(el.nested_group, _seen))
        return out

    @_locked
    def group_devices(self, token: str) -> List[Device]:
        return [self.devices[t] for t in self._group_device_tokens(token) if t in self.devices]

    # -- alarms --------------------------------------------------------------

    def create_device_alarm(self, token: Optional[str] = None, **fields) -> DeviceAlarm:
        with self._lock:
            token = token or mint_token("alarm")
            require(token not in self.alarms, DuplicateToken(f"alarm {token}"))
            al = DeviceAlarm(token=token, **fields)
            require(al.device in self.devices, InvalidReference(f"device {al.device}"))
            self.alarms[token] = al
            self._notify("alarm.created", al)
            return al

    def get_device_alarm(self, token: str) -> DeviceAlarm:
        al = self.alarms.get(token)
        require(al is not None, EntityNotFound(f"alarm {token}"))
        return al

    def acknowledge_alarm(self, token: str) -> DeviceAlarm:
        with self._lock:
            al = self.get_device_alarm(token)
            al.state = "Acknowledged"
            al.acknowledged_date_s = now_s()
            al.touch()
            return al

    def resolve_alarm(self, token: str) -> DeviceAlarm:
        with self._lock:
            al = self.get_device_alarm(token)
            al.state = "Resolved"
            al.resolved_date_s = now_s()
            al.touch()
            return al

    @_locked
    def list_device_alarms(
        self,
        criteria: Optional[SearchCriteria] = None,
        device: Optional[str] = None,
        state: Optional[str] = None,
    ) -> SearchResults[DeviceAlarm]:
        items = sorted(self.alarms.values(), key=lambda a: a.token)
        if device is not None:
            items = [a for a in items if a.device == device]
        if state is not None:
            items = [a for a in items if a.state == state]
        return paged(items, criteria)

    def delete_device_alarm(self, token: str) -> DeviceAlarm:
        with self._lock:
            al = self.alarms.pop(token, None)
            require(al is not None, EntityNotFound(f"alarm {token}"))
            return al

    # -- helpers -------------------------------------------------------------

    def _scoped(self, token: str) -> str:
        """Tenant-scope a token for the shared handle spaces.

        Device tokens stay global (the ingest edge resolves raw device
        tokens without knowing the tenant — same as Kafka keying on the raw
        token); every other namespace is tenant-scoped so tenants can reuse
        names (reference: per-tenant Mongo databases give the same isolation).
        """
        return f"{self.tenant}:{token}"

    def handle_for(self, space: str, token: str) -> int:
        """Dense handle of a tenant-scoped entity (assignment/area/customer/
        asset/device_type…) — what the enrichment columns carry.  Device
        tokens are global: use ``identity.device.lookup`` directly.
        Returns ``NULL_ID`` if unknown."""
        return getattr(self.identity, space).lookup(self._scoped(token))

    def alert_type_handle(self, name: str) -> int:
        return self.identity.alert_type.mint(self._scoped(name))
