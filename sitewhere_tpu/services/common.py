"""Shared service plumbing: errors, paging, entity base.

Reference analogs: ``SiteWhereException``/``SiteWhereSystemException`` error
codes (``sitewhere-core-api``), ``ISearchResults``/``ISearchCriteria`` paging
(used by every list API, e.g. ``IDeviceManagement.listDevices``), and the
create/update field validation of ``sitewhere-core/.../persistence/
Persistence.java``.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import Callable, Dict, Generic, Iterable, List, Optional, TypeVar

T = TypeVar("T")


class ServiceError(Exception):
    """Base for service-level failures (maps to HTTP codes at the gateway)."""

    http_status = 500


class EntityNotFound(ServiceError):
    http_status = 404


class DuplicateToken(ServiceError):
    http_status = 409


class InvalidReference(ServiceError):
    """A referenced entity (type, area, customer…) does not exist."""

    http_status = 400


class ValidationError(ServiceError):
    http_status = 400


class AuthError(ServiceError):
    http_status = 401


class ForbiddenError(ServiceError):
    http_status = 403


class ServiceUnavailable(ServiceError):
    """Optional work refused under overload (degradation ladder) —
    clients should back off and retry once the instance recovers."""

    http_status = 503


class QuotaExceeded(ServiceError):
    """A tenant exhausted its metered quota (eval seconds per window).

    Retryable by design: the quota is measured over the usage ledger's
    sliding window, so the refusal clears as the window rotates — 429,
    not 403.  Never raised on the ingest hot path; only the optional
    eval surfaces (rule compile/eval, analytics runs) enforce quotas."""

    http_status = 429


@dataclasses.dataclass(frozen=True)
class SearchCriteria:
    """Page + optional time-range criteria.

    Reference: ``ISearchCriteria`` (1-based page index) and
    ``IDateRangeSearchCriteria`` used across every list API.
    """

    page: int = 1
    page_size: int = 100
    start_s: Optional[int] = None  # inclusive unix-seconds lower bound
    end_s: Optional[int] = None    # inclusive upper bound

    def slice(self, items: List[T]) -> List[T]:
        if self.page_size <= 0:
            return list(items)
        lo = (max(self.page, 1) - 1) * self.page_size
        return items[lo : lo + self.page_size]


@dataclasses.dataclass
class SearchResults(Generic[T]):
    """A page of results + the total match count (reference ``ISearchResults``)."""

    results: List[T]
    total: int

    def __iter__(self):
        return iter(self.results)

    def __len__(self):
        return len(self.results)


def paged(matches: List[T], criteria: Optional[SearchCriteria]) -> SearchResults[T]:
    criteria = criteria or SearchCriteria()
    return SearchResults(results=criteria.slice(matches), total=len(matches))


def now_s() -> int:
    return int(time.time())


_uuid_counter = itertools.count()
_uuid_lock = threading.Lock()


def mint_token(prefix: str) -> str:
    """Generate a unique token for entities created without one.

    Reference: entity tokens default to UUIDs
    (``Persistence.java`` create helpers).  Uses a counter + time so tokens
    are unique and stable within a process without consuming entropy.
    """
    with _uuid_lock:
        n = next(_uuid_counter)
    return f"{prefix}-{int(time.time() * 1000):x}-{n:x}"


@dataclasses.dataclass
class Entity:
    """Base fields shared by every persisted entity.

    Reference: ``IPersistentEntity`` — token, created/updated audit stamps,
    free-form metadata map.
    """

    token: str
    created_s: int = dataclasses.field(default_factory=now_s)
    updated_s: int = dataclasses.field(default_factory=now_s)
    metadata: Dict[str, str] = dataclasses.field(default_factory=dict)

    def touch(self) -> None:
        self.updated_s = now_s()


def require(condition: bool, error: ServiceError) -> None:
    if not condition:
        raise error


def update_fields(
    entity: Entity,
    fields: Dict[str, object],
    allowed: Iterable[str],
    validate: Optional[Callable[[Dict[str, object]], None]] = None,
) -> None:
    """Validate-then-apply entity update (reference: the update half of
    ``Persistence.java`` create/update validation).

    All checks — unknown fields and the optional ``validate`` hook — run
    before any attribute is written, so a rejected update never leaves a
    partial write behind.
    """
    unknown = set(fields) - set(allowed)
    require(not unknown, ValidationError(f"unknown fields {sorted(unknown)}"))
    if validate is not None:
        validate(fields)
    for key, value in fields.items():
        setattr(entity, key, value)
    entity.touch()
