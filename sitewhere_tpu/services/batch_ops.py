"""Batch operations — fan one command over many devices with throttling.

Reference: ``service-batch-operations`` — ``BatchOperationManager.java:61-70,
349,419`` consumes unprocessed-batch-operations, emits one element per
device, paces with ``throttleDelayMs``, and records per-element + overall
processing status; ``BatchCommandInvocationHandler`` performs the
per-element command invocation; ``BatchUtils`` expands device groups into
device lists; ``BatchManagementTriggers`` notifies on status changes.

Here the batch operation is a host record, elements invoke through
:class:`~sitewhere_tpu.commands.CommandProcessor`, and processing runs on a
worker thread with the same throttle semantic.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from typing import Callable, Dict, List, Optional

from sitewhere_tpu.commands.model import CommandInvocation
from sitewhere_tpu.commands.processing import CommandProcessor
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.services.common import (
    Entity,
    EntityNotFound,
    InvalidReference,
    SearchCriteria,
    SearchResults,
    ValidationError,
    mint_token,
    now_s,
    paged,
    require,
)
from sitewhere_tpu.services.device_management import DeviceManagement

logger = logging.getLogger("sitewhere_tpu.batch")

# Reference enums: BatchOperationStatus / ElementProcessingStatus.
OP_UNPROCESSED = "Unprocessed"
OP_INITIALIZING = "Initializing"
OP_PROCESSING = "InProcessing"
OP_DONE = "FinishedSuccessfully"
OP_DONE_ERRORS = "FinishedWithErrors"

EL_UNPROCESSED = "Unprocessed"
EL_SUCCEEDED = "Succeeded"
EL_FAILED = "Failed"


@dataclasses.dataclass
class BatchElement:
    """Per-device slice of a batch operation (reference ``IBatchElement``)."""

    device: str
    index: int
    status: str = EL_UNPROCESSED
    processed_s: Optional[int] = None
    error: Optional[str] = None


@dataclasses.dataclass
class BatchOperation(Entity):
    operation_type: str = "InvokeCommand"
    parameters: Dict[str, object] = dataclasses.field(default_factory=dict)
    status: str = OP_UNPROCESSED
    started_s: Optional[int] = None
    finished_s: Optional[int] = None
    elements: List[BatchElement] = dataclasses.field(default_factory=list)

    @property
    def counts(self) -> Dict[str, int]:
        out = {EL_UNPROCESSED: 0, EL_SUCCEEDED: 0, EL_FAILED: 0}
        for el in self.elements:
            out[el.status] = out.get(el.status, 0) + 1
        return out


Listener = Callable[[str, BatchOperation], None]


class BatchOperationManager(LifecycleComponent):
    """Create + process batch operations (see module docstring)."""

    def __init__(
        self,
        device_management: DeviceManagement,
        command_processor: CommandProcessor,
        throttle_delay_ms: int = 0,
        name: str = "batch-operations",
    ):
        super().__init__(name)
        self.dm = device_management
        self.commands = command_processor
        self.throttle_delay_ms = throttle_delay_ms
        self.operations: Dict[str, BatchOperation] = {}
        self._lock = threading.RLock()
        self._queue: "queue.Queue[Optional[str]]" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._listeners: List[Listener] = []

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        super().start()
        self._stop.clear()
        self._worker = threading.Thread(
            target=self._process_loop, name=self.name, daemon=True
        )
        self._worker.start()
        # Requeue operations interrupted by a previous shutdown.
        with self._lock:
            for op in self.operations.values():
                if op.status == OP_UNPROCESSED:
                    self._queue.put(op.token)

    def stop(self) -> None:
        self._stop.set()
        self._queue.put(None)
        if self._worker is not None:
            self._worker.join(timeout=5)
            self._worker = None
        super().stop()

    def add_listener(self, listener: Listener) -> None:
        self._listeners.append(listener)

    def _notify(self, kind: str, op: BatchOperation) -> None:
        for listener in self._listeners:
            try:
                listener(kind, op)
            except Exception:
                logger.exception("batch listener failed")

    # -- creation ------------------------------------------------------------

    def create_batch_command_invocation(
        self,
        command_token: str,
        parameter_values: Optional[Dict[str, object]] = None,
        devices: Optional[List[str]] = None,
        group: Optional[str] = None,
        token: Optional[str] = None,
    ) -> BatchOperation:
        """Queue a command invocation over a device list or group.

        Reference: REST ``createBatchCommandInvocation`` +
        ``BatchUtils.getDevicesFromGroup`` group expansion.
        """
        with self._lock:
            token = token or mint_token("batch")
            require(token not in self.operations, ValidationError(f"batch {token} exists"))
            targets = list(devices or [])
            if group is not None:
                targets.extend(d.token for d in self.dm.group_devices(group))
            # de-dup, preserve order
            seen = set()
            targets = [t for t in targets if not (t in seen or seen.add(t))]
            require(bool(targets), ValidationError("batch has no target devices"))
            for t in targets:
                require(t in self.dm.devices, InvalidReference(f"device {t}"))
            op = BatchOperation(
                token=token,
                operation_type="InvokeCommand",
                parameters={
                    "commandToken": command_token,
                    "parameterValues": dict(parameter_values or {}),
                },
                elements=[BatchElement(device=t, index=i) for i, t in enumerate(targets)],
            )
            self.operations[token] = op
            self.identity_mint(token)
            self._queue.put(token)
            self._notify("batch.created", op)
            return op

    def identity_mint(self, token: str) -> None:
        self.dm.identity.batch_operation.mint(f"{self.dm.tenant}:{token}")

    # -- queries -------------------------------------------------------------

    def get_operation(self, token: str) -> BatchOperation:
        op = self.operations.get(token)
        require(op is not None, EntityNotFound(f"batch operation {token}"))
        return op

    def list_operations(
        self, criteria: Optional[SearchCriteria] = None, status: Optional[str] = None
    ) -> SearchResults[BatchOperation]:
        with self._lock:
            items = sorted(self.operations.values(), key=lambda o: o.token)
        if status is not None:
            items = [o for o in items if o.status == status]
        return paged(items, criteria)

    def list_elements(
        self, token: str, criteria: Optional[SearchCriteria] = None,
        status: Optional[str] = None,
    ) -> SearchResults[BatchElement]:
        op = self.get_operation(token)
        items = op.elements
        if status is not None:
            items = [e for e in items if e.status == status]
        return paged(items, criteria)

    # -- processing ----------------------------------------------------------

    def process_now(self, token: str) -> BatchOperation:
        """Synchronously process one operation (worker calls this too)."""
        op = self.get_operation(token)
        with self._lock:
            if op.status not in (OP_UNPROCESSED,):
                return op
            op.status = OP_INITIALIZING
        op.started_s = now_s()
        op.status = OP_PROCESSING
        self._notify("batch.started", op)

        command_token = str(op.parameters.get("commandToken", ""))
        values = dict(op.parameters.get("parameterValues", {}))
        failures = 0
        interrupted = False
        for el in op.elements:
            if self._stop.is_set():
                interrupted = True
                break
            if el.status != EL_UNPROCESSED:
                continue  # resume path: already-processed elements keep status
            a = self.dm.get_active_assignment(el.device) if el.device in self.dm.devices else None
            if a is None:
                el.status, el.error = EL_FAILED, "no active assignment"
                failures += 1
            else:
                ok = self.commands.invoke(
                    CommandInvocation(
                        command_token=command_token,
                        target_assignment=a.token,
                        parameter_values=values,
                        initiator="BatchOperation",
                        initiator_id=op.token,
                    )
                )
                el.status = EL_SUCCEEDED if ok else EL_FAILED
                if not ok:
                    el.error = "undelivered"
                    failures += 1
            el.processed_s = now_s()
            if self.throttle_delay_ms and el is not op.elements[-1]:
                # Reference: BatchOperationManager throttleDelayMs pacing so
                # a huge fleet doesn't stampede the delivery path.
                time.sleep(self.throttle_delay_ms / 1000.0)
        if interrupted:
            # Shutdown mid-batch: mark unprocessed so a restart resumes the
            # remaining elements (the Kafka-offset-replay analog).
            op.status = OP_UNPROCESSED
            return op
        op.finished_s = now_s()
        op.status = OP_DONE_ERRORS if failures else OP_DONE
        self._notify("batch.finished", op)
        return op

    def _process_loop(self) -> None:
        while not self._stop.is_set():
            token = self._queue.get()
            if token is None:
                continue
            try:
                self.process_now(token)
            except Exception:
                logger.exception("batch %s processing failed", token)

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until the queue is drained and operations settle (tests)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                busy = any(
                    op.status in (OP_UNPROCESSED, OP_INITIALIZING, OP_PROCESSING)
                    for op in self.operations.values()
                )
            if not busy and self._queue.empty():
                return True
            time.sleep(0.01)
        return False
