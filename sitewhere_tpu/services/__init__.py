"""Host-side management services — the L6 domain services of the reference.

Each module mirrors one ``service-*-management`` microservice of the
reference (SURVEY.md §2.3), re-shaped for the TPU design: the services own
authoritative records (strings, metadata, hierarchy) on the host and
publish dense tensor epochs (``Registry``, ``ZoneTable``…) that the SPMD
pipeline gathers against.  There is no gRPC fabric between them — they are
in-process components addressed directly; the network surface is the REST
gateway (:mod:`sitewhere_tpu.web`).
"""

from sitewhere_tpu.services.common import (
    DuplicateToken,
    EntityNotFound,
    InvalidReference,
    SearchCriteria,
    SearchResults,
)
from sitewhere_tpu.services.device_management import DeviceManagement, RegistryMirror
from sitewhere_tpu.services.streams import (
    DeviceStreamManagement,
    DeviceStreamManager,
    DeviceStreamStatus,
)

__all__ = [
    "DeviceStreamManagement",
    "DeviceStreamManager",
    "DeviceStreamStatus",
    "DuplicateToken",
    "EntityNotFound",
    "InvalidReference",
    "SearchCriteria",
    "SearchResults",
    "DeviceManagement",
    "RegistryMirror",
]
