"""Asset management — the asset catalog bound to device assignments.

Reference: ``service-asset-management`` implements ``IAssetManagement``
(``sitewhere-core-api/.../spi/asset/IAssetManagement.java:25-135``): asset
types (category person/device/hardware) and assets, referenced by device
assignments (``DeviceAssignment.asset_id``) so events can be enriched with
"who/what this device is attached to".  (The reference's bulk of LoC is a
generated WSO2 SOAP client — an external identity-provider integration we
deliberately do not replicate; the capability is the catalog + binding.)

TPU-first reshape: assets are host-only records; the pipeline sees only
the dense ``asset_id`` column already present in
:class:`~sitewhere_tpu.schema.Registry` — binding an asset to an
assignment flows through ``DeviceManagement`` into the registry epoch.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional

from sitewhere_tpu.ids import IdentityMap
from sitewhere_tpu.services.common import (
    DuplicateToken,
    Entity,
    EntityNotFound,
    InvalidReference,
    SearchCriteria,
    SearchResults,
    ValidationError,
    mint_token,
    paged,
    require,
    update_fields,
)


class AssetCategory:
    """Reference: ``AssetCategory`` enum (java-model)."""

    PERSON = "person"
    DEVICE = "device"
    HARDWARE = "hardware"

    ALL = (PERSON, DEVICE, HARDWARE)


@dataclasses.dataclass
class AssetType(Entity):
    """Reference: ``IAssetType`` — category + branding for a class of assets."""

    name: str = ""
    description: str = ""
    category: str = AssetCategory.DEVICE
    image_url: str = ""
    icon: str = ""


@dataclasses.dataclass
class Asset(Entity):
    """Reference: ``IAsset`` — a concrete asset of some type."""

    name: str = ""
    asset_type: str = ""  # AssetType token
    image_url: str = ""


class AssetManagement:
    """The ``IAssetManagement`` SPI as an in-process host service.

    Dense asset ids are minted per tenant from the shared
    :class:`~sitewhere_tpu.ids.IdentityMap` (``identity.asset`` space) — the
    same handles ``DeviceManagement`` writes into the registry's
    ``asset_id`` column, so enrichment output resolves back to these
    records.
    """

    def __init__(self, tenant: str, identity: IdentityMap):
        self.tenant = tenant
        self.identity = identity
        self._lock = threading.RLock()
        self._types: Dict[str, AssetType] = {}
        self._assets: Dict[str, Asset] = {}

    def _scoped(self, token: str) -> str:
        return f"{self.tenant}:{token}"

    # -- asset types -------------------------------------------------------

    def create_asset_type(self, token: Optional[str] = None, **fields) -> AssetType:
        with self._lock:
            token = token or mint_token("asset-type")
            require(token not in self._types, DuplicateToken(f"asset type {token!r} exists"))
            at = AssetType(token=token, **fields)
            require(bool(at.name), ValidationError("asset type name required"))
            require(
                at.category in AssetCategory.ALL,
                ValidationError(f"bad category {at.category!r}"),
            )
            self._types[token] = at
            return at

    def get_asset_type(self, token: str) -> AssetType:
        with self._lock:
            at = self._types.get(token)
            require(at is not None, EntityNotFound(f"no asset type {token!r}"))
            return at

    def update_asset_type(self, token: str, **fields) -> AssetType:
        with self._lock:
            at = self.get_asset_type(token)

            def validate(f):
                require(
                    f.get("category", at.category) in AssetCategory.ALL,
                    ValidationError(f"bad category {f.get('category')!r}"),
                )

            update_fields(
                at,
                fields,
                ("name", "description", "category", "image_url", "icon", "metadata"),
                validate,
            )
            return at

    def list_asset_types(
        self, criteria: Optional[SearchCriteria] = None
    ) -> SearchResults[AssetType]:
        with self._lock:
            return paged(sorted(self._types.values(), key=lambda t: t.token), criteria)

    def delete_asset_type(self, token: str) -> AssetType:
        with self._lock:
            at = self.get_asset_type(token)
            used = [a.token for a in self._assets.values() if a.asset_type == token]
            require(
                not used,
                InvalidReference(f"asset type {token!r} in use by assets {used[:3]}"),
            )
            del self._types[token]
            return at

    # -- assets ------------------------------------------------------------

    def create_asset(self, token: Optional[str] = None, **fields) -> Asset:
        with self._lock:
            token = token or mint_token("asset")
            require(token not in self._assets, DuplicateToken(f"asset {token!r} exists"))
            asset = Asset(token=token, **fields)
            require(bool(asset.name), ValidationError("asset name required"))
            require(
                asset.asset_type in self._types,
                InvalidReference(f"unknown asset type {asset.asset_type!r}"),
            )
            self._assets[token] = asset
            self.identity.asset.mint(self._scoped(token))
            return asset

    def get_asset(self, token: str) -> Asset:
        with self._lock:
            asset = self._assets.get(token)
            require(asset is not None, EntityNotFound(f"no asset {token!r}"))
            return asset

    def get_asset_by_id(self, asset_id: int) -> Asset:
        """Resolve a dense id from pipeline output back to the record."""
        scoped = self.identity.asset.token_of(asset_id)
        require(
            scoped is not None and scoped.startswith(self.tenant + ":"),
            EntityNotFound(f"no asset with id {asset_id}"),
        )
        return self.get_asset(scoped.split(":", 1)[1])

    def asset_dense_id(self, token: str) -> int:
        self.get_asset(token)
        return self.identity.asset.mint(self._scoped(token))

    def update_asset(self, token: str, **fields) -> Asset:
        with self._lock:
            asset = self.get_asset(token)

            def validate(f):
                if "asset_type" in f:
                    require(
                        f["asset_type"] in self._types,
                        InvalidReference(f"unknown asset type {f['asset_type']!r}"),
                    )

            update_fields(
                asset, fields, ("name", "asset_type", "image_url", "metadata"), validate
            )
            return asset

    def list_assets(
        self,
        criteria: Optional[SearchCriteria] = None,
        asset_type: Optional[str] = None,
    ) -> SearchResults[Asset]:
        with self._lock:
            matches = [
                a
                for a in self._assets.values()
                if asset_type is None or a.asset_type == asset_type
            ]
            return paged(sorted(matches, key=lambda a: a.token), criteria)

    def delete_asset(self, token: str) -> Asset:
        with self._lock:
            asset = self.get_asset(token)
            del self._assets[token]
            # The dense handle is NOT freed: registry rows and stored events
            # may still carry it, and a recycled handle would silently make
            # them resolve to an unrelated asset.  The tombstoned handle
            # resolves to EntityNotFound ("asset deleted"), and recreating
            # the same token reclaims the same handle.
            return asset
