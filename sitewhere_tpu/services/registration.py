"""Device auto-registration — turning unknown-device dead-letters into devices.

Reference: ``service-device-registration`` consumes the unregistered-events
and registration-request dead-letter topics and creates the device (+
assignment) through device management
(``DeviceRegistrationManager.java:81-139``), falling back to a configured
default device type / customer / area when the request doesn't name one
(``:56-68``); the original event is then replayed via the reprocess topic
(``KafkaTopicNaming.java:172-174``, SURVEY.md §3.5).

Here the dead letters arrive as the pipeline's ``unregistered`` mask rows:
the dispatcher hands this manager the raw :class:`DecodedRequest`s it
diverted (via their journal payload refs), the manager registers them
through :class:`~sitewhere_tpu.services.device_management.DeviceManagement`
(which publishes a fresh registry epoch), and returns the requests so the
caller re-injects them into the batcher — the reprocess path.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, List, Optional

from sitewhere_tpu.ingest.decoders import DecodedRequest, RequestKind
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.services.common import ServiceError
from sitewhere_tpu.services.device_management import DeviceManagement

logger = logging.getLogger("sitewhere_tpu.registration")


class RegistrationManager(LifecycleComponent):
    """Auto-register unknown devices and replay their events.

    ``allow_new_devices=False`` mirrors the reference's
    ``isAllowNewDevices`` switch: unknown devices stay dead-lettered.
    """

    def __init__(
        self,
        device_management: DeviceManagement,
        default_device_type: Optional[str] = None,
        default_customer: Optional[str] = None,
        default_area: Optional[str] = None,
        allow_new_devices: bool = True,
        auto_assign: bool = True,
        name: str = "registration-manager",
    ):
        super().__init__(name)
        self.dm = device_management
        self.default_device_type = default_device_type
        self.default_customer = default_customer
        self.default_area = default_area
        self.allow_new_devices = allow_new_devices
        self.auto_assign = auto_assign
        self._lock = threading.Lock()
        self.registered = 0
        self.rejected = 0

    def handle_registration(self, req: DecodedRequest) -> bool:
        """Process one explicit registration request (device announces itself).

        Reference: ``DeviceRegistrationManager.handleDeviceRegistration:81-105``.
        Returns True if the device exists (already or newly registered).
        """
        token = req.device_token
        if token in self.dm.devices:
            return True  # already registered — idempotent, like the reference
        if not self.allow_new_devices:
            with self._lock:
                self.rejected += 1
            return False
        device_type = req.device_type_token or self.default_device_type
        if device_type is None or device_type not in self.dm.device_types:
            logger.warning("registration for %s names no known device type", token)
            with self._lock:
                self.rejected += 1
            return False
        try:
            self.dm.create_device(
                token=token, device_type=device_type, metadata=dict(req.metadata or {})
            )
            if self.auto_assign:
                customer = req.customer_token or self.default_customer
                area = req.area_token or self.default_area
                self.dm.create_device_assignment(
                    device=token,
                    customer=customer if customer in self.dm.customers else None,
                    area=area if area in self.dm.areas else None,
                )
        except ServiceError:
            logger.exception("auto-registration of %s failed", token)
            with self._lock:
                self.rejected += 1
            return False
        with self._lock:
            self.registered += 1
        return True

    def process_unregistered(
        self, requests: List[DecodedRequest]
    ) -> List[DecodedRequest]:
        """Register the senders of dead-lettered events; return the events
        that can now be replayed (the reprocess-topic analog)."""
        replay: List[DecodedRequest] = []
        for req in requests:
            synthetic = DecodedRequest(
                kind=RequestKind.REGISTRATION,
                device_token=req.device_token,
                ts_s=req.ts_s,
                metadata=req.metadata,
            )
            if self.handle_registration(synthetic):
                replay.append(req)
        return replay
