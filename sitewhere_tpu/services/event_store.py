"""Durable columnar event store — persistence + query for device events.

Reference: ``service-event-management`` persists the six event types to a
big-data backend and serves list APIs over gRPC
(``grpc/EventManagementImpl.java:109-584``).  The perf-shaping mechanisms it
uses map directly here:

- **Write buffering** — Mongo ``DeviceEventBuffer.java:40-46`` queues up to
  10k events and bulk-inserts within ≤250 ms → :class:`EventStore` buffers
  appended column batches and a flusher thread seals them into immutable
  columnar chunks on the same (rows, interval) thresholds.
- **Denormalized query paths** — Cassandra writes events into by-id /
  by-assignment / by-customer / by-area / by-asset tables with hour buckets
  (``CassandraDeviceEventManagement.java:374-428``, bucketing
  ``CassandraClient.java:47,117``) → every chunk stores the *enriched*
  context columns (assignment/customer/area/asset ids from the pipeline's
  enrichment gather) plus per-chunk min/max timestamps, so any index query
  is a vectorized mask over pruned chunks instead of a table per index.
- **Event ids** — ``(chunk_seq << 24) | row`` packed int64, stable across
  restarts (the Mongo ObjectId analog).

Chunks are numpy struct-of-arrays persisted as ``.npz`` segments — i.e. the
store speaks the same columnar layout the TPU pipeline computes in, so the
analytics runner (:mod:`sitewhere_tpu.analytics`) maps chunks straight into
device arrays with no row pivot.

The resident set is BOUNDED: sealed chunks keep only ~33 KB of prune
metadata (zone-map bounds + Blooms + row count/ts range, persisted inside
the npz) in memory; column arrays page in on demand through a byte-bounded
LRU (:class:`_ColumnCache`).  Like Cassandra's disk-resident, bucket-pruned
reads (``CassandraDeviceEventManagement.java:374-428``), retention-scale
history costs disk, not RAM.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.runtime import faults
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import global_registry
from sitewhere_tpu.runtime.resilience import dead_letter

from sitewhere_tpu.schema import EventType
from sitewhere_tpu.services.common import (
    EntityNotFound,
    SearchCriteria,
    SearchResults,
    ValidationError,
)

logger = logging.getLogger("sitewhere_tpu.event_store")

# The storage format — column schema, zone-map/Bloom prune metadata,
# the lazy Segment (né _Chunk) and its byte-bounded column LRU — now
# lives in sitewhere_tpu/store/segment.py, the canonical home shared
# with the log-structured segment store (sitewhere_tpu/store).  The
# legacy private names stay importable here: this module's chunk
# machinery IS the segment format, single-writer edition.
from sitewhere_tpu.store.segment import (  # noqa: E402
    COLUMNS,
    ROW_BITS as _ROW_BITS,
    ColumnCache as _ColumnCache,
    Segment as _Chunk,
    SegmentPruned as _ChunkPruned,
    bloom_probe as _bloom_probe,
    bloom_member as _bloom_member,
    event_id,
    open_segment,
    segment_pruned as _chunk_pruned,
    split_event_id,
    write_segment_file,
)
from sitewhere_tpu.store.segment import (  # noqa: E402
    BLOOM_BITS as _BLOOM_BITS,
    BLOOM_COLUMNS as _BLOOM_COLUMNS,
    COLUMN_NAMES as _COLUMN_NAMES,
    FILTER_COLUMNS as _FILTER_COLUMNS,
    META_BOUNDS as _META_BOUNDS,
    META_CORE as _META_CORE,
    META_VERSION as _META_VERSION,
)

_CHUNK_RE = re.compile(r"^events-(\d{10})\.npz$")


@dataclasses.dataclass
class EventRecord:
    """One event, host-facing (REST marshaling resolves handles to tokens)."""

    event_id: int
    device_id: int
    tenant_id: int
    event_type: int
    ts_s: int
    ts_ns: int
    mtype_id: int
    value: float
    lat: float
    lon: float
    elevation: float
    alert_code: int
    alert_level: int
    command_id: int
    payload_ref: int
    device_type_id: int
    assignment_id: int
    area_id: int
    customer_id: int
    asset_id: int
    received_s: int


class EventStore(LifecycleComponent):
    """Buffered columnar event persistence with indexed queries.

    ``flush_rows`` / ``flush_interval_s`` mirror the reference buffer's
    (10k, 250ms) thresholds (``DeviceEventBuffer.java:40-46``).
    """

    def __init__(
        self,
        root: str,
        flush_rows: int = 10_000,
        flush_interval_s: float = 0.25,
        retention_s: Optional[int] = None,
        resident_bytes: int = 256 << 20,
        dead_letters=None,
        max_seal_retries: int = 8,
        seal_retry_window_s: float = 30.0,
        name: str = "event-store",
    ):
        super().__init__(name)
        self.dir = os.path.join(root, "events")
        os.makedirs(self.dir, exist_ok=True)
        self.flush_rows = flush_rows
        self.flush_interval_s = flush_interval_s
        # Bounded working set over sealed columns: blooms + zone-map
        # bounds + the write buffer stay resident; everything else pages
        # in through this LRU (VERDICT r4 item 5 — the npz files are the
        # memory manager, not just durability).
        self._cache = _ColumnCache(resident_bytes)
        # event-time retention window; 0/None = keep forever.  The
        # reference delegates retention to its datastores (Cassandra
        # hour buckets, CassandraClient.java:47, are exactly
        # prune-whole-bucket); here the flusher enforces it.
        self.retention_s = int(retention_s) if retention_s else 0
        self._last_prune = 0.0
        self._lock = threading.Lock()
        self._buffer: List[Dict[str, np.ndarray]] = []
        self._buffered_rows = 0
        self._last_flush = time.monotonic()
        self._chunks: List[_Chunk] = []
        self._next_seq = 0
        self._flusher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Writer→flusher handoff: append_columns signals instead of
        # sealing inline, so the dispatcher's egress thread never pays the
        # npz write + fsyncs (measured up to ~16 ms/seal on the wire-path
        # p99).  The inline safety valve below bounds the buffer if the
        # flusher ever falls behind.
        self._flush_wake = threading.Event()
        # Files sealed with deferred durability (chunks + marker) not yet
        # fsync'd — settled by _sync_durable at explicit flush()/prune
        # points.  Guarded by _lock.
        self._unsynced_paths: set = set()
        # Serializes flush()'s two-phase seal across threads (writer
        # valve, background flusher, commit gate); _lock is only held for
        # the memory-side phases inside it.
        self._flush_io = threading.Lock()
        # Chunks published to _chunks whose npz write failed — columns
        # still attached; retried by the next flush.  Guarded by _lock.
        self._unwritten: List[tuple] = []
        # Seal failures retry (bounded): once a chunk has failed more
        # than max_seal_retries times AND its first failure is at least
        # seal_retry_window_s old, it dead-letters instead of pinning
        # its columns in memory and blocking the commit gate's sync
        # flush forever — the dead-letter record is the durable trace of
        # those rows (see flush()).  The wall-clock window matters: the
        # flusher ticks every flush_interval_s (plus commit-gate sync
        # flushes), so an attempt count alone would burn the whole
        # budget inside ~2 s and drop data over a transient disk blip.
        self.dead_letters = dead_letters
        self.max_seal_retries = int(max_seal_retries)
        self.seal_retry_window_s = float(seal_retry_window_s)
        self._seal_attempts: Dict[int, Tuple[int, float]] = {}
        self.sealed_dead_lettered = 0
        self._load_existing()

    # -- lifecycle ----------------------------------------------------------

    def _load_existing(self) -> None:
        for fname in sorted(os.listdir(self.dir)):
            m = _CHUNK_RE.match(fname)
            if not m:
                continue
            seq = int(m.group(1))
            path = os.path.join(self.dir, fname)
            try:
                chunk = self._open_chunk(seq, path)
            except Exception:
                # A torn chunk file must not stop the store from booting:
                # deferred-fsync seals rename before their content fsync,
                # so a power loss can leave garbage at the canonical name.
                # Quarantine it (keep the bytes for forensics) and move
                # on — the rows are covered by at-least-once journal
                # replay, because the offset covering them can only have
                # committed AFTER a sync flush made the chunk durable.
                logger.exception(
                    "chunk %d unreadable; quarantining %s", seq, path)
                try:
                    os.replace(path, path + ".corrupt")
                except OSError:
                    pass
                self._next_seq = max(self._next_seq, seq + 1)
                continue
            self._chunks.append(chunk)
            self._next_seq = max(self._next_seq, seq + 1)
        # high-water marker: retention may have pruned EVERY chunk file,
        # and seqs must never regress — a reissued event id would resolve
        # to an unrelated newer event (ids embed the chunk seq)
        marker = os.path.join(self.dir, "next-seq")
        marker_value = -1
        try:
            with open(marker) as f:
                marker_value = int(f.read() or 0)
                self._next_seq = max(self._next_seq, marker_value)
        except (FileNotFoundError, ValueError):
            pass
        if self._next_seq > max(marker_value, 0):
            # Marker absent (store predates it) or stale (crash between a
            # chunk seal and its marker write): bring it up to the
            # chunk-derived value NOW, or an idle store fully pruned by
            # retention would regress seqs on the next boot.
            self._write_marker()

    def _open_chunk(self, seq: int, path: str) -> _Chunk:
        """Open a sealed chunk reading ONLY its prune metadata.

        np.load on an npz reads the zip directory, not the members; the
        metadata arrays written at seal time (``_meta_core``, bounds,
        blooms — ~33 KB/chunk) are the only members touched here.  A
        pre-metadata chunk (older store) falls back to a one-time full
        read to rebuild its metadata, then releases the columns.
        """
        with np.load(path) as data:
            files = set(data.files)
            if _META_CORE in files and _META_BOUNDS in files:
                core = data[_META_CORE]
                bounds_arr = data[_META_BOUNDS]
                if (int(core[0]) == _META_VERSION
                        and len(bounds_arr) == len(_FILTER_COLUMNS)):
                    bounds = {
                        name: (int(bounds_arr[i][0]), int(bounds_arr[i][1]))
                        for i, name in enumerate(_FILTER_COLUMNS)
                    }
                    blooms = {
                        name: data[_bloom_member(name)]
                        for name in _BLOOM_COLUMNS
                        if _bloom_member(name) in files
                    }
                    return _Chunk.lazy(
                        seq, path, self._cache, n=int(core[1]),
                        min_ts=int(core[2]), max_ts=int(core[3]),
                        bounds=bounds, blooms=blooms)
            # metadata absent/unknown-version: rebuild from the columns
            cols = {name: data[name] for name in _COLUMN_NAMES
                    if name in files}
        for name, dtype in COLUMNS:  # forward-compat: absent → default
            if name not in cols:
                cols[name] = np.full(len(cols["ts_s"]), NULL_ID, dtype)
        chunk = _Chunk(seq, cols)
        try:
            # persist the rebuilt metadata so this full read happens ONCE,
            # not on every boot (same atomic seal path flush() uses)
            self._write_chunk_file(path, cols, chunk)
        except OSError:
            logger.exception("could not upgrade chunk %d metadata", seq)
        chunk.detach(path, self._cache)
        return chunk

    def _write_chunk_file(self, path: str, cols: Dict[str, np.ndarray],
                          chunk: _Chunk, sync: bool = True) -> None:
        """Atomically write one sealed chunk: columns + prune metadata.

        ``sync=False`` defers the fsyncs: the write stays atomic (tmp +
        rename) but durability is settled later by :meth:`_sync_durable`.
        Routine seals use this — the at-least-once premise only requires
        a chunk to be DURABLE before the journal offset covering its rows
        is committed (the commit gate's explicit ``flush()``), not at
        seal time, and per-seal fsyncs measured as the single largest
        cost on the wire path (they also stall the ingest journal's
        writes through the filesystem journal)."""
        meta = {
            _META_CORE: np.asarray(
                [_META_VERSION, chunk.n, chunk.min_ts, chunk.max_ts],
                np.int64),
            _META_BOUNDS: np.asarray(
                [chunk.bounds[name] for name in _FILTER_COLUMNS], np.int64),
        }
        for bname, bloom in chunk.blooms.items():
            meta[_bloom_member(bname)] = bloom
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(f, **cols, **meta)
            if sync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if sync:
            self._fsync_dir()
        else:
            self._unsynced_paths.add(path)

    def _write_marker(self, sync: bool = True) -> None:
        """Record the seq high-water mark (the marker is what keeps seqs
        from regressing after retention prunes every chunk).  With
        ``sync=False`` durability is deferred to :meth:`_sync_durable`;
        boot recovers a stale marker from the chunk files themselves, so
        the marker only MUST be durable before a prune unlinks chunks."""
        marker = os.path.join(self.dir, "next-seq")
        tmp = f"{marker}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(str(self._next_seq))
            if sync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, marker)
        if sync:
            self._fsync_dir()
        else:
            self._unsynced_paths.add(marker)

    def _sync_durable(self) -> None:
        """Settle deferred durability: fsync every async-sealed file, then
        the directory once.  Called under ``_lock``."""
        if not self._unsynced_paths:
            return
        for path in list(self._unsynced_paths):
            try:
                fd = os.open(path, os.O_RDONLY)
            except FileNotFoundError:
                self._unsynced_paths.discard(path)  # pruned before syncing
                continue
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
            self._unsynced_paths.discard(path)
        self._fsync_dir()

    def _fsync_dir(self) -> None:
        """Make the latest rename itself durable: fsyncing file CONTENTS
        does not persist the directory entry — without this a power loss
        can vanish a freshly sealed chunk/marker whose journal copy was
        already reclaimed."""
        try:
            fd = os.open(self.dir, os.O_RDONLY)
        except OSError:
            return  # platform without directory fds: best effort
        try:
            os.fsync(fd)
        except OSError:
            pass
        finally:
            os.close(fd)

    def start(self) -> None:
        super().start()
        self._stop.clear()
        self._flusher = threading.Thread(
            target=self._flush_loop, name=f"{self.name}-flusher", daemon=True
        )
        self._flusher.start()

    def stop(self) -> None:
        self._stop.set()
        self._flush_wake.set()
        if self._flusher is not None:
            self._flusher.join(timeout=5)
            self._flusher = None
        self.flush()
        super().stop()

    def _flush_loop(self) -> None:
        while not self._stop.is_set():
            self._flush_wake.wait(timeout=self.flush_interval_s / 2)
            self._flush_wake.clear()
            if self._stop.is_set():
                break
            with self._lock:
                due = self._buffered_rows > 0 and (
                    self._buffered_rows >= self.flush_rows
                    or time.monotonic() - self._last_flush >= self.flush_interval_s
                )
            if due:
                try:
                    self.flush(sync=False)
                except Exception:  # transient I/O failure must not kill the
                    # flusher; the buffer is retained and retried next tick.
                    logger.exception("event flush failed; will retry")
            if (self.retention_s
                    and time.monotonic() - self._last_prune >= 60.0):
                self._last_prune = time.monotonic()
                try:
                    self.prune_older_than(int(time.time()) - self.retention_s)
                except Exception:
                    logger.exception(
                        "event retention prune failed; will retry")

    # -- writes -------------------------------------------------------------

    def append_columns(
        self, cols: Dict[str, np.ndarray], mask: Optional[np.ndarray] = None
    ) -> int:
        """Append a column batch (optionally row-masked).  Returns rows added.

        The dispatcher calls this with the post-pipeline batch columns +
        enrichment outputs; REST-created events arrive via :meth:`add_event`.
        """
        n = None
        out: Dict[str, np.ndarray] = {}
        received = np.int32(int(time.time()))
        # One index vector shared by every column: boolean-mask indexing
        # re-scans the mask per column, and the masked take already yields
        # a fresh array, so the defensive astype copy is only needed on
        # the unmasked path (buffered columns must never alias caller
        # arrays the intake may reuse).
        mask_arr = None if mask is None else np.asarray(mask)
        idx = None if mask_arr is None else np.nonzero(mask_arr)[0]
        src_n = None
        for name, dtype in COLUMNS:
            if name == "received_s":
                continue
            if name not in cols:
                raise ValidationError(f"missing event column {name}")
            arr = np.asarray(cols[name])
            if src_n is None:
                src_n = len(arr)
                n = len(idx) if idx is not None else src_n
                if mask_arr is not None and len(mask_arr) != src_n:
                    raise ValidationError(
                        f"mask length {len(mask_arr)} != {src_n}")
            elif len(arr) != src_n:
                raise ValidationError(
                    f"column {name} length {len(arr)} != {src_n}")
            if idx is not None:
                out[name] = arr.take(idx).astype(dtype, copy=False)
            else:
                out[name] = arr.astype(dtype, copy=True)
        if not n:
            return 0
        out["received_s"] = np.full(n, received, np.int32)
        with self._lock:
            self._buffer.append(out)
            self._buffered_rows += n
            rows = self._buffered_rows
        if rows >= self.flush_rows:
            # Seal on the flusher thread — the writer only signals, so the
            # dispatcher's egress never pays the npz write + fsyncs.  The
            # inline flush is a safety valve: past 4× the threshold the
            # writer pays the seal itself, bounding memory if the flusher
            # falls behind (commit-gate callers still flush() explicitly).
            # Without a running flusher (unstarted store) seal inline as
            # before.
            if self._flusher is None or rows >= 4 * self.flush_rows:
                self.flush(sync=False)
            else:
                self._flush_wake.set()
        return n

    def _buffer_chunk_locked(self) -> Optional[_Chunk]:
        """The unsealed buffer viewed as a virtual chunk at ``_next_seq``
        (read paths include it instead of forcing a flush per query)."""
        if not self._buffer:
            return None
        merged = {
            name: np.concatenate([b[name] for b in self._buffer])
            for name in _COLUMN_NAMES
        }
        return _Chunk(self._next_seq, merged, light=True)

    def _buffer_chunks_locked(self) -> List[_Chunk]:
        """Virtual chunk(s) over every unsealed row, newest-last.  The
        single-writer store has exactly one unsealed buffer; the sharded
        segment store overrides this with one virtual segment per open
        shard buffer and queued seal job."""
        chunk = self._buffer_chunk_locked()
        return [] if chunk is None else [chunk]

    def add_event(self, **fields) -> EventRecord:
        """Append one event (REST create path, ``Assignments.java:428-433``).

        The event id is computed from the buffered position under the append
        lock — appends between this call and the sealing flush land *after*
        this row, so the (seq, row) the caller gets back stays correct.
        """
        row = {}
        received = np.int32(int(time.time()))
        for name, dtype in COLUMNS:
            if name == "received_s":
                row[name] = np.asarray([received], dtype)
                continue
            default = NULL_ID if np.issubdtype(dtype, np.integer) else 0.0
            row[name] = np.asarray([fields.get(name, default)], dtype)
        with self._lock:
            seq, base = self._next_seq, self._buffered_rows
            self._buffer.append(row)
            self._buffered_rows += 1
        return EventRecord(
            event_id=event_id(seq, base),
            **{name: row[name][0].item() for name in _COLUMN_NAMES},
        )

    def flush(self, sync: bool = True) -> int:
        """Seal the buffer into chunk(s).  Returns rows sealed.

        Two phases so appends/readers never wait on file IO: under
        ``_lock`` the buffer is merged and turned into _Chunk objects
        (memory-only: zone maps + blooms, columns stay attached) that are
        published to ``_chunks`` immediately — reads serve them from the
        resident columns meanwhile.  The npz writes then happen OUTSIDE
        ``_lock`` (serialized by ``_flush_io``); each written chunk
        detaches to its file, and a write failure parks the chunk on a
        retry list the next flush drains.  ``sync=True`` (explicit
        callers: the dispatcher's commit gate, shutdown) settles every
        deferred fsync before returning and raises if any chunk is still
        unwritten — the durability point the journal-reclaim premise
        needs.  ``sync=False`` (the background flusher) keeps all IO off
        the writer's p99.
        """
        max_rows = (1 << _ROW_BITS) - 1
        with self._flush_io:
            with self._lock:
                new = []
                if self._buffer:
                    merged = {
                        name: np.concatenate([b[name] for b in self._buffer])
                        for name in _COLUMN_NAMES
                    }
                    total = len(merged["ts_s"])
                    done = 0
                    try:
                        for lo in range(0, total, max_rows):
                            part = {k: v[lo : lo + max_rows]
                                    for k, v in merged.items()}
                            # prune metadata computed once, WHILE the
                            # columns are in memory, and persisted with
                            # them — a restart then reads ~33 KB/chunk
                            # instead of the columns
                            chunk = _Chunk(self._next_seq, part)
                            path = os.path.join(
                                self.dir, f"events-{chunk.seq:010d}.npz")
                            self._chunks.append(chunk)
                            # registered as unwritten in the SAME critical
                            # section that publishes the chunk: no failure
                            # below can strand a published chunk off the
                            # retry list (a stranded chunk would let the
                            # commit gate report durable-success for rows
                            # that exist nowhere on disk)
                            self._unwritten.append((chunk, part, path))
                            new.append((chunk, part, path))
                            self._next_seq += 1
                            done += len(part["ts_s"])
                    finally:
                        remainder = {k: v[done:] for k, v in merged.items()}
                        self._buffer = (
                            [remainder] if len(remainder["ts_s"]) else []
                        )
                        self._buffered_rows = total - done
                work = list(self._unwritten)
                if new:
                    # once per flush, not per chunk: boot recovers a stale
                    # marker from the chunk files themselves.  Non-fatal:
                    # a failed marker write must not abort the seal work
                    # queued above (it is itself recoverable from the
                    # chunk files at boot).
                    try:
                        self._write_marker(sync=False)
                    except OSError:
                        logger.exception("next-seq marker write failed")
                self._last_flush = time.monotonic()
            flushed = sum(len(p["ts_s"]) for _, p, _ in new)

            # Phase 2: file IO with _lock released.  Journal reclaim
            # deletes raw records below the committed offset on the
            # premise that sealed chunks are durable by COMMIT time: the
            # commit gate flushes sync=True, which settles the deferred
            # fsyncs (and refuses on any unwritten chunk) first.
            failed = []
            for chunk, part, path in work:
                try:
                    faults.fire("event_store.flush")
                    # chaos kill point: death mid-seal leaves a partial
                    # chunk file; boot must tolerate it and journal
                    # replay must re-derive the chunk's rows
                    faults.crosspoint("crash.mid_seal")
                    self._write_chunk_file(path, part, chunk, sync=False)
                except OSError as e:
                    now = time.monotonic()
                    with self._lock:
                        attempts, first_t = self._seal_attempts.get(
                            id(chunk), (0, now))
                        attempts += 1
                        self._seal_attempts[id(chunk)] = (attempts, first_t)
                    global_registry().counter(
                        "resilience.retries.event_store.seal").inc()
                    if (attempts > self.max_seal_retries
                            and now - first_t >= self.seal_retry_window_s):
                        # Terminal: dead-letter the chunk's rows instead
                        # of retrying forever — bounded memory, and the
                        # commit gate's sync flush can succeed again (the
                        # dead-letter record is the durable trace).
                        logger.error(
                            "chunk %d seal failed %d times; dead-lettering"
                            " %d rows: %s", chunk.seq, attempts, chunk.n, e)
                        if self._dead_letter_chunk(chunk, part, path, e):
                            continue
                        # the durable trace could not be written (often
                        # the same dead disk): dropping the chunk now
                        # would be SILENT loss — keep it resident and
                        # keep the sync flush failing instead
                        failed.append((chunk, part, path))
                        continue
                    logger.exception("chunk %d seal failed; will retry",
                                     chunk.seq)
                    failed.append((chunk, part, path))
                    continue
                with self._lock:
                    self._seal_attempts.pop(id(chunk), None)
                    if any(c is chunk for c in self._chunks):
                        # release the resident columns: reads reload (and
                        # LRU-cache) from the file from here on
                        chunk.detach(path, self._cache)
                    else:
                        # retention pruned it while being written — don't
                        # resurrect the file at next boot
                        self._unsynced_paths.discard(path)
                        try:
                            os.unlink(path)
                        except OSError:
                            pass
            with self._lock:
                # entries stayed registered throughout; release the ones
                # whose files landed (failed ones remain for retry — as
                # do any a concurrent prune already filtered out)
                written = ({id(e[0]) for e in work}
                           - {id(e[0]) for e in failed})
                self._unwritten = [e for e in self._unwritten
                                   if id(e[0]) not in written]
                if sync:
                    self._sync_durable()
            if sync and failed:
                raise OSError(
                    f"{len(failed)} chunk(s) not durably sealed")
            return flushed

    def _dead_letter_chunk(self, chunk, part, path, exc) -> bool:
        """Terminal seal failure: record the chunk's rows to the
        dead-letter sink, then drop it from the store.  The ingest journal
        may reclaim the raw records once commits resume — the dead-letter
        record IS the durable trace of these rows from here on, so the
        chunk is only dropped once that record landed (a configured sink
        that also fails returns False and the caller keeps retrying the
        seal — bounded memory loses to silent loss)."""
        recorded = dead_letter(self.dead_letters, {
            "kind": "event-flush-failed",
            "seq": int(chunk.seq),
            "rows": int(chunk.n),
            "ts_min": int(part["ts_s"].min()) if len(part["ts_s"]) else 0,
            "ts_max": int(part["ts_s"].max()) if len(part["ts_s"]) else 0,
            "error": str(exc),
        })
        if self.dead_letters is not None and not recorded:
            return False
        with self._lock:
            self._seal_attempts.pop(id(chunk), None)
            self._chunks = [c for c in self._chunks if c is not chunk]
            self._unsynced_paths.discard(path)
            self.sealed_dead_lettered += int(chunk.n)
        return True

    # -- reads --------------------------------------------------------------

    @property
    def total_events(self) -> int:
        with self._lock:
            return sum(c.n for c in self._chunks) + self._buffered_rows

    def prune_older_than(self, cutoff_s: int) -> int:
        """Delete whole sealed chunks whose NEWEST row predates
        ``cutoff_s`` (event time).  A chunk straddling the cutoff is
        kept whole — retention is per-bucket, exactly like dropping an
        expired Cassandra hour bucket, never a row-level rewrite.
        Event ids inside pruned chunks become unresolvable, as expired
        ids do in any TTL'd store.  Returns rows removed."""
        with self._lock:
            doomed = {id(c): c for c in self._chunks
                      if c.n and c.max_ts < cutoff_s}
            if not doomed:
                return 0
            # Seqs must never regress: make the high-water marker durable
            # BEFORE any chunk file disappears (boot recovers a stale
            # marker from chunk files — which are about to be gone).
            for chunk in doomed.values():
                self._unsynced_paths.discard(
                    os.path.join(self.dir, f"events-{chunk.seq:010d}.npz"))
            self._write_marker(sync=True)
            removed = 0
            for chunk in doomed.values():
                removed += chunk.n
                self._cache.drop_seq(chunk.seq)
                try:
                    os.unlink(os.path.join(
                        self.dir, f"events-{chunk.seq:010d}.npz"))
                except FileNotFoundError:
                    pass
            self._chunks = [c for c in self._chunks if id(c) not in doomed]
            # an expired chunk still awaiting its npz write must not be
            # rewritten by the next flush
            self._unwritten = [e for e in self._unwritten
                               if id(e[0]) not in doomed]
        return removed

    def get_event(self, eid: int) -> EventRecord:
        seq, row = split_event_id(eid)
        with self._lock:
            candidates = list(self._chunks)
            candidates.extend(self._buffer_chunks_locked())
        for chunk in candidates:
            if chunk.seq == seq:
                if row >= chunk.n:
                    break
                try:
                    return self._record(chunk, row)
                except _ChunkPruned:
                    break  # expired mid-lookup: same as an expired id
        raise EntityNotFound(f"event {eid}")

    def query(self, criteria: Optional[SearchCriteria] = None,
              **kwargs) -> SearchResults[EventRecord]:
        """Indexed event listing, newest-first — see :meth:`_query_once`.

        Retries on a fresh chunk snapshot when retention unlinks a chunk
        file mid-read (each retry's snapshot excludes the pruned chunk,
        so the loop is bounded by the chunk count)."""
        while True:
            try:
                return self._query_once(criteria, **kwargs)
            except _ChunkPruned as e:
                self._discard_vanished(e.seq)
                continue

    def _discard_vanished(self, seq: int) -> None:
        """Drop a chunk whose file is gone but which is still listed —
        a file deleted outside ``prune_older_than`` would otherwise make
        every retry hit the same chunk forever (livelock)."""
        path = os.path.join(self.dir, f"events-{seq:010d}.npz")
        if os.path.exists(path):
            return  # normal retention race: the fresh snapshot excludes it
        with self._lock:
            before = len(self._chunks)
            self._chunks = [c for c in self._chunks if c.seq != seq]
            if len(self._chunks) != before:
                logger.warning(
                    "event chunk %d vanished outside retention; discarded",
                    seq)
        self._cache.drop_seq(seq)

    def _query_once(
        self,
        criteria: Optional[SearchCriteria] = None,
        *,
        tenant_id: Optional[int] = None,
        device_id: Optional[int] = None,
        assignment_id: Optional[int] = None,
        customer_id: Optional[int] = None,
        area_id: Optional[int] = None,
        asset_id: Optional[int] = None,
        event_type: Optional[int] = None,
        mtype_id: Optional[int] = None,
        alert_code: Optional[int] = None,
        command_id: Optional[int] = None,
    ) -> SearchResults[EventRecord]:
        """Indexed event listing, newest-first (reference list* semantics).

        Each keyword mirrors one reference index path: device
        (``listDeviceEventsForIndex`` DeviceEventIndex.Device), assignment,
        customer, area, asset; ``event_type`` narrows to one add/list family
        (e.g. ``listMeasurementsForIndex``).
        """
        criteria = criteria or SearchCriteria()
        active = [
            (name, want)
            for name, want in (
                ("tenant_id", tenant_id), ("device_id", device_id),
                ("assignment_id", assignment_id),
                ("customer_id", customer_id), ("area_id", area_id),
                ("asset_id", asset_id), ("event_type", event_type),
                ("mtype_id", mtype_id), ("alert_code", alert_code),
                ("command_id", command_id))
            if want is not None
        ]
        t0, t1 = criteria.start_s, criteria.end_s
        with self._lock:
            chunks = list(self._chunks)
            chunks.extend(self._buffer_chunks_locked())

        probes = {
            name: _bloom_probe(int(want)) for name, want in active
            if name in _BLOOM_COLUMNS
        }

        def pruned(c: _Chunk) -> bool:
            return _chunk_pruned(c, active, probes, t0, t1)

        def match_mask(c: _Chunk) -> Optional[np.ndarray]:
            """Row mask, or None meaning every row matches (a filterless
            or fully-in-range chunk never touches its columns)."""
            mask = None
            for name, want in active:
                m = c.col(name) == want
                mask = m if mask is None else (mask & m)
            if t0 is not None and c.min_ts < t0:
                m = c.col("ts_s") >= t0
                mask = m if mask is None else (mask & m)
            if t1 is not None and c.max_ts > t1:
                m = c.col("ts_s") <= t1
                mask = m if mask is None else (mask & m)
            return mask

        # Phase 1 — exact total: a zone-map-pruned or filterless chunk
        # counts without touching (or materializing) any row.
        masks: List[Optional[np.ndarray]] = []
        counts: List[int] = []
        for c in chunks:
            if pruned(c):
                masks.append(None)
                counts.append(0)
                continue
            mask = match_mask(c)
            masks.append(mask)
            counts.append(c.n if mask is None else int(np.count_nonzero(mask)))
        total = sum(counts)
        if total == 0:
            return SearchResults(results=[], total=0)

        # Phase 2 — newest-first page WITHOUT sorting every hit: walk
        # chunks newest-max_ts-first and stop once the page's worst
        # candidate is strictly newer than anything a remaining chunk
        # could hold (chunk max_ts bounds its best key).  Only the
        # collected candidates sort; the worst case (fully overlapping
        # time ranges or an unlimited page) degrades to the full sort.
        unlimited = criteria.page_size <= 0
        # max(page, 1): SearchCriteria.slice clamps page<=0 to page 1,
        # so the candidate budget must too (0 would make the kth-newest
        # partition index fall out of bounds)
        needed = total if unlimited else min(
            total, max(criteria.page, 1) * criteria.page_size)
        by_newest = sorted(
            (i for i in range(len(chunks)) if counts[i]),
            key=lambda i: chunks[i].max_ts, reverse=True)
        sel_key: List[np.ndarray] = []
        sel_chunk: List[np.ndarray] = []
        sel_row: List[np.ndarray] = []
        collected = 0
        for pos, ci in enumerate(by_newest):
            chunk = chunks[ci]
            mask = masks[ci]
            rows = (np.arange(chunk.n, dtype=np.int64) if mask is None
                    else np.nonzero(mask)[0])
            # one int64 key: ts_s fits 2^31, ns < 1e9 → ts*1e9+ns < 2^63
            key = (chunk.col("ts_s")[rows].astype(np.int64)
                   * 1_000_000_000 + chunk.col("ts_ns")[rows])
            sel_key.append(key)
            sel_chunk.append(np.full(rows.size, ci, np.int32))
            sel_row.append(rows.astype(np.int32))
            collected += rows.size
            if collected >= needed and pos + 1 < len(by_newest):
                # kth-newest collected key vs the best key any remaining
                # chunk could hold; > (not >=) so equal-key rows in older
                # chunks keep their stable tie order
                kth = np.partition(
                    np.concatenate(sel_key), collected - needed
                )[collected - needed]
                next_best = (chunks[by_newest[pos + 1]].max_ts
                             * 1_000_000_000 + 999_999_999)
                if int(kth) > next_best:
                    break

        key = np.concatenate(sel_key)
        cidx = np.concatenate(sel_chunk)
        rix = np.concatenate(sel_row)
        # newest-first; ties keep chunk/insertion order (stable, matching
        # the previous full sort)
        order = np.lexsort((rix, cidx, -key))
        page = criteria.slice(order)
        # one column fetch per (chunk, column) for the whole page — not
        # per row: col() takes the cache lock, and a 100-row page over
        # lazy chunks would otherwise pay 2000 locked lookups
        cols_by_chunk: Dict[int, Dict[str, np.ndarray]] = {}
        results = []
        for i in page:
            ci, row = int(cidx[i]), int(rix[i])
            cols = cols_by_chunk.get(ci)
            if cols is None:
                cols = cols_by_chunk[ci] = chunks[ci].materialize()
            results.append(EventRecord(
                event_id=event_id(chunks[ci].seq, row),
                **{name: cols[name][row].item()
                   for name in _COLUMN_NAMES}))
        return SearchResults(results=results, total=total)

    def iter_chunks(
        self,
        *,
        event_type: Optional[int] = None,
        mtype_id: Optional[int] = None,
        device_id: Optional[int] = None,
        tenant_id: Optional[int] = None,
        start_s: Optional[int] = None,
        end_s: Optional[int] = None,
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Sealed chunks oldest-first — the analytics scan API.

        Lazy chunks materialize through the column cache, so a scan over
        a store far larger than ``resident_bytes`` streams (the LRU
        evicts behind the scan) instead of accumulating.

        Optional exact-match/time filters make this the retrospective
        query path: a chunk whose zone-map bounds (or Bloom, for
        device_id) exclude the wanted key is skipped without touching
        its columns — the same pruning the indexed ``query`` API uses —
        and surviving chunks yield row-filtered column dicts with
        relative order preserved (append order, i.e. the order live
        evaluation saw the events).  The filter/straddle rules are the
        SHARED scan-lane helpers (store/scan.py), so this path and the
        catalog edition can never disagree about which rows match."""
        from sitewhere_tpu.store.scan import filters_active, row_mask

        self.flush()
        with self._lock:
            chunks = list(self._chunks)
        active = filters_active(event_type, mtype_id, device_id,
                                tenant_id)
        probes = {
            name: _bloom_probe(want) for name, want in active
            if name in _BLOOM_COLUMNS
        }
        for chunk in chunks:
            if _chunk_pruned(chunk, active, probes, start_s, end_s):
                continue
            try:
                cols = chunk.materialize()
            except _ChunkPruned:
                continue  # expired mid-scan: same as scanning after it
            mask = row_mask(chunk, cols, active, start_s, end_s)
            if mask is None or mask.all():
                yield cols
            elif mask.any():
                yield {k: v[mask] for k, v in cols.items()}

    def cache_stats(self) -> Dict[str, int]:
        """Resident-set accounting (observability + tests)."""
        c = self._cache
        return {"bytes": c.bytes, "max_bytes": c.max_bytes,
                "loads": c.loads, "hits": c.hits, "evictions": c.evictions}

    def _record(self, chunk: _Chunk, row: int) -> EventRecord:
        return EventRecord(
            event_id=event_id(chunk.seq, row),
            **{name: chunk.col(name)[row].item()
               for name in _COLUMN_NAMES},
        )
