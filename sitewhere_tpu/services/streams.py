"""Device binary streams: chunked media storage + stream manager.

Reference: ``service-streaming-media`` — streams are created per active
assignment (``IDeviceStreamManagement.createDeviceStream(assignmentId, …)``)
and filled with sequence-numbered binary chunks
(``IDeviceStreamDataManagement.addDeviceStreamData``); the request-level
manager resolves the device's current assignment and answers send-back
requests with stored chunks or empty payloads
(``media/DeviceStreamManager.java:50-120``).

Storage design: all chunks of a tenant land in ONE durable
:class:`~sitewhere_tpu.ingest.journal.Journal` (the hardened CRC-framed
segment log with torn-tail recovery), each record framed as
``(stream_token, seq, data)``; a host index maps ``(stream, seq) → journal
offset`` with last-write-wins per sequence number (the Cassandra
``(streamId, seq)`` primary-key semantics).  Stream ids are scoped PER
ASSIGNMENT, as in the reference SPI — one device can never collide with or
read another assignment's streams.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
import struct
import threading
from typing import Dict, List, Optional, Tuple

from sitewhere_tpu.ingest.journal import Journal
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.services.common import (
    DuplicateToken,
    EntityNotFound,
    InvalidReference,
    SearchCriteria,
    SearchResults,
    ValidationError,
    mint_token,
    now_s,
    paged,
    require,
)


class DeviceStreamStatus(enum.Enum):
    """Ack status for stream-create requests (reference
    ``spi/device/command/DeviceStreamStatus``)."""

    CREATED = "created"
    EXISTS = "exists"
    FAILED = "failed"


@dataclasses.dataclass
class DeviceStream:
    """Stream descriptor (reference ``IDeviceStream``): ``token`` is the
    system-wide handle (reference UUID), ``stream_id`` the device-chosen
    name unique within its assignment."""

    token: str
    stream_id: str
    assignment_token: str
    content_type: str
    created_s: int = dataclasses.field(default_factory=now_s)
    metadata: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class DeviceStreamData:
    """One chunk of a stream (reference ``IDeviceStreamData``)."""

    stream_token: str
    sequence_number: int
    data: bytes
    received_s: int


# Journal record: [u16 token_len][u64 seq][u32 ts][token utf8][data]
_REC = struct.Struct("<HQI")
_MAX_SEQ = (1 << 64) - 1


def _pack_chunk(token: str, seq: int, ts: int, data: bytes) -> bytes:
    tok = token.encode("utf-8")
    return _REC.pack(len(tok), seq, ts) + tok + data


def _unpack_chunk(payload: bytes) -> Tuple[str, int, int, bytes]:
    tok_len, seq, ts = _REC.unpack_from(payload)
    tok_end = _REC.size + tok_len
    return payload[_REC.size:tok_end].decode("utf-8"), seq, ts, payload[tok_end:]


class DeviceStreamManagement(LifecycleComponent):
    """Durable stream + chunk store for one tenant.

    Capability parity: create/get/list streams
    (``IDeviceStreamManagement``), add/get/list chunk data
    (``IDeviceStreamDataManagement``), assembled download.
    """

    def __init__(self, root: str, name: str = "stream-management"):
        super().__init__(name)
        self.dir = os.path.join(root, "streams")
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.RLock()
        # dense journal index: chunk point reads (send-back hot path) seek
        # straight to the record instead of rolling forward through
        # neighboring multi-KB media records
        self._journal = Journal(self.dir, name="media", index_every=1)
        self._streams: Dict[str, DeviceStream] = {}          # token -> stream
        self._by_scope: Dict[Tuple[str, str], str] = {}      # (assignment, stream_id) -> token
        # stream token -> {seq: (journal offset, received_s)}
        self._index: Dict[str, Dict[int, Tuple[int, int]]] = {}
        self._load_existing()

    # -- durability ---------------------------------------------------------

    def _meta_path(self) -> str:
        return os.path.join(self.dir, "streams.meta")

    def _save_meta(self) -> None:
        tmp = self._meta_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    tok: {
                        "stream_id": s.stream_id,
                        "assignment_token": s.assignment_token,
                        "content_type": s.content_type,
                        "created_s": s.created_s,
                        "metadata": s.metadata,
                    }
                    for tok, s in self._streams.items()
                },
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._meta_path())

    def _load_existing(self) -> None:
        if os.path.exists(self._meta_path()):
            with open(self._meta_path()) as f:
                for tok, fields in json.load(f).items():
                    stream = DeviceStream(token=tok, **fields)
                    self._streams[tok] = stream
                    self._by_scope[(stream.assignment_token, stream.stream_id)] = tok
                    self._index[tok] = {}
        # one streaming pass over the journal rebuilds every chunk index
        for offset, payload in self._journal.scan(0):
            token, seq, ts, _ = _unpack_chunk(payload)
            if token in self._index:  # chunks of unknown streams are skipped
                self._index[token][seq] = (offset, ts)

    def stop(self) -> None:
        self._journal.flush()
        super().stop()

    def terminate(self) -> None:
        self._journal.close()
        super().terminate()

    # -- stream CRUD --------------------------------------------------------

    def create_device_stream(
        self,
        assignment_token: str,
        stream_id: str,
        content_type: str = "application/octet-stream",
        metadata: Optional[Dict[str, str]] = None,
    ) -> DeviceStream:
        require(bool(stream_id), ValidationError("stream_id required"))
        with self._lock:
            scope = (assignment_token, stream_id)
            require(
                scope not in self._by_scope,
                DuplicateToken(
                    f"stream {stream_id!r} exists for assignment {assignment_token!r}"
                ),
            )
            stream = DeviceStream(
                token=mint_token("stream"),
                stream_id=stream_id,
                assignment_token=assignment_token,
                content_type=content_type,
                metadata=metadata or {},
            )
            self._streams[stream.token] = stream
            self._by_scope[scope] = stream.token
            self._index[stream.token] = {}
            self._save_meta()
            return stream

    def get_device_stream(self, stream_token: str) -> DeviceStream:
        """Lookup by system token (reference ``getDeviceStream(UUID)``)."""
        with self._lock:
            stream = self._streams.get(stream_token)
            require(stream is not None, EntityNotFound(f"no stream {stream_token!r}"))
            return stream

    def get_assignment_stream(
        self, assignment_token: str, stream_id: str
    ) -> Optional[DeviceStream]:
        """Lookup by (assignment, device-chosen id) — the manager's scope."""
        with self._lock:
            token = self._by_scope.get((assignment_token, stream_id))
            return self._streams.get(token) if token is not None else None

    def list_device_streams(
        self,
        assignment_token: Optional[str] = None,
        criteria: Optional[SearchCriteria] = None,
    ) -> SearchResults[DeviceStream]:
        with self._lock:
            matches = [
                s
                for s in self._streams.values()
                if assignment_token is None or s.assignment_token == assignment_token
            ]
        matches.sort(key=lambda s: s.created_s)
        return paged(matches, criteria)

    # -- chunk data ---------------------------------------------------------

    def add_device_stream_data(
        self, stream_token: str, sequence_number: int, data: bytes
    ) -> DeviceStreamData:
        require(
            0 <= sequence_number <= _MAX_SEQ,
            ValidationError(f"sequence_number out of range: {sequence_number}"),
        )
        with self._lock:
            self.get_device_stream(stream_token)
            ts = now_s()
            offset = self._journal.append(
                _pack_chunk(stream_token, sequence_number, ts, data)
            )
            self._index[stream_token][sequence_number] = (offset, ts)
            return DeviceStreamData(stream_token, sequence_number, bytes(data), ts)

    def get_device_stream_data(
        self, stream_token: str, sequence_number: int
    ) -> Optional[DeviceStreamData]:
        with self._lock:
            self.get_device_stream(stream_token)
            entry = self._index[stream_token].get(sequence_number)
            if entry is None:
                return None
            offset, ts = entry
        _, seq, _, data = _unpack_chunk(self._journal.read_one(offset))
        return DeviceStreamData(stream_token, seq, data, ts)

    def _chunks_in_order(self, stream_token: str) -> List[Tuple[int, int, int]]:
        """Sorted ``(seq, offset, ts)`` rows for a stream."""
        with self._lock:
            self.get_device_stream(stream_token)
            return sorted(
                (seq, off, ts) for seq, (off, ts) in self._index[stream_token].items()
            )

    def list_device_stream_data(
        self, stream_token: str, criteria: Optional[SearchCriteria] = None
    ) -> SearchResults[DeviceStreamData]:
        """Chunks in sequence order (reference list API sorts by seq)."""
        rows = self._chunks_in_order(stream_token)
        page = paged(rows, criteria)
        return SearchResults(
            results=self._read_rows(page.results), total=page.total
        )

    def stream_content(self, stream_token: str) -> bytes:
        """Assembled stream payload in sequence order (media download)."""
        rows = self._read_rows(self._chunks_in_order(stream_token))
        return b"".join(chunk.data for chunk in rows)

    def _read_rows(self, rows: List[Tuple[int, int, int]]) -> List[DeviceStreamData]:
        """Bulk chunk fetch: one journal range scan instead of a point read
        per chunk (offsets of one stream are usually clustered)."""
        if not rows:
            return []
        wanted = {off: (seq, ts) for seq, off, ts in rows}
        lo, hi = min(wanted), max(wanted) + 1
        out = {}
        for offset, payload in self._journal.scan(lo, hi):
            if offset in wanted:
                token, seq, _, data = _unpack_chunk(payload)
                out[offset] = DeviceStreamData(token, seq, data, wanted[offset][1])
        return [out[off] for _, off, _ in rows]


class DeviceStreamManager(LifecycleComponent):
    """Request-level stream handling against the active assignment.

    Reference: ``media/DeviceStreamManager.java`` — resolve the device's
    current assignment, then create the stream / append data / answer
    send-back requests.  Every operation is scoped to the caller's own
    assignment: a device can only ever touch streams created under it.
    Acks and send-back payloads go to the (optional) ``deliver_command``
    hook, the analog of the reference's ``deliverSystemCommand`` path.
    """

    def __init__(
        self,
        device_management,  # services.device_management.DeviceManagement
        stream_management: DeviceStreamManagement,
        deliver_command=None,  # Callable[[str, dict], None]
    ):
        super().__init__("device-stream-manager")
        self.dm = device_management
        self.streams = stream_management
        self.deliver_command = deliver_command

    def _current_assignment(self, device_token: str):
        device = self.dm.get_device(device_token)
        assignment = self.dm.get_active_assignment(device.token)
        require(
            assignment is not None,
            InvalidReference(f"device {device_token!r} not assigned"),
        )
        return assignment

    def _own_stream(self, device_token: str, stream_id: str) -> DeviceStream:
        assignment = self._current_assignment(device_token)
        stream = self.streams.get_assignment_stream(assignment.token, stream_id)
        require(
            stream is not None,
            EntityNotFound(
                f"no stream {stream_id!r} for assignment {assignment.token!r}"
            ),
        )
        return stream

    def handle_device_stream_request(
        self, device_token: str, stream_id: str,
        content_type: str = "application/octet-stream",
    ) -> DeviceStreamStatus:
        assignment = self._current_assignment(device_token)
        try:
            self.streams.create_device_stream(
                assignment.token, stream_id, content_type
            )
            status = DeviceStreamStatus.CREATED
        except DuplicateToken:
            status = DeviceStreamStatus.EXISTS
        except ValidationError:
            # reference: create failures ack FAILED rather than erroring the
            # device's request (DeviceStreamManager.java:62-66)
            status = DeviceStreamStatus.FAILED
        if self.deliver_command is not None:
            self.deliver_command(
                device_token,
                {"type": "stream_ack", "stream_id": stream_id,
                 "status": status.value},
            )
        return status

    def handle_device_stream_data_request(
        self, device_token: str, stream_id: str, sequence_number: int, data: bytes
    ) -> DeviceStreamData:
        stream = self._own_stream(device_token, stream_id)
        return self.streams.add_device_stream_data(
            stream.token, sequence_number, data
        )

    def handle_send_device_stream_data_request(
        self, device_token: str, stream_id: str, sequence_number: int
    ) -> bytes:
        """Device asks for chunk N back; absent chunks answer empty
        (reference sends ``new byte[0]``)."""
        stream = self._own_stream(device_token, stream_id)
        chunk = self.streams.get_device_stream_data(stream.token, sequence_number)
        data = chunk.data if chunk is not None else b""
        if self.deliver_command is not None:
            self.deliver_command(
                device_token,
                {"type": "stream_data", "stream_id": stream_id,
                 "sequence_number": sequence_number, "data": data},
            )
        return data
