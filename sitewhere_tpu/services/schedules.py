"""Schedule management — timed (batch) command invocations.

Reference: ``service-schedule-management`` — Quartz-backed
``QuartzScheduleManager.java`` with ``ISchedule`` (simple
interval/repeat or cron trigger, optional start/end window) and scheduled
jobs (``jobs/CommandInvocationJob.java``,
``jobs/BatchCommandInvocationJob.java``).  Quartz is replaced by a single
ticker thread + a pure next-fire computation (unit-testable without
sleeping): simple triggers fire every ``interval_s`` up to ``repeat_count``
times; cron triggers support the standard 5-field subset
(``m h dom mon dow`` with ``*``, lists, ranges, ``*/n``).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.services.common import (
    Entity,
    EntityNotFound,
    SearchCriteria,
    SearchResults,
    ValidationError,
    mint_token,
    now_s,
    paged,
    require,
)

logger = logging.getLogger("sitewhere_tpu.schedules")


# -- cron subset -------------------------------------------------------------


def _parse_field(spec: str, lo: int, hi: int) -> frozenset:
    out = set()
    for part in spec.split(","):
        step = 1
        rng = part
        has_step = "/" in part
        if has_step:
            rng, step_s = part.split("/", 1)
            step = int(step_s)
            if step <= 0:
                raise ValidationError(f"bad cron step {step_s}")
        if rng in ("*", ""):
            lo_p, hi_p = lo, hi
        elif "-" in rng:
            a, b = rng.split("-", 1)
            lo_p, hi_p = int(a), int(b)
        else:
            lo_p = int(rng)
            # cron: "a/n" means start at a, step n to the field max.
            hi_p = hi if has_step else lo_p
        if lo_p > hi_p:
            raise ValidationError(f"reversed cron range {part!r}")
        if not (lo <= lo_p <= hi and lo <= hi_p <= hi):
            raise ValidationError(f"cron field {spec} out of range [{lo},{hi}]")
        out.update(range(lo_p, hi_p + 1, step))
    return frozenset(out)


@dataclasses.dataclass(frozen=True)
class CronSpec:
    """Parsed 5-field cron expression."""

    minutes: frozenset
    hours: frozenset
    dom: frozenset
    months: frozenset
    dow: frozenset  # cron numbering: 0=Sunday .. 6=Saturday (7 accepted as Sunday)
    # Vixie-cron day rule: when BOTH day fields are restricted (neither was
    # "*"), a day matches if EITHER matches; otherwise both must match.
    dom_star: bool = True
    dow_star: bool = True

    @classmethod
    def parse(cls, expr: str) -> "CronSpec":
        fields = expr.split()
        require(len(fields) == 5, ValidationError(f"cron needs 5 fields: {expr!r}"))
        return cls(
            minutes=_parse_field(fields[0], 0, 59),
            hours=_parse_field(fields[1], 0, 23),
            dom=_parse_field(fields[2], 1, 31),
            months=_parse_field(fields[3], 1, 12),
            dow=frozenset(d % 7 for d in _parse_field(fields[4], 0, 7)),
            # Vixie sets the star flag when the field BEGINS with '*'
            # ("*/2" counts as star for the day-OR rule).
            dom_star=fields[2].startswith("*"),
            dow_star=fields[4].startswith("*"),
        )

    def _day_matches(self, t: time.struct_time) -> bool:
        dom_ok = t.tm_mday in self.dom
        dow_ok = (t.tm_wday + 1) % 7 in self.dow
        if not self.dom_star and not self.dow_star:
            return dom_ok or dow_ok
        return dom_ok and dow_ok

    def matches(self, t: time.struct_time) -> bool:
        return (
            t.tm_min in self.minutes
            and t.tm_hour in self.hours
            and t.tm_mon in self.months
            and self._day_matches(t)
        )

    def next_fire(self, after_s: int, horizon_days: int = 366) -> Optional[int]:
        """Smallest minute-aligned time > after_s matching the spec.

        Skips whole days/hours whose date/hour fields don't match, so a
        never-matching spec (e.g. Feb 31) costs ~hundreds of localtime
        calls over the horizon, not one per minute.
        """
        t = (after_s // 60 + 1) * 60
        end = after_s + horizon_days * 86400
        while t <= end:
            st = time.localtime(t)
            if not (st.tm_mon in self.months and self._day_matches(st)):
                # jump to the next local midnight (sec offset keeps t
                # minute-aligned; DST shifts are re-checked next loop)
                t += (
                    (24 - st.tm_hour) * 3600 - st.tm_min * 60 - st.tm_sec
                )
                continue
            if st.tm_hour not in self.hours:
                t += 3600 - st.tm_min * 60 - st.tm_sec
                continue
            if st.tm_min in self.minutes:
                return t
            t += 60
        return None


# -- model -------------------------------------------------------------------


@dataclasses.dataclass
class Schedule(Entity):
    """Reference ``ISchedule``: trigger + optional active window."""

    name: str = ""
    trigger_type: str = "Simple"  # Simple | Cron
    interval_s: int = 60          # Simple
    repeat_count: int = -1        # Simple; -1 = forever
    cron: str = ""                # Cron
    start_s: Optional[int] = None
    end_s: Optional[int] = None

    def spec(self) -> Optional[CronSpec]:
        return CronSpec.parse(self.cron) if self.trigger_type == "Cron" else None


@dataclasses.dataclass
class ScheduledJob(Entity):
    """Reference ``IScheduledJob``: what to run when the schedule fires."""

    schedule: str = ""
    job_type: str = "CommandInvocation"  # or BatchCommandInvocation
    config: Dict[str, object] = dataclasses.field(default_factory=dict)
    fire_count: int = 0
    last_fire_s: Optional[int] = None


JobExecutor = Callable[[ScheduledJob], None]


class ScheduleManager(LifecycleComponent):
    """Schedules + jobs + the ticker that fires them.

    ``executors`` maps job type → callable; the node wires
    ``CommandInvocation`` to the command processor and
    ``BatchCommandInvocation`` to the batch manager (reference job classes).
    """

    def __init__(
        self,
        executors: Optional[Dict[str, JobExecutor]] = None,
        tick_s: float = 1.0,
        name: str = "schedule-manager",
    ):
        super().__init__(name)
        self.executors = dict(executors or {})
        self.tick_s = tick_s
        self.schedules: Dict[str, Schedule] = {}
        self.jobs: Dict[str, ScheduledJob] = {}
        self._lock = threading.RLock()
        # schedule token → (next_fire_s, fires_so_far)
        self._next: Dict[str, int] = {}
        self._fires: Dict[str, int] = {}
        self._ticker: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- CRUD ----------------------------------------------------------------

    def create_schedule(self, token: Optional[str] = None, **fields) -> Schedule:
        with self._lock:
            token = token or mint_token("sched")
            require(token not in self.schedules, ValidationError(f"schedule {token} exists"))
            s = Schedule(token=token, **fields)
            require(
                s.trigger_type in ("Simple", "Cron"),
                ValidationError(f"bad trigger type {s.trigger_type}"),
            )
            if s.trigger_type == "Cron":
                CronSpec.parse(s.cron)  # validate now
            else:
                require(s.interval_s > 0, ValidationError("interval must be positive"))
            self.schedules[token] = s
            self._schedule_next(s, base_s=max(now_s(), s.start_s or 0))
            return s

    def get_schedule(self, token: str) -> Schedule:
        s = self.schedules.get(token)
        require(s is not None, EntityNotFound(f"schedule {token}"))
        return s

    def list_schedules(self, criteria: Optional[SearchCriteria] = None) -> SearchResults[Schedule]:
        with self._lock:
            return paged(sorted(self.schedules.values(), key=lambda s: s.token), criteria)

    def delete_schedule(self, token: str) -> Schedule:
        with self._lock:
            s = self.schedules.pop(token, None)
            require(s is not None, EntityNotFound(f"schedule {token}"))
            self._next.pop(token, None)
            self._fires.pop(token, None)
            for job in [j for j in self.jobs.values() if j.schedule == token]:
                del self.jobs[job.token]
            return s

    def create_job(self, token: Optional[str] = None, **fields) -> ScheduledJob:
        with self._lock:
            token = token or mint_token("job")
            require(token not in self.jobs, ValidationError(f"job {token} exists"))
            job = ScheduledJob(token=token, **fields)
            require(job.schedule in self.schedules, EntityNotFound(f"schedule {job.schedule}"))
            require(
                job.job_type in self.executors or not self.executors,
                ValidationError(f"no executor for job type {job.job_type}"),
            )
            self.jobs[token] = job
            return job

    def get_job(self, token: str) -> ScheduledJob:
        job = self.jobs.get(token)
        require(job is not None, EntityNotFound(f"job {token}"))
        return job

    def list_jobs(
        self, criteria: Optional[SearchCriteria] = None, schedule: Optional[str] = None
    ) -> SearchResults[ScheduledJob]:
        with self._lock:
            items = sorted(self.jobs.values(), key=lambda j: j.token)
        if schedule is not None:
            items = [j for j in items if j.schedule == schedule]
        return paged(items, criteria)

    def delete_job(self, token: str) -> ScheduledJob:
        with self._lock:
            job = self.jobs.pop(token, None)
            require(job is not None, EntityNotFound(f"job {token}"))
            return job

    # -- firing --------------------------------------------------------------

    def _schedule_next(self, s: Schedule, base_s: int) -> None:
        fires = self._fires.get(s.token, 0)
        if s.trigger_type == "Simple":
            if s.repeat_count >= 0 and fires > s.repeat_count:
                self._next.pop(s.token, None)
                return
            nxt = base_s if fires == 0 else base_s + s.interval_s
        else:
            spec = s.spec()
            nxt = spec.next_fire(base_s)
            if nxt is None:
                self._next.pop(s.token, None)
                return
        if s.end_s is not None and nxt > s.end_s:
            self._next.pop(s.token, None)
            return
        self._next[s.token] = nxt

    def due_schedules(self, at_s: Optional[int] = None) -> List[str]:
        at_s = at_s if at_s is not None else now_s()
        with self._lock:
            return [tok for tok, t in self._next.items() if t <= at_s]

    def fire(self, schedule_token: str, at_s: Optional[int] = None) -> int:
        """Run all jobs attached to a schedule; returns jobs fired.

        Public so tests (and the REST trigger endpoint) can fire without
        waiting on wall-clock.
        """
        at_s = at_s if at_s is not None else now_s()
        with self._lock:
            s = self.get_schedule(schedule_token)
            jobs = [j for j in self.jobs.values() if j.schedule == schedule_token]
            self._fires[schedule_token] = self._fires.get(schedule_token, 0) + 1
            self._schedule_next(s, base_s=at_s)
        fired = 0
        for job in jobs:
            executor = self.executors.get(job.job_type)
            if executor is None:
                logger.warning("no executor for job type %s", job.job_type)
                continue
            try:
                executor(job)
                job.fire_count += 1
                job.last_fire_s = at_s
                fired += 1
            except Exception:
                logger.exception("scheduled job %s failed", job.token)
        return fired

    def _tick(self) -> None:
        for token in self.due_schedules():
            try:
                self.fire(token)
            except EntityNotFound:
                # deleted between due_schedules() and fire() — drop its slot
                with self._lock:
                    self._next.pop(token, None)
            except Exception:
                logger.exception("firing schedule %s failed", token)

    def _tick_loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self._tick()
            except Exception:
                logger.exception("schedule tick failed")

    def start(self) -> None:
        super().start()
        self._stop.clear()
        self._ticker = threading.Thread(target=self._tick_loop, name=self.name, daemon=True)
        self._ticker.start()

    def stop(self) -> None:
        self._stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=5)
            self._ticker = None
        super().stop()
