"""The rule-program runner: live evaluation of compiled tenant programs.

The execution half of the bring-your-own-rules subsystem, shaped like
``analytics.runner.QueryRunner``: the dispatcher's egress hands every
accepted enriched batch to :meth:`submit_live` (non-blocking bounded
offer; sheds from SHEDDING as a non-priority consumer), a single worker
thread runs the compiled kernels, fired programs become ALERT rows
re-injected through the dispatcher's derived-alert path, and each
batch's eval wall time bills to tenants by row share through the
``UsageLedger`` — rule evaluation is metered compute, same as analytics
``eval_s``.

Compile-stall contract: :meth:`refresh` (the mutation-side publish)
warms any kernel whose (structure, shape) signature has not run yet —
on the MUTATING thread, BEFORE the new epoch becomes current — so the
eval worker only ever calls already-compiled kernels.  An operand-only
swap reuses both the epoch's shape signature and the structure-keyed
trace cache, making the swap cost one host build + device put with zero
recompiles (asserted by the hot-swap tests and measured by
``tools/rulebench.py``).
"""

from __future__ import annotations

import logging
import pickle
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.rules import compile as rcompile
from sitewhere_tpu.rules.enrich import AttributeStore
from sitewhere_tpu.rules.registry import ProgramRegistry, RulesEpoch
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.schema import DEFAULT_EWMA_TAUS, EventType

_LOG = logging.getLogger("sitewhere_tpu.rules")

_CHECKPOINT_VERSION = 1


class RuleEngineRunner(LifecycleComponent):
    """Lifecycle wrapper: trail state + attribute tables + program
    registry + the eval worker."""

    _LIVE_COLS = ("device_id", "tenant_id", "event_type", "mtype_id",
                  "value", "lon", "lat", "ts_s", "ts_ns")

    def __init__(self, capacity: int, n_mtype_slots: int = 8,
                 asset_capacity: int = 1024,
                 resolve_mtype=None, resolve_alert=None,
                 overload=None, metrics=None,
                 programs_per_tenant: int = 4,
                 max_programs: int = 262144,
                 queue_depth: int = 64,
                 mesh=None, rows_per_shard: Optional[int] = None,
                 name: str = "rule-programs"):
        import queue as _queue

        super().__init__(name)
        self.capacity = int(capacity)
        self.n_mtype_slots = int(n_mtype_slots)
        self.overload = overload
        self.mesh = mesh
        self.rows_per_shard = rows_per_shard
        if metrics is None:
            from sitewhere_tpu.runtime.metrics import MetricsRegistry

            metrics = MetricsRegistry()
        self.metrics = metrics
        self.attributes = AttributeStore(capacity, asset_capacity)
        self.registry = ProgramRegistry(
            programs_per_tenant=programs_per_tenant,
            max_programs=max_programs,
            resolve_alert=resolve_alert,
            resolve_mtype=resolve_mtype,
            resolve_attr=self.attributes.resolve)
        self.taus = jnp.asarray(DEFAULT_EWMA_TAUS, jnp.float32)
        self._trail = self._fresh_trail()
        self._q: "_queue.Queue" = _queue.Queue(maxsize=queue_depth)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # serializes kernel eval + trail mutation against checkpoint
        # snapshots (the QueryRunner _eval_mutex discipline)
        self._eval_mutex = threading.Lock()
        self._warm_lock = threading.Lock()
        self._warmed: set = set()
        self._prepare_sharded = None
        # dispatcher hooks (instance-wired): alert re-injection
        self.inject = None
        self.usage_ledger = None
        # metered-quota table (runtime/metering.py QuotaTable): rows of
        # deprioritized-or-refused tenants are skipped before eval —
        # enforcement happens HERE on the worker thread, never on the
        # dispatcher egress path that offers the batch
        self.quotas = None
        # rules.* metric family (closed; analysis/metric_names.py)
        self._m_programs = metrics.gauge("rules.programs")
        self._m_groups = metrics.gauge("rules.groups")
        self._m_shapes = metrics.gauge("rules.compiled_shapes")
        self._m_swaps = metrics.counter("rules.swaps")
        self._m_compiles = metrics.counter("rules.compiles")
        self._m_batches = metrics.counter("rules.live_batches")
        self._m_dropped = metrics.counter("rules.live_dropped")
        self._m_shed = metrics.counter("rules.live_shed")
        self._m_alerts = metrics.counter("rules.alerts")
        self._t_eval = metrics.timer("rules.eval_s")
        self._swaps_seen = 0
        self._compiles_seen = 0

    def _fresh_trail(self):
        D, M = self.capacity, self.n_mtype_slots
        K = len(DEFAULT_EWMA_TAUS)
        return (jnp.zeros((D, M), jnp.int32), jnp.zeros((D, M), jnp.int32),
                jnp.zeros((D, M), jnp.float32),
                jnp.zeros((D, M, K), jnp.float32))

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        super().start()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._worker, name=f"{self.name}-eval", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self.drain(timeout_s=5.0)
        self._stop.set()
        if self._thread is not None:
            try:
                self._q.put_nowait(None)
            except Exception:
                pass
            self._thread.join(timeout=5)
            self._thread = None
        super().stop()

    # -- mutation side -------------------------------------------------------

    def put_program(self, tenant: int, doc: dict) -> Dict[str, object]:
        out = self.registry.put_program(tenant, doc)
        self.refresh()
        return out

    def delete_program(self, tenant: int, token: str) -> bool:
        found = self.registry.delete_program(tenant, token)
        if found:
            self.refresh()
        return found

    def refresh(self) -> Optional[RulesEpoch]:
        """Publish registry + attribute epochs and warm any kernel whose
        shape signature has not executed yet — all on the calling
        (mutation) thread, so the eval worker never pays a compile."""
        epoch = self.registry.publish()
        self.attributes.publish()
        if epoch is not None:
            for group in epoch.groups:
                self._warm(group)
        self._publish_metrics()
        return epoch

    def _warm(self, group) -> None:
        sig = group.shape_sig()
        with self._warm_lock:
            if sig in self._warmed:
                return
        B = 8  # dummy width; XLA re-specializes per real batch width,
        #        which the first real batch pays once per width — the
        #        swap path's widths are already warm by then
        zi = jnp.zeros(B, jnp.int32)
        zf = jnp.zeros(B, jnp.float32)
        K = len(DEFAULT_EWMA_TAUS)
        feats = rcompile.BatchFeatures(
            ewma=jnp.zeros((B, K), jnp.float32), rate=zf,
            rate_valid=jnp.zeros(B, bool),
            dev_attr=jnp.zeros((B, self.attributes.max_columns),
                               jnp.int32),
            asset_attr=jnp.zeros((B, self.attributes.max_columns),
                                 jnp.int32))
        fired, _, _, _ = group.eval_fn(
            group.tables, feats, zi, zi, zi, zf, zf, zf,
            jnp.zeros(B, bool), has_geo=group.has_geo)
        fired.block_until_ready()
        with self._warm_lock:
            self._warmed.add(sig)

    def _publish_metrics(self) -> None:
        self._m_programs.set(self.registry.program_count())
        self._m_groups.set(self.registry.group_count())
        self._m_shapes.set(rcompile.structure_keys_compiled())
        swaps = self.registry.swaps
        if swaps > self._swaps_seen:
            self._m_swaps.inc(swaps - self._swaps_seen)
            self._swaps_seen = swaps
        compiles = rcompile.compile_count()
        if compiles > self._compiles_seen:
            self._m_compiles.inc(compiles - self._compiles_seen)
            self._compiles_seen = compiles

    # -- live path -----------------------------------------------------------

    def submit_live(self, cols, mask: np.ndarray, trace=None,
                    committed: Optional[int] = None) -> None:
        """Offer one accepted enriched batch (non-blocking, called from
        dispatcher egress).  Sheds as a non-priority consumer from
        SHEDDING up; drops (counted) when the queue is full."""
        if self.registry.current_epoch() is None:
            return
        if self.overload is not None \
                and not self.overload.allow_fanout(priority=False):
            self._m_shed.inc()
            return
        mask = np.asarray(mask)
        batch = {k: np.asarray(cols[k])[mask] for k in self._LIVE_COLS}
        batch["asset_id"] = np.asarray(
            cols["asset_id"])[mask] if "asset_id" in cols else np.full(
                len(batch["device_id"]), NULL_ID, np.int32)
        if not len(batch["device_id"]):
            return
        try:
            self._q.put_nowait(batch)
        except Exception:
            self._m_dropped.inc()

    def drain(self, timeout_s: float = 10.0) -> None:
        deadline = time.monotonic() + timeout_s
        with self._q.all_tasks_done:
            while self._q.unfinished_tasks:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                self._q.all_tasks_done.wait(remaining)

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._q.get(timeout=0.1)
            except Exception:
                continue
            try:
                if item is None:
                    continue
                self._m_batches.inc()
                self._eval_batch(item)
            except Exception:
                _LOG.exception("rule program eval failed")
            finally:
                self._q.task_done()

    def _prepare(self, batch: Dict[str, np.ndarray], attrs):
        """Run the (possibly mesh-sharded) prepare kernel; updates the
        trail in place and returns the per-row features."""
        args = (self._trail + (attrs.device, attrs.asset)
                + tuple(jnp.asarray(batch[k]) for k in
                        ("device_id", "asset_id", "ts_s", "ts_ns",
                         "mtype_id", "value"))
                + (jnp.asarray(batch["event_type"]),
                   jnp.asarray(batch.get(
                       "accepted",
                       np.ones(len(batch["device_id"]), bool))),
                   self.taus))
        if self.mesh is not None:
            if self._prepare_sharded is None:
                rows = (self.rows_per_shard
                        or self.capacity // self.mesh.devices.size)
                self._prepare_sharded = rcompile.sharded_prepare(
                    self.mesh, rows)
            feats, self._trail = self._prepare_sharded(*args)
        else:
            feats, self._trail = rcompile.prepare_kernel()(*args)
        return feats

    def _eval_batch(self, batch: Dict[str, np.ndarray]) -> None:
        # epoch isolation: grab the published world ONCE — a swap
        # landing mid-batch takes effect next batch, and the outgoing
        # epoch's tables are immutable for as long as we hold them
        epoch = self.registry.current_epoch()
        if epoch is None:
            return
        if self.quotas is not None and "tenant_id" in batch:
            # quota gate: deprioritized/refused tenants lose their rows
            # here (off the hot path); the mask is None when no quota
            # is configured so un-metered deployments pay one branch
            try:
                skip = self.quotas.skip_mask(np.asarray(batch["tenant_id"]))
            except Exception:
                _LOG.exception("rules quota mask failed")
                skip = None
            if skip is not None and skip.any():
                keep = ~skip
                if not keep.any():
                    return
                n = len(skip)
                batch = {k: (np.asarray(v)[keep]
                             if np.ndim(v) >= 1 and len(v) == n else v)
                         for k, v in batch.items()}
        attrs = self.attributes.publish()
        t0 = time.perf_counter()
        fired_out: List[Tuple[np.ndarray, ...]] = []
        with self._eval_mutex:
            with self._t_eval.time():
                feats = self._prepare(batch, attrs)
                bi = {k: jnp.asarray(batch[k]) for k in
                      ("tenant_id", "event_type", "mtype_id")}
                bf = {k: jnp.asarray(batch[k]) for k in
                      ("value", "lon", "lat")}
                acc = jnp.asarray(batch.get(
                    "accepted", np.ones(len(batch["device_id"]), bool)))
                for group in epoch.groups:
                    fired, code, level, _pid = group.eval_fn(
                        group.tables, feats, bi["tenant_id"],
                        bi["event_type"], bi["mtype_id"], bf["value"],
                        bf["lon"], bf["lat"], acc,
                        has_geo=group.has_geo)
                    fired_out.append((np.asarray(fired),
                                      np.asarray(code),
                                      np.asarray(level)))
        self._fanout(batch, fired_out)
        tenants = batch.get("tenant_id")
        if self.usage_ledger is not None and tenants is not None \
                and len(tenants):
            # rule eval is metered compute: bill wall time by row share,
            # the same attribution rule as analytics eval_s
            try:
                per_row = (time.perf_counter() - t0) / len(tenants)
                self.usage_ledger.charge_rows_host(
                    np.asarray(tenants), "eval_s",
                    weights=np.full(len(tenants), per_row))
            except Exception:
                _LOG.exception("rules usage charge failed")

    def _fanout(self, batch, fired_out) -> None:
        """Fired (row, program-slot) pairs become ALERT event columns
        re-injected through the dispatcher's derived-alert path."""
        rows_all: List[np.ndarray] = []
        codes_all: List[np.ndarray] = []
        levels_all: List[np.ndarray] = []
        for fired, code, level in fired_out:
            rows, slots = np.nonzero(fired)
            if rows.size:
                rows_all.append(rows)
                codes_all.append(code[rows, slots])
                levels_all.append(level[rows, slots])
        if not rows_all:
            return
        rows = np.concatenate(rows_all)
        n = int(rows.size)
        self._m_alerts.inc(n)
        if self.inject is None:
            return
        cols = {
            "device_id": batch["device_id"][rows].astype(np.int32),
            "tenant_id": batch["tenant_id"][rows].astype(np.int32),
            "event_type": np.full(n, int(EventType.ALERT), np.int32),
            "ts_s": batch["ts_s"][rows].astype(np.int32),
            "ts_ns": batch["ts_ns"][rows].astype(np.int32),
            "value": batch["value"][rows].astype(np.float32),
            "alert_code": np.concatenate(codes_all).astype(np.int32),
            "alert_level": np.concatenate(levels_all).astype(np.int32),
            # derived alerts never re-fold trailing state
            "update_state": np.zeros(n, bool),
        }
        try:
            self.inject(cols)
        except Exception:
            _LOG.exception("rule alert injection failed")

    # -- checkpoint plane ----------------------------------------------------

    def snapshot_state(self) -> Tuple[bytes, Optional[dict]]:
        """StateProvider body: program docs + attribute tables.  The
        trailing EWMA/rate state deliberately restarts fresh — like the
        usage ledger's sliding window, it describes the CURRENT stream;
        window predicates re-seed from the first post-restore sample
        (first sample seeds the average, no zero bias)."""
        self.drain(timeout_s=2.0)
        with self._eval_mutex:
            progs, header = self.registry.snapshot_payload()
            cols, arrays = self.attributes.snapshot_payload()
        payload = pickle.dumps(
            {"version": _CHECKPOINT_VERSION, "programs": progs,
             "attr_cols": cols, "attr_arrays": arrays}, protocol=4)
        return payload, header

    def restore_state(self, header, payload) -> int:
        doc = pickle.loads(payload)
        self.attributes.restore_payload(doc.get("attr_cols") or {},
                                        doc.get("attr_arrays") or {})
        self.registry.restore_payload(header or {}, doc["programs"])
        self._trail = self._fresh_trail()
        self.refresh()
        return self.registry.program_count()

    # -- introspection -------------------------------------------------------

    def stats(self) -> Dict[str, object]:
        return {
            "programs": self.registry.program_count(),
            "groups": self.registry.group_count(),
            "structures": self.registry.structure_keys(),
            "compiledShapes": rcompile.structure_keys_compiled(),
            "kernelExecutables": rcompile.compile_count(),
            "swaps": self.registry.swaps,
            "builds": self.registry.builds,
            "epoch": (self.registry.current_epoch().epoch
                      if self.registry.current_epoch() else 0),
        }


__all__ = ["RuleEngineRunner"]
