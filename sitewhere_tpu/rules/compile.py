"""The bucketing compiler: structure-shared kernels over operand tables.

Programs sharing a :func:`~sitewhere_tpu.rules.dsl.structure_key` share
ONE jitted kernel; everything that distinguishes them — thresholds,
comparison ops, window choices, polygon rings, attribute ids, alert
codes — is data in padded operand tables indexed by a per-row program
id, exactly the ``RuleTable`` design scaled out to arbitrary programs.
The trace cache is keyed by structure: :func:`kernel_for` returns the
same jitted callable for every group with the same key, so loading 100k
programs mints at most ``dsl.MAX_STRUCTURE_KEYS`` compiled shapes and a
tenant hot-swapping constants can never trigger a retrace.

Two kernels per batch:

- :func:`rules_prepare_batch` (ONE compile, shared by every group):
  folds each row against the engine's trailing per-(device, mtype-slot)
  state — EWMA ladder + rate since the previous sample, reusing the
  fused step's :func:`~sitewhere_tpu.pipeline.step.fold_ewma_arrays` —
  updates the trail with the batch winners (``ops/scatter``'s
  time-ordered scatter, the same winner contract as ``DeviceState``),
  and gathers the metadata-join enrichment rows from the device/asset
  attribute tables.  On a mesh this is the sharded part: trail and
  device-attribute tables shard by ``device_id // rows_per_shard``
  exactly like device state (:func:`sharded_prepare`), each shard masks
  the rows it owns, and the per-row features combine with one psum.

- :func:`rules_group_eval` (one compile per structure key): decodes the
  operand tables for up to ``S`` programs per row-tenant and reduces the
  padded ``[B, S, C, P]`` predicate lattice to fired/alert outputs.
  Runs on replicated features, so the mesh path needs no second
  shard_map.
"""

from __future__ import annotations

import threading
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.pipeline.step import compare_select, fold_ewma_arrays
from sitewhere_tpu.ops.scatter import scatter_last_by_time
from sitewhere_tpu.rules.dsl import (
    PK_ATTR,
    PK_EVENT_TYPE,
    PK_GEO,
    PK_PAD,
    PK_RATE,
    PK_VALUE,
)
from sitewhere_tpu.schema import EventType


class GroupTables(NamedTuple):
    """Operand tables for ONE structure group (epoch-immutable).

    ``kind``/``pf`` are ``[G, C, P]``; ``pint`` packs the four int
    operands ``(op, i0, i1, i2)`` as ``[G, C, P, 4]`` so the per-row
    decode is two gathers, not six.  ``meta`` packs per-program
    ``(tenant_id, alert_code, alert_level, active)`` as ``[G, 4]``;
    ``slots`` maps dense tenant id to up to ``S`` program rows
    (``[T, S]``, NULL_ID padded); ``verts`` is the group's polygon pool
    ``[Z, V, 2]`` (a 1-row dummy for geo-less structures)."""

    kind: jax.Array
    pint: jax.Array
    pf: jax.Array
    meta: jax.Array
    slots: jax.Array
    verts: jax.Array


class BatchFeatures(NamedTuple):
    """Per-row features produced by the prepare kernel, consumed by
    every group kernel (replicated on a mesh)."""

    ewma: jax.Array        # f32[B, K]   candidate EWMAs incl. this row
    rate: jax.Array        # f32[B]      value delta / dt vs prev sample
    rate_valid: jax.Array  # bool[B]     previous sample exists, dt > 0
    dev_attr: jax.Array    # i32[B, Ad]  device attribute row (NULL_ID unset)
    asset_attr: jax.Array  # i32[B, Aa]  asset attribute row


def _pip_rows(px: jax.Array, py: jax.Array, verts: jax.Array) -> jax.Array:
    """Ray-crossing containment for per-row gathered polygons.

    ``ops/geo.points_in_polygons`` tests every point against every
    polygon — dense ``[B, Z]`` — which is the wrong shape here: a batch
    references only the polygons its rows' programs name, so the verts
    arrive pre-gathered as ``[..., V, 2]`` aligned with the predicate
    lattice.  The arithmetic (slope-first ordering, guarded denominator)
    mirrors ``points_in_polygons`` exactly so both lanes agree on
    boundary rounding."""
    x1 = verts[..., :, 0]
    y1 = verts[..., :, 1]
    x2 = jnp.roll(verts[..., :, 0], -1, axis=-1)
    y2 = jnp.roll(verts[..., :, 1], -1, axis=-1)
    pxe = px[..., None]
    pye = py[..., None]
    straddles = (y1 > pye) != (y2 > pye)
    denom = jnp.where(y2 == y1, 1.0, y2 - y1)
    slope = (x2 - x1) / denom
    x_cross = slope * (pye - y1) + x1
    crossing = straddles & (pxe < x_cross)
    return (jnp.sum(crossing.astype(jnp.int32), axis=-1) % 2) == 1


def _attr_col(attr: jax.Array, col: jax.Array) -> jax.Array:
    """Select per-predicate attribute columns from a per-row attribute
    block: ``attr[B, A]`` x ``col[B, S, C, P]`` → ``[B, S, C, P]``.
    One-hot accumulate over the (small, static) column count — a
    take-along on this shape lowers to a scalar gather loop."""
    out = jnp.full(col.shape, NULL_ID, jnp.int32)
    for c in range(attr.shape[1]):
        out = jnp.where(col == c, attr[:, c][:, None, None, None], out)
    return out


def rules_group_eval(
    tables: GroupTables,
    feats: BatchFeatures,
    tenant_id: jax.Array,
    event_type: jax.Array,
    mtype_id: jax.Array,
    value: jax.Array,
    lon: jax.Array,
    lat: jax.Array,
    accepted: jax.Array,
    *,
    has_geo: bool,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Evaluate every program of one structure group over one batch.

    Returns ``(fired[B, S], code[B, S], level[B, S], pid[B, S])`` — up
    to S programs per row-tenant, each independently firing its own
    alert.  Cost is O(B * S * C * P) regardless of how many programs the
    group holds: the per-row program-id indirection (``slots``) is what
    decouples eval cost from program count."""
    T, S = tables.slots.shape
    G = tables.kind.shape[0]

    pid = tables.slots[jnp.clip(tenant_id, 0, T - 1)]          # [B, S]
    g = jnp.clip(pid, 0, G - 1)
    meta = tables.meta[g]                                      # [B, S, 4]
    # BYO programs evaluate device telemetry; alert rows (including this
    # engine's own re-injected alerts) are masked to keep the
    # re-injection loop contraction-free
    row_ok = accepted & (event_type != EventType.ALERT)
    ok = ((pid != NULL_ID) & row_ok[:, None]
          & (meta[..., 0] == tenant_id[:, None]) & (meta[..., 3] != 0))

    kind = tables.kind[g]                                      # [B, S, C, P]
    pint = tables.pint[g]                                      # [B, S, C, P, 4]
    f0 = tables.pf[g]
    op = pint[..., 0]
    i0 = pint[..., 1]
    i1 = pint[..., 2]
    i2 = pint[..., 3]

    # float lane: value / EWMA / rate vs threshold, gated on measurement
    # rows + optional mtype filter (NULL_ID = any), rate additionally on
    # a usable previous sample — the built-in pass's gates, generalized
    is_meas = accepted & (event_type == EventType.MEASUREMENT)
    e_sel = jnp.zeros(kind.shape, jnp.float32)
    for k in range(feats.ewma.shape[1]):
        e_sel = jnp.where(i1 == k, feats.ewma[:, k][:, None, None, None],
                          e_sel)
    v = value[:, None, None, None]
    fval = jnp.where(kind == PK_VALUE, v,
                     jnp.where(kind == PK_RATE,
                               feats.rate[:, None, None, None], e_sel))
    mtype_ok = (i0 == NULL_ID) | (i0 == mtype_id[:, None, None, None])
    fgate = (is_meas[:, None, None, None] & mtype_ok
             & ((kind != PK_RATE)
                | feats.rate_valid[:, None, None, None]))
    fhit = compare_select(op, fval, f0) & fgate

    # int lane: attribute joins (unset attributes never match) and
    # event-type gates
    aval = jnp.where(i2 == 1, _attr_col(feats.asset_attr, i1),
                     _attr_col(feats.dev_attr, i1))
    ahit = compare_select(op, aval, i0) & (aval != NULL_ID)
    ehit = compare_select(op, event_type[:, None, None, None], i0)

    if has_geo:
        Z = tables.verts.shape[0]
        vg = tables.verts[jnp.clip(i1, 0, Z - 1)]     # [B, S, C, P, V, 2]
        inside = _pip_rows(lon[:, None, None, None],
                           lat[:, None, None, None], vg)
        is_loc = accepted & (event_type == EventType.LOCATION)
        ghit = (jnp.where(i0 == 1, inside, ~inside)
                & is_loc[:, None, None, None])
    else:
        ghit = jnp.zeros(kind.shape, bool)

    res = jnp.where(
        kind == PK_PAD, True,
        jnp.where(kind <= PK_RATE, fhit,
                  jnp.where(kind == PK_GEO, ghit,
                            jnp.where(kind == PK_ATTR, ahit, ehit))))
    clause_real = (kind != PK_PAD).any(axis=-1)        # [B, S, C]
    clause_hit = res.all(axis=-1) & clause_real
    fired = clause_hit.any(axis=-1) & ok               # [B, S]
    code = jnp.where(fired, meta[..., 1], NULL_ID)
    level = jnp.where(fired, meta[..., 2], 0)
    return fired, code, level, pid


def rules_prepare_batch(
    trail_ts: jax.Array,
    trail_ns: jax.Array,
    trail_v: jax.Array,
    trail_ewma: jax.Array,
    dev_attr: jax.Array,
    asset_attr: jax.Array,
    device_id: jax.Array,
    asset_id: jax.Array,
    ts_s: jax.Array,
    ts_ns: jax.Array,
    mtype_id: jax.Array,
    value: jax.Array,
    event_type: jax.Array,
    accepted: jax.Array,
    taus: jax.Array,
) -> Tuple[BatchFeatures, Tuple[jax.Array, jax.Array, jax.Array, jax.Array]]:
    """Per-row features + updated trailing state for one batch.

    The trail is the engine's own per-(device, mtype-slot) last-sample /
    EWMA store, ``[D, M]``-shaped like ``DeviceState`` and updated with
    the same newest-(ts_s, ts_ns)-wins winner scatter, so window and
    rate predicates see exactly the semantics ``rules/interp.py``
    defines.  Attribute rows gather NULL_ID for ids outside the tables
    (unset attributes never match a join predicate)."""
    D, M = trail_ts.shape
    K = trail_ewma.shape[2]
    is_meas = accepted & (event_type == EventType.MEASUREMENT)

    ids = jnp.clip(device_id, 0, D - 1)
    slot = jnp.where(mtype_id >= 0, mtype_id % M, 0)
    flat = ids * M + slot
    ipack = jnp.stack([trail_ts.reshape(-1), trail_ns.reshape(-1)],
                      axis=1)[flat]                        # [B, 2]
    fpack = jnp.concatenate(
        [trail_v.reshape(-1, 1), trail_ewma.reshape(-1, K)],
        axis=1)[flat]                                      # [B, 1 + K]
    prev_ts, prev_ns = ipack[:, 0], ipack[:, 1]
    prev_v, ewma_prev = fpack[:, 0], fpack[:, 1:]

    seeded = prev_ts > 0
    dt = jnp.maximum(
        (ts_s - prev_ts).astype(jnp.float32)
        + (ts_ns - prev_ns).astype(jnp.float32) * 1e-9, 0.0)
    rate_valid = seeded & (dt > 0) & is_meas
    rate = jnp.where(rate_valid,
                     (value - prev_v) / jnp.maximum(dt, 1e-9), 0.0)
    ewma_new = fold_ewma_arrays(prev_ts, prev_ns, ewma_prev,
                                ts_s, ts_ns, value, taus)   # [B, K]

    new_ts, new_ns, (new_v, new_ewma) = scatter_last_by_time(
        trail_ts.reshape(-1), trail_ns.reshape(-1),
        (trail_v.reshape(-1), trail_ewma.reshape(-1, K)),
        flat, ts_s, ts_ns, (value, ewma_new),
        is_meas & (device_id >= 0) & (device_id < D),
    )

    dev_ok = (device_id >= 0) & (device_id < dev_attr.shape[0])
    da = jnp.where(dev_ok[:, None],
                   dev_attr[jnp.clip(device_id, 0, dev_attr.shape[0] - 1)],
                   NULL_ID)
    asset_ok = (asset_id >= 0) & (asset_id < asset_attr.shape[0])
    aa = jnp.where(asset_ok[:, None],
                   asset_attr[jnp.clip(asset_id, 0,
                                       asset_attr.shape[0] - 1)],
                   NULL_ID)

    feats = BatchFeatures(ewma=ewma_new, rate=rate, rate_valid=rate_valid,
                          dev_attr=da, asset_attr=aa)
    trail = (new_ts.reshape(D, M), new_ns.reshape(D, M),
             new_v.reshape(D, M), new_ewma.reshape(D, M, K))
    return feats, trail


# -- trace cache (keyed by structure) ---------------------------------------

_CACHE_LOCK = threading.Lock()
_EVAL_KERNELS: Dict[str, object] = {}
_PREPARE_KERNEL = None


def kernel_for(key: str):
    """The jitted group kernel for a structure key.  Every group with
    the same key shares the SAME callable (and thus XLA's per-shape
    executable cache) — the trace cache the hot-swap contract rests on."""
    with _CACHE_LOCK:
        fn = _EVAL_KERNELS.get(key)
        if fn is None:
            fn = jax.jit(rules_group_eval, static_argnames=("has_geo",))
            _EVAL_KERNELS[key] = fn
        return fn


def prepare_kernel():
    """The (single) jitted prepare kernel, trail buffers donated."""
    global _PREPARE_KERNEL
    with _CACHE_LOCK:
        if _PREPARE_KERNEL is None:
            _PREPARE_KERNEL = jax.jit(rules_prepare_batch,
                                      donate_argnums=(0, 1, 2, 3))
        return _PREPARE_KERNEL


def compile_count() -> int:
    """Total XLA executables minted across the rules kernels — the
    number ``tools/rulebench.py`` bounds and the hot-swap tests assert
    is FLAT across an operand swap."""
    total = 0
    with _CACHE_LOCK:
        kernels = list(_EVAL_KERNELS.values())
        if _PREPARE_KERNEL is not None:
            kernels.append(_PREPARE_KERNEL)
    for fn in kernels:
        size = getattr(fn, "_cache_size", None)
        if callable(size):
            try:
                total += int(size())
            except Exception:
                pass
    return total


def structure_keys_compiled() -> int:
    with _CACHE_LOCK:
        return len(_EVAL_KERNELS)


def reset_trace_cache() -> None:
    """Test/bench hook: drop every cached kernel (fresh compile counts)."""
    global _PREPARE_KERNEL
    with _CACHE_LOCK:
        _EVAL_KERNELS.clear()
        _PREPARE_KERNEL = None


# -- mesh-sharded prepare ----------------------------------------------------

def sharded_prepare(mesh, rows_per_shard: int):
    """shard_map'd prepare: trail + device-attribute tables sharded by
    ``device_id // rows_per_shard`` exactly like device state; batch and
    the (small) asset table replicated; features psummed.

    Each shard computes features only for rows whose device it owns and
    contributes neutral values elsewhere, so the single psum reassembles
    the full per-row feature block bit-identically to the unsharded
    kernel (every accepted row's device lives on exactly one shard; the
    NULL_ID attribute fill rides the ``x + 1`` shift so never-owned rows
    still read as unset).  Trail updates stay shard-local — no
    cross-shard traffic beyond the one feature psum."""
    from jax.sharding import PartitionSpec as P

    from sitewhere_tpu.parallel.mesh import SHARD_AXIS
    from sitewhere_tpu.parallel.shmap import shard_map

    shard1 = P(SHARD_AXIS)
    rep = P()
    in_specs = (
        shard1, shard1, shard1, shard1,          # trail ts/ns/v/ewma
        shard1, rep,                             # dev_attr, asset_attr
        rep, rep, rep, rep, rep, rep, rep, rep,  # batch columns
        rep,                                     # taus
    )
    out_specs = (
        BatchFeatures(ewma=rep, rate=rep, rate_valid=rep,
                      dev_attr=rep, asset_attr=rep),
        (shard1, shard1, shard1, shard1),
    )

    def local_prepare(trail_ts, trail_ns, trail_v, trail_ewma,
                      dev_attr, asset_attr, device_id, asset_id,
                      ts_s, ts_ns, mtype_id, value, event_type,
                      accepted, taus):
        offset = (jax.lax.axis_index(SHARD_AXIS).astype(jnp.int32)
                  * rows_per_shard)
        local_id = device_id - offset
        owned = (local_id >= 0) & (local_id < rows_per_shard)
        feats, trail = rules_prepare_batch(
            trail_ts, trail_ns, trail_v, trail_ewma, dev_attr,
            asset_attr, jnp.where(owned, local_id, NULL_ID), asset_id,
            ts_s, ts_ns, mtype_id, value, event_type,
            accepted & owned, taus)
        own_f = owned.astype(jnp.float32)
        shifted = BatchFeatures(
            ewma=feats.ewma * own_f[:, None],
            rate=feats.rate * own_f,
            rate_valid=feats.rate_valid & owned,
            # +1 shift: psum of zeros from non-owner shards recovers
            # NULL_ID (-1) for rows no shard owns, the attr value itself
            # for owned rows
            dev_attr=jnp.where(owned[:, None], feats.dev_attr + 1, 0),
            asset_attr=feats.asset_attr + 1,
        )
        summed = jax.tree_util.tree_map(
            lambda x: jax.lax.psum(
                x.astype(jnp.int32) if x.dtype == bool else x, SHARD_AXIS),
            shifted)
        n = jax.lax.psum(1, SHARD_AXIS)
        feats_out = BatchFeatures(
            ewma=summed.ewma, rate=summed.rate,
            rate_valid=summed.rate_valid > 0,
            dev_attr=summed.dev_attr - 1,
            # the asset table is replicated: every shard contributes the
            # same shifted row, so divide the psum back out
            asset_attr=summed.asset_attr // n - 1,
        )
        return feats_out, trail

    return jax.jit(shard_map(
        local_prepare, mesh=mesh, in_specs=in_specs,
        out_specs=out_specs))


__all__ = [
    "GroupTables", "BatchFeatures", "rules_group_eval",
    "rules_prepare_batch", "kernel_for", "prepare_kernel",
    "compile_count", "structure_keys_compiled", "reset_trace_cache",
    "sharded_prepare",
]
