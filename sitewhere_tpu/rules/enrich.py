"""Metadata-join enrichment tables: on-device device/asset attributes.

Rule programs join against operational metadata — firmware generation,
site class, maintenance flag, asset criticality — that lives outside the
event stream.  Following the local-vs-external join tradeoff analysis in
PAPERS.md (arXiv 2307.14287: per-event external lookups serialize the
pipeline; co-partitioned local state joins at memory bandwidth), the
attributes live in dense int32 tables on device, row-indexed by the SAME
dense ids the pipeline enriches with:

- the device table shards by ``device_id // rows_per_shard`` exactly
  like ``DeviceState`` — the join is a shard-local gather, no
  cross-device traffic (``compile.sharded_prepare`` takes the shard);
- the asset table is replicated (small by construction: asset catalogs
  are orders of magnitude smaller than device fleets), so asset joins
  never care which shard a row landed on.

Columns are minted by name (``resolve()`` is the DSL's attribute-column
resolver) and bounded: the per-row gather cost in the prepare kernel is
O(columns), so the ceiling is a schema decision, not a config knob.
Mutations are host-side writes under a lock; :meth:`publish` snapshots
both tables into an immutable epoch the eval thread reads — same
double-buffer discipline as the program registry, so an attribute flip
under traffic is one device put, never a stall.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.rules.dsl import RuleProgramError
from sitewhere_tpu.schema import pow2_at_least

MAX_ATTR_COLUMNS = 8


@dataclass(frozen=True)
class AttrEpoch:
    """Published, immutable device arrays: ``[N, A]`` int32 each."""

    epoch: int
    device: jnp.ndarray
    asset: jnp.ndarray


class AttributeStore:
    """Named int32 attribute columns for devices and assets."""

    def __init__(self, device_capacity: int, asset_capacity: int = 1024,
                 max_columns: int = MAX_ATTR_COLUMNS):
        self.max_columns = int(max_columns)
        self._lock = threading.RLock()
        self._cols: Dict[str, Dict[str, int]] = {"device": {}, "asset": {}}
        self._host = {
            "device": np.full((pow2_at_least(device_capacity, 8),
                               self.max_columns), NULL_ID, np.int32),
            "asset": np.full((pow2_at_least(asset_capacity, 8),
                              self.max_columns), NULL_ID, np.int32),
        }
        self._dirty = True
        self._epoch: Optional[AttrEpoch] = None
        self._epoch_id = 0

    def _table(self, table: str) -> np.ndarray:
        if table not in self._host:
            raise RuleProgramError(f"attr table must be one of "
                                   f"{sorted(self._host)}")
        return self._host[table]

    def resolve(self, table: str, name: str) -> int:
        """Mint (or look up) a column index — the DSL's attribute
        resolver, so registering a program defines its columns."""
        with self._lock:
            self._table(table)
            cols = self._cols[table]
            idx = cols.get(name)
            if idx is None:
                if len(cols) >= self.max_columns:
                    raise RuleProgramError(
                        f"{table} attribute column limit "
                        f"{self.max_columns} reached (columns: "
                        f"{sorted(cols)})")
                idx = len(cols)
                cols[name] = idx
            return idx

    def columns(self, table: str) -> Dict[str, int]:
        with self._lock:
            return dict(self._cols[table])

    def set(self, table: str, entity_id: int, column: str,
            value: int) -> None:
        """Set one attribute (NULL_ID clears: an unset attribute never
        matches a join predicate)."""
        with self._lock:
            host = self._table(table)
            eid = int(entity_id)
            if not (0 <= eid < host.shape[0]):
                raise RuleProgramError(
                    f"{table} id {eid} outside capacity {host.shape[0]}")
            host[eid, self.resolve(table, column)] = np.int32(value)
            self._dirty = True

    def set_many(self, table: str, entity_ids, column: str,
                 values) -> None:
        with self._lock:
            host = self._table(table)
            col = self.resolve(table, column)
            ids = np.asarray(entity_ids, np.int64)
            if ids.size and (ids.min() < 0
                             or ids.max() >= host.shape[0]):
                raise RuleProgramError(
                    f"{table} ids outside capacity {host.shape[0]}")
            host[ids, col] = np.asarray(values, np.int32)
            self._dirty = True

    def publish(self) -> AttrEpoch:
        """Snapshot both tables into a fresh immutable epoch when dirty
        (double-buffered: readers of the outgoing epoch are unaffected)."""
        with self._lock:
            if self._dirty or self._epoch is None:
                self._epoch_id += 1
                self._epoch = AttrEpoch(
                    epoch=self._epoch_id,
                    device=jnp.asarray(self._host["device"]),
                    asset=jnp.asarray(self._host["asset"]),
                )
                self._dirty = False
            return self._epoch

    # -- checkpoint plane ----------------------------------------------------

    def snapshot_payload(self) -> Tuple[dict, Dict[str, np.ndarray]]:
        """(column maps, host arrays) — folded into the engine's
        StateProvider section alongside the program registry."""
        with self._lock:
            return ({t: dict(c) for t, c in self._cols.items()},
                    {t: a.copy() for t, a in self._host.items()})

    def restore_payload(self, cols: dict, arrays: Dict[str, np.ndarray]
                        ) -> None:
        with self._lock:
            for table in self._host:
                self._cols[table] = {str(k): int(v) for k, v in
                                     (cols.get(table) or {}).items()}
                arr = arrays.get(table)
                if arr is not None:
                    host = self._host[table]
                    n = min(host.shape[0], arr.shape[0])
                    a = min(host.shape[1], arr.shape[1])
                    host.fill(NULL_ID)
                    host[:n, :a] = np.asarray(arr, np.int32)[:n, :a]
            self._dirty = True


__all__ = ["AttributeStore", "AttrEpoch", "MAX_ATTR_COLUMNS"]
