"""Bring-your-own-rules DSL: declarative per-tenant rule programs.

The reference platform's scenario diversity came from user-supplied
Groovy scripts hot-loaded into every microservice — arbitrary host code,
one interpreter activation per event.  Here the same surface is a small
declarative language whose programs COMPILE: a program is a disjunction
of conjunctive clauses over typed predicates (threshold / EWMA-window /
rate, geofence containment, metadata-join attribute compares, event-type
gates), and every constant in it — thresholds, polygon vertices, window
choices, attribute ids — is lifted out of the program body into operand
tables.  What remains is the *structure*: padded clause/predicate counts
plus whether the geofence lane is live.  Programs sharing a structure
share one jitted kernel (see ``rules/compile.py``), which is how 100k
tenant programs collapse into single-digit compiled shapes.

Structure-key contract
----------------------
``structure_key()`` maps a canonical program to one of at most
``len(CLAUSE_BUCKETS) * len(PRED_BUCKETS) * 2`` strings (8 with the
default buckets).  The key depends ONLY on padded shape + geo-lane
presence — never on constants — so swapping a tenant's thresholds,
polygons or alert levels can never mint a new kernel.  The bound is a
*guarantee by construction*, not a property of any particular workload:
``tools/rulebench.py`` loads 100k skewed synthetic programs and measures
exactly this.

Normal form: ``when`` is normalized to DNF — ``{"any": [{"all": [...]},
...]}`` — with clause/predicate lists canonically sorted (AND/OR are
commutative), so programs that differ only in spelling order share
structure AND operand layout.  Nested ``any`` inside ``all`` is rejected
(v1 keeps the kernel a fixed two-level reduction; de Morgan rewrites are
the caller's job).
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.schema import (
    AlertLevel,
    ComparisonOp,
    DEFAULT_EWMA_HALFLIVES_S,
    EventType,
)

# -- limits (the structure-bucket ladder) -----------------------------------

MAX_CLAUSES = 4
MAX_PREDS = 8
# padded sizes snap UP onto these rungs; the coarse floors are what caps
# the distinct-shape count at 2 * 2 * 2 = 8 regardless of program mix
CLAUSE_BUCKETS = (2, 4)
PRED_BUCKETS = (4, 8)
MAX_POLY_VERTS = 8
MAX_STRUCTURE_KEYS = len(CLAUSE_BUCKETS) * len(PRED_BUCKETS) * 2

# -- predicate kinds (operand-table codes) ----------------------------------

PK_PAD = 0          # padding slot: identity under AND
PK_VALUE = 1        # instantaneous measurement value vs threshold
PK_EWMA = 2         # trailing EWMA (window_s snaps to a shared timescale)
PK_RATE = 3         # rate of change since the device's previous sample
PK_GEO = 4          # geofence containment (polygon in the group's pool)
PK_ATTR = 5         # device/asset attribute compare (metadata join)
PK_EVENT_TYPE = 6   # event-type gate

_PRED_NAMES = {
    "value": PK_VALUE, "ewma": PK_EWMA, "rate": PK_RATE,
    "geo": PK_GEO, "attr": PK_ATTR, "event_type": PK_EVENT_TYPE,
}

_OP_NAMES = {
    "gt": ComparisonOp.GT, "lt": ComparisonOp.LT,
    "gte": ComparisonOp.GTE, "lte": ComparisonOp.LTE,
    "eq": ComparisonOp.EQ, "neq": ComparisonOp.NEQ,
}

_LEVEL_NAMES = {
    "info": AlertLevel.INFO, "warning": AlertLevel.WARNING,
    "error": AlertLevel.ERROR, "critical": AlertLevel.CRITICAL,
}

# Alert events are the one type a program may NOT gate on: BYO programs
# evaluate device telemetry; matching the engine's own (or the built-in
# path's) derived alerts would self-amplify through the re-injection
# loop.  The engine additionally masks ALERT rows at eval time.
_EVENT_TYPE_NAMES = {
    t.name.lower(): int(t) for t in EventType if t != EventType.ALERT
}

ATTR_TABLE_DEVICE = 0
ATTR_TABLE_ASSET = 1
_ATTR_TABLES = {"device": ATTR_TABLE_DEVICE, "asset": ATTR_TABLE_ASSET}


class RuleProgramError(ValueError):
    """Validation failure for a rule-program doc (maps to HTTP 400)."""


@dataclass(frozen=True)
class CanonicalPred:
    """One predicate slot in canonical operand form.

    Every constant lives in the operand fields — ``f0`` (float compare
    value), ``i0``/``i1``/``i2`` (int operands, meaning per ``kind``;
    see ``rules/compile.py`` for the kernel-side decode) — plus the
    polygon ring for geo predicates (pooled per group at build time).
    """

    kind: int
    op: int = 0
    f0: float = 0.0
    i0: int = NULL_ID
    i1: int = 0
    i2: int = 0
    polygon: Optional[Tuple[Tuple[float, float], ...]] = None

    def sort_key(self) -> tuple:
        return (self.kind, self.op, self.i0, self.i1, self.i2, self.f0,
                self.polygon or ())


@dataclass(frozen=True)
class CanonicalProgram:
    """A validated, canonically-ordered program ready for bucketing."""

    token: str
    name: str
    alert_type: str
    alert_level: int
    clauses: Tuple[Tuple[CanonicalPred, ...], ...]
    doc: str = ""  # original JSON doc (checkpoint round-trip carrier)

    @property
    def n_clauses(self) -> int:
        return len(self.clauses)

    @property
    def max_preds(self) -> int:
        return max(len(c) for c in self.clauses)

    def structure_key(self) -> str:
        return structure_key(self)


def _bucket(n: int, rungs: Sequence[int], what: str) -> int:
    for r in rungs:
        if n <= r:
            return r
    raise RuleProgramError(f"{what} count {n} exceeds the maximum "
                           f"{rungs[-1]}")


def structure_key(prog: CanonicalProgram) -> str:
    """The bucketed shape identity: ``c{C}p{P}`` plus a ``g`` suffix when
    the geofence lane is live.  Constants never appear here — that is the
    whole hot-swap contract."""
    c = _bucket(prog.n_clauses, CLAUSE_BUCKETS, "clause")
    p = _bucket(prog.max_preds, PRED_BUCKETS, "predicate")
    geo = any(pr.kind == PK_GEO for cl in prog.clauses for pr in cl)
    return f"c{c}p{p}" + ("g" if geo else "")


def snap_window_idx(window_s: float,
                    halflives_s: Sequence[float] = DEFAULT_EWMA_HALFLIVES_S
                    ) -> int:
    """Snap a requested EWMA window to the nearest shared timescale.

    The trailing state carries one EWMA per shared halflife (exactly the
    ``DeviceState`` contract) — per-program timescales would turn the
    window choice into a *shape* and defeat bucketing, so the window is
    an operand: an index into the shared ladder."""
    if not (window_s > 0):
        raise RuleProgramError(f"window_s must be > 0, got {window_s!r}")
    return int(min(range(len(halflives_s)),
                   key=lambda i: abs(math.log(window_s)
                                     - math.log(halflives_s[i]))))


def _parse_pred(doc: dict, resolve_mtype, resolve_attr) -> CanonicalPred:
    if not isinstance(doc, dict) or "pred" not in doc:
        raise RuleProgramError(f"predicate must be an object with a "
                               f"'pred' field, got {doc!r}")
    kind = _PRED_NAMES.get(doc["pred"])
    if kind is None:
        raise RuleProgramError(
            f"unknown predicate {doc['pred']!r} (one of "
            f"{sorted(_PRED_NAMES)})")

    def op_of(default: Optional[str] = None) -> int:
        raw = doc.get("op", default)
        if raw not in _OP_NAMES:
            raise RuleProgramError(f"unknown op {raw!r} (one of "
                                   f"{sorted(_OP_NAMES)})")
        return int(_OP_NAMES[raw])

    if kind in (PK_VALUE, PK_EWMA, PK_RATE):
        if "value" not in doc:
            raise RuleProgramError(f"{doc['pred']!r} predicate needs a "
                                   "numeric 'value' threshold")
        thr = float(doc["value"])
        mtype = NULL_ID
        if doc.get("mtype") is not None:
            if resolve_mtype is None:
                raise RuleProgramError("mtype filters need a measurement-"
                                       "type resolver")
            mtype = int(resolve_mtype(str(doc["mtype"])))
        widx = 0
        if kind == PK_EWMA:
            widx = snap_window_idx(float(doc.get("window_s", 0) or 0))
        return CanonicalPred(kind=kind, op=op_of(), f0=thr, i0=mtype,
                             i1=widx)

    if kind == PK_GEO:
        poly = doc.get("polygon")
        if (not isinstance(poly, (list, tuple)) or len(poly) < 3
                or len(poly) > MAX_POLY_VERTS
                or not all(isinstance(v, (list, tuple)) and len(v) == 2
                           for v in poly)):
            raise RuleProgramError(
                "geo predicate needs 'polygon': [[lon, lat] x 3.."
                f"{MAX_POLY_VERTS}]")
        inside = bool(doc.get("inside", True))
        ring = tuple((float(v[0]), float(v[1])) for v in poly)
        return CanonicalPred(kind=kind, i0=1 if inside else 0,
                             polygon=ring)

    if kind == PK_ATTR:
        table = _ATTR_TABLES.get(doc.get("table", "device"))
        if table is None:
            raise RuleProgramError(f"attr table must be one of "
                                   f"{sorted(_ATTR_TABLES)}")
        col_name = doc.get("column")
        if not col_name:
            raise RuleProgramError("attr predicate needs a 'column' name")
        if resolve_attr is None:
            raise RuleProgramError("attr predicates need an attribute-"
                                   "column resolver")
        col = int(resolve_attr(
            "device" if table == ATTR_TABLE_DEVICE else "asset",
            str(col_name)))
        if "value" not in doc:
            raise RuleProgramError("attr predicate needs an integer "
                                   "'value' to compare against")
        return CanonicalPred(kind=kind, op=op_of("eq"),
                             i0=int(doc["value"]), i1=col, i2=table)

    # PK_EVENT_TYPE
    et = _EVENT_TYPE_NAMES.get(str(doc.get("value", "")).lower())
    if et is None:
        raise RuleProgramError(
            f"event_type predicate value must be one of "
            f"{sorted(_EVENT_TYPE_NAMES)} (alert events are reserved "
            "for the derived-alert path)")
    return CanonicalPred(kind=PK_EVENT_TYPE, op=op_of("eq"), i0=et)


def _normalize_when(when) -> List[List[dict]]:
    """Normalize ``when`` to DNF clause lists; reject deeper nesting."""
    if isinstance(when, dict) and "any" in when:
        clauses = when["any"]
        if not isinstance(clauses, (list, tuple)) or not clauses:
            raise RuleProgramError("'any' needs a non-empty clause list")
        out = []
        for cl in clauses:
            if isinstance(cl, dict) and "all" in cl:
                preds = cl["all"]
            elif isinstance(cl, dict) and "any" in cl:
                raise RuleProgramError("nested 'any' is not supported — "
                                       "flatten to one level of any-of-all")
            else:
                preds = [cl]
            if not isinstance(preds, (list, tuple)) or not preds:
                raise RuleProgramError("'all' needs a non-empty "
                                       "predicate list")
            out.append(list(preds))
        return out
    if isinstance(when, dict) and "all" in when:
        preds = when["all"]
        if not isinstance(preds, (list, tuple)) or not preds:
            raise RuleProgramError("'all' needs a non-empty predicate list")
        if any(isinstance(p, dict) and ("any" in p or "all" in p)
               for p in preds):
            raise RuleProgramError("nested combinators inside 'all' are "
                                   "not supported")
        return [list(preds)]
    if isinstance(when, dict) and "pred" in when:
        return [[when]]
    raise RuleProgramError("'when' must be a predicate, {'all': [...]} "
                           "or {'any': [{'all': [...]} ...]}")


def parse_program(doc: dict,
                  resolve_mtype: Optional[Callable[[str], int]] = None,
                  resolve_attr: Optional[Callable[[str, str], int]] = None,
                  ) -> CanonicalProgram:
    """Validate + canonicalize one program doc.

    Raises :class:`RuleProgramError` on any malformed field so a bad
    spec fails the POST, never the first traffic batch (the same
    compile-at-registration contract as ``analytics.runner.register``).
    """
    if not isinstance(doc, dict):
        raise RuleProgramError("program must be a JSON object")
    token = str(doc.get("token") or "").strip()
    if not token:
        raise RuleProgramError("program needs a non-empty 'token'")
    alert = doc.get("alert")
    if not isinstance(alert, dict) or not alert.get("type"):
        raise RuleProgramError("program needs 'alert': {'type': ..., "
                               "'level': ...}")
    level = _LEVEL_NAMES.get(str(alert.get("level", "warning")).lower())
    if level is None:
        raise RuleProgramError(f"alert level must be one of "
                               f"{sorted(_LEVEL_NAMES)}")

    raw_clauses = _normalize_when(doc.get("when"))
    if len(raw_clauses) > MAX_CLAUSES:
        raise RuleProgramError(f"{len(raw_clauses)} clauses exceeds the "
                               f"maximum {MAX_CLAUSES}")
    clauses: List[Tuple[CanonicalPred, ...]] = []
    for cl in raw_clauses:
        if len(cl) > MAX_PREDS:
            raise RuleProgramError(f"{len(cl)} predicates in one clause "
                                   f"exceeds the maximum {MAX_PREDS}")
        preds = sorted((_parse_pred(p, resolve_mtype, resolve_attr)
                        for p in cl), key=CanonicalPred.sort_key)
        clauses.append(tuple(preds))
    clauses.sort(key=lambda c: tuple(p.sort_key() for p in c))

    return CanonicalProgram(
        token=token,
        name=str(doc.get("name", token)),
        alert_type=str(alert["type"]),
        alert_level=int(level),
        clauses=tuple(clauses),
        doc=json.dumps(doc, sort_keys=True),
    )


def describe_program(prog: CanonicalProgram) -> Dict[str, object]:
    """REST body for one registered program."""
    return {
        "token": prog.token,
        "name": prog.name,
        "alert": {"type": prog.alert_type,
                  "level": AlertLevel(prog.alert_level).name.lower()},
        "structure": prog.structure_key(),
        "clauses": prog.n_clauses,
        "predicates": sum(len(c) for c in prog.clauses),
        "doc": json.loads(prog.doc) if prog.doc else None,
    }


__all__ = [
    "MAX_CLAUSES", "MAX_PREDS", "CLAUSE_BUCKETS", "PRED_BUCKETS",
    "MAX_POLY_VERTS", "MAX_STRUCTURE_KEYS",
    "PK_PAD", "PK_VALUE", "PK_EWMA", "PK_RATE", "PK_GEO", "PK_ATTR",
    "PK_EVENT_TYPE", "ATTR_TABLE_DEVICE", "ATTR_TABLE_ASSET",
    "RuleProgramError", "CanonicalPred", "CanonicalProgram",
    "parse_program", "describe_program", "structure_key",
    "snap_window_idx",
]
