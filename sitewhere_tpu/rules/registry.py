"""Per-tenant program store with epoch-published operand tables.

The ``RegistryMirror`` pattern applied to rule programs: mutations (REST
CRUD, checkpoint restore) edit a host-side program catalog under a lock;
:meth:`ProgramRegistry.publish` rebuilds the operand tables of exactly
the structure groups that changed and swaps in a new immutable
:class:`RulesEpoch`.  The eval thread grabs the current epoch once per
batch and never sees a half-built table; an in-flight batch keeps
evaluating the epoch it started with (epoch isolation — the hot-swap
tests pin this).

The hot-swap contract, concretely: editing a program whose structure key
already exists changes only operand *values* — array shapes are
identical, the structure-keyed kernel cache (``rules/compile.py``) is
untouched, and the swap costs one host build + device put.  Only a
genuinely novel structure (or a power-of-two capacity step: program
rows, tenant map, polygon pool — all on ``pow2_at_least`` ladders, so
growth mints O(log) shapes, not O(n)) can mint a kernel, and the engine
warms it on the MUTATING thread before the epoch becomes current, so
traffic never pays a compile (``engine.RuleEngineRunner.refresh``).
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.ops.geo import pad_polygon
from sitewhere_tpu.rules import compile as rcompile
from sitewhere_tpu.rules.dsl import (
    CanonicalProgram,
    MAX_POLY_VERTS,
    PK_GEO,
    RuleProgramError,
    describe_program,
    parse_program,
)
from sitewhere_tpu.schema import pow2_at_least

_CHECKPOINT_VERSION = 1


@dataclass(frozen=True)
class GroupEpoch:
    """One structure group's published, immutable device tables."""

    key: str
    has_geo: bool
    tables: rcompile.GroupTables
    eval_fn: object
    n_programs: int

    def shape_sig(self) -> tuple:
        """The shape identity a compile is keyed on (structure key plus
        the pow2 capacities) — the engine warms one dummy eval per
        unseen signature."""
        return (self.key,) + tuple(
            tuple(a.shape) for a in self.tables)


@dataclass(frozen=True)
class RulesEpoch:
    """The registry's published world: read atomically by the eval
    thread, replaced wholesale by :meth:`ProgramRegistry.publish`."""

    epoch: int
    groups: Tuple[GroupEpoch, ...]


@dataclass
class _Program:
    tenant: int
    canonical: CanonicalProgram
    alert_code: int


class _Group:
    def __init__(self, key: str):
        self.key = key
        self.programs: Dict[Tuple[int, str], _Program] = {}
        self.dirty = True
        self.built: Optional[GroupEpoch] = None

    def tenant_count(self, tenant: int) -> int:
        return sum(1 for (t, _tok) in self.programs if t == tenant)


class ProgramRegistry:
    """Host-side program catalog + operand-table builder."""

    def __init__(self,
                 programs_per_tenant: int = 4,
                 max_programs: int = 262144,
                 tenant_floor: int = 64,
                 resolve_alert: Optional[Callable[[str], int]] = None,
                 resolve_mtype: Optional[Callable[[str], int]] = None,
                 resolve_attr: Optional[Callable[[str, str], int]] = None):
        self.programs_per_tenant = int(programs_per_tenant)
        self.max_programs = int(max_programs)
        self.tenant_floor = int(tenant_floor)
        self.resolve_alert = resolve_alert or self._default_mint
        self.resolve_mtype = resolve_mtype
        self.resolve_attr = resolve_attr
        self._alert_codes: Dict[str, int] = {}
        self._lock = threading.RLock()
        self._groups: Dict[str, _Group] = {}
        self._by_token: Dict[Tuple[int, str], str] = {}  # -> group key
        self._max_tenant = -1
        self._epoch: Optional[RulesEpoch] = None
        self._epoch_id = 0
        # counters the engine publishes as the rules.* family
        self.swaps = 0          # publishes that rebuilt >= 1 group
        self.builds = 0         # group table rebuilds (host + H2D)

    def _default_mint(self, alert_type: str) -> int:
        code = self._alert_codes.get(alert_type)
        if code is None:
            code = len(self._alert_codes)
            self._alert_codes[alert_type] = code
        return code

    # -- CRUD ----------------------------------------------------------------

    def put_program(self, tenant: int, doc: dict) -> Dict[str, object]:
        """Create or replace one tenant program (validated + canonical
        BEFORE any state changes, so a bad doc can never dirty a group)."""
        prog = parse_program(doc, resolve_mtype=self.resolve_mtype,
                             resolve_attr=self.resolve_attr)
        tenant = int(tenant)
        if tenant < 0:
            raise RuleProgramError(f"bad tenant id {tenant}")
        key = prog.structure_key()
        code = int(self.resolve_alert(prog.alert_type))
        with self._lock:
            handle = (tenant, prog.token)
            old_key = self._by_token.get(handle)
            group = self._groups.get(key)
            if group is None:
                group = _Group(key)
            per_tenant = group.tenant_count(tenant)
            if old_key == key:
                per_tenant -= 1  # replacing in place
            if per_tenant >= self.programs_per_tenant:
                raise RuleProgramError(
                    f"tenant has {self.programs_per_tenant} programs of "
                    f"structure {key!r} already (raise "
                    "rules.programs_per_tenant or vary the structure)")
            if old_key is None \
                    and self.program_count() >= self.max_programs:
                raise RuleProgramError(
                    f"program limit {self.max_programs} reached")
            if old_key is not None and old_key != key:
                old = self._groups[old_key]
                old.programs.pop(handle, None)
                old.dirty = True
                if not old.programs:
                    del self._groups[old_key]
            self._groups.setdefault(key, group)
            group.programs[handle] = _Program(tenant, prog, code)
            group.dirty = True
            self._by_token[handle] = key
            self._max_tenant = max(self._max_tenant, tenant)
        return describe_program(prog)

    def delete_program(self, tenant: int, token: str) -> bool:
        with self._lock:
            handle = (int(tenant), str(token))
            key = self._by_token.pop(handle, None)
            if key is None:
                return False
            group = self._groups[key]
            group.programs.pop(handle, None)
            group.dirty = True
            if not group.programs:
                del self._groups[key]
            return True

    def get_program(self, tenant: int, token: str
                    ) -> Optional[Dict[str, object]]:
        with self._lock:
            key = self._by_token.get((int(tenant), str(token)))
            if key is None:
                return None
            prog = self._groups[key].programs[(int(tenant), str(token))]
        return describe_program(prog.canonical)

    def list_programs(self, tenant: Optional[int] = None
                      ) -> List[Dict[str, object]]:
        with self._lock:
            progs = [p for g in self._groups.values()
                     for (t, _tok), p in sorted(g.programs.items())
                     if tenant is None or t == int(tenant)]
        return [describe_program(p.canonical) for p in progs]

    def program_count(self) -> int:
        with self._lock:
            return sum(len(g.programs) for g in self._groups.values())

    def group_count(self) -> int:
        with self._lock:
            return len(self._groups)

    # -- epoch build ---------------------------------------------------------

    def _build_group(self, group: _Group) -> GroupEpoch:
        from sitewhere_tpu.rules.dsl import CLAUSE_BUCKETS, PRED_BUCKETS

        progs = [group.programs[h] for h in sorted(group.programs)]
        has_geo = group.key.endswith("g")
        # padded shape straight from the structure key — every group
        # with this key builds congruent tables
        c_pad = int(group.key[1:group.key.index("p")])
        p_pad = int(group.key[group.key.index("p") + 1:].rstrip("g"))
        G = pow2_at_least(len(progs), 8)
        T = pow2_at_least(self._max_tenant + 1, self.tenant_floor)
        S = self.programs_per_tenant

        kind = np.zeros((G, c_pad, p_pad), np.int32)
        pint = np.zeros((G, c_pad, p_pad, 4), np.int32)
        pf = np.zeros((G, c_pad, p_pad), np.float32)
        meta = np.full((G, 4), NULL_ID, np.int32)
        meta[:, 3] = 0
        slots = np.full((T, S), NULL_ID, np.int32)
        polys: List[np.ndarray] = []

        for row, p in enumerate(progs):
            meta[row] = (p.tenant, p.alert_code,
                         p.canonical.alert_level, 1)
            free = np.nonzero(slots[p.tenant] == NULL_ID)[0]
            slots[p.tenant, free[0]] = row
            for ci, clause in enumerate(p.canonical.clauses):
                for pi, pred in enumerate(clause):
                    i1 = pred.i1
                    if pred.kind == PK_GEO:
                        i1 = len(polys)
                        polys.append(pad_polygon(pred.polygon,
                                                 MAX_POLY_VERTS))
                    kind[row, ci, pi] = pred.kind
                    pint[row, ci, pi] = (pred.op, pred.i0, i1, pred.i2)
                    pf[row, ci, pi] = np.float32(pred.f0)

        Z = pow2_at_least(len(polys), 8)
        verts = np.zeros((Z if has_geo else 1, MAX_POLY_VERTS, 2),
                         np.float32)
        if polys:
            verts[:len(polys)] = np.stack(polys)

        tables = rcompile.GroupTables(
            kind=jnp.asarray(kind), pint=jnp.asarray(pint),
            pf=jnp.asarray(pf), meta=jnp.asarray(meta),
            slots=jnp.asarray(slots), verts=jnp.asarray(verts))
        self.builds += 1
        return GroupEpoch(key=group.key, has_geo=has_geo, tables=tables,
                          eval_fn=rcompile.kernel_for(group.key),
                          n_programs=len(progs))

    def publish(self) -> Optional[RulesEpoch]:
        """Rebuild dirty groups and swap in a fresh epoch (double-buffer:
        the outgoing epoch's arrays are never touched).  Returns the
        current epoch, or None when no programs exist."""
        with self._lock:
            if not self._groups:
                self._epoch = None
                return None
            changed = False
            groups: List[GroupEpoch] = []
            for key in sorted(self._groups):
                g = self._groups[key]
                if g.dirty or g.built is None:
                    g.built = self._build_group(g)
                    g.dirty = False
                    changed = True
                groups.append(g.built)
            if changed or self._epoch is None:
                self._epoch_id += 1
                self.swaps += 1
                self._epoch = RulesEpoch(self._epoch_id, tuple(groups))
            return self._epoch

    def current_epoch(self) -> Optional[RulesEpoch]:
        return self._epoch

    def structure_keys(self) -> List[str]:
        with self._lock:
            return sorted(self._groups)

    # -- checkpoint plane ----------------------------------------------------

    def snapshot_payload(self) -> Tuple[bytes, Optional[dict]]:
        """StateProvider body: the program DOCS (the durable identity —
        operand tables and kernels are derived state, rebuilt on the
        first post-restore publish)."""
        with self._lock:
            progs = [{"tenant": t, "doc": json.loads(p.canonical.doc)}
                     for g in self._groups.values()
                     for (t, _tok), p in sorted(g.programs.items())]
            doc = {"version": _CHECKPOINT_VERSION, "programs": progs,
                   "max_tenant": self._max_tenant}
        return (json.dumps(doc).encode(),
                {"programs": len(progs), "epoch": self._epoch_id})

    def restore_payload(self, header: dict, payload: bytes) -> None:
        doc = json.loads(payload.decode())
        with self._lock:
            self._groups.clear()
            self._by_token.clear()
            self._epoch = None
            self._max_tenant = int(doc.get("max_tenant", -1))
        for entry in doc.get("programs", []):
            self.put_program(int(entry["tenant"]), entry["doc"])


__all__ = ["ProgramRegistry", "RulesEpoch", "GroupEpoch"]
