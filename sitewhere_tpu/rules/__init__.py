"""Bring-your-own-rules: per-tenant rule & enrichment programs compiled
into a bounded set of batched kernels.

- ``dsl``       — declarative program documents, validation, canonical
                  form, and the structure key that buckets programs;
- ``interp``    — slow numpy reference interpreter (golden semantics);
- ``compile``   — the bucketing compiler: one jitted kernel group per
                  structure key, constants lifted into operand tables;
- ``registry``  — per-tenant store with epoch-published operand tables
                  (hot-swap under traffic, zero recompiles);
- ``enrich``    — sharded/replicated on-device attribute tables for
                  metadata-join predicates;
- ``engine``    — the lifecycle runner wired into the dispatcher.
"""

from sitewhere_tpu.rules.dsl import (  # noqa: F401
    RuleProgramError,
    parse_program,
    structure_key,
)
from sitewhere_tpu.rules.engine import RuleEngineRunner  # noqa: F401
from sitewhere_tpu.rules.enrich import AttributeStore  # noqa: F401
from sitewhere_tpu.rules.registry import ProgramRegistry  # noqa: F401

__all__ = [
    "RuleProgramError",
    "parse_program",
    "structure_key",
    "RuleEngineRunner",
    "AttributeStore",
    "ProgramRegistry",
]
