"""Reference interpreter: the golden semantics for rule programs.

Slow, obvious numpy — one program, one row, one predicate at a time.
``rules/compile.py`` is REQUIRED to agree with this module bit-for-bit
on fired alerts and enrichment values (the tier-1 golden-equivalence
tests drive both over the same random program/event streams, including
the mesh-sharded prepare path), so every semantic question about the
DSL is answered HERE, in straight-line code:

- float predicates (value / ewma / rate) apply only to MEASUREMENT rows
  and honor the optional mtype filter; rate additionally needs a seeded
  previous sample with positive dt;
- the trailing state folds with the irregular-sampling EWMA
  (``alpha = 1 - exp(-dt/tau)``, float32 throughout) and each
  (device, mtype-slot) stores the batch's newest-(ts_s, ts_ns) row,
  highest batch row winning exact ties — the ``scatter_last_by_time``
  contract;
- geo predicates apply to LOCATION rows; containment uses the same
  slope-first ray-crossing arithmetic as ``ops/geo``;
- attr predicates join the device/asset attribute tables; unset
  attributes (NULL_ID) never match;
- ALERT rows are never evaluated (re-injection loop guard);
- a clause of nothing but padding never fires.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.rules.dsl import (
    ATTR_TABLE_ASSET,
    CanonicalPred,
    CanonicalProgram,
    PK_ATTR,
    PK_EVENT_TYPE,
    PK_EWMA,
    PK_GEO,
    PK_PAD,
    PK_RATE,
    PK_VALUE,
)
from sitewhere_tpu.schema import ComparisonOp, EventType


class InterpTrail:
    """Host mirror of the engine's trailing per-(device, slot) state."""

    def __init__(self, capacity: int, n_mtype_slots: int, n_scales: int):
        self.D = int(capacity)
        self.M = int(n_mtype_slots)
        self.K = int(n_scales)
        self.ts_s = np.zeros((self.D, self.M), np.int32)
        self.ts_ns = np.zeros((self.D, self.M), np.int32)
        self.value = np.zeros((self.D, self.M), np.float32)
        self.ewma = np.zeros((self.D, self.M, self.K), np.float32)


def _compare(op: int, val, thr) -> bool:
    if op == ComparisonOp.GT:
        return bool(val > thr)
    if op == ComparisonOp.LT:
        return bool(val < thr)
    if op == ComparisonOp.GTE:
        return bool(val >= thr)
    if op == ComparisonOp.LTE:
        return bool(val <= thr)
    if op == ComparisonOp.EQ:
        return bool(val == thr)
    return bool(val != thr)


def _point_in_polygon(px: float, py: float, ring) -> bool:
    verts = np.asarray(ring, np.float32)
    if len(verts) < 8:  # mirror the pool's pad-with-last-vertex contract
        pad = np.repeat(verts[-1:], 8 - len(verts), axis=0)
        verts = np.concatenate([verts, pad])
    crossings = 0
    V = len(verts)
    for i in range(V):
        x1, y1 = np.float32(verts[i][0]), np.float32(verts[i][1])
        x2, y2 = (np.float32(verts[(i + 1) % V][0]),
                  np.float32(verts[(i + 1) % V][1]))
        straddles = (y1 > py) != (y2 > py)
        denom = np.float32(1.0) if y2 == y1 else y2 - y1
        slope = (x2 - x1) / denom
        x_cross = slope * (np.float32(py) - y1) + x1
        if straddles and np.float32(px) < x_cross:
            crossings += 1
    return crossings % 2 == 1


def interp_features(
    trail: InterpTrail,
    cols: Dict[str, np.ndarray],
    taus: Sequence[float],
    dev_attr: np.ndarray,
    asset_attr: np.ndarray,
) -> Dict[str, np.ndarray]:
    """Per-row features for one batch + in-place trail update.

    Mirrors ``rules_prepare_batch``: fold every row against the
    PRE-batch trail, then store each slot's winner.  All float math in
    float32."""
    B = len(cols["device_id"])
    K = trail.K
    taus32 = np.asarray(taus, np.float32)
    accepted = np.asarray(cols.get("accepted",
                                   np.ones(B, bool))).astype(bool)
    ewma_new = np.zeros((B, K), np.float32)
    rate = np.zeros(B, np.float32)
    rate_valid = np.zeros(B, bool)
    da = np.full((B, dev_attr.shape[1]), NULL_ID, np.int32)
    aa = np.full((B, asset_attr.shape[1]), NULL_ID, np.int32)

    for b in range(B):
        did = int(cols["device_id"][b])
        mt = int(cols["mtype_id"][b])
        slot = mt % trail.M if mt >= 0 else 0
        d = min(max(did, 0), trail.D - 1)
        prev_ts = np.int32(trail.ts_s[d, slot])
        prev_ns = np.int32(trail.ts_ns[d, slot])
        prev_v = np.float32(trail.value[d, slot])
        seeded = prev_ts > 0
        dt = np.float32(max(
            np.float32(np.int32(cols["ts_s"][b]) - prev_ts)
            + np.float32(np.int32(cols["ts_ns"][b]) - prev_ns)
            * np.float32(1e-9), np.float32(0.0)))
        v = np.float32(cols["value"][b])
        is_meas = (accepted[b]
                   and int(cols["event_type"][b]) == EventType.MEASUREMENT)
        if seeded:
            alpha = np.float32(1.0) - np.exp(
                -dt / np.maximum(taus32, np.float32(1e-9)))
            ewma_new[b] = trail.ewma[d, slot] + alpha * (
                v - trail.ewma[d, slot])
        else:
            ewma_new[b] = v
        if seeded and dt > 0 and is_meas:
            rate_valid[b] = True
            rate[b] = (v - prev_v) / np.maximum(dt, np.float32(1e-9))
        if 0 <= did < dev_attr.shape[0]:
            da[b] = dev_attr[did]
        aid = int(cols.get("asset_id", np.full(B, NULL_ID))[b])
        if 0 <= aid < asset_attr.shape[0]:
            aa[b] = asset_attr[aid]

    # winner scatter: newest (ts_s, ts_ns), highest row on ties, events
    # winning exact ties against the stored slot key
    winners: Dict[Tuple[int, int], int] = {}
    for b in range(B):
        did = int(cols["device_id"][b])
        mt = int(cols["mtype_id"][b])
        is_meas = (accepted[b]
                   and int(cols["event_type"][b]) == EventType.MEASUREMENT)
        if not is_meas or not (0 <= did < trail.D):
            continue
        slot = mt % trail.M if mt >= 0 else 0
        key = (did, slot)
        cur = winners.get(key)
        if cur is None or (
                (int(cols["ts_s"][b]), int(cols["ts_ns"][b]), b)
                >= (int(cols["ts_s"][cur]), int(cols["ts_ns"][cur]), cur)):
            winners[key] = b
    for (did, slot), b in winners.items():
        w_s, w_ns = int(cols["ts_s"][b]), int(cols["ts_ns"][b])
        if (w_s, w_ns) >= (int(trail.ts_s[did, slot]),
                           int(trail.ts_ns[did, slot])):
            trail.ts_s[did, slot] = w_s
            trail.ts_ns[did, slot] = w_ns
            trail.value[did, slot] = np.float32(cols["value"][b])
            trail.ewma[did, slot] = ewma_new[b]

    return {"ewma": ewma_new, "rate": rate, "rate_valid": rate_valid,
            "dev_attr": da, "asset_attr": aa}


def _eval_pred(pred: CanonicalPred, b: int, cols, feats) -> bool:
    et = int(cols["event_type"][b])
    if pred.kind == PK_PAD:
        return True
    if pred.kind in (PK_VALUE, PK_EWMA, PK_RATE):
        if et != EventType.MEASUREMENT:
            return False
        if pred.i0 != NULL_ID and pred.i0 != int(cols["mtype_id"][b]):
            return False
        if pred.kind == PK_VALUE:
            val = np.float32(cols["value"][b])
        elif pred.kind == PK_EWMA:
            val = np.float32(feats["ewma"][b, pred.i1])
        else:
            if not feats["rate_valid"][b]:
                return False
            val = np.float32(feats["rate"][b])
        return _compare(pred.op, val, np.float32(pred.f0))
    if pred.kind == PK_GEO:
        if et != EventType.LOCATION:
            return False
        inside = _point_in_polygon(float(cols["lon"][b]),
                                   float(cols["lat"][b]), pred.polygon)
        return inside if pred.i0 == 1 else not inside
    if pred.kind == PK_ATTR:
        attrs = (feats["asset_attr"] if pred.i2 == ATTR_TABLE_ASSET
                 else feats["dev_attr"])
        val = int(attrs[b, pred.i1])
        if val == NULL_ID:
            return False
        return _compare(pred.op, val, pred.i0)
    # PK_EVENT_TYPE
    return _compare(pred.op, et, pred.i0)


def interp_eval(
    programs: Sequence[Tuple[int, CanonicalProgram, int]],
    cols: Dict[str, np.ndarray],
    feats: Dict[str, np.ndarray],
) -> List[Tuple[int, str, int, int]]:
    """Evaluate ``(tenant_dense, program, alert_code)`` triples over one
    prepared batch.  Returns fired ``(row, token, alert_code,
    alert_level)`` tuples in (row, token) order."""
    B = len(cols["device_id"])
    accepted = np.asarray(cols.get("accepted",
                                   np.ones(B, bool))).astype(bool)
    out: List[Tuple[int, str, int, int]] = []
    for b in range(B):
        if not accepted[b]:
            continue
        if int(cols["event_type"][b]) == EventType.ALERT:
            continue
        tid = int(cols["tenant_id"][b])
        for tenant, prog, code in programs:
            if tenant != tid:
                continue
            fired = any(
                all(_eval_pred(p, b, cols, feats) for p in clause)
                for clause in prog.clauses if clause)
            if fired:
                out.append((b, prog.token, code, prog.alert_level))
    out.sort(key=lambda t: (t[0], t[1]))
    return out


__all__ = ["InterpTrail", "interp_features", "interp_eval"]
