#!/usr/bin/env python
"""Kill-point chaos harness: prove the instance survives ``kill -9``
anywhere, with measured recovery.

The crash contract under test (runtime/checkpoint.py): restart = restore
the newest complete snapshot + replay the journal from each component's
as-of offset, converging to what an uninterrupted run produces.  The
harness makes that an experiment instead of an argument:

1. a GOLDEN child runs the fixed workload uninterrupted — its durable
   event set and analytics match set are the reference;
2. for each kill point, a fresh child runs the same workload with
   ``SW_CRASHPOINT=<point>:<n>`` armed (runtime/faults.py crosspoint),
   so the Nth crossing of a named pipeline point — mid-ring chain, after
   the journal append, mid-egress, mid-seal, mid-checkpoint-save, just
   before the manifest swap — SIGKILLs the process cold;
3. the parent restarts an instance on the survivor's data dir (restore +
   replay run inside ``Instance.start``) and asserts:
   - **zero committed-event loss**: every journaled event is in the
     event store, and events below the crash-time committed offset
     appear EXACTLY once (the store-dedup floor's no-duplicate half);
   - **analytics equivalence**: union(child's delivered matches,
     post-restore matches) == the golden match set — open windows,
     sessions and CEP stages crossed the kill;
   - **measured RTO**: ``recovery.restore_s`` / ``recovery.replay_s`` /
     ``recovery.replay_events`` gauges are exported by the restarted
     instance (reported per kill).

Usage::

    python tools/crashrec_bench.py --smoke            # 3 fixed points
    python tools/crashrec_bench.py --sweep 50 [seed]  # randomized
    python tools/crashrec_bench.py --json out.json --sweep 50

Exit status 0 = every kill recovered clean.
"""

import argparse
import json
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

WIDTH = 32
N_DEVICES = 8
N_PAYLOADS = 14
SAVE_EVERY = 4          # explicit checkpoint every K payloads
T0 = 1_754_000_000

# (crosspoint, hit count): where the child dies.  Counts are chosen so
# the point has certainly been reached mid-workload.
SMOKE_KILLS = [
    ("crash.mid_ring", 2),
    ("crash.mid_egress", 5),
    # kill a background SEAL WORKER mid-segment-write (the segment
    # store's parallel seal pool): boot must quarantine/ignore the torn
    # file and journal replay re-derives the job's rows — zero
    # committed-event loss, consistent catalog
    ("crash.mid_seal", 2),
    # kill between the merged compaction segment landing and the input
    # unlink: boot's tombstone resolution must drop the inputs (rows
    # appear exactly once), not double them
    ("crash.mid_compact", 1),
    ("crash.pre_manifest", 2),
    # kill a forward-spool sender between the spool poll and the peer
    # ack (a 2-host fleet in one process): the uncommitted spool tail
    # must replay to the owner on restart — at-least-once across the
    # DCN hop, no lost rows (runs through run_forward_kill_case)
    ("crash.mid_forward", 1),
]
SWEEP_CATALOG = {
    "crash.mid_ring": (1, 5),
    "crash.post_journal": (1, N_PAYLOADS - 1),
    "crash.mid_egress": (1, 10),
    "crash.mid_seal": (1, 4),
    "crash.mid_compact": (1, 2),
    "crash.mid_checkpoint": (1, 3),
    "crash.pre_manifest": (1, 3),
    "crash.mid_forward": (1, 3),
}

QUERY_DOCS = [
    {"kind": "window", "name": "hot-mean", "mtype": "temp", "agg": "mean",
     "op": "gt", "threshold": 20.0, "windowS": 60},
    {"kind": "session", "name": "chatty", "gapS": 30, "agg": "count",
     "op": "gt", "threshold": 10.0},
    {"kind": "pattern", "name": "spike", "windowS": 60,
     "steps": [{"eventType": "measurement", "mtype": "temp",
                "op": "gt", "threshold": 90.0}]},
]


def _config(data_dir):
    from sitewhere_tpu.runtime.config import Config

    return Config({
        "instance": {"id": "crashrec", "data_dir": data_dir},
        "pipeline": {"width": WIDTH, "registry_capacity": 256,
                     "mtype_slots": 4, "deadline_ms": 5.0, "n_shards": 1,
                     "ring_depth": 2},
        "presence": {"scan_interval_s": 3600.0, "missing_after_s": 86400},
        "checkpoint": {"interval_s": 0},   # explicit saves: deterministic
        "registration": {"default_device_type": "sensor",
                         "allow_new_devices": True},
        # shedding would turn "zero loss" into "zero loss minus audited
        # sheds" — keep the contract sharp for the harness
        "overload": {"enabled": False},
        "slo": {"enabled": False},
    }, apply_env=False)


def _make_instance(data_dir):
    from sitewhere_tpu.instance import Instance

    return Instance(_config(data_dir))


def _payload(k):
    """Payload k: WIDTH NDJSON measurement lines, globally unique ts."""
    lines = []
    for r in range(WIDTH):
        i = k * WIDTH + r
        value = 100.0 if i % 7 == 0 else float(i % 50)
        lines.append(json.dumps({
            "deviceToken": f"d-{i % N_DEVICES}", "type": "Measurement",
            "request": {"name": "temp", "value": value,
                        "eventDate": T0 + i},
        }))
    return "\n".join(lines).encode()


def expected_events(data_dir):
    """(ts, value) for every durably journaled measurement row — the
    zero-loss reference set (opening the journal truncates any torn
    tail, which is exactly the not-yet-durable boundary)."""
    from sitewhere_tpu.ingest.journal import Journal

    journal = Journal(data_dir, name="ingest")
    out = {}
    try:
        for _off, payload in journal.scan(0):
            for line in payload.split(b"\n"):
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                req = doc.get("request") or {}
                if doc.get("type", "").lower() != "measurement":
                    continue
                out[int(req["eventDate"])] = float(req["value"])
    finally:
        journal.close()
    return out


def committed_offset(data_dir):
    try:
        with open(os.path.join(data_dir, "ingest", "pipeline.offset")) as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def attach_match_sink(inst, path):
    """File sink for analytics matches (STATE_CHANGE fan-out rows):
    line-flushed so rows survive a SIGKILL once written."""
    import numpy as np

    from sitewhere_tpu.ids import NULL_ID
    from sitewhere_tpu.outbound.connectors import CallbackConnector
    from sitewhere_tpu.schema import EventType

    f = open(path, "a")

    def on_batch(cols, mask):
        et = np.asarray(cols["event_type"])
        rows = np.asarray(mask) & (et == int(EventType.STATE_CHANGE)) \
            & (np.asarray(cols["alert_code"]) == NULL_ID)
        for i in np.nonzero(rows)[0]:
            token = inst.identity.device.token_of(
                int(cols["device_id"][i])) or "?"
            f.write(json.dumps({
                "d": token, "ts": int(cols["ts_s"][i]),
                "v": round(float(cols["value"][i]), 4)}) + "\n")
        f.flush()

    inst.outbound.add_connector(
        CallbackConnector(connector_id="crashrec-matches", fn=on_batch))
    return f


def read_matches(path):
    out = set()
    try:
        with open(path) as f:
            for line in f:
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue  # torn final line: its match replays
                out.add((doc["d"], doc["ts"], doc["v"]))
    except OSError:
        pass
    return out


def _ensure_model(inst):
    """Device model + queries, idempotent: present after a successful
    restore, recreated from scratch when the kill predates the anchor
    checkpoint's manifest commit (fresh-boot recovery path)."""
    if any(q["query"]["name"] == "hot-mean"
           for q in inst.analytics.list_queries()):
        return False
    dm = inst.device_management
    dm.create_device_type(token="sensor", name="Sensor")
    for i in range(N_DEVICES):
        dm.create_device(token=f"d-{i}", device_type="sensor")
        dm.create_device_assignment(device=f"d-{i}")
    for doc in QUERY_DOCS:
        inst.analytics.register(doc)
    return True


def run_child(data_dir, matches_path):
    """One instance life: register model + queries, drive the workload
    with periodic quiesced checkpoints.  Run under SW_CRASHPOINT this
    dies mid-flight; unarmed it stops cleanly (the golden run)."""
    inst = _make_instance(data_dir)
    sink = attach_match_sink(inst, matches_path)
    inst.start()
    _ensure_model(inst)
    # deterministic anchor: model + queries are snapshotted before any
    # traffic, so every kill point lands past a restorable generation
    inst.dispatcher.flush()
    inst.checkpointer.save()
    for k in range(N_PAYLOADS):
        inst.dispatcher.ingest_wire_lines(_payload(k), "crashrec")
        if (k + 1) % SAVE_EVERY == 0:
            # quiesce before the save so the snapshot's as-of offsets
            # only ever cover matches already durably in the sink file
            inst.dispatcher.flush()
            inst.analytics.drain()
            inst.outbound.drain()
            inst.checkpointer.save()
            # drive one background-compaction round mid-workload (the
            # interval loop is too slow for this harness), so the
            # crash.mid_compact crosspoint is certainly crossed
            compactor = getattr(inst.event_store, "compactor", None)
            if compactor is not None:
                compactor.run_once()
    inst.dispatcher.flush()
    inst.analytics.drain()
    inst.analytics.flush_live()
    inst.outbound.drain()
    inst.stop()
    inst.terminate()
    sink.close()


def verify(data_dir, matches_path, expected, committed_at_kill):
    """Restart on the survivor's data dir, COMPLETE the interrupted
    workload, and check the recovery contract; return (failures,
    report).  Completing the workload is what makes the golden
    comparison meaningful: restored + replayed + resumed must equal one
    uninterrupted run — events the child never journaled are not
    "lost", they simply haven't happened yet."""
    import numpy as np

    from sitewhere_tpu.schema import EventType

    failures = []
    t0 = time.perf_counter()
    inst = _make_instance(data_dir)
    sink = attach_match_sink(inst, matches_path)
    restored = inst.restored
    inst.start()   # restore already ran in __init__; start replays
    try:
        if _ensure_model(inst):
            # killed before the anchor checkpoint committed: model +
            # queries recreated; re-run the whole journal through
            # analytics (offset 0; the store-dedup floor keeps
            # persistence exactly-once)
            inst.dispatcher.replay_journal(from_offset=0)
        # resume: each payload is ONE journal record, so a payload is
        # either fully journaled (replay re-derived it) or absent —
        # ingest the absent ones to finish the golden workload
        journaled = {(ts - T0) // WIDTH for ts in expected}
        for k in range(N_PAYLOADS):
            if k not in journaled:
                inst.dispatcher.ingest_wire_lines(_payload(k), "crashrec")
        inst.dispatcher.flush()
        inst.analytics.drain()
        inst.analytics.flush_live()
        inst.outbound.drain()
        inst.event_store.flush()

        # segment-catalog consistency: the restarted store's manifest
        # must be internally consistent (no dangling files, no
        # unresolved compaction tombstones, sorted scan order)
        verify_catalog = getattr(inst.event_store, "verify_catalog", None)
        if verify_catalog is not None:
            problems = verify_catalog()
            if problems:
                failures.append(
                    f"segment catalog inconsistent after restart: "
                    f"{problems[:3]}")

        stored = {}
        for cols in inst.event_store.iter_chunks():
            m = cols["event_type"] == int(EventType.MEASUREMENT)
            for ts, val in zip(np.asarray(cols["ts_s"])[m],
                               np.asarray(cols["value"])[m]):
                stored.setdefault(int(ts), []).append(float(val))

        lost = [ts for ts in expected if ts not in stored]
        if lost:
            failures.append(
                f"committed-event loss: {len(lost)} journaled events "
                f"missing from the store (e.g. ts={sorted(lost)[:5]})")
        missing = [ts for k in range(N_PAYLOADS) if k not in journaled
                   for ts in range(T0 + k * WIDTH, T0 + (k + 1) * WIDTH)
                   if ts not in stored]
        if missing:
            failures.append(
                f"resumed-workload loss: {len(missing)} re-ingested "
                f"events missing from the store")
        # the store-dedup half: rows committed BEFORE the kill sealed
        # before the offset did, and the recovery replay must not
        # re-append them.  (Rows ABOVE the committed offset may store
        # twice — that is exactly at-least-once.)
        dup = [ts for ts, vals in stored.items()
               if ts in expected and len(vals) > 1
               and (ts - T0) // WIDTH < committed_at_kill]
        if dup:
            failures.append(
                f"{len(dup)} events below the committed offset stored "
                f"more than once (store-dedup floor failed)")

        snap = inst.metrics.snapshot() if hasattr(inst.metrics,
                                                  "snapshot") else {}
        gauges = snap.get("gauges", {})
        report = {
            "restored": bool(restored),
            "committed_at_kill": committed_at_kill,
            "journaled_events": len(expected),
            "stored_events": sum(len(v) for v in stored.values()),
            "replayed": int(gauges.get("recovery.replay_events", 0)),
            "restore_s": round(float(
                gauges.get("recovery.restore_s", 0.0)), 4),
            "replay_s": round(float(
                gauges.get("recovery.replay_s", 0.0)), 4),
            "verify_wall_s": round(time.perf_counter() - t0, 3),
        }
        if "recovery.restore_s" not in gauges \
                and "recovery.replay_s" not in gauges:
            failures.append("recovery.* gauges missing from the "
                            "restarted instance's registry")
    finally:
        inst.stop()
        inst.terminate()
        sink.close()
    return failures, report


# ---------------------------------------------------------------------------
# crash.mid_forward: the forward-spool sender's kill window (2-host fleet)
# ---------------------------------------------------------------------------

FWD_PAYLOADS = 10
FWD_ROWS = 8


def _fwd_free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _forward_config(data_dir, ports, pid):
    from sitewhere_tpu.runtime.config import Config

    return Config({
        "instance": {"id": f"crashfwd-{pid}", "data_dir": data_dir},
        "pipeline": {"width": 16, "registry_capacity": 128,
                     "mtype_slots": 4, "deadline_ms": 5.0, "n_shards": 1},
        "presence": {"scan_interval_s": 3600.0, "missing_after_s": 86400},
        "checkpoint": {"interval_s": 0},
        "analytics": {"enabled": False},
        "slo": {"enabled": False},
        "overload": {"enabled": False},
        # forwarded rows auto-register on the OWNER (no model setup)
        "registration": {"default_device_type": "sensor",
                         "allow_new_devices": True},
        "rpc": {
            "server": {"enabled": True, "host": "127.0.0.1",
                       "port": ports[pid]},
            "process_id": pid,
            "peers": [f"127.0.0.1:{p}" for p in ports],
            "forward_deadline_ms": 10.0,
            "heartbeat_interval_s": 0.2,
        },
        "security": {"jwt_secret": "crashfwd-secret"},
    }, apply_env=False)


def _forward_payload(k):
    lines = []
    for r in range(FWD_ROWS):
        i = k * FWD_ROWS + r
        lines.append(json.dumps({
            "deviceToken": f"f-{i % 6}", "type": "Measurement",
            "request": {"name": "temp", "value": float(i % 40),
                        "eventDate": T0 + i},
        }))
    return "\n".join(lines).encode()


def _forward_boot(root, ports):
    from sitewhere_tpu.instance import Instance
    from sitewhere_tpu.rpc.forward import owning_process
    from sitewhere_tpu.services.common import DuplicateToken

    # owner (host 1) first so the sender's spool can drain into it
    insts = []
    for pid in (1, 0):
        inst = Instance(_forward_config(
            os.path.join(root, f"host{pid}"), ports, pid))
        # model BEFORE start(): the boot-time journal replay needs the
        # device type (and each host its own devices) already present
        dm = inst.device_management
        try:
            dm.create_device_type(token="sensor", name="Sensor")
        except DuplicateToken:
            pass
        for i in range(6):
            tok = f"f-{i}"
            if owning_process(tok, 2) != pid:
                continue
            try:
                dm.create_device(token=tok, device_type="sensor")
                dm.create_device_assignment(device=tok)
            except DuplicateToken:
                pass
        inst.start()
        insts.append(inst)
    insts.reverse()     # [host0, host1]
    return insts


def run_forward_child(root, ports):
    """One 2-host fleet life: every payload enters host 0's forwarder,
    remote rows spool and ship to host 1.  Under SW_CRASHPOINT=
    crash.mid_forward the whole process SIGKILLs in the sender's
    poll→send window; unarmed it drains and stops clean."""
    insts = _forward_boot(root, ports)
    for k in range(FWD_PAYLOADS):
        insts[0].forwarder.ingest_payload(_forward_payload(k),
                                          source_id="crashfwd")
        insts[0].forwarder.flush()
        time.sleep(0.02)
    insts[0].forwarder.flush(wait=True)
    for inst in insts:
        inst.dispatcher.flush()
        inst.stop()
        inst.terminate()


def _journal_rows(data_dir, name):
    """(ts → value) for every measurement row in one journal (forward
    spools store multi-line payloads; same NDJSON decode)."""
    from sitewhere_tpu.ingest.journal import Journal

    out = {}
    path = os.path.join(data_dir, name)
    if not os.path.isdir(path):
        return out
    journal = Journal(data_dir, name=name)
    try:
        for _off, payload in journal.scan(0):
            for line in payload.split(b"\n"):
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                if doc.get("type", "").lower() != "measurement":
                    continue
                req = doc.get("request") or {}
                out[int(req["eventDate"])] = float(req["value"])
    finally:
        journal.close()
    return out


def verify_forward(root, ports):
    """Reboot the 2-host fleet on the survivors' dirs and check the
    FORWARD contract: host 0's forwarder replays the uncommitted spool
    tail on start(), and every row that was durably SPOOLED toward
    host 1 lands in host 1's durable intake journal — at-least-once
    across the DCN hop (duplicates above the sender's committed cursor
    are legal, loss is not).  Store materialization past the journal is
    the other kill points' contract, not this one's."""
    failures = []
    # the spool's surviving content, read BEFORE the restart drains it
    expected = _journal_rows(os.path.join(root, "host0"), "forward-1")
    if not expected:
        failures.append("forward spool empty at the kill — the "
                        "crosspoint fired too early to test anything")
    t0 = time.perf_counter()
    insts = _forward_boot(root, ports)
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline \
                and insts[0].forwarder.pending_rows() > 0:
            insts[0].forwarder.flush()
            time.sleep(0.05)
        pending = insts[0].forwarder.pending_rows()
        if pending:
            failures.append(
                f"forward spool never drained after restart ({pending})")
        dead = int(insts[0].forwarder.dead_lettered)
        if dead:
            failures.append(
                f"{dead} rows dead-lettered during forward replay")
        for inst in insts:
            inst.dispatcher.flush()
    finally:
        for inst in insts:
            inst.stop()
            inst.terminate()
    # journals are closed now: read the owner's durable intake
    delivered = _journal_rows(os.path.join(root, "host1"), "ingest")
    lost = sorted(ts for ts in expected if ts not in delivered)
    if lost:
        failures.append(
            f"forward-replay loss: {len(lost)} spooled rows never "
            f"reached the owner's journal (e.g. ts={lost[:5]})")
    report = {
        "spooled_rows": len(expected),
        "owner_journal_rows": len(delivered),
        "spool_pending_after": pending,
        "verify_wall_s": round(time.perf_counter() - t0, 3),
    }
    return failures, report


def run_forward_kill_case(root, case, hits, child_cmd):
    data_dir = os.path.join(root, f"{case:03d}-crash-mid-forward-{hits}")
    os.makedirs(data_dir, exist_ok=True)
    ports = [_fwd_free_port(), _fwd_free_port()]
    env = dict(os.environ,
               SW_CRASHPOINT=f"crash.mid_forward:{hits}",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        child_cmd + ["--forward-child", data_dir,
                     "--ports", f"{ports[0]},{ports[1]}"],
        env=env, capture_output=True, timeout=300)
    killed = proc.returncode == -signal.SIGKILL
    failures = []
    if not killed:
        failures.append(
            f"forward child was not killed (rc={proc.returncode}): "
            f"{proc.stderr.decode(errors='replace')[-800:]}")
        return failures, {"killed": False}
    vfail, report = verify_forward(data_dir, ports)
    failures.extend(vfail)
    report["killed"] = killed
    return failures, report


def run_kill_case(root, case, point, hits, golden_matches, child_cmd):
    data_dir = os.path.join(
        root, f"{case:03d}-{point.replace('.', '-')}-{hits}")
    matches_child = os.path.join(data_dir, "matches-child.jsonl")
    matches_verify = os.path.join(data_dir, "matches-verify.jsonl")
    os.makedirs(data_dir, exist_ok=True)
    env = dict(os.environ,
               SW_CRASHPOINT=f"{point}:{hits}", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        child_cmd + ["--child", data_dir, "--matches", matches_child],
        env=env, capture_output=True, timeout=300)
    killed = proc.returncode == -signal.SIGKILL
    failures = []
    if not killed and proc.returncode != 0:
        failures.append(
            f"child failed without being killed (rc={proc.returncode}): "
            f"{proc.stderr.decode(errors='replace')[-800:]}")
        return failures, {"killed": False}
    committed = committed_offset(data_dir)
    expected = expected_events(data_dir)
    vfail, report = verify(data_dir, matches_verify, expected, committed)
    failures.extend(vfail)
    matches = read_matches(matches_child) | read_matches(matches_verify)
    missing = golden_matches - matches
    extra = matches - golden_matches
    if missing:
        failures.append(
            f"analytics divergence: {len(missing)} golden matches never "
            f"produced (e.g. {sorted(missing)[:3]})")
    if extra:
        failures.append(
            f"analytics divergence: {len(extra)} matches the golden run "
            f"never produced (e.g. {sorted(extra)[:3]})")
    report.update({
        "killed": killed,
        "matches": len(matches),
        "golden_matches": len(golden_matches),
    })
    return failures, report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--child", metavar="DATA_DIR")
    parser.add_argument("--forward-child", metavar="DATA_DIR")
    parser.add_argument("--ports", default="")
    parser.add_argument("--matches", default="matches.jsonl")
    parser.add_argument("--smoke", action="store_true")
    parser.add_argument("--sweep", type=int, default=0)
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--json", dest="json_out")
    args = parser.parse_args(argv)

    if args.child:
        run_child(args.child, args.matches)
        return 0
    if args.forward_child:
        run_forward_child(args.forward_child,
                          [int(p) for p in args.ports.split(",")])
        return 0

    seed = args.seed if args.seed is not None \
        else random.SystemRandom().randrange(1 << 30)
    rng = random.Random(seed)
    if args.sweep:
        points = list(SWEEP_CATALOG)
        kills = [(p, rng.randint(*SWEEP_CATALOG[p]))
                 for p in (rng.choice(points) for _ in range(args.sweep))]
    else:
        kills = list(SMOKE_KILLS)

    child_cmd = [sys.executable, os.path.abspath(__file__)]
    root = tempfile.mkdtemp(prefix="crashrec-")
    results = {"seed": seed, "kills": [], "ok": True}
    all_failures = []
    try:
        # golden reference: the uninterrupted run
        golden_dir = os.path.join(root, "golden")
        golden_matches_path = os.path.join(root, "matches-golden.jsonl")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("SW_CRASHPOINT", None)
        proc = subprocess.run(
            child_cmd + ["--child", golden_dir,
                         "--matches", golden_matches_path],
            env=env, capture_output=True, timeout=300)
        if proc.returncode != 0:
            print(proc.stderr.decode(errors="replace")[-2000:],
                  file=sys.stderr)
            print("FAIL: golden run did not complete", file=sys.stderr)
            return 1
        golden_matches = read_matches(golden_matches_path)
        golden_events = expected_events(golden_dir)
        print(f"crashrec: seed={seed} golden: "
              f"{len(golden_events)} events, "
              f"{len(golden_matches)} matches")

        for case, (point, hits) in enumerate(kills):
            if point == "crash.mid_forward":
                # fleet-shaped case: its own 2-host child + verifier
                failures, report = run_forward_kill_case(
                    root, case, hits, child_cmd)
            else:
                failures, report = run_kill_case(
                    root, case, point, hits, golden_matches, child_cmd)
            report.update({"point": point, "hit": hits,
                           "failures": failures})
            results["kills"].append(report)
            all_failures.extend(f"{point}:{hits}: {f}" for f in failures)
            status = "ok" if not failures else "FAIL"
            print(f"  {point}:{hits}  killed={report.get('killed')} "
                  f"restored={report.get('restored')} "
                  f"replayed={report.get('replayed')} "
                  f"restore_s={report.get('restore_s')} "
                  f"replay_s={report.get('replay_s')}  {status}")
        killed_n = sum(1 for r in results["kills"] if r.get("killed"))
        restores = [r["restore_s"] for r in results["kills"]
                    if r.get("restore_s") is not None]
        results["summary"] = {
            "points": len(kills),
            "killed": killed_n,
            "golden_events": len(golden_events),
            "golden_matches": len(golden_matches),
            "restore_s_max": max(restores) if restores else None,
        }
        results["ok"] = not all_failures
        if args.json_out:
            with open(args.json_out, "w") as f:
                json.dump(results, f, indent=2)
        print(json.dumps(results["summary"], indent=2))
    finally:
        shutil.rmtree(root, ignore_errors=True)

    if all_failures:
        for f in all_failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("crashrec: every kill recovered clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
