#!/usr/bin/env python
"""Fleet chaos bench: prove goodput degrades smoothly, not collapses.

A 3-host in-process fleet (real :class:`~sitewhere_tpu.instance.Instance`
objects wired over localhost RPC — the same topology the multi-host
tests use) takes sustained keyed traffic at host 0's frontend while
host 2 is driven through the ISSUE-14 failure script:

1. **baseline** — all three hosts healthy; record per-host goodput.
2. **shed** — host 2 forced into SHEDDING: its admission refuses
   telemetry, host 0's health table must learn it (heartbeat +
   response piggyback), park the spool, and pace single probe batches;
   the device-facing edge refuses pure host-2 payloads with host 2's
   Retry-After hint.
3. **partition** — host 2's endpoint additionally drops every packet
   (``faults.net_inject``): the failure detector walks SUSPECT → DOWN;
   probes stay paced.
4. **recover** — partition healed, overload cleared: the health table
   returns to ALIVE/NORMAL and the spool drains to zero.

Asserted contract (the bench FAILS otherwise):

- healthy-host goodput never collapses (min phase ≥ ``collapse_frac``
  of baseline);
- send attempts to the unhealthy peer stay BOUNDED (paced probes, not
  a retry storm);
- ZERO forward-plane dead letters — every retained row is replayable
  and the spool drains to zero on recovery;
- the health table does not flap (bounded transitions for host 2).

Usage::

    python tools/fleet_chaos_bench.py [--smoke] [--json FLEETCHAOS.json]

``--smoke`` shrinks phases for the tier-1 gate; the full run writes the
FLEETCHAOS_rNN.json evidence captures.
"""

import argparse
import json
import math
import os
import shutil
import socket
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# deterministic CPU: the bench measures host-plane behavior, not chips
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from sitewhere_tpu.runtime import faults  # noqa: E402

N_HOSTS = 3
N_DEVICES = 48
T0 = 1_754_000_000


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _config(data_dir, ports, pid, heartbeat_s):
    from sitewhere_tpu.runtime.config import Config

    return Config({
        "instance": {"id": f"fleet-{pid}", "data_dir": data_dir},
        "pipeline": {"width": 64, "registry_capacity": 256,
                     "mtype_slots": 4, "deadline_ms": 5.0, "n_shards": 1},
        "presence": {"scan_interval_s": 3600.0, "missing_after_s": 86400},
        "checkpoint": {"interval_s": 0},
        "analytics": {"enabled": False},
        "slo": {"enabled": False},
        # forced overload states must hold for the scripted phase: a
        # short cooldown would let the controller self-recover mid-test
        "overload": {"enabled": True, "cooldown_s": 600.0},
        "rpc": {
            "server": {"enabled": True, "host": "127.0.0.1",
                       "port": ports[pid]},
            "process_id": pid,
            "peers": [f"127.0.0.1:{p}" for p in ports],
            "forward_deadline_ms": 10.0,
            "heartbeat_interval_s": heartbeat_s,
            "call_timeout_s": 3.0,
        },
        "security": {"jwt_secret": "fleet-chaos-secret"},
    }, apply_env=False)


def _boot_fleet(root, heartbeat_s):
    from sitewhere_tpu.instance import Instance
    from sitewhere_tpu.rpc.forward import owning_process

    ports = [_free_port() for _ in range(N_HOSTS)]
    insts = []
    for pid in range(N_HOSTS):
        inst = Instance(_config(os.path.join(root, f"host{pid}"), ports,
                                pid, heartbeat_s))
        inst.start()
        insts.append(inst)
    # every host registers the devices IT owns (dense handles are
    # host-local; forwarded rows must find a registered device)
    tokens_by_owner = {p: [] for p in range(N_HOSTS)}
    for i in range(N_DEVICES):
        tok = f"d-{i}"
        tokens_by_owner[owning_process(tok, N_HOSTS)].append(tok)
    for pid, inst in enumerate(insts):
        dm = inst.device_management
        dm.create_device_type(token="sensor", name="Sensor")
        for tok in tokens_by_owner[pid]:
            dm.create_device(token=tok, device_type="sensor")
            dm.create_device_assignment(device=tok)
    return insts, ports, tokens_by_owner


def _payload(tokens, seq):
    lines = []
    for k, tok in enumerate(tokens):
        lines.append(json.dumps({
            "deviceToken": tok, "type": "Measurement",
            "request": {"name": "temp", "value": float(seq % 50),
                        "eventDate": T0 + seq * 64 + k},
        }))
    return "\n".join(lines).encode()


class _Driver(threading.Thread):
    """Sustained mixed traffic into host 0's frontend: every round one
    payload carrying rows for ALL owners (the gateway-bulk shape — the
    edge gate never refuses it, the spool absorbs unhealthy owners)."""

    def __init__(self, fwd, tokens_by_owner, period_s=0.02):
        super().__init__(name="fleet-driver", daemon=True)
        self.fwd = fwd
        self.tokens_by_owner = tokens_by_owner
        self.period_s = period_s
        self.sent_rows = {p: 0 for p in tokens_by_owner}
        self.seq = 0
        self._halt = threading.Event()
        self._lock = threading.Lock()

    def run(self):
        while not self._halt.wait(self.period_s):
            batch = {p: toks[self.seq % len(toks):][:4]
                     for p, toks in self.tokens_by_owner.items()}
            payload = b"\n".join(
                _payload(toks, self.seq) for toks in batch.values() if toks)
            self.seq += 1
            self.fwd.ingest_payload(payload, source_id="fleet-bench")
            with self._lock:
                for p, toks in batch.items():
                    self.sent_rows[p] += len(toks)

    def snapshot(self):
        with self._lock:
            return dict(self.sent_rows)

    def stop(self):
        self._halt.set()
        self.join(timeout=10)


def _accepted(insts):
    return [int(i.dispatcher.metrics_snapshot()["accepted"]) for i in insts]


def _count_ingest_calls(demux):
    """Wrap one peer demux's call() to count events.ingest attempts —
    the bounded-probe assertion reads this."""
    counts = {"events.ingest": 0}
    orig = demux.call

    def counted(method, *a, **kw):
        if method in counts:
            counts[method] += 1
        return orig(method, *a, **kw)

    demux.call = counted
    return counts


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true")
    parser.add_argument("--json", dest="json_out")
    args = parser.parse_args(argv)

    phase_s = 1.5 if args.smoke else 4.0
    heartbeat_s = 0.1
    probe_interval_s = 2 * heartbeat_s      # health-table default
    drain_timeout_s = 30.0
    collapse_frac = 0.25                    # generous: CI boxes jitter

    from sitewhere_tpu.runtime.overload import OverloadShed, OverloadState

    root = tempfile.mkdtemp(prefix="fleet-chaos-")
    failures = []
    report = {"phases": {}, "smoke": bool(args.smoke)}
    insts = []
    driver = None
    try:
        t_boot = time.perf_counter()
        insts, ports, tokens_by_owner = _boot_fleet(root, heartbeat_s)
        report["boot_s"] = round(time.perf_counter() - t_boot, 2)
        fwd = insts[0].forwarder
        sick = 2                            # the host under test
        sick_ep = f"127.0.0.1:{ports[sick]}"

        # warm-up OUTSIDE the timed phases: the first batch on every
        # host pays the jit compile of the pipeline step — baseline
        # goodput must measure steady state, not compile time
        for p, toks in tokens_by_owner.items():
            fwd.ingest_payload(_payload(toks[:4], 0), source_id="warmup")
        fwd.flush(wait=True)
        warm_deadline = time.monotonic() + 120
        while time.monotonic() < warm_deadline:
            if all(a >= 4 for a in _accepted(insts)):
                break
            fwd.flush()
            time.sleep(0.1)
        if not all(a >= 4 for a in _accepted(insts)):
            failures.append("warm-up rows never landed on every host")
        report["warmup_accepted"] = _accepted(insts)

        ingest_calls = _count_ingest_calls(insts[0]._peer_demuxes[sick])

        driver = _Driver(fwd, tokens_by_owner,
                         period_s=0.03 if args.smoke else 0.02)
        driver.start()

        def run_phase(name, setup=None):
            if setup:
                setup()
            a0 = _accepted(insts)
            s0 = driver.snapshot()
            c0 = ingest_calls["events.ingest"]
            t0 = time.perf_counter()
            time.sleep(phase_s)
            dt = time.perf_counter() - t0
            a1 = _accepted(insts)
            s1 = driver.snapshot()
            healthy_goodput = sum(a1[p] - a0[p]
                                  for p in range(N_HOSTS) if p != sick) / dt
            phase = {
                "wall_s": round(dt, 2),
                "sent_rows": {str(p): s1[p] - s0[p] for p in s1},
                "accepted_delta": [a1[i] - a0[i] for i in range(N_HOSTS)],
                "healthy_goodput_rows_s": round(healthy_goodput, 1),
                "sick_ingest_attempts": ingest_calls["events.ingest"] - c0,
                "pending_to_sick": fwd.pending_for(sick),
                "health": fwd.health.snapshot().get(str(sick)),
            }
            report["phases"][name] = phase
            return phase

        # -- phase 1: baseline -------------------------------------------
        baseline = run_phase("baseline")
        if baseline["healthy_goodput_rows_s"] <= 0:
            failures.append("baseline produced no goodput — bench broken")

        # -- phase 2: host 2 forced into SHEDDING ------------------------
        shed = run_phase(
            "shed",
            setup=lambda: insts[sick].overload.force(
                OverloadState.SHEDDING, reason="fleet-chaos"))
        # host 0's table must have learned the state (heartbeat or
        # piggyback — both race the phase window, so check at the end)
        if fwd.health.overload_state(sick) != int(OverloadState.SHEDDING):
            failures.append(
                "health table never learned the SHEDDING state "
                f"(saw {fwd.health.overload_state(sick)})")
        # the device-facing edge reflects the OWNER's state: a purely
        # host-2-owned telemetry payload is refused with its hint
        edge = {"refused": False, "retry_after_s": None}
        try:
            fwd.ingest_payload(
                _payload(tokens_by_owner[sick][:4], 999_999),
                source_id="edge-check")
        except OverloadShed as e:
            edge = {"refused": True, "retry_after_s": e.retry_after_s,
                    "state": e.state.name}
        report["edge_refusal"] = edge
        if not edge["refused"]:
            failures.append("edge did not refuse a pure sick-owner payload "
                            "while the owner sheds")

        # -- phase 3: partition the sick host ----------------------------
        partition = run_phase(
            "partition",
            setup=lambda: faults.net_inject(sick_ep, drop=1.0))
        state_after = fwd.health.state(sick).name
        report["state_after_partition"] = state_after
        if state_after == "ALIVE":
            failures.append("partitioned peer still ALIVE in the table")

        # -- phase 4: recover --------------------------------------------
        def heal():
            faults.net_clear(sick_ep)
            insts[sick].overload.force(OverloadState.NORMAL,
                                       reason="fleet-chaos-recover")
        recover = run_phase("recover", setup=heal)

        driver.stop()
        # the spool must drain to ZERO once the peer is healthy again
        deadline = time.monotonic() + drain_timeout_s
        while time.monotonic() < deadline and fwd.pending_rows() > 0:
            fwd.flush()
            time.sleep(0.1)
        pending_final = fwd.pending_rows()
        report["pending_after_recovery"] = pending_final
        if pending_final != 0:
            failures.append(
                f"spool did not drain on recovery ({pending_final} rows)")

        # -- contract checks ---------------------------------------------
        # 1. bounded attempts while unhealthy: paced probes, not a storm.
        #    Budget = one probe per interval + discovery slack per phase.
        for name in ("shed", "partition"):
            attempts = report["phases"][name]["sick_ingest_attempts"]
            budget = math.ceil(phase_s / probe_interval_s) + 8
            report["phases"][name]["attempt_budget"] = budget
            if attempts > budget:
                failures.append(
                    f"{name}: {attempts} send attempts to the unhealthy "
                    f"peer (budget {budget}) — retry storm")
        # 2. smooth degradation: healthy goodput never collapses
        floor = collapse_frac * baseline["healthy_goodput_rows_s"]
        for name in ("shed", "partition", "recover"):
            gp = report["phases"][name]["healthy_goodput_rows_s"]
            if gp < floor:
                failures.append(
                    f"{name}: healthy goodput collapsed "
                    f"({gp:.0f} < {floor:.0f} rows/s)")
        # 3. zero forward-plane dead letters (everything replayable)
        dead = int(fwd.dead_lettered)
        report["forward_dead_lettered"] = dead
        if dead:
            failures.append(f"{dead} rows dead-lettered by the forwarder")
        # 4. no flapping: the sick peer's table entry moved a bounded
        #    number of times across the whole script
        transitions = fwd.health.transitions(sick)
        report["sick_transitions"] = transitions
        if transitions > 8:
            failures.append(
                f"health table flapped: {transitions} transitions")
        # 5. at-least-once: after recovery + drain, the sick host holds
        #    every row sent its way (duplicates allowed, loss is not)
        insts[sick].dispatcher.flush()
        sick_accepted = _accepted(insts)[sick]
        sick_sent = driver.snapshot()[sick]
        report["sick_sent_rows"] = sick_sent
        report["sick_accepted_rows"] = sick_accepted
        if sick_accepted < sick_sent:
            failures.append(
                f"rows lost toward the sick host: sent {sick_sent}, "
                f"accepted {sick_accepted}")
        report["forward_metrics"] = {
            k: v for k, v in fwd.metrics().items() if k != "peers"}
        report["ok"] = not failures
        print(json.dumps(report, indent=2))
        if args.json_out:
            with open(args.json_out, "w") as f:
                json.dump(report, f, indent=2)
    finally:
        faults.net_clear()
        if driver is not None and driver.is_alive():
            driver.stop()
        for inst in insts:
            try:
                inst.stop()
                inst.terminate()
            except Exception:   # noqa: BLE001 — teardown best-effort
                pass
        shutil.rmtree(root, ignore_errors=True)

    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("fleet_chaos: goodput degraded smoothly, spool drained, "
          "zero dead letters")
    return 0


if __name__ == "__main__":
    sys.exit(main())
