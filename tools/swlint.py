#!/usr/bin/env python
"""swlint CLI: run the project-invariant static-analysis suite.

    python tools/swlint.py sitewhere_tpu/            # lint, apply baseline
    python tools/swlint.py sitewhere_tpu/ --json     # machine output
    python tools/swlint.py sitewhere_tpu/ --update-baseline
    python tools/swlint.py path/to/file.py --no-baseline
    python tools/swlint.py --list-passes

Exit codes: 0 = clean (every finding suppressed by the baseline),
1 = unsuppressed findings, 2 = usage/config error.  Stale baseline
entries (suppressions that no longer fire) are reported as notes and
never fail the run — delete them when convenient, the worklist is
supposed to shrink.

``--update-baseline`` rewrites the baseline from the CURRENT findings,
preserving existing justifications by fingerprint; new entries get a
``TODO: justify`` note that a reviewer must replace — a baseline entry
without a reason is a bug report, not a suppression.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from sitewhere_tpu.analysis import (  # noqa: E402
    Baseline,
    PASS_FACTORIES,
    Project,
    default_baseline_path,
    run_suite,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="swlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", help="package dirs / files to lint")
    ap.add_argument("--baseline", default=None,
                    help="suppression file (default: "
                         "tools/swlint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, suppress nothing")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(keeps existing justifications)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="JSON output (findings + suppressed + stale)")
    ap.add_argument("--passes", default=None,
                    help="comma-separated pass ids to run (default: all)")
    ap.add_argument("--list-passes", action="store_true")
    args = ap.parse_args(argv)

    if args.list_passes:
        for pass_id in PASS_FACTORIES:
            print(pass_id)
        return 0
    if not args.paths:
        ap.error("no paths given (try: python tools/swlint.py "
                 "sitewhere_tpu/)")
    for p in args.paths:
        if not os.path.exists(p):
            print(f"swlint: no such path: {p}", file=sys.stderr)
            return 2

    passes = None
    if args.passes:
        wanted = [s.strip() for s in args.passes.split(",") if s.strip()]
        unknown = [w for w in wanted if w not in PASS_FACTORIES]
        if unknown:
            print(f"swlint: unknown passes {unknown}; known: "
                  f"{list(PASS_FACTORIES)}", file=sys.stderr)
            return 2
        passes = [PASS_FACTORIES[w]() for w in wanted]

    # Anchor the project root at the REPO whenever every path is inside
    # it: finding fingerprints embed project-relative paths, so a
    # subset run (`swlint.py sitewhere_tpu/runtime`) must produce the
    # SAME fingerprints as the full run or the checked-in baseline
    # stops matching (and --update-baseline would shred it).
    paths_abs = [os.path.abspath(p) for p in args.paths]
    root = _REPO if all(p == _REPO or p.startswith(_REPO + os.sep)
                        for p in paths_abs) else None
    project = Project.from_paths(paths_abs, root=root)
    findings = run_suite(paths_abs, passes=passes, project=project)

    baseline_path = args.baseline or default_baseline_path()
    if args.no_baseline and args.update_baseline:
        print("swlint: --no-baseline with --update-baseline would reset "
              "every justification; refusing", file=sys.stderr)
        return 2
    if args.no_baseline:
        baseline = Baseline()
    else:
        try:
            baseline = Baseline.load(baseline_path)
        except (ValueError, OSError) as e:
            print(f"swlint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2

    if args.update_baseline:
        updated = Baseline.from_findings(findings, old=baseline)
        # A NARROWED run (subset of passes, or a path subset) must not
        # delete baseline entries it never re-checked: keep every old
        # entry whose pass did not run or whose file was not scanned.
        run_pass_ids = {p.pass_id for p in
                        (passes if passes is not None
                         else [f() for f in PASS_FACTORIES.values()])}
        scanned = {m.rel for m in project.modules.values()}
        have = updated.fingerprints
        for e in baseline.entries:
            # an unscanned path only protects the entry while the file
            # still EXISTS — entries for deleted/renamed modules must
            # drop here, or update-baseline could never shrink the file
            path_out = (e.get("path") not in scanned
                        and os.path.exists(
                            os.path.join(project.root, str(e.get("path")))))
            out_of_scope = e.get("pass") not in run_pass_ids or path_out
            if out_of_scope and str(e["fp"]) not in have:
                updated.entries.append(e)
        updated.save(baseline_path)
        print(f"swlint: baseline updated: {len(updated.entries)} entries "
              f"-> {baseline_path}")
        todo = sum(1 for e in updated.entries
                   if str(e.get("note", "")).startswith("TODO"))
        if todo:
            print(f"swlint: {todo} entries need a justification "
                  "(note starts with TODO)")
        return 0

    unsuppressed, suppressed, stale = baseline.apply(findings)

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in unsuppressed],
            "suppressed": [f.to_dict() for f in suppressed],
            "stale_baseline_entries": stale,
            "counts": {
                "unsuppressed": len(unsuppressed),
                "suppressed": len(suppressed),
                "stale": len(stale),
            },
        }, indent=1))
    else:
        for f in unsuppressed:
            print(f.format())
        if stale:
            print(f"\nswlint: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (no longer "
                  "firing — prune when convenient):")
            for e in stale:
                print(f"  - [{e['pass']}/{e['rule']}] {e['qualname']}: "
                      f"{e.get('note', '')}")
        print(f"\nswlint: {len(unsuppressed)} finding"
              f"{'' if len(unsuppressed) == 1 else 's'}, "
              f"{len(suppressed)} suppressed by baseline")
    return 1 if unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
