"""Device-side packed-step latency vs batch width (config-1 phase-C
methodology via bench.py's SHARED helpers — packed_chain + measure_rtt —
so the sweep always measures exactly what the bench measures).
Run on any backend; widths via argv.  Reproduces TPU_EVIDENCE_r05.md §7.

    python tools/width_sweep.py [width ...]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

import bench  # noqa: E402
from sitewhere_tpu.pipeline.packed import (  # noqa: E402
    pack_batch_host,
    pack_state,
    pack_tables,
)

print("backend:", jax.default_backend(), flush=True)
capacity, n_active = 16384, 10000
chain_k = 64
n_batches = 4
registry, state, rules, zones = bench.build_tables(capacity, n_active)
tables = jax.jit(pack_tables)(registry, rules, zones)
pack_state_fn = jax.jit(pack_state)  # one jit wrapper: state is
# width-independent, so every width reuses the same compiled pack

rtt = bench.measure_rtt()
print(f"rtt_ms={rtt*1e3:.1f}", flush=True)

widths = tuple(int(a) for a in sys.argv[1:]) or (
    4_096, 16_384, 131_072, 262_144)
for width in widths:
    try:
        raw = bench.host_batches(width, n_active, n_batches=n_batches)
        staged = [tuple(jax.device_put(a) for a in pack_batch_host(b, width))
                  for b in raw]
        jax.block_until_ready(staged)
        carry = pack_state_fn(state)
        chain = bench.packed_chain(tables, staged, chain_k)
        carry, probe = chain(carry)
        int(probe)  # compile + settle
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            carry, probe = chain(carry)
            int(probe)
            # same clamp as bench.py phase C: on a co-located backend
            # the whole chain can finish in under one startup-probe RTT
            dt = max(0.0, time.perf_counter() - t0 - rtt)
            step_ms = dt / chain_k * 1e3
            if best is None or step_ms < best:
                best = step_ms
        if best > 0:
            print(f"width={width} step_ms={best:.3f} "
                  f"device_eps={width/best*1e3/1e6:.2f}M", flush=True)
        else:
            print(f"width={width} step_ms<rtt (chain faster than the "
                  f"RTT probe resolution)", flush=True)
        del staged, carry, chain
    except Exception as e:
        print(f"width={width} FAILED: {type(e).__name__}: {str(e)[:200]}",
              flush=True)
