"""Device-side packed-step latency vs batch width (config-1 phase-C
methodology: K chained steps in one compiled program, one fetch, RTT
subtracted).  Run on any backend; widths via argv (defaults cover the
config-1/2 operating points).  Reproduces TPU_EVIDENCE_r05.md §7.

    python tools/width_sweep.py [width ...]
"""
import sys
import time

import numpy as np

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))))
import jax
import jax.numpy as jnp

import bench
from sitewhere_tpu.pipeline.packed import (
    pack_batch_host,
    pack_state,
    pack_tables,
    packed_pipeline_step,
)

print("backend:", jax.default_backend(), flush=True)
capacity, n_active = 16384, 10000
chain_k = 64
registry, state, rules, zones = bench.build_tables(capacity, n_active)
tables = jax.jit(pack_tables)(registry, rules, zones)

trivial = jax.jit(lambda x: x + 1)
int(trivial(jnp.int32(0)))
rtts = []
for _ in range(5):
    t = time.perf_counter()
    int(trivial(jnp.int32(0)))
    rtts.append(time.perf_counter() - t)
rtt = float(np.median(rtts))
print(f"rtt_ms={rtt*1e3:.1f}", flush=True)

widths = tuple(int(a) for a in sys.argv[1:]) or (
    4_096, 16_384, 131_072, 262_144)
for width in widths:
    try:
        raw = bench.host_batches(width, n_active, n_batches=4)
        staged = [tuple(jax.device_put(a) for a in pack_batch_host(b, width))
                  for b in raw]
        jax.block_until_ready(staged)
        carry = jax.jit(pack_state)(state)
        stacked_i = jnp.stack([b for b, _ in staged])
        stacked_f = jnp.stack([f for _, f in staged])

        @jax.jit
        def chain(c, si=stacked_i, sf=stacked_f):
            def body(i, cr):
                c, acc = cr
                k = i % 4
                bi = jax.lax.dynamic_index_in_dim(si, k, keepdims=False)
                bf = jax.lax.dynamic_index_in_dim(sf, k, keepdims=False)
                c, oi, metrics, present = packed_pipeline_step(
                    tables, c, bi, bf)
                acc = acc + metrics.sum() + oi.sum() + present.sum()
                return c, acc
            return jax.lax.fori_loop(0, chain_k, body, (c, jnp.int32(0)))

        carry, probe = chain(carry)
        int(probe)  # compile + settle
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            carry, probe = chain(carry)
            int(probe)
            dt = time.perf_counter() - t0 - rtt
            step_ms = dt / chain_k * 1e3
            if best is None or step_ms < best:
                best = step_ms
        print(f"width={width} step_ms={best:.3f} "
              f"device_eps={width/best*1e3/1e6:.2f}M", flush=True)
        del staged, stacked_i, stacked_f, carry
    except Exception as e:
        print(f"width={width} FAILED: {type(e).__name__}: {str(e)[:200]}",
              flush=True)
