"""Device-side packed-step latency vs batch width (config-1 phase-C
methodology via bench.py's SHARED helpers — packed_chain + measure_rtt —
so the sweep always measures exactly what the bench measures), extended
with the per-stage host attribution the device-resident dispatch loop is
judged by: for every width it also times the H2D slot staging
(``device_put`` of one packed batch), the blocking D2H output fetch, and
derives the per-batch host-sync budget — step_ms is the device dwell, and
``rtt/K + h2d + d2h`` is what a ring slot actually adds on the host side.
Run on any backend; widths via argv.  Reproduces TPU_EVIDENCE_r05.md §7.

    python tools/width_sweep.py [width ...]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

import bench  # noqa: E402
from sitewhere_tpu.pipeline.packed import (  # noqa: E402
    pack_batch_host,
    pack_state,
    pack_tables,
)

print("backend:", jax.default_backend(), flush=True)
capacity, n_active = 16384, 10000
chain_k = 64
n_batches = 4
registry, state, rules, zones = bench.build_tables(capacity, n_active)
tables = jax.jit(pack_tables)(registry, rules, zones)
pack_state_fn = jax.jit(pack_state)  # one jit wrapper: state is
# width-independent, so every width reuses the same compiled pack

rtt = bench.measure_rtt()
print(f"rtt_ms={rtt*1e3:.1f}", flush=True)


def _median(fn, n=3):
    samples = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return samples[len(samples) // 2]


widths = tuple(int(a) for a in sys.argv[1:]) or (
    4_096, 16_384, 131_072, 262_144)
for width in widths:
    try:
        raw = bench.host_batches(width, n_active, n_batches=n_batches)
        packed = [pack_batch_host(b, width) for b in raw]

        # H2D stage: device_put of one packed (bi, bf) pair — the ring
        # slot fill the double-buffered path hides behind compute
        def h2d_once(pair=packed[0]):
            jax.block_until_ready(tuple(jax.device_put(a) for a in pair))

        h2d_once()
        h2d_ms = _median(h2d_once) * 1e3

        staged = [tuple(jax.device_put(a) for a in pair) for pair in packed]
        jax.block_until_ready(staged)
        carry = pack_state_fn(state)
        chain = bench.packed_chain(tables, staged, chain_k)
        carry, probe = chain(carry)
        int(probe)  # compile + settle
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            carry, probe = chain(carry)
            int(probe)
            # same clamp as bench.py phase C: on a co-located backend
            # the whole chain can finish in under one startup-probe RTT
            dt = max(0.0, time.perf_counter() - t0 - rtt)
            step_ms = dt / chain_k * 1e3
            if best is None or step_ms < best:
                best = step_ms

        # D2H fetch: one step's output block + metrics, fresh buffers
        # per sample (jax caches a fetched array's host copy)
        from sitewhere_tpu.pipeline.packed import packed_pipeline_step

        step = jax.jit(packed_pipeline_step)
        d2h_samples = []
        for _ in range(3):
            _, oi, mets, _present = step(tables, carry, *staged[0])
            jax.block_until_ready(mets)
            t0 = time.perf_counter()
            jax.device_get((oi, mets))
            d2h_samples.append(time.perf_counter() - t0)
        d2h_samples.sort()
        d2h_ms = d2h_samples[1] * 1e3

        # per-batch host cost of a K-deep ring slot: one dispatch+fetch
        # RTT amortized over K, plus this slot's own h2d and its share
        # of the chain's stacked d2h
        ring_host_ms = rtt * 1e3 / chain_k + h2d_ms + d2h_ms
        if best > 0:
            print(f"width={width} step_ms={best:.3f} "
                  f"device_eps={width/best*1e3/1e6:.2f}M "
                  f"h2d_ms={h2d_ms:.3f} d2h_ms={d2h_ms:.3f} "
                  f"ring_host_ms_per_batch={ring_host_ms:.3f} "
                  f"host_syncs_per_batch={1.0/chain_k:.4f}", flush=True)
        else:
            print(f"width={width} step_ms<rtt (chain faster than the "
                  f"RTT probe resolution) h2d_ms={h2d_ms:.3f} "
                  f"d2h_ms={d2h_ms:.3f}", flush=True)
        del staged, carry, chain
    except Exception as e:
        print(f"width={width} FAILED: {type(e).__name__}: {str(e)[:200]}",
              flush=True)
