#!/usr/bin/env python
"""Overload bench: goodput vs offered load — graceful degradation proof.

Stream-platform comparisons (arXiv:1807.07724) show the difference
between a deployable system and a benchmark system is the SHAPE of the
throughput curve past saturation: a system without overload control
collapses (goodput falls as offered load rises — every class starves
together), one with admission + priority shedding degrades gracefully
(goodput plateaus near capacity, CRITICAL traffic keeps flowing, the
excess is shed loudly).

This tool measures that curve on a real instance: mixed telemetry +
alert wire traffic is offered at multiples of the measured base
capacity, and per-multiplier goodput (rows that actually sealed),
sheds, alert delivery, and the overload state reached are reported.

Usage::

    python tools/overload_bench.py [--width 256] [--duration 0.5]
                                   [--multipliers 0.5,1,2,4] [--json]

Exit status 0 = graceful (goodput at the top multiplier held at least
``--collapse-floor`` of peak goodput AND zero alert-class sheds);
1 = collapse or alert loss.
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def _measurement_lines(token, base, n, ts=1_753_800_000):
    return "\n".join(
        json.dumps({"deviceToken": token, "type": "Measurement",
                    "request": {"name": "temp", "value": float(base + i),
                                "eventDate": ts}})
        for i in range(n)).encode()


def _alert_line(token, ts=1_753_800_000):
    return json.dumps({
        "deviceToken": token, "type": "Alert",
        "request": {"type": "overheat", "level": "warning",
                    "message": "hot", "eventDate": ts}}).encode()


def _make_instance(data_dir, width):
    from sitewhere_tpu.instance import Instance
    from sitewhere_tpu.runtime.config import Config

    cfg = Config({
        "instance": {"id": "overload-bench", "data_dir": data_dir},
        "pipeline": {"width": width, "registry_capacity": 1024,
                     "mtype_slots": 4, "deadline_ms": 2.0, "n_shards": 1},
        "presence": {"scan_interval_s": 3600.0, "missing_after_s": 1800},
        "overload": {
            "enabled": True,
            # bench-tight loop: sample every controller tick, recover
            # fast enough that per-multiplier phases stay independent
            "cooldown_s": 0.2,
            "sample_interval_s": 0.0,
            # the batcher emits full plans inline at width, so pending
            # oscillates around 1.0×width under sustained overload —
            # put the DEGRADED/SHEDDING watermarks around that pivot
            "watermarks": {"batcher_backlog": [0.75, 1.05, 8.0]},
        },
    }, apply_env=False)
    return Instance(cfg)


def run(width=256, duration_s=0.5, multipliers=(0.5, 1.0, 2.0, 4.0),
        lines_per_payload=8, alert_every=10, data_dir=None):
    """Run the sweep; returns {capacity_rows_per_s, rows: [...]}."""
    from sitewhere_tpu.runtime.overload import OverloadShed, OverloadState

    root = data_dir or tempfile.mkdtemp(prefix="overload-bench-")
    owns_root = data_dir is None
    inst = _make_instance(os.path.join(root, "data"), width)
    inst.start()
    try:
        inst.device_management.create_device_type(token="sensor",
                                                  name="Sensor")
        inst.device_management.create_device(token="dev-0",
                                             device_type="sensor")
        inst.device_management.create_device_assignment(device="dev-0")

        disp = inst.dispatcher

        def sealed():
            return disp.totals["accepted"]

        # ---- base capacity: unpaced blast with admission OFF — this
        # phase measures the DRAIN side (decode → step → seal), and the
        # controller shedding its own yardstick would corrupt it.  The
        # warm pass runs the jit compiles outside the timed window.
        disp.overload = None
        for w in range(4):
            disp.ingest_wire_lines(_measurement_lines("dev-0", w, width))
        disp.flush()
        t0 = time.perf_counter()
        sealed0 = sealed()
        i = 0
        while time.perf_counter() - t0 < max(duration_s, 0.2):
            disp.ingest_wire_lines(
                _measurement_lines("dev-0", i, lines_per_payload))
            i += 1
        disp.flush()
        elapsed = time.perf_counter() - t0
        capacity = max(1.0, (sealed() - sealed0) / elapsed)
        disp.overload = inst.overload
        # DEGRADED telemetry budget tracks the measured drain rate with
        # headroom for critical traffic + recovery: the bucket admits
        # ~80% of capacity and sheds the overhang cheaply — the
        # graceful-degradation shape this bench exists to demonstrate
        inst.overload.degraded_telemetry_rate_per_s = capacity * 0.8
        inst.overload.degraded_telemetry_burst = lines_per_payload * 2.0

        rows = []
        for mult in multipliers:
            # let the controller recover between phases
            disp.flush()
            t_rec = time.monotonic()
            while inst.overload.state != OverloadState.NORMAL \
                    and time.monotonic() - t_rec < 5.0:
                inst.overload.tick()
                time.sleep(0.01)

            target_rate = capacity * mult     # rows/s offered
            interval = lines_per_payload / target_rate
            sealed_before = sealed()
            shed_before = inst.overload.shed_total
            crit_before = inst.metrics.counter(
                "overload.shed.critical").value
            offered = 0
            alerts_offered = 0
            signalled = 0
            worst = OverloadState.NORMAL
            t0 = time.perf_counter()
            next_send = t0
            i = 0
            while time.perf_counter() - t0 < duration_s:
                now = time.perf_counter()
                if now < next_send:
                    time.sleep(min(next_send - now, 0.001))
                    continue
                next_send += interval
                try:
                    # alerts lead the cadence so even a starved phase
                    # (contended box, short duration) offers at least one
                    if alert_every and i % alert_every == 0:
                        disp.ingest_wire_lines(_alert_line("dev-0"))
                        alerts_offered += 1
                        offered += 1
                    else:
                        disp.ingest_wire_lines(
                            _measurement_lines("dev-0", i,
                                               lines_per_payload))
                        offered += lines_per_payload
                except OverloadShed:
                    signalled += 1
                    offered += lines_per_payload
                i += 1
                worst = max(worst, inst.overload.tick())
            disp.flush()
            elapsed = time.perf_counter() - t0
            row = {
                "multiplier": mult,
                "offered_rows_per_s": round(offered / elapsed, 1),
                "goodput_rows_per_s": round(
                    (sealed() - sealed_before) / elapsed, 1),
                "shed_rows": inst.overload.shed_total - shed_before,
                "alert_sheds": inst.metrics.counter(
                    "overload.shed.critical").value - crit_before,
                "alerts_offered": alerts_offered,
                "backpressure_signals": signalled,
                "worst_state": OverloadState(worst).name,
            }
            snap = disp.metrics_snapshot()
            if "latency_p99_ms" in snap:
                row["p99_ms"] = snap["latency_p99_ms"]
            rows.append(row)
        return {"capacity_rows_per_s": round(capacity, 1),
                "width": width, "rows": rows}
    finally:
        inst.stop()
        inst.terminate()
        if owns_root:
            shutil.rmtree(root, ignore_errors=True)


def _render(result) -> str:
    rows = result["rows"]
    peak = max(r["goodput_rows_per_s"] for r in rows) or 1.0
    out = [f"overload_bench: base capacity ≈ "
           f"{result['capacity_rows_per_s']:.0f} rows/s "
           f"(width {result['width']})",
           f"{'offered':>10} {'goodput':>10} {'shed':>8} "
           f"{'alerts':>7} {'state':>10}  goodput vs offered"]
    for r in rows:
        bar = "#" * max(1, int(30 * r["goodput_rows_per_s"] / peak))
        alerts = f"{r['alerts_offered'] - r['alert_sheds']}" \
                 f"/{r['alerts_offered']}"
        out.append(
            f"{r['offered_rows_per_s']:>10.0f} "
            f"{r['goodput_rows_per_s']:>10.0f} "
            f"{r['shed_rows']:>8d} {alerts:>7} "
            f"{r['worst_state']:>10}  {bar} ({r['multiplier']}x)")
    return "\n".join(out)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="goodput vs offered load under overload control")
    parser.add_argument("--width", type=int, default=256)
    parser.add_argument("--duration", type=float, default=0.5,
                        help="seconds per offered-load phase")
    parser.add_argument("--multipliers", default="0.5,1,2,4",
                        help="offered-load multiples of base capacity")
    parser.add_argument("--collapse-floor", type=float, default=0.3,
                        help="min goodput fraction of peak at the top "
                             "multiplier before the run counts as a "
                             "throughput collapse")
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)
    multipliers = tuple(float(m) for m in args.multipliers.split(","))
    result = run(width=args.width, duration_s=args.duration,
                 multipliers=multipliers)
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(_render(result))
    rows = result["rows"]
    peak = max(r["goodput_rows_per_s"] for r in rows)
    top = rows[-1]
    if any(r["alert_sheds"] for r in rows):
        print("FAIL: alert-class events were shed", file=sys.stderr)
        return 1
    if peak > 0 and top["goodput_rows_per_s"] < args.collapse_floor * peak:
        print(f"FAIL: goodput collapsed at {top['multiplier']}x "
              f"({top['goodput_rows_per_s']:.0f} < "
              f"{args.collapse_floor:.0%} of peak {peak:.0f})",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
