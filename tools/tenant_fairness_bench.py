#!/usr/bin/env python
"""Tenant fairness bench: the noisy-neighbor isolation proof.

One adversarial tenant is pinned at ~10× its fair share of offered load
while a fleet of quiet tenants (device counts Zipf-distributed, O(100k)
devices at the full tier) keeps its steady trickle.  The run drives the
whole ladder — DEGRADED admission, SHEDDING, recovery — with a fake
clock so every token-bucket decision is deterministic, and proves four
isolation invariants:

1. **Fairness floor** — every quiet tenant's contended goodput stays
   within ``--goodput-floor`` (default 90%) of its isolated baseline:
   per-(tenant, source) budget buckets mean the noisy tenant can only
   exhaust its OWN budget.
2. **Budget clip** — the noisy tenant is held to its configured
   ``tenants.<token>.overload.*`` budget overlay (min-composed with the
   measured-share scaling), its sheds dead-lettered under the
   replayable ``tenant-budget`` kind.
3. **Zero loss** — every offered row is accounted: accepted rows seal,
   refused rows dead-letter with per-class counts, and a post-recovery
   requeue returns budget-shed rows to the pipeline.
4. **Partition isolation** — a registration churn storm in the noisy
   tenant never bumps an untouched tenant's partition ``compile_count``
   (state/manager.py TenantPartitions rung ladder).

Usage::

    python tools/tenant_fairness_bench.py [--devices 100000] [--json]
                                          [--out TENANTFAIR_r01.json]
    python tools/tenant_fairness_bench.py --smoke --json   # tier-1 gate

Exit status 0 = every check passed.
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

# deterministic admission plane: uniform DEGRADED telemetry budget and
# the adversarial tenant's configured overlay (rows/s)
UNIFORM_RATE = 1_000.0
UNIFORM_BURST = 2_000.0
NOISY_RATE = 150.0
NOISY_BURST = 150.0
QUIET_DEMAND = 200.0       # rows/s per quiet tenant (under fair share)
NOISY_DEMAND = 2_000.0     # rows/s — ~10× the noisy tenant's fair cut
DT = 0.05                  # simulated seconds per offered step


class FakeClock:
    def __init__(self, t=1_000.0):
        self.t = t

    def __call__(self):
        return self.t


def _make_instance(data_dir, capacity):
    from sitewhere_tpu.instance import Instance
    from sitewhere_tpu.runtime.config import Config

    cfg = Config({
        "instance": {"id": "tenantfair-bench", "data_dir": data_dir},
        "pipeline": {"width": 256, "registry_capacity": capacity,
                     "mtype_slots": 4, "deadline_ms": 2.0, "n_shards": 1},
        "presence": {"scan_interval_s": 3600.0, "missing_after_s": 1800},
        "overload": {
            "enabled": True,
            # the bench FORCES ladder states; a signal-driven transition
            # mid-phase would clear buckets and corrupt the accounting,
            # so the watermarks are parked out of reach and cooldown is
            # effectively infinite under the fake clock
            "cooldown_s": 1e9,
            "sample_interval_s": 1e9,
            "degraded_telemetry_rate_per_s": UNIFORM_RATE,
            "degraded_telemetry_burst": UNIFORM_BURST,
            "budget_refresh_s": 5.0,
            "watermarks": {
                "seal_lag_s": [1e9, 2e9, 3e9],
                "decode_backlog": [1e9, 2e9, 3e9],
                "egress_inflight": [1e9, 2e9, 3e9],
                "batcher_backlog": [1e9, 2e9, 3e9],
                "fsync_latency_s": [1e9, 2e9, 3e9],
            },
        },
        "tenants": {
            "t-noisy": {"overload": {
                "degraded_telemetry_rate_per_s": NOISY_RATE,
                "degraded_telemetry_burst": NOISY_BURST,
            }},
        },
        "metering": {"window_s": 60.0},
        "tracing": {"sample_rate": 0.0},
    }, apply_env=False)
    return Instance(cfg)


def _zipf_counts(total, n_tenants, s=1.1):
    """Zipf-ish device counts over ``n_tenants`` ranks summing ~total."""
    weights = 1.0 / np.arange(1, n_tenants + 1) ** s
    counts = np.maximum(1, (total * weights / weights.sum()).astype(int))
    counts[0] += total - int(counts.sum())   # remainder to the head
    return counts.tolist()


def _populate(inst, quiet_tokens, noisy_token, total_devices, probes=16):
    """Create tenants + Zipf-distributed devices through their engines.

    Only ``probes`` devices per tenant get assignments (the ingest
    sample); the rest are bare registrations — they exist to give the
    partition ladder its 100k-device tenant column, and assignment-less
    rows never receive traffic.
    """
    tokens = [noisy_token] + quiet_tokens
    counts = _zipf_counts(total_devices, len(tokens))
    fleet = {}
    for tok, count in zip(tokens, counts):
        inst.tenants.create_tenant(token=tok, name=tok,
                                   auth_token=f"{tok}-auth-token-000")
        tdm = inst.engines.get_engine(tok).device_management
        tdm.create_device_type(token=f"{tok}-type", name=f"{tok} sensor")
        for i in range(count):
            tdm.create_device(token=f"{tok}-d{i}",
                              device_type=f"{tok}-type")
        n_probe = min(probes, count)
        for i in range(n_probe):
            tdm.create_device_assignment(device=f"{tok}-d{i}")
        fleet[tok] = {"devices": count, "probes": n_probe}
    return fleet


def _requests(tok, n_probe, rows):
    """A reusable decoded batch of ``rows`` measurement requests cycling
    the tenant's probe devices, tenancy stamped in metadata (the same
    shape a tenant-authenticated source attaches).  The payload is the
    REAL wire NDJSON so a ``tenant-budget`` dead letter of this batch is
    replayable through the recovery decoder."""
    from sitewhere_tpu.ingest.decoders import JsonLinesDecoder

    payload = "\n".join(json.dumps({
        "deviceToken": f"{tok}-d{r % n_probe}", "type": "Measurement",
        "request": {"name": "temp", "value": float(r),
                    "eventDate": 1_753_800_000 + r},
    }) for r in range(rows)).encode()
    reqs = JsonLinesDecoder()(payload)
    for r in reqs:
        r.metadata = dict(r.metadata or {}, tenant=tok)
    return reqs, payload


def _shed_of(inst, tok):
    return inst.metrics.counter(f"tenant.shed.{tok}").value


def _offer_phase(inst, clock, demands, duration_s):
    """Paced fake-clock offering: each simulated ``DT`` tick offers
    ``demand × DT`` rows per tenant through the tenant-attributed scalar
    intake.  Returns per-tenant offered/accepted/shed."""
    from sitewhere_tpu.runtime.overload import OverloadShed

    disp = inst.dispatcher
    batches = {tok: _requests(tok, probes, max(1, int(rate * DT)))
               for tok, (rate, probes) in demands.items()}
    offered = dict.fromkeys(demands, 0)
    shed0 = {tok: _shed_of(inst, tok) for tok in demands}
    steps = int(round(duration_s / DT))
    for _ in range(steps):
        for tok, (reqs, payload) in batches.items():
            offered[tok] += len(reqs)
            try:
                disp.ingest_many(list(reqs), payload, f"src-{tok}")
            except OverloadShed:
                pass
        clock.t += DT
    disp.flush()
    out = {}
    for tok in demands:
        shed = _shed_of(inst, tok) - shed0[tok]
        out[tok] = {"offered": offered[tok], "shed": int(shed),
                    "accepted": offered[tok] - int(shed)}
    return out


def _dead_letter_rows(inst, kinds):
    rows = 0
    by_kind = {}
    for doc in inst.list_dead_letters(limit=100_000):
        kind = doc.get("kind")
        if kind in kinds:
            n = sum(doc.get("classes", {}).values())
            rows += n
            by_kind[kind] = by_kind.get(kind, 0) + n
    return rows, by_kind


def run(total_devices=100_000, n_quiet=8, duration_s=10.0,
        churn_waves=8, goodput_floor=0.9, data_dir=None, tier="full"):
    from sitewhere_tpu.runtime.overload import OverloadState

    root = data_dir or tempfile.mkdtemp(prefix="tenantfair-")
    owns_root = data_dir is None
    churn_per_wave = max(64, total_devices // 20)
    capacity = 1 << int(
        total_devices + churn_waves * churn_per_wave + 4096).bit_length()
    inst = _make_instance(os.path.join(root, "data"), capacity)
    t_wall = time.perf_counter()
    inst.start()
    checks = []

    def check(name, ok, detail):
        checks.append({"name": name, "pass": bool(ok), "detail": detail})

    try:
        quiet = [f"t-quiet{i}" for i in range(n_quiet)]
        fleet = _populate(inst, quiet, "t-noisy", total_devices)
        setup_s = time.perf_counter() - t_wall

        # deterministic admission: swap the controller onto a fake
        # clock BEFORE any bucket exists, pin DEGRADED
        clock = FakeClock()
        inst.overload._clock = clock
        inst.overload._buckets.clear()
        inst.overload.force(OverloadState.DEGRADED, "bench")
        quiet_demand = {tok: (QUIET_DEMAND, fleet[tok]["probes"])
                        for tok in quiet}

        # ---- phase 1: isolated baseline — the quiet fleet alone
        baseline = _offer_phase(inst, clock, quiet_demand, duration_s)

        # ---- phase 2: contended — the adversarial tenant joins at
        # ~10× its fair cut; same quiet demand, same duration
        demands = dict(quiet_demand)
        demands["t-noisy"] = (NOISY_DEMAND, fleet["t-noisy"]["probes"])
        contended = _offer_phase(inst, clock, demands, duration_s)

        worst_frac = min(
            (contended[t]["accepted"] / max(1, baseline[t]["accepted"]))
            for t in quiet)
        check("quiet_goodput_floor", worst_frac >= goodput_floor,
              f"worst quiet contended/baseline goodput "
              f"{worst_frac:.3f} (floor {goodput_floor})")
        check("quiet_never_shed",
              all(contended[t]["shed"] == 0 for t in quiet),
              f"quiet sheds: { {t: contended[t]['shed'] for t in quiet} }")

        noisy = contended["t-noisy"]
        budget_ceiling = NOISY_RATE * duration_s + NOISY_BURST
        check("noisy_clipped_to_budget",
              0 < noisy["accepted"] <= budget_ceiling + 1,
              f"noisy accepted {noisy['accepted']} of "
              f"{noisy['offered']} offered "
              f"(budget ceiling {budget_ceiling:.0f})")
        budget_letters = [d for d in inst.list_dead_letters(limit=100_000)
                          if d.get("kind") == "tenant-budget"]
        check("budget_sheds_dead_lettered",
              sum(sum(d["classes"].values()) for d in budget_letters)
              == noisy["shed"]
              and all(d["tenant"] == "t-noisy" and "budget" in d
                      for d in budget_letters),
              f"{len(budget_letters)} tenant-budget letters carry "
              f"{noisy['shed']} shed rows with the clipping budget")

        # ---- phase 3: SHEDDING — telemetry refused wholesale, but the
        # critical class still flows (the ladder's priority floor)
        from sitewhere_tpu.ingest.decoders import DecodedRequest, RequestKind
        from sitewhere_tpu.runtime.overload import OverloadShed

        inst.overload.force(OverloadState.SHEDDING, "bench")
        shedding = _offer_phase(
            inst, clock, {quiet[0]: (QUIET_DEMAND, 1)}, duration_s / 5)
        alert = DecodedRequest(
            kind=RequestKind.ALERT, device_token=f"{quiet[0]}-d0",
            ts_s=1_753_800_000, mtype="overheat", value=1.0,
            metadata={"tenant": quiet[0], "level": "warning",
                      "message": "hot"})
        alert_refused = False
        try:
            inst.dispatcher.ingest_many([alert], b"bench:alert",
                                        "src-alert")
        except OverloadShed:
            alert_refused = True
        check("shedding_refuses_telemetry_not_critical",
              shedding[quiet[0]]["accepted"] == 0 and not alert_refused,
              f"SHEDDING: {shedding[quiet[0]]['shed']} telemetry rows "
              f"refused, critical alert admitted={not alert_refused}")

        # ---- phase 4: recovery + budget-shed replay
        inst.overload.force(OverloadState.NORMAL, "bench")
        recovered = _offer_phase(
            inst, clock, {"t-noisy": (NOISY_DEMAND, 4)}, duration_s / 5)
        requeue = inst.requeue_dead_letter(budget_letters[0]["offset"])
        inst.dispatcher.flush()
        check("recovery_restores_noisy_and_replays_budget_sheds",
              recovered["t-noisy"]["shed"] == 0
              and requeue.get("requeued") is True,
              f"NORMAL: noisy {recovered['t-noisy']['accepted']} rows "
              f"admitted unclipped; tenant-budget requeue returned "
              f"{requeue.get('rows', 0)} rows")

        # ---- phase 5: zero-loss accounting over every phase
        inst.dispatcher.flush()
        inst.event_store.flush()
        offered_total = (
            sum(p[t]["offered"] for p, sel in
                ((baseline, quiet), (contended, list(demands)),
                 (shedding, [quiet[0]]), (recovered, ["t-noisy"]))
                for t in sel) + 1)                      # + the alert
        letter_rows, by_kind = _dead_letter_rows(
            inst, ("tenant-budget", "intake-shed"))
        accepted_total = int(inst.dispatcher.totals["accepted"])
        requeued_rows = int(requeue.get("rows", 0))
        lost = offered_total + requeued_rows - accepted_total - letter_rows
        check("zero_rows_lost", lost == 0,
              f"offered {offered_total} + requeued {requeued_rows} = "
              f"accepted {accepted_total} + dead-lettered {letter_rows} "
              f"(delta {lost})")
        sealed = int(inst.event_store.total_events)
        check("accepted_rows_sealed", sealed == accepted_total,
              f"{sealed} sealed of {accepted_total} accepted")

        # ---- phase 6: churn storm — noisy registers devices in waves;
        # untouched tenants' partition compile_count must stay flat
        parts = inst.device_state.partitions
        parts.refresh()
        tid = {tok: int(inst.identity.tenant.lookup(tok))
               for tok in quiet + ["t-noisy"]}
        before = {tok: parts.compile_count(tid[tok])
                  for tok in quiet + ["t-noisy"]}
        tdm = inst.engines.get_engine("t-noisy").device_management
        base = fleet["t-noisy"]["devices"]
        for wave in range(churn_waves):
            for i in range(churn_per_wave):
                tdm.create_device(
                    token=f"t-noisy-churn{wave}-{i}",
                    device_type="t-noisy-type")
            parts.refresh()
        after = {tok: parts.compile_count(tid[tok])
                 for tok in quiet + ["t-noisy"]}
        check("churn_storm_partition_isolation",
              all(after[t] == before[t] for t in quiet)
              and after["t-noisy"] > before["t-noisy"],
              f"quiet compile_counts flat at "
              f"{ {t: after[t] for t in quiet} }; noisy "
              f"{before['t-noisy']} -> {after['t-noisy']} over "
              f"{churn_waves} waves x {churn_per_wave} devices")
        summary = inst.device_state.tenant_state_summary(tid["t-noisy"])
        check("partition_view_consistent",
              summary["devices"] == base + churn_waves * churn_per_wave
              and summary["capacity"] >= summary["devices"],
              f"noisy partition {summary['devices']} devices on a "
              f"{summary['capacity']}-row rung "
              f"(compile_count {summary['compile_count']})")

        return {
            "tier": tier,
            "devices": total_devices,
            "registry_capacity": capacity,
            "tenants": {tok: f["devices"] for tok, f in fleet.items()},
            "setup_s": round(setup_s, 2),
            "wall_s": round(time.perf_counter() - t_wall, 2),
            "config": {
                "uniform_rate_per_s": UNIFORM_RATE,
                "uniform_burst": UNIFORM_BURST,
                "noisy_budget_rate_per_s": NOISY_RATE,
                "noisy_budget_burst": NOISY_BURST,
                "quiet_demand_rows_per_s": QUIET_DEMAND,
                "noisy_demand_rows_per_s": NOISY_DEMAND,
                "duration_s": duration_s,
            },
            "phases": {
                "baseline": baseline,
                "contended": contended,
                "shedding": shedding,
                "recovery": recovered,
                "dead_letters": by_kind,
            },
            "checks": checks,
            "ok": all(c["pass"] for c in checks),
        }
    finally:
        inst.stop()
        inst.terminate()
        if owns_root:
            shutil.rmtree(root, ignore_errors=True)


def _render(result) -> str:
    out = [f"tenant_fairness_bench [{result['tier']}]: "
           f"{result['devices']} devices, "
           f"{len(result['tenants'])} tenants, "
           f"wall {result['wall_s']:.1f}s"]
    contended = result["phases"]["contended"]
    for tok in sorted(contended):
        r = contended[tok]
        frac = r["accepted"] / max(1, r["offered"])
        bar = "#" * max(1, int(30 * frac))
        out.append(f"  {tok:>10} {r['accepted']:>7}/{r['offered']:<7} "
                   f"{bar}")
    for c in result["checks"]:
        out.append(f"  [{'PASS' if c['pass'] else 'FAIL'}] "
                   f"{c['name']}: {c['detail']}")
    return "\n".join(out)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="noisy-neighbor isolation proof "
                    "(budgets, quotas, partitions)")
    parser.add_argument("--devices", type=int, default=100_000)
    parser.add_argument("--quiet-tenants", type=int, default=8)
    parser.add_argument("--duration", type=float, default=10.0,
                        help="simulated seconds per offered phase")
    parser.add_argument("--churn-waves", type=int, default=8)
    parser.add_argument("--goodput-floor", type=float, default=0.9)
    parser.add_argument("--smoke", action="store_true",
                        help="small fleet, short phases (tier-1 gate)")
    parser.add_argument("--json", action="store_true")
    parser.add_argument("--out", help="write the JSON result here")
    args = parser.parse_args(argv)
    kw = dict(total_devices=args.devices, n_quiet=args.quiet_tenants,
              duration_s=args.duration, churn_waves=args.churn_waves,
              goodput_floor=args.goodput_floor, tier="full")
    if args.smoke:
        kw.update(total_devices=min(args.devices, 2_000), n_quiet=4,
                  duration_s=2.0, churn_waves=4, tier="smoke")
    result = run(**kw)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(_render(result))
    if not result["ok"]:
        for c in result["checks"]:
            if not c["pass"]:
                print(f"FAIL: {c['name']}: {c['detail']}",
                      file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
