#!/usr/bin/env python
"""Chaos smoke: boot an instance under random seeded faults, assert
clean recovery.

Arms a random (but seed-reproducible) subset of the pipeline's fault
injection points (``sitewhere_tpu/runtime/faults.py``), drives wire
traffic through a real instance, then clears the faults, simulates the
crash/restart recovery path (journal replay past the committed offset),
and asserts the at-least-once contract: every journaled row is in the
event store afterwards, and the resilience counters surfaced.

Usage::

    python tools/chaos_smoke.py [seed]

Exit status 0 = clean recovery; any loss or a boot abort is fatal.
Re-running with the printed seed reproduces the exact fault schedule.
"""

import json
import os
import random
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Chaos wants deterministic CPU, and the JAX_PLATFORMS env var is
# overridden by platform sitecustomize hooks — force it via the config
# API before any backend initializes (same approach as tests/conftest.py).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from sitewhere_tpu.runtime import faults  # noqa: E402

# Points on the wire → journal → step → store path.  Probabilistic and
# permanent-until-cleared: the run is a storm, recovery happens after.
FAULT_CATALOG = [
    ("dispatcher.step", 0.3),
    ("dispatcher.egress", 0.3),
    # the segment store's background seal workers (store/sealer.py);
    # event_store.flush is the legacy single-writer point, kept for
    # stores still on the base EventStore
    ("event_store.seal", 0.5),
    ("event_store.flush", 0.5),
]

N_PAYLOADS = 40
ROWS_PER_PAYLOAD = 8


def _line(token, value, ts):
    return json.dumps({
        "deviceToken": token, "type": "Measurement",
        "request": {"name": "temp", "value": value, "eventDate": ts},
    })


def _make_instance(data_dir):
    from sitewhere_tpu.instance import Instance
    from sitewhere_tpu.runtime.config import Config

    cfg = Config({
        "instance": {"id": "chaos-smoke", "data_dir": data_dir},
        "pipeline": {"width": 64, "registry_capacity": 256,
                     "mtype_slots": 4, "deadline_ms": 5.0, "n_shards": 1},
        "presence": {"scan_interval_s": 3600.0, "missing_after_s": 1800},
    }, apply_env=False)
    return Instance(cfg)


def main() -> int:
    seed = int(sys.argv[1]) if len(sys.argv) > 1 else random.SystemRandom().randrange(1 << 30)
    rng = random.Random(seed)
    armed = [(point, p) for point, p in FAULT_CATALOG if rng.random() < 0.8]
    print(f"chaos_smoke: seed={seed} armed={[p for p, _ in armed]}")

    root = tempfile.mkdtemp(prefix="chaos-smoke-")
    data_dir = os.path.join(root, "data")
    failures = []
    try:
        inst = _make_instance(data_dir)
        inst.start()
        dm = inst.device_management
        dm.create_device_type(token="sensor", name="Sensor")
        for i in range(8):
            dm.create_device(token=f"d-{i}", device_type="sensor")
            dm.create_device_assignment(device=f"d-{i}")

        # -- the storm ----------------------------------------------------
        for point, prob in armed:
            faults.inject(point, exc=OSError(f"chaos {point}"),
                          times=None, probability=prob,
                          seed=rng.randrange(1 << 30))
        ingested = 0
        for k in range(N_PAYLOADS):
            lines = [
                _line(f"d-{(k + r) % 8}", float(k),
                      1_753_800_000 + k * ROWS_PER_PAYLOAD + r)
                for r in range(ROWS_PER_PAYLOAD)
            ]
            payload = "\n".join(lines).encode()
            try:
                inst.dispatcher.ingest_wire_lines(payload)
                ingested += ROWS_PER_PAYLOAD
            except Exception:
                # the payload is journaled before the plan runs: a
                # mid-step fault loses nothing durable
                ingested += ROWS_PER_PAYLOAD
        time.sleep(0.1)  # let the deadline loop chew (and crash) freely
        fault_hits = {p: faults.fired(p) for p, _ in armed}

        # -- recovery -----------------------------------------------------
        faults.clear()
        # crash analog: in-memory outstanding-plan state dies with the
        # process; the journal (committed offset) is the durable truth
        with inst.dispatcher._lock:
            inst.dispatcher._plans_outstanding = 0
            inst.dispatcher._inflight.clear()
        inst.dispatcher.replay_journal()
        inst.dispatcher.flush()
        inst.event_store.flush()

        stored = inst.event_store.total_events
        dead = inst.dead_letters.end_offset
        resilience = inst.topology().get("resilience", {})
        # the overload controller may legitimately shed telemetry DURING
        # the fault storm (seal lag spikes are exactly its signal); shed
        # rows are dead-lettered at intake, not journaled — they are
        # audited, not lost
        storm_sheds = (inst.overload.shed_total
                       if inst.overload is not None else 0)
        if stored + storm_sheds < ingested:
            # at-least-once: replay may duplicate, must never lose
            failures.append(
                f"event loss: ingested {ingested}, stored {stored}, "
                f"shed (audited) {storm_sheds}")
        if fault_hits.get("event_store.flush") and not resilience.get(
                "resilience.retries.event_store.seal"):
            # seal failures route through the shared retry primitive —
            # its counter must reach the topology surface
            failures.append("seal faults fired but the retry counter "
                            "never reached the topology surface")
        # -- overload: the ladder sheds telemetry, never alerts -----------
        from sitewhere_tpu.runtime.overload import (
            OverloadShed,
            OverloadState,
        )

        overload_report = {}
        if inst.overload is not None:
            inst.overload.force(OverloadState.SHEDDING, reason="chaos")
            telemetry = _line("d-0", 1.0, 1_753_900_000).encode()
            alert = json.dumps({
                "deviceToken": "d-0", "type": "Alert",
                "request": {"type": "overheat", "level": "warning",
                            "eventDate": 1_753_900_001}}).encode()
            shed_signalled = False
            try:
                inst.dispatcher.ingest_wire_lines(telemetry, "chaos-smoke")
            except OverloadShed:
                shed_signalled = True
            if not shed_signalled:
                failures.append("SHEDDING did not shed telemetry intake")
            alert_rows = inst.dispatcher.ingest_wire_lines(
                alert, "chaos-smoke")
            if alert_rows != 1:
                failures.append("alert-class intake was shed (never "
                                "allowed, in any overload state)")
            shed_letters = [
                d for d in inst.list_dead_letters(limit=50)
                if d.get("kind") == "intake-shed"
            ]
            if not shed_letters:
                failures.append("shed intake was not dead-lettered")
            inst.overload.force(OverloadState.NORMAL, reason="chaos-done")
            inst.dispatcher.flush()
            inst.event_store.flush()
            stored = inst.event_store.total_events  # alert row sealed too
            ingested += 1
            overload_report = inst.overload.snapshot()

        # -- device-fault phase (ISSUE 16): mid-storm device faults are
        # CONTAINED — faulted dispatches retry/bisect with zero row
        # loss, and a NaN row is masked + counted on the device's
        # packed telemetry instead of corrupting state
        faults.device_inject("device.dispatch", exc=OSError("dead chip"),
                             times=2, seed=rng.randrange(1 << 30))
        dev_rows = 0
        for k in range(6):
            lines = [
                _line(f"d-{(k + r) % 8}",
                      float("nan") if k == 3 and r == 0 else float(k),
                      1_754_000_000 + k * ROWS_PER_PAYLOAD + r)
                for r in range(ROWS_PER_PAYLOAD)
            ]
            inst.dispatcher.ingest_wire_lines("\n".join(lines).encode())
            dev_rows += ROWS_PER_PAYLOAD
        inst.dispatcher.flush()
        faults.device_clear()
        inst.event_store.flush()
        dev_after = inst.event_store.total_events
        counters = inst.metrics.snapshot()["counters"]
        dev_faults = (int(counters.get("device.fault.step_faults", 0))
                      + int(counters.get("device.fault.chain_faults", 0)))
        if dev_faults < 1:
            failures.append("device faults armed but the containment "
                            "path never counted one")
        if dev_after - stored < dev_rows:
            failures.append(
                f"device-fault containment lost rows: {dev_rows} "
                f"ingested, {dev_after - stored} stored")
        if int(counters.get("pipeline.quarantine.rows_nonfinite", 0)) < 1:
            failures.append("a NaN row never reached the device-counted "
                            "nonfinite telemetry")
        stored = dev_after
        ingested += dev_rows
        device_report = {
            "rows": dev_rows,
            "step_faults": dev_faults,
            "rows_nonfinite": int(counters.get(
                "pipeline.quarantine.rows_nonfinite", 0)),
            "breaker": inst.dispatcher.breaker.snapshot(),
        }

        inst.stop()
        inst.terminate()

        # -- reboot: the store + journal must come back clean -------------
        inst2 = _make_instance(data_dir)
        inst2.start()
        restored = inst2.event_store.total_events
        if restored < stored - inst2.event_store.sealed_dead_lettered:
            failures.append(
                f"restart lost events: {stored} before, {restored} after")

        # -- kill-restart phase (ISSUE 12): journal records that never
        # reach the pipeline (the crash window between Journal.append
        # and egress), kill without stop, and prove the next boot
        # restores the checkpoint + replays them with measured RTO
        crash_rows = 3
        for r in range(crash_rows):
            inst2.ingest_journal.append(
                _line(f"d-{r}", 77.0, 1_753_950_000 + r).encode())
        inst2.ingest_journal.close()
        inst2.dead_letters.close()
        del inst2  # simulated SIGKILL — no stop, no final checkpoint

        inst3 = _make_instance(data_dir)
        if not inst3.restored:
            failures.append("kill-restart: checkpoint did not restore")
        inst3.start()  # restore ran in __init__; start replays
        inst3.dispatcher.flush()
        inst3.event_store.flush()
        gauges = inst3.metrics.snapshot()["gauges"]
        replayed = int(gauges.get("recovery.replay_events", 0))
        if replayed < crash_rows:
            failures.append(
                f"kill-restart: expected >= {crash_rows} replayed "
                f"events, recovery.replay_events={replayed}")
        if not gauges.get("recovery.restore_s", 0.0) > 0:
            failures.append(
                "kill-restart: recovery.restore_s gauge missing/zero")
        after_kill = inst3.event_store.total_events
        if after_kill < restored + crash_rows:
            failures.append(
                f"kill-restart lost events: {restored}+{crash_rows} "
                f"journaled, {after_kill} stored")
        recovery_report = {
            "replayed": replayed,
            "restore_s": round(float(gauges.get("recovery.restore_s",
                                                0.0)), 4),
            "replay_s": round(float(gauges.get("recovery.replay_s",
                                               0.0)), 4),
        }
        inst3.stop()
        inst3.terminate()

        # -- fleet phase (ISSUE 14): a SHEDDING peer must park the
        # forward spool (paced probes, zero dead letters), the edge
        # must refuse with the OWNER's hint, and recovery must drain
        # the spool to zero
        from sitewhere_tpu.rpc import (
            HostForwarder,
            RpcDemux,
            RpcServer,
            bind_instance,
        )
        from sitewhere_tpu.rpc.forward import owning_process

        peer = _make_instance(os.path.join(root, "peer"))
        peer.start()
        peer.device_management.create_device_type(token="sensor", name="S")
        tok = next(f"p-{i}" for i in range(100)
                   if owning_process(f"p-{i}", 2) == 1)
        peer.device_management.create_device(token=tok,
                                             device_type="sensor")
        peer.device_management.create_device_assignment(device=tok)
        srv = RpcServer(port=0, tokens=peer.tokens)
        bind_instance(srv, peer)
        srv.overload_provider = lambda: (int(peer.overload.state),
                                         peer.overload.retry_after())
        srv.start()
        jwt = peer.tokens.mint("system", ["ROLE_ADMIN"])
        demux = RpcDemux([srv.endpoint], token_provider=lambda: jwt)
        fwd = HostForwarder(None, 0, {0: None, 1: demux},
                            data_dir=os.path.join(root, "fwd-spool"),
                            max_retries=1, heartbeat_interval_s=0)
        fwd.start()
        fleet_report = {}
        try:
            line = _line(tok, 5.0, 1_753_960_000).encode()
            peer.overload.force(OverloadState.SHEDDING, reason="chaos-fleet")
            # rows sent into a shedding owner park in the spool (the
            # first delivery learns the state off the refusal's
            # piggyback headers) — never a dead letter
            fwd.ingest_payload(line)
            fwd.flush(wait=True)
            if fwd.dead_lettered:
                failures.append("fleet: rows for a SHEDDING owner were "
                                "dead-lettered instead of retained")
            if fwd.pending_rows() != 1:
                failures.append("fleet: shed rows not retained in spool")
            # a paced-probe window must stay bounded: hammer flushes
            attempts0 = int(fwd._m_attempts.value)
            for _ in range(25):
                fwd.flush(wait=True)
            storm = int(fwd._m_attempts.value) - attempts0
            fleet_report["parked_window_attempts"] = storm
            if storm > 3:
                failures.append(
                    f"fleet: {storm} send attempts while parked — "
                    "retry storm, probes not paced")
            # the device-facing edge refuses with the owner's hint
            try:
                fwd.ingest_payload(_line(tok, 6.0, 1_753_960_001).encode())
                failures.append("fleet: edge accepted a payload for a "
                                "SHEDDING owner without backpressure")
            except OverloadShed as e:
                fleet_report["edge_retry_after_s"] = e.retry_after_s
            # recovery: probes redeliver, the spool drains to zero
            peer.overload.force(OverloadState.NORMAL, reason="chaos-done")
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and fwd.pending_rows():
                fwd.flush(wait=True)
                time.sleep(0.2)
            fleet_report["pending_after_recovery"] = fwd.pending_rows()
            if fwd.pending_rows():
                failures.append("fleet: spool did not drain on recovery")
            if fwd.dead_lettered:
                failures.append("fleet: recovery dead-lettered rows")
            fleet_report["peer_health"] = fwd.health.snapshot().get("1")
        finally:
            fwd.stop()
            demux.close()
            srv.stop()
            peer.stop()
            peer.terminate()

        print(json.dumps({
            "seed": seed,
            "ingested": ingested,
            "stored": stored,
            "restored": restored,
            "dead_letters": dead,
            "fault_hits": fault_hits,
            "resilience": resilience,
            "overload": overload_report,
            "device_fault": device_report,
            "recovery": recovery_report,
            "fleet": fleet_report,
            "ok": not failures,
        }, indent=2))
    finally:
        faults.clear()
        faults.device_clear()
        shutil.rmtree(root, ignore_errors=True)

    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("chaos_smoke: clean recovery")
    return 0


if __name__ == "__main__":
    sys.exit(main())
