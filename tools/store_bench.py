#!/usr/bin/env python
"""Segment-store benchmark: seal throughput + retrospective scan lane.

Two phases, matching the segment store's two claims (ISSUE 13):

1. **seal** — sustained ``append_columns`` throughput into the sharded
   segment store with the background worker pool live, vs the legacy
   single-writer ``EventStore``.  The number that matters is the
   PERCEIVED per-batch append cost (the hot path's whole seal bill:
   shard-routed packed row copy + O(1) job enqueue) next to the
   measured background seal time per segment (``store.seal_s``).

2. **retro** — a retrospective windowed query over the stored history,
   two ways over the SAME segment files:

   - *legacy row scan*: materialize every segment's columns from disk
     and row-filter — the pre-catalog behavior (no zone-map/Bloom
     segment pruning, no hot tier);
   - *scan lane*: ``SegmentStore.iter_chunks`` — catalog-pruned,
     hot-tier-served, the same packed pipeline the live path feeds.

   Results must be BIT-IDENTICAL (every column compared, after a
   canonical row sort — catalog scan order interleaves shards
   differently than raw seq order, which is immaterial to a windowed
   query's result set).

Usage::

    python tools/store_bench.py                  # 10M rows (CI-scaled)
    python tools/store_bench.py --rows 2000000
    python tools/store_bench.py --smoke          # tier-1: ~100k rows
    python tools/store_bench.py --json out.json

Exit status 0 = ran + bit-identical; 1 = result divergence.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

T0 = 1_754_000_000
N_DEVICES = 512
N_TENANTS = 4


def _batch(lo: int, n: int, rng: np.random.Generator) -> dict:
    """One append batch of n rows, event time increasing with index."""
    from sitewhere_tpu.ids import NULL_ID

    dev = rng.integers(0, N_DEVICES, n, dtype=np.int64).astype(np.int32)
    return {
        "device_id": dev,
        "tenant_id": (dev % N_TENANTS).astype(np.int32),
        "event_type": (rng.random(n) < 0.9).astype(np.int32),
        "ts_s": (T0 + (lo + np.arange(n)) // 100).astype(np.int32),
        "ts_ns": ((lo + np.arange(n)) % 100).astype(np.int32) * 1000,
        "mtype_id": (dev % 4).astype(np.int32),
        "value": rng.random(n).astype(np.float32) * 100.0,
        "lat": np.zeros(n, np.float32),
        "lon": np.zeros(n, np.float32),
        "elevation": np.zeros(n, np.float32),
        "alert_code": np.full(n, NULL_ID, np.int32),
        "alert_level": np.zeros(n, np.int32),
        "command_id": np.full(n, NULL_ID, np.int32),
        "payload_ref": np.full(n, NULL_ID, np.int32),
        "device_type_id": np.zeros(n, np.int32),
        "assignment_id": dev,
        "area_id": np.zeros(n, np.int32),
        "customer_id": np.zeros(n, np.int32),
        "asset_id": np.zeros(n, np.int32),
    }


def _fill(store, rows: int, batch_rows: int, seed: int = 7,
          append_samples: list | None = None) -> float:
    """Append ``rows`` rows; returns wall seconds to fully durable.
    ``append_samples`` (optional) collects per-append wall seconds —
    the PERCEIVED ingest cost, where "gated on seal" shows up as p99
    spikes."""
    rng = np.random.default_rng(seed)
    t0 = time.perf_counter()
    lo = 0
    while lo < rows:
        n = min(batch_rows, rows - lo)
        batch = _batch(lo, n, rng)
        ta = time.perf_counter()
        store.append_columns(batch)
        if append_samples is not None:
            append_samples.append(time.perf_counter() - ta)
        lo += n
    store.flush(sync=True)
    return time.perf_counter() - t0


def _pctl(samples: list, q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(q * len(s)))]


def _row_key(cols: dict) -> np.ndarray:
    """Canonical sort order for result comparison (time, then device,
    then sub-second) — windowed-query results are row SETS; scan order
    across shards is an implementation detail."""
    return np.lexsort((cols["ts_ns"], cols["device_id"],
                       cols["ts_s"]))


def _concat(parts: list) -> dict:
    from sitewhere_tpu.store.segment import COLUMN_NAMES

    if not parts:
        return {name: np.zeros(0, np.int32) for name in COLUMN_NAMES}
    return {name: np.concatenate([p[name] for p in parts])
            for name in COLUMN_NAMES}


def _legacy_row_scan(store, **filters) -> list:
    """The pre-catalog retrospective path: EVERY segment's columns come
    off disk and every row is mask-filtered — no zone-map/Bloom segment
    pruning, no hot tier.  (This is what ``iter_chunks`` did before the
    segment catalog, modulo the time-bound chunk skip it shared with
    the query path — withheld here to represent the plain row scan the
    H-STREAM comparison argues against.)"""
    from sitewhere_tpu.store.segment import SegmentPruned

    store.flush()
    with store._lock:
        segments = list(store._chunks)
    out = []
    for seg in segments:
        try:
            cols = seg.materialize()
        except SegmentPruned:
            continue
        mask = np.ones(seg.n, bool)
        for name in ("event_type", "mtype_id", "device_id", "tenant_id"):
            want = filters.get(name)
            if want is not None:
                mask &= cols[name] == want
        if filters.get("start_s") is not None:
            mask &= cols["ts_s"] >= filters["start_s"]
        if filters.get("end_s") is not None:
            mask &= cols["ts_s"] <= filters["end_s"]
        if mask.all():
            out.append(cols)
        elif mask.any():
            out.append({k: v[mask] for k, v in cols.items()})
    return out


def _bit_identical(a: dict, b: dict) -> bool:
    from sitewhere_tpu.store.segment import COLUMN_NAMES

    if len(a["ts_s"]) != len(b["ts_s"]):
        return False
    ia, ib = _row_key(a), _row_key(b)
    return all(np.array_equal(a[name][ia], b[name][ib])
               for name in COLUMN_NAMES)


def run(rows: int = 10_000_000, batch_rows: int = 65_536,
        flush_rows: int = 65_536, seal_workers: int = 2,
        n_shards: int = 4, keep_dir: str | None = None) -> dict:
    from sitewhere_tpu.runtime.metrics import MetricsRegistry
    from sitewhere_tpu.services.event_store import EventStore
    from sitewhere_tpu.store.segmented import SegmentStore

    results: dict = {"rows": rows, "batch_rows": batch_rows,
                     "flush_rows": flush_rows,
                     "seal_workers": seal_workers, "n_shards": n_shards}
    root = keep_dir or tempfile.mkdtemp(prefix="store-bench-")
    try:
        # -- phase 1: seal throughput ------------------------------------
        seal_rows = min(rows, 2_000_000)
        legacy = EventStore(os.path.join(root, "legacy-seal"),
                            flush_rows=flush_rows)
        legacy.start()
        legacy_appends: list = []
        try:
            dt = _fill(legacy, seal_rows, batch_rows,
                       append_samples=legacy_appends)
        finally:
            legacy.stop()
        results["seal_rows"] = seal_rows
        results["legacy_seal_s"] = dt
        results["legacy_seal_rows_per_s"] = seal_rows / dt
        results["legacy_append_p50_s"] = _pctl(legacy_appends, 0.50)
        results["legacy_append_p99_s"] = _pctl(legacy_appends, 0.99)

        metrics = MetricsRegistry()
        seg = SegmentStore(os.path.join(root, "segmented-seal"),
                           flush_rows=flush_rows, n_shards=n_shards,
                           seal_workers=seal_workers,
                           compact_interval_s=0.0, metrics=metrics)
        seg.sealer.start()
        seg_appends: list = []
        try:
            dt = _fill(seg, seal_rows, batch_rows,
                       append_samples=seg_appends)
        finally:
            seg.sealer.stop()
        results["store_seal_s"] = dt
        results["store_seal_rows_per_s"] = seal_rows / dt
        results["store_append_p50_s"] = _pctl(seg_appends, 0.50)
        results["store_append_p99_s"] = _pctl(seg_appends, 0.99)
        hist = metrics.histogram("store.seal_s")
        results["store_seal_bg_s_per_segment"] = (
            hist.total / hist.count if hist.count else 0.0)
        results["store_seal_segments"] = int(hist.count)
        results["seal_speedup"] = (results["store_seal_rows_per_s"]
                                   / results["legacy_seal_rows_per_s"])
        results["append_p99_speedup"] = (
            results["legacy_append_p99_s"]
            / results["store_append_p99_s"]
            if results["store_append_p99_s"] else 0.0)

        # -- phase 2: retrospective windowed query -----------------------
        data_dir = os.path.join(root, "retro")
        metrics2 = MetricsRegistry()
        store = SegmentStore(data_dir, flush_rows=flush_rows,
                             n_shards=n_shards, seal_workers=seal_workers,
                             compact_interval_s=0.0, metrics=metrics2)
        store.sealer.start()
        try:
            results["retro_fill_s"] = _fill(store, rows, batch_rows)
        finally:
            store.sealer.stop()

        # the window: the central ~1% of event time, measurements only —
        # a "what happened around the incident" retrospective query
        # (the 100 h slice of a ~1-year history)
        span = rows // 100
        mid = T0 + (rows // 100) // 2
        filters = {"event_type": 1, "start_s": int(mid - span // 200),
                   "end_s": int(mid + span // 200)}
        results["retro_filters"] = dict(filters)

        # legacy pass on a COLD store instance (empty column cache)
        cold = SegmentStore(data_dir, flush_rows=flush_rows,
                            n_shards=n_shards, seal_workers=seal_workers,
                            compact_interval_s=0.0,
                            metrics=MetricsRegistry())
        t0 = time.perf_counter()
        legacy_parts = _legacy_row_scan(cold, **filters)
        legacy_dt = time.perf_counter() - t0
        legacy_res = _concat(legacy_parts)

        # scan lane on a second cold instance (fair: same cache state)
        lane_metrics = MetricsRegistry()
        lane = SegmentStore(data_dir, flush_rows=flush_rows,
                            n_shards=n_shards, seal_workers=seal_workers,
                            compact_interval_s=0.0, metrics=lane_metrics)
        t0 = time.perf_counter()
        lane_parts = list(lane.iter_chunks(**filters))
        lane_dt = time.perf_counter() - t0
        lane_res = _concat(lane_parts)

        n_match = int(len(lane_res["ts_s"]))
        results["retro_matched_rows"] = n_match
        results["retro_legacy_scan_s"] = legacy_dt
        results["retro_lane_scan_s"] = lane_dt
        results["retro_legacy_events_per_s"] = rows / legacy_dt
        results["retro_lane_events_per_s"] = rows / lane_dt
        results["retro_speedup"] = legacy_dt / lane_dt if lane_dt else 0.0
        results["retro_segments_pruned"] = int(
            lane_metrics.counter("store.scan_pruned").value)
        results["retro_hot_hits"] = int(
            lane_metrics.counter("store.scan_hot_hits").value)
        results["bit_identical"] = _bit_identical(legacy_res, lane_res)

        # a second lane pass: promote-on-scan has heated the window
        t0 = time.perf_counter()
        for _ in lane.iter_chunks(**filters):
            pass
        results["retro_lane_warm_s"] = time.perf_counter() - t0
    finally:
        if keep_dir is None:
            shutil.rmtree(root, ignore_errors=True)
    return results


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="segment-store seal + retrospective-scan benchmark")
    parser.add_argument("--rows", type=int, default=10_000_000)
    parser.add_argument("--batch-rows", type=int, default=65_536)
    parser.add_argument("--flush-rows", type=int, default=65_536)
    parser.add_argument("--seal-workers", type=int, default=2)
    parser.add_argument("--shards", type=int, default=4)
    parser.add_argument("--smoke", action="store_true",
                        help="CI scale: ~100k rows")
    parser.add_argument("--json", dest="json_out")
    args = parser.parse_args(argv)

    rows = 100_000 if args.smoke else args.rows
    flush_rows = 8_192 if args.smoke else args.flush_rows
    batch_rows = min(args.batch_rows, flush_rows)
    r = run(rows=rows, batch_rows=batch_rows, flush_rows=flush_rows,
            seal_workers=args.seal_workers, n_shards=args.shards)
    print(f"seal ({r['seal_rows']:,} rows): "
          f"legacy {r['legacy_seal_rows_per_s']:,.0f} rows/s | "
          f"segmented {r['store_seal_rows_per_s']:,.0f} rows/s "
          f"({r['seal_speedup']:.2f}x; background "
          f"{r['store_seal_bg_s_per_segment'] * 1e3:.1f} ms/segment "
          f"x {r['store_seal_segments']} segments)")
    print(f"  perceived append (ingest gated on seal?): legacy "
          f"p50 {r['legacy_append_p50_s'] * 1e3:.2f} / p99 "
          f"{r['legacy_append_p99_s'] * 1e3:.2f} ms | segmented "
          f"p50 {r['store_append_p50_s'] * 1e3:.2f} / p99 "
          f"{r['store_append_p99_s'] * 1e3:.2f} ms "
          f"({r['append_p99_speedup']:.1f}x at p99)")
    print(f"retro ({r['rows']:,} rows, {r['retro_matched_rows']:,} "
          f"matched): legacy row scan {r['retro_legacy_scan_s']:.3f} s "
          f"({r['retro_legacy_events_per_s']:,.0f} events/s) | scan "
          f"lane {r['retro_lane_scan_s']:.3f} s "
          f"({r['retro_lane_events_per_s']:,.0f} events/s) -> "
          f"{r['retro_speedup']:.1f}x  "
          f"[{r['retro_segments_pruned']} segments pruned, warm rescan "
          f"{r['retro_lane_warm_s']:.3f} s]")
    print(f"bit-identical: {r['bit_identical']}")
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(r, f, indent=2)
    return 0 if r["bit_identical"] else 1


if __name__ == "__main__":
    sys.exit(main())
