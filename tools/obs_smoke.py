#!/usr/bin/env python
"""Observability smoke: boot an instance, push events, scrape the
OpenMetrics exposition, and assert the whole surface holds together.

Proof obligations (the PR-2 acceptance criteria, end to end over HTTP):

- ``GET /api/instance/metrics.prom`` serves parseable OpenMetrics text
  (``parse_exposition`` VALIDATES — it does not best-effort skip);
- at least one latency histogram has non-zero bucket counts;
- the ingest→seal watermark gauge is populated after traffic;
- the ``slo.*`` burn-rate and ``device.occupancy.*`` families are on
  the scrape surface;
- a forced flight-recorder anomaly produces a JSONL snapshot that the
  REST surface lists and serves, and that parses back with committed
  batch records in it (ISSUE 9 acceptance);
- a forced-error RPC call leaves a retained trace on BOTH sides of the
  boundary (tail sampling at a 0% head rate) with the same trace_id;
- a skewed two-tenant load attributes exactly through the metering
  plane: ``GET /api/tenants/usage`` ranks the heavy tenant first with
  exact row counts, the drill-down serves its ledger row, and the
  governed ``tenant.*`` family round-trips the OpenMetrics exposition
  (ISSUE 17 acceptance).

Usage::

    python tools/obs_smoke.py

Exit status 0 = all assertions hold.
"""

import json
import os
import shutil
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Deterministic CPU: the JAX_PLATFORMS env var is overridden by platform
# sitecustomize hooks — force it via the config API before any backend
# initializes (same approach as tests/conftest.py).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

N_EVENTS = 256


def _make_instance(data_dir):
    from sitewhere_tpu.instance import Instance
    from sitewhere_tpu.runtime.config import Config

    cfg = Config({
        "instance": {"id": "obs-smoke", "data_dir": data_dir},
        "pipeline": {"width": 64, "registry_capacity": 256,
                     "mtype_slots": 4, "deadline_ms": 5.0, "n_shards": 1},
        "presence": {"scan_interval_s": 3600.0, "missing_after_s": 1800},
        # head sampler off: every retained trace below is the tail
        # sampler's doing
        "tracing": {"sample_rate": 0.0, "tail_latency_ms": 50.0},
    }, apply_env=False)
    return Instance(cfg)


def main() -> int:
    from sitewhere_tpu.runtime.metrics import parse_exposition
    from sitewhere_tpu.web import WebServer

    root = tempfile.mkdtemp(prefix="obs-smoke-")
    failures = []
    try:
        inst = _make_instance(os.path.join(root, "data"))
        inst.start()
        dm = inst.device_management
        dm.create_device_type(token="sensor", name="Sensor")
        for i in range(4):
            dm.create_device(token=f"d-{i}", device_type="sensor")
            dm.create_device_assignment(device=f"d-{i}")
        web = WebServer(inst)
        web.start()

        # -- traffic ------------------------------------------------------
        lines = [json.dumps({
            "deviceToken": f"d-{r % 4}", "type": "Measurement",
            "request": {"name": "temp", "value": float(r),
                        "eventDate": 1_753_800_000 + r}})
            for r in range(N_EVENTS)]
        inst.dispatcher.ingest_wire_lines("\n".join(lines).encode())
        inst.dispatcher.flush()
        inst.event_store.flush()

        # -- tenant metering: skewed two-tenant load (ISSUE 17).  Devices
        #    are tenant-owned, so per-tenant attribution needs tenants +
        #    devices created through their engines; per-row tenancy rides
        #    the decoded-request metadata.
        from sitewhere_tpu.ingest.decoders import DecodedRequest, RequestKind

        tenant_rows = {"acme": 384, "beta": 128}    # 3:1 skew
        for tok, n in tenant_rows.items():
            inst.tenants.create_tenant(token=tok, name=tok.title(),
                                       auth_token=f"{tok}-auth-token-123")
            tdm = inst.engines.get_engine(tok).device_management
            tdm.create_device_type(token=f"{tok}-sensor", name="Sensor")
            tdm.create_device(token=f"{tok}-dev",
                              device_type=f"{tok}-sensor")
            tdm.create_device_assignment(device=f"{tok}-dev")
            reqs = [DecodedRequest(
                kind=RequestKind.MEASUREMENT, device_token=f"{tok}-dev",
                ts_s=1_753_800_000 + r, mtype="temp", value=float(r),
                metadata={"tenant": tok}) for r in range(n)]
            inst.dispatcher.ingest_many(reqs, payload=b"obs-smoke")
        inst.dispatcher.flush()
        inst.event_store.flush()

        # top-K over REST: heavy tenant ranks first, counts are exact
        admin_jwt = inst.tokens.mint("admin", ["ROLE_ADMIN"])
        req = urllib.request.Request(
            f"http://127.0.0.1:{web.port}/api/tenants/usage?top=8",
            headers={"Authorization": f"Bearer {admin_jwt}"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            usage = json.loads(resp.read())
        ranked = [t["tenant"] for t in usage.get("tenants", [])]
        rows_by_tenant = {t["tenant"]: t["usage"]["rows"]
                          for t in usage.get("tenants", [])}
        if ranked[:1] != ["acme"]:
            failures.append(f"heavy tenant not ranked first: {ranked}")
        for tok, n in tenant_rows.items():
            if rows_by_tenant.get(tok) != n:
                failures.append(
                    f"tenant {tok}: expected {n} rows, "
                    f"got {rows_by_tenant.get(tok)}")
        req = urllib.request.Request(
            f"http://127.0.0.1:{web.port}/api/tenants/usage/acme",
            headers={"Authorization": f"Bearer {admin_jwt}"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            drill = json.loads(resp.read())
        if not drill.get("tracked") or \
                drill.get("usage", {}).get("rows") != 384:
            failures.append(f"tenant drill-down wrong: {drill}")

        # -- a forced-error RPC call: the acceptance proof.  The server
        #    runs on the INSTANCE tracer; the handler raises inside the
        #    rpc.server span, so the instance's tail sampler must retain
        #    it — and the caller's side retains its half with the SAME
        #    trace id (both at a 0% head rate).
        from sitewhere_tpu.rpc import RpcChannel, RpcError, RpcServer
        from sitewhere_tpu.runtime.tracing import Tracer

        def boom(ctx, body):
            raise ValueError("forced observability error")

        srv = RpcServer(port=0, tracer=inst.tracer)
        srv.register("boom", boom, auth_required=False)
        srv.start()
        client_tracer = Tracer(sample_rate=0.0, tail_errors=True)
        chan = RpcChannel(srv.endpoint)
        client_trace = client_tracer.trace("forward.batch")
        try:
            chan.call("boom", {}, trace=client_trace)
            failures.append("forced-error RPC unexpectedly succeeded")
        except RpcError:
            pass
        client_trace.end()
        chan.close()
        srv.stop()

        server_spans = [s for s in inst.tracer.recent(200)
                        if s["name"] == "rpc.server.boom"]
        client_spans = [s for s in client_tracer.recent(10)
                        if s["name"] == "rpc.client.boom"]
        if not (server_spans and server_spans[0]["error"]):
            failures.append("server side did not retain the error trace")
        if not client_spans or client_tracer.retained_tail != 1:
            failures.append("client side did not retain the error trace")
        if server_spans and client_spans and \
                client_spans[0]["trace_id"] != server_spans[0]["trace_id"]:
            failures.append("trace id did not cross the RPC boundary")

        # -- scrape -------------------------------------------------------
        url = f"http://127.0.0.1:{web.port}/api/instance/metrics.prom"
        with urllib.request.urlopen(url, timeout=10) as resp:
            ctype = resp.headers.get("Content-Type", "")
            text = resp.read().decode("utf-8")
        if not ctype.startswith("application/openmetrics-text"):
            failures.append(f"unexpected content type: {ctype}")
        families = parse_exposition(text)  # raises on malformed exposition

        histograms = {f: v for f, v in families.items()
                      if v["type"] == "histogram"}
        if not histograms:
            failures.append("no histogram families in the exposition")
        populated = [
            f for f, v in histograms.items()
            if v["samples"].get(f + "_count", 0) > 0
            and any("_bucket{" in k for k in v["samples"])
        ]
        if not populated:
            failures.append("no histogram with non-zero bucket counts")

        seal = families.get("pipeline_ingest_to_seal_latency_s", {})
        seal_v = seal.get("samples", {}).get(
            "pipeline_ingest_to_seal_latency_s", 0.0)
        if seal_v <= 0.0:
            failures.append("ingest->seal watermark gauge not populated")

        # -- SLO + device-occupancy families on the scrape ----------------
        for family in ("slo_burn_rate_p99_ms_fast",
                       "device_occupancy_rows_admitted"):
            if family not in families:
                failures.append(f"{family} missing from the exposition")

        # -- governed tenant.* family round-trips the exposition ----------
        for family in ("tenant_meter_tracked", "tenant_usage_rows_acme",
                       "tenant_usage_rows_beta", "tenant_usage_rows_other"):
            if family not in families:
                failures.append(f"{family} missing from the exposition")
        acme_rows = families.get("tenant_usage_rows_acme", {}).get(
            "samples", {}).get("tenant_usage_rows_acme", 0.0)
        if acme_rows != 384.0:
            failures.append(
                f"tenant_usage_rows_acme scraped {acme_rows}, want 384")

        # -- flight recorder: trigger an anomaly dump, read it back -------
        from sitewhere_tpu.runtime.flightrec import parse_snapshot

        if not inst.flightrec.recent(10):
            failures.append("flight recorder captured no batch records")
        dump = inst.flightrec.anomaly("obs-smoke",
                                      detail="forced by obs_smoke")
        if dump is None:
            failures.append("anomaly did not produce a snapshot")
        else:
            token = inst.tokens.mint("admin", ["ROLE_ADMIN"])
            base = f"http://127.0.0.1:{web.port}/api/instance"
            req = urllib.request.Request(
                f"{base}/flightrecorder",
                headers={"Authorization": f"Bearer {token}"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                listing = json.loads(resp.read())
            names = [s["name"] for s in listing.get("snapshots", [])]
            name = os.path.basename(dump)
            if name not in names:
                failures.append(
                    f"snapshot {name} not listed by the REST surface")
            req = urllib.request.Request(
                f"{base}/flightrecorder/snapshots/{name}",
                headers={"Authorization": f"Bearer {token}"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                snap = parse_snapshot(resp.read())   # raises on malformed
            if snap["header"]["reason"] != "obs-smoke":
                failures.append("snapshot header lost the anomaly reason")
            if not any(r.get("commit") == "ok"
                       for r in snap["records"]):
                failures.append(
                    "snapshot carries no committed batch records")

        stats = inst.tracer.stats()
        if stats["traces_retained_tail"] < 1:
            failures.append(
                f"forced-error trace was not retained: {stats}")

        web.stop()
        inst.stop()
        inst.terminate()

        print(json.dumps({
            "families": len(families),
            "histograms_populated": populated,
            "ingest_to_seal_latency_s": seal_v,
            "tenant_usage": rows_by_tenant,
            "tracer": stats,
            "flightrec": inst.flightrec.stats(),
            "ok": not failures,
        }, indent=2))
    finally:
        shutil.rmtree(root, ignore_errors=True)

    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("obs_smoke: exposition parses, histograms populated, "
          "error trace retained")
    return 0


if __name__ == "__main__":
    sys.exit(main())
