"""On-chip profiler for the fused pipeline step and its stages.

The measurement methodology that produced TPU_EVIDENCE_r03.md §7 —
fori-chain probes inside one jit call, loop-index input perturbation so
XLA cannot hoist the work, a FETCHED result (never ``block_until_ready``,
which returns early through the axon tunnel), and median-RTT
subtraction — now lives in :mod:`sitewhere_tpu.pipeline.telemetry`
(``profile_device_stages``), where the instance's on-demand calibration
endpoint and ``bench.py`` config-2 share it.  This tool is the CLI
front-end over that ONE implementation, so bench evidence and the
production ``device.stage_ms.*`` histograms can never measure different
things.

Usage::

    python tools/profile_step.py              # default backend (TPU)
    python tools/profile_step.py --cpu        # forced CPU
    python tools/profile_step.py --width 16384

Prints one line per stage: validate+enrich, threshold rules, zone rules
(geofence), state update, and the full step, plus derived events/s.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STAGE_LABELS = (
    ("validate", "validate+enrich"),
    ("rules", "threshold rules"),
    ("zones", "zone rules (geofence)"),
    ("state", "state update"),
    ("full", "FULL pipeline step"),
)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (config API, not env)")
    parser.add_argument("--width", type=int, default=131_072)
    parser.add_argument("--capacity", type=int, default=16_384)
    parser.add_argument("--active", type=int, default=10_000)
    parser.add_argument("--iters", type=int, default=64)
    parser.add_argument("--repeats", type=int, default=3,
                        help="timed chain runs per stage (median)")
    args = parser.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from sitewhere_tpu.pipeline.telemetry import profile_device_stages

    print(f"backend={jax.default_backend()} width={args.width} "
          f"capacity={args.capacity} iters={args.iters}")
    result = profile_device_stages(
        width=args.width, capacity=args.capacity, active=args.active,
        iters=args.iters, repeats=args.repeats)
    rtt_ms = result["host_rtt_ms"]
    for stage, label in STAGE_LABELS:
        print(f"{label:<24} {result[f'{stage}_ms']:8.3f} ms/iter   "
              f"(rtt {rtt_ms:.1f} ms)")
    if result.get("device_events_per_s"):
        print(f"device-side rate: {result['device_events_per_s']:,.0f} "
              "events/s")


if __name__ == "__main__":
    main()
