"""On-chip profiler for the fused pipeline step and its stages.

The measurement methodology that produced TPU_EVIDENCE_r03.md §7:

- every probe is a ``fori_loop`` CHAIN inside one jit call, so per-call
  dispatch (~30 ms through the axon tunnel, µs on a host-attached chip)
  amortizes away;
- inputs are perturbed by the LOOP INDEX (ids rotated, timestamps
  advanced) — without that, XLA hoists loop-invariant work out of the
  chain and the probe measures an empty loop (observed: a "0.07 ms"
  winner-map that really costs 3 ms);
- the chain's result is FETCHED (``float(...)``), never
  ``block_until_ready`` — through the axon tunnel block_until_ready has
  returned before execution completes;
- the tunnel round-trip (median of 7 trivial-jit fetches) is subtracted.

Usage::

    python tools/profile_step.py              # default backend (TPU)
    python tools/profile_step.py --cpu        # forced CPU
    python tools/profile_step.py --width 16384

Prints one line per stage: validate+enrich, threshold rules, zone rules
(geofence), state update, and the full step, plus derived events/s.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (config API, not env)")
    parser.add_argument("--width", type=int, default=131_072)
    parser.add_argument("--capacity", type=int, default=16_384)
    parser.add_argument("--active", type=int, default=10_000)
    parser.add_argument("--iters", type=int, default=64)
    args = parser.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from bench import build_tables, host_batches
    from sitewhere_tpu.pipeline.step import (
        eval_threshold_rules,
        eval_zone_rules,
        pipeline_step,
        update_device_state,
        validate_and_enrich,
    )
    from sitewhere_tpu.schema import EventBatch

    B, K = args.width, args.iters
    registry, state, rules, zones = build_tables(args.capacity, args.active)
    raw = host_batches(B, args.active, n_batches=1)
    batch = EventBatch(**{k: jax.device_put(v) for k, v in raw[0].items()})
    jax.block_until_ready(batch)
    print(f"backend={jax.default_backend()} width={B} "
          f"capacity={args.capacity} iters={K}")

    trivial = jax.jit(lambda x: x + 1)
    int(trivial(jnp.int32(0)))

    def get_rtt() -> float:
        rtts = []
        for _ in range(7):
            t = time.perf_counter()
            int(trivial(jnp.int32(0)))
            rtts.append(time.perf_counter() - t)
        return float(np.median(rtts))

    def chain_time(body, carry0, label):
        @jax.jit
        def chain(c):
            return lax.fori_loop(0, K, body, c)

        out = chain(carry0)
        jax.tree.map(lambda x: x.block_until_ready(), out)
        rtt = get_rtt()
        t0 = time.perf_counter()
        out = chain(carry0)
        # fetch the SCALAR accumulator (the carry's last leaf) — pulling a
        # width-sized array would add an untimed transfer the subtracted
        # scalar RTT does not cover
        float(np.asarray(jax.tree.leaves(out)[-1]).reshape(-1)[0])
        ms = (time.perf_counter() - t0 - rtt) / K * 1e3
        print(f"{label:<24} {ms:8.3f} ms/iter   (rtt {rtt * 1e3:.1f} ms)")
        return ms

    def pb(i):
        i = jnp.int32(i)
        return batch.replace(
            device_id=(batch.device_id + i) % args.active,
            ts_s=batch.ts_s + i,
            value=batch.value + i.astype(jnp.float32) * 1e-6,
        )

    def b_validate(i, acc):
        a, u, un, e = validate_and_enrich(registry, pb(i))
        return acc + a.sum(dtype=jnp.int32) + e["area_id"].sum()

    chain_time(b_validate, jnp.int32(0), "validate+enrich")

    def b_rules(i, c):
        st, acc = c
        bt = pb(i)
        a, _, _, _ = validate_and_enrich(registry, bt)
        f, rid, ew = eval_threshold_rules(rules, st, bt, a)
        return (st, acc + f.sum(dtype=jnp.int32) + rid.sum()
                + ew.sum().astype(jnp.int32))

    chain_time(b_rules, (state, jnp.int32(0)), "threshold rules")

    def b_zones(i, acc):
        bt = pb(i)
        a, _, _, e = validate_and_enrich(registry, bt)
        f, zid = eval_zone_rules(zones, bt, a, e["area_id"])
        return acc + f.sum(dtype=jnp.int32) + zid.sum()

    chain_time(b_zones, jnp.int32(0), "zone rules (geofence)")

    def b_state(i, c):
        st, acc = c
        bt = pb(i)
        st2, present = update_device_state(st, bt, bt.valid)
        return (st2, acc + st2.last_event_ts_s.sum()
                + present.sum(dtype=jnp.int32))

    chain_time(b_state, (state, jnp.int32(0)), "state update")

    def b_full(i, c):
        st, acc = c
        st2, out = pipeline_step(registry, st, rules, zones, pb(i))
        # fold EVERY output leg into the carry or XLA dead-code-eliminates
        # the rules/geofence/enrichment work
        return (st2, acc + out.metrics.accepted + out.rule_id.sum()
                + out.zone_id.sum() + out.assignment_id.sum()
                + out.derived_alerts.alert_code.sum()
                + out.present_now.sum(dtype=jnp.int32))

    ms = chain_time(b_full, (state, jnp.int32(0)), "FULL pipeline step")
    if ms > 0:
        print(f"device-side rate: {B / ms * 1e3:,.0f} events/s")


if __name__ == "__main__":
    main()
