#!/usr/bin/env python
"""Rule-program bench: the bucketing guarantee + hot-swap contract,
measured.

Loads a skewed synthetic population of tenant rule programs (default
100k across ~25k tenants) through the bring-your-own-rules compiler
(``sitewhere_tpu/rules``) and reports:

1. **Bucketing** — distinct structure keys and distinct COMPILED kernel
   shapes after loading the whole population (the ≤10-shapes acceptance
   bar; ``MAX_STRUCTURE_KEYS`` bounds it by construction) plus the
   load/publish/warm wall time.
2. **Eval throughput** — events/s through the compiled group kernels
   (prepare fold + every structure group) vs the built-in dense
   ``eval_threshold_rules`` path over the same event stream — the cost
   of tenant-programmable rules relative to the fixed-function table.
3. **Swap under traffic** — per-batch eval latency while a random
   program's constants republish every few batches; reports p50/p99 for
   the swap phase vs the quiet phase and asserts the kernel-executable
   count stayed FLAT across every swap (operand swaps must never
   recompile — the zero-stall contract).

Usage::

    python tools/rulebench.py [--programs 100000] [--tenants 25000]
                              [--devices 4096] [--events 100000]
                              [--batch 4096] [--smoke] [--json]

Exit status is always 0 (reporting tool); the tier-1 smoke test asserts
shape + sanity, like analytics_bench/hostpath_bench.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

_POLY = [[0.0, 0.0], [10.0, 0.0], [10.0, 10.0], [0.0, 10.0]]


def _program_doc(rng, idx: int) -> dict:
    """One synthetic program, drawn from a skewed structure mix.

    The mix is deliberately lopsided — most tenants write simple
    threshold specs — and most of the *spelling* diversity (thresholds,
    ops, windows, polygons) is operand diversity, which must collapse
    into the same handful of structure keys."""
    token = f"p{idx}"
    thr = float(rng.uniform(10.0, 90.0))
    op = str(rng.choice(["gt", "lt", "gte", "lte"]))
    level = str(rng.choice(["info", "warning", "error", "critical"]))
    alert = {"type": f"byo.kind{int(rng.integers(0, 16))}",
             "level": level}
    shape = rng.random()
    if shape < 0.55:
        # simple threshold (the common tenant)
        when = {"pred": "value", "op": op, "value": thr}
    elif shape < 0.70:
        # trailing trend: ewma + rate in one clause
        when = {"all": [
            {"pred": "ewma", "op": op, "value": thr,
             "window_s": float(rng.choice([60, 600, 3600]))},
            {"pred": "rate", "op": "gt",
             "value": float(rng.uniform(0.1, 5.0))}]}
    elif shape < 0.82:
        # multi-clause disjunction
        when = {"any": [
            {"pred": "value", "op": "gt", "value": thr},
            {"pred": "value", "op": "lt", "value": thr - 30.0},
            {"all": [{"pred": "rate", "op": "gt", "value": 1.0},
                     {"pred": "value", "op": "gt", "value": thr - 10.0}]}]}
    elif shape < 0.90:
        # geofence containment
        jx, jy = rng.uniform(-2, 2, 2)
        poly = [[x + jx, y + jy] for x, y in _POLY]
        when = {"pred": "geo", "polygon": poly,
                "inside": bool(rng.random() < 0.5)}
    elif shape < 0.95:
        # wide conjunction with metadata joins (c4p8 bucket)
        when = {"any": [
            {"all": [
                {"pred": "value", "op": "gt", "value": thr},
                {"pred": "attr", "table": "device", "column": "tier",
                 "value": int(rng.integers(0, 4)), "op": "eq"},
                {"pred": "event_type", "value": "measurement"},
                {"pred": "ewma", "op": "gt", "value": thr - 5.0,
                 "window_s": 600.0},
                {"pred": "rate", "op": "gt", "value": 0.5}]},
            {"all": [{"pred": "value", "op": "lt", "value": 5.0}]},
            {"all": [{"pred": "value", "op": "gt", "value": 95.0}]}]}
    else:
        # geo + float lanes combined
        when = {"any": [
            {"all": [{"pred": "geo", "polygon": _POLY, "inside": True},
                     {"pred": "value", "op": "gt", "value": thr}]},
            {"all": [{"pred": "rate", "op": "gt", "value": 2.0}]},
            {"all": [{"pred": "value", "op": "lt", "value": 2.0}]}]}
    return {"token": token, "name": f"bench-{idx}", "alert": alert,
            "when": when}


def _stream(rng, n_events, n_devices, n_tenants, batch):
    """Synthetic telemetry batches (measurements + some locations)."""
    from sitewhere_tpu.schema import EventType

    out = []
    t0 = 1_753_800_000
    for lo in range(0, n_events, batch):
        n = min(batch, n_events - lo)
        et = np.where(rng.random(n) < 0.9,
                      int(EventType.MEASUREMENT),
                      int(EventType.LOCATION)).astype(np.int32)
        out.append({
            "device_id": rng.integers(0, n_devices, n).astype(np.int32),
            "tenant_id": rng.integers(0, n_tenants, n).astype(np.int32),
            "event_type": et,
            "mtype_id": rng.integers(0, 4, n).astype(np.int32),
            "value": rng.uniform(0.0, 100.0, n).astype(np.float32),
            "lon": rng.uniform(-5.0, 15.0, n).astype(np.float32),
            "lat": rng.uniform(-5.0, 15.0, n).astype(np.float32),
            "ts_s": (t0 + lo + np.arange(n)).astype(np.int32),
            "ts_ns": np.zeros(n, np.int32),
            "asset_id": np.full(n, -1, np.int32),
        })
    return out


def run(n_programs: int = 100_000, n_tenants: int = 25_000,
        n_devices: int = 4096, n_events: int = 100_000,
        batch: int = 4096, swap_every: int = 8, seed: int = 11):
    from sitewhere_tpu.rules import compile as rcompile
    from sitewhere_tpu.rules.dsl import MAX_STRUCTURE_KEYS
    from sitewhere_tpu.rules.engine import RuleEngineRunner

    rng = np.random.default_rng(seed)
    result = {"programs": n_programs, "tenants": n_tenants,
              "devices": n_devices, "events": n_events, "batch": batch,
              "max_structure_keys": MAX_STRUCTURE_KEYS}

    rcompile.reset_trace_cache()
    eng = RuleEngineRunner(
        capacity=n_devices, n_mtype_slots=4,
        # the population is uniform over tenants, so per-tenant-per-
        # structure collisions follow a birthday bound; 8 slots holds
        # 100k over 25k tenants comfortably
        programs_per_tenant=8, max_programs=max(n_programs, 1024),
        queue_depth=4)
    alerts = [0]
    eng.inject = lambda cols: alerts.__setitem__(
        0, alerts[0] + len(cols["device_id"]))

    # ---- 1. load + publish + warm (compile) time
    t0 = time.perf_counter()
    loaded = 0
    for i in range(n_programs):
        doc = _program_doc(rng, i)
        tenant = int(rng.integers(0, n_tenants))
        try:
            eng.registry.put_program(tenant, doc)
            loaded += 1
        except Exception:
            # per-tenant structure-slot collision in the random draw —
            # counted, not fatal (real tenants hit a 400 at POST)
            pass
    t_put = time.perf_counter() - t0
    t0 = time.perf_counter()
    eng.refresh()  # single publish: builds every group + warms kernels
    t_publish = time.perf_counter() - t0
    result["programs_loaded"] = loaded
    result["programs_rejected"] = n_programs - loaded
    result["structure_keys"] = eng.registry.structure_keys()
    result["compiled_shapes"] = rcompile.structure_keys_compiled()
    result["load_s"] = round(t_put, 3)
    result["publish_and_warm_s"] = round(t_publish, 3)
    result["shapes_within_bound"] = (
        result["compiled_shapes"] <= MAX_STRUCTURE_KEYS)

    # ---- 2. eval throughput: compiled groups vs built-in dense table
    batches = _stream(rng, n_events, n_devices, n_tenants, batch)
    eng._eval_batch(dict(batches[0]))  # warm the batch width
    t0 = time.perf_counter()
    for b in batches:
        eng._eval_batch(dict(b))
    dt = time.perf_counter() - t0
    result["eval_events_per_s"] = round(n_events / dt, 1)
    result["alerts_fired"] = alerts[0]
    result["builtin_events_per_s"] = _builtin_throughput(
        batches, n_devices)
    result["relative_cost"] = round(
        result["builtin_events_per_s"]
        / max(result["eval_events_per_s"], 1e-9), 2)

    # ---- 3. swap under traffic: operand republish must not recompile
    quiet: list = []
    swap_lat: list = []
    executables_before = rcompile.compile_count()
    swaps_before = eng.registry.swaps
    for i, b in enumerate(batches):
        if i and i % swap_every == 0:
            # operand-only mutation: same token, same structure, new
            # constants — the hot-swap the zero-stall contract covers
            idx = int(rng.integers(0, n_programs))
            doc = _program_doc(np.random.default_rng(seed + idx), idx)
            tenant = int(rng.integers(0, n_tenants))
            try:
                eng.put_program(tenant, doc)
            except Exception:
                pass
        t0 = time.perf_counter()
        eng._eval_batch(dict(b))
        (swap_lat if i % swap_every == 0 and i else quiet).append(
            time.perf_counter() - t0)
    result["swaps_applied"] = eng.registry.swaps - swaps_before
    result["recompiles_during_swaps"] = (
        rcompile.compile_count() - executables_before)
    if quiet:
        result["quiet_p50_ms"] = round(
            float(np.percentile(quiet, 50)) * 1e3, 3)
        result["quiet_p99_ms"] = round(
            float(np.percentile(quiet, 99)) * 1e3, 3)
    if swap_lat:
        result["swap_p50_ms"] = round(
            float(np.percentile(swap_lat, 50)) * 1e3, 3)
        result["swap_p99_ms"] = round(
            float(np.percentile(swap_lat, 99)) * 1e3, 3)
    return result


def _builtin_throughput(batches, n_devices: int) -> float:
    """The fixed-function comparison: the dense [B, R] built-in
    threshold kernel over the same stream (1024 rules, one compile)."""
    import jax.numpy as jnp

    from sitewhere_tpu.ids import NULL_ID
    from sitewhere_tpu.pipeline.step import eval_threshold_rules
    from sitewhere_tpu.schema import (
        DeviceState,
        EventBatch,
        RuleKind,
        RuleTable,
    )

    R = 1024
    rng = np.random.default_rng(3)
    rules = RuleTable.empty(R)
    rules = RuleTable(
        active=jnp.ones(R, bool),
        tenant_id=jnp.full(R, NULL_ID, jnp.int32),
        mtype_id=jnp.full(R, NULL_ID, jnp.int32),
        op=jnp.asarray(rng.integers(0, 4, R), jnp.int32),
        threshold=jnp.asarray(rng.uniform(10, 90, R), jnp.float32),
        alert_code=jnp.arange(R, dtype=jnp.int32),
        alert_level=jnp.ones(R, jnp.int32),
        kind=jnp.full(R, int(RuleKind.INSTANT), jnp.int32),
        window_idx=jnp.zeros(R, jnp.int32),
        ewma_tau_s=rules.ewma_tau_s,
    )
    state = DeviceState.empty(n_devices, num_mtype_slots=4)
    jitted = jax.jit(eval_threshold_rules)

    def to_batch(cols):
        n = len(cols["device_id"])
        eb = EventBatch.empty(n)
        return eb.replace(
            valid=jnp.ones(n, bool),
            device_id=jnp.asarray(cols["device_id"]),
            tenant_id=jnp.asarray(cols["tenant_id"]),
            event_type=jnp.asarray(cols["event_type"]),
            mtype_id=jnp.asarray(cols["mtype_id"]),
            value=jnp.asarray(cols["value"]),
            ts_s=jnp.asarray(cols["ts_s"]),
            ts_ns=jnp.asarray(cols["ts_ns"]),
        )

    eb = to_batch(batches[0])
    acc = jnp.ones(len(batches[0]["device_id"]), bool)
    jax.block_until_ready(jitted(rules, state, eb, acc))  # warm
    n_events = sum(len(b["device_id"]) for b in batches)
    t0 = time.perf_counter()
    for b in batches:
        eb = to_batch(b)
        acc = jnp.ones(len(b["device_id"]), bool)
        out = jitted(rules, state, eb, acc)
    jax.block_until_ready(out)
    return round(n_events / (time.perf_counter() - t0), 1)


def _render(r) -> str:
    lines = [
        f"rule-program bench — {r['programs_loaded']} programs, "
        f"{r['tenants']} tenants, {r['events']} events, "
        f"batch {r['batch']}",
        f"  structure keys   : {len(r['structure_keys'])} "
        f"({', '.join(r['structure_keys'])})",
        f"  compiled shapes  : {r['compiled_shapes']} "
        f"(bound {r['max_structure_keys']}; "
        f"{'OK' if r['shapes_within_bound'] else 'EXCEEDED'})",
        f"  load / publish   : {r['load_s']:.2f} s / "
        f"{r['publish_and_warm_s']:.2f} s",
        f"  compiled eval    : {r['eval_events_per_s']:>12,.0f} ev/s "
        f"({r['alerts_fired']} alerts)",
        f"  built-in table   : {r['builtin_events_per_s']:>12,.0f} ev/s "
        f"({r['relative_cost']}x)",
        f"  swap under load  : {r['swaps_applied']} swaps, "
        f"{r['recompiles_during_swaps']} recompiles",
    ]
    if "swap_p99_ms" in r:
        lines.append(
            f"  eval latency     : quiet p50/p99 "
            f"{r.get('quiet_p50_ms', 0)}/{r.get('quiet_p99_ms', 0)} ms, "
            f"swap-batch p50/p99 "
            f"{r['swap_p50_ms']}/{r['swap_p99_ms']} ms")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--programs", type=int, default=100_000)
    ap.add_argument("--tenants", type=int, default=25_000)
    ap.add_argument("--devices", type=int, default=4096)
    ap.add_argument("--events", type=int, default=100_000)
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--swap-every", type=int, default=8)
    ap.add_argument("--smoke", action="store_true",
                    help="small population (tier-1 CI sizing)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        result = run(n_programs=512, n_tenants=64, n_devices=256,
                     n_events=8192, batch=1024, swap_every=4)
    else:
        result = run(args.programs, args.tenants, args.devices,
                     args.events, args.batch, args.swap_every)
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(_render(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
