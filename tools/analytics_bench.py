#!/usr/bin/env python
"""Analytics bench: windowed-query throughput + CEP match latency.

Measures the streaming analytics subsystem over a synthetic fleet:

1. **Grid aggregation** — events/s through the jitted [D, W] scatter
   kernel (``aggregate_windows``), the substrate charts and
   retrospective estimates share.
2. **Windowed-query operator** — events/s through one compiled
   ``WindowQuery`` (sort + segment reduction + carry merge per batch),
   i.e. the live-mode eval cost the dispatcher's egress offer pays for.
3. **CEP match latency** — wall time for a compiled two-step pattern
   ("window-mean cross then alert") to evaluate the batch carrying the
   completing alert and surface the match, per batch size.

Usage::

    python tools/analytics_bench.py [--devices 1024] [--events 200000]
                                    [--batch 8192] [--json]

Exit status is always 0 (reporting tool); the tier-1 smoke test asserts
shape + sanity, like hostpath_bench/overload_bench.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _fleet(n_devices: int, n_events: int, t0: int = 1_753_800_000,
           seed: int = 7):
    """Synthetic fleet telemetry: per-device random-walk measurements in
    (device, time)-interleaved arrival order."""
    rng = np.random.default_rng(seed)
    dev = rng.integers(0, n_devices, n_events).astype(np.int32)
    ts = (t0 + np.arange(n_events) // max(1, n_events // 3600)).astype(
        np.int32)
    val = (20.0 + rng.normal(0, 2.0, n_events)).astype(np.float32)
    return dev, ts, val


def run(n_devices: int = 1024, n_events: int = 200_000,
        batch: int = 8192, window_s: int = 300):
    from sitewhere_tpu.schema import ComparisonOp, EventType
    from sitewhere_tpu.analytics.query import (
        PatternQuery,
        WindowQuery,
        compile_query,
    )
    from sitewhere_tpu.analytics.cep import PatternStep
    from sitewhere_tpu.analytics.windows import aggregate_windows

    import jax.numpy as jnp

    dev, ts, val = _fleet(n_devices, n_events)
    result = {"devices": n_devices, "events": n_events, "batch": batch}

    # ---- 1. grid kernel throughput
    win = ((ts - ts.min()) // window_s).astype(np.int32)
    n_windows = max(64, int(win.max()) + 1)
    args = (jnp.asarray(dev), jnp.asarray(win), jnp.asarray(val),
            jnp.ones(n_events, bool))
    grid = aggregate_windows(*args, n_devices=n_devices,
                             n_windows=n_windows)  # warm/compile
    jax.block_until_ready(grid.counts)
    t0 = time.perf_counter()
    reps = 5
    for _ in range(reps):
        grid = aggregate_windows(*args, n_devices=n_devices,
                                 n_windows=n_windows)
    jax.block_until_ready(grid.counts)
    dt = (time.perf_counter() - t0) / reps
    result["grid_events_per_s"] = round(n_events / dt, 1)
    result["grid_occupancy"] = round(float(grid.occupancy()), 4)

    # ---- 2. windowed-query operator throughput (live-mode eval)
    q = WindowQuery(name="bench-mean", threshold=21.0, agg="mean",
                    window_s=window_s)
    compiled = compile_query(q, capacity=n_devices)
    mt = np.ones(n_events, np.int32)
    et = np.full(n_events, int(EventType.MEASUREMENT), np.int32)
    # warm the (pow2-bucketed) batch shape
    cols0 = {"device_id": dev[:batch], "ts_s": ts[:batch],
             "event_type": et[:batch], "mtype_id": mt[:batch],
             "value": val[:batch]}
    compiled.eval_cols(cols0)
    compiled.reset()
    matches = 0
    t0 = time.perf_counter()
    for lo in range(0, n_events, batch):
        cols = {"device_id": dev[lo:lo + batch], "ts_s": ts[lo:lo + batch],
                "event_type": et[lo:lo + batch],
                "mtype_id": mt[lo:lo + batch],
                "value": val[lo:lo + batch]}
        matches += len(compiled.eval_cols(cols))
    matches += len(compiled.flush())
    dt = time.perf_counter() - t0
    result["window_query_events_per_s"] = round(n_events / dt, 1)
    result["window_query_matches"] = matches

    # ---- 3. CEP match latency (arm, then time the completing batch)
    pat = PatternQuery(
        name="bench-pattern",
        steps=[PatternStep(window_cross=True),
               PatternStep(event_type=int(EventType.ALERT), within_s=60)],
        window_s=window_s, cross_op=int(ComparisonOp.GT),
        cross_threshold=21.0)
    cep = compile_query(pat, capacity=n_devices)
    lat_ms = []
    cep_matches = 0
    for trial in range(5):
        cep.reset()
        arm = {"device_id": np.asarray([3], np.int32),
               "ts_s": np.asarray([1_753_900_000 + trial * 1000], np.int32),
               "event_type": np.asarray([int(EventType.MEASUREMENT)],
                                        np.int32),
               "mtype_id": np.asarray([-1], np.int32),
               "value": np.asarray([50.0], np.float32)}
        cep.eval_cols(arm)   # window-cross arms the machine
        fire = dict(arm)
        fire["ts_s"] = arm["ts_s"] + 10
        fire["event_type"] = np.asarray([int(EventType.ALERT)], np.int32)
        fire["value"] = np.asarray([0.0], np.float32)
        t0 = time.perf_counter()
        out = cep.eval_cols(fire)
        lat_ms.append((time.perf_counter() - t0) * 1e3)
        cep_matches += len(out)
    result["cep_match_latency_ms"] = round(min(lat_ms), 3)
    result["cep_matches"] = cep_matches
    return result


def _render(result) -> str:
    lines = [
        f"analytics bench — {result['devices']} devices, "
        f"{result['events']} events, batch {result['batch']}",
        f"  grid aggregation : {result['grid_events_per_s']:>12,.0f} ev/s "
        f"(occupancy {result['grid_occupancy']})",
        f"  window query     : "
        f"{result['window_query_events_per_s']:>12,.0f} ev/s "
        f"({result['window_query_matches']} matches)",
        f"  cep match latency: {result['cep_match_latency_ms']:>8.3f} ms "
        f"({result['cep_matches']} matches)",
    ]
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, default=1024)
    ap.add_argument("--events", type=int, default=200_000)
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    result = run(args.devices, args.events, args.batch)
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(_render(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
