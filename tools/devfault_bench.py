#!/usr/bin/env python
"""Device-tier fault containment bench: poison-row quarantine,
chain-failure re-lease, breaker ladder, hung-step watchdog.

Drives a REAL instance through the dispatcher's device-fault plane
(``sitewhere_tpu/runtime/faults.py`` device points) and asserts the
containment contract end to end:

- ``chain-fault``: a transient fault inside a chained (donated-carry)
  ring dispatch re-parks every ring plan, re-leases the carry from the
  last committed epoch on the SAME live state manager
  (``lease_generation`` advances without restart), and re-dispatches
  single-step with ZERO row loss.
- ``breaker``: repeated faults across distinct batches demote dispatch
  chained → single-step → cpu-fallback, ride the overload ladder
  (DEGRADED while demoted), and a cooldown probe restores chained
  dispatch + releases the ladder.
- ``poison``: rows that fault the device bisect down to the exact
  poison singles, which dead-letter replayably (``device-poison``); all
  clean rows commit (zero committed-row loss) and the surviving state is
  BIT-IDENTICAL to a fault-free run of the same clean traffic.
- ``quarantine``: requeuing the poison letters re-ingests the rows; the
  device masks the nonfinite values out of state/analytics, counts them
  on the packed telemetry vector (zero extra host syncs), and the host
  attribution scan quarantines the offending device with one
  STATE_CHANGE through the normal egress.
- ``watchdog``: a stalled dispatch trips the soft then the hard budget
  (flight-recorder anomalies; the tier goes unhealthy for peers) and
  self-clears when the dispatch drains.
- ``shard_containment``: on a 4-way mesh running the fused ring, poison
  rows landing on ONE shard demote only that shard's breaker; the other
  shards keep chaining, every clean row commits, and the episode leaves
  a flight-recorder dump behind.

Usage::

    python tools/devfault_bench.py [--smoke] [--json]

Exit status 0 = every phase held its contract.
"""

import argparse
import json
import math
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Deterministic CPU (sitecustomize hooks may override the env var —
# force via the config API before any backend initializes).  The
# shard-containment phase needs a multi-device mesh, so force virtual
# host devices BEFORE the backend comes up.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from sitewhere_tpu.runtime import faults  # noqa: E402

WIDTH = 64
N_DEVICES = 8
POISON_DEVICE = f"d-{N_DEVICES - 1}"
TS0 = 1_754_500_000


def _make_instance(data_dir, **overrides):
    from sitewhere_tpu.instance import Instance
    from sitewhere_tpu.runtime.config import Config

    pipeline = {"width": WIDTH, "registry_capacity": 256,
                "mtype_slots": 4, "deadline_ms": 5.0, "n_shards": 1,
                "ring_depth": 0, "quarantine_after": 3}
    pipeline.update(overrides)
    cfg = Config({
        "instance": {"id": "devfault-bench", "data_dir": data_dir},
        "pipeline": pipeline,
        # only the bench releases the forced DEGRADED (via the breaker
        # restore) — the ladder's own cooldown must not race it
        "overload": {"cooldown_s": 3600.0},
        "presence": {"scan_interval_s": 3600.0, "missing_after_s": 1800},
    }, apply_env=False)
    return Instance(cfg)


def _register(inst):
    dm = inst.device_management
    dm.create_device_type(token="sensor", name="Sensor")
    for i in range(N_DEVICES):
        dm.create_device(token=f"d-{i}", device_type="sensor")
        dm.create_device_assignment(device=f"d-{i}")


class _Traffic:
    """Deterministic full-width payload builder (one fill plan each)."""

    def __init__(self, ts0=TS0, clean_devices=N_DEVICES):
        self.ts = ts0
        self.clean = clean_devices

    def _row(self, token, value, ts):
        return json.dumps({
            "deviceToken": token, "type": "Measurement",
            "request": {"name": "temp", "value": value, "eventDate": ts},
        })

    def payload(self, rows=WIDTH, poison_rows=0):
        """``rows`` wire lines; the LAST ``poison_rows`` of them carry a
        NaN value on the dedicated poison device."""
        lines = []
        for r in range(rows):
            self.ts += 1
            if r >= rows - poison_rows:
                lines.append(self._row(POISON_DEVICE, float("nan"),
                                       self.ts))
            else:
                tok = f"d-{r % self.clean}"
                lines.append(self._row(tok, float(self.ts % 997), self.ts))
        return "\n".join(lines).encode()


def _counters(inst):
    return inst.metrics.snapshot()["counters"]


def _gauges(inst):
    return inst.metrics.snapshot()["gauges"]


def _settle(inst):
    inst.dispatcher.flush()
    inst.event_store.flush()
    return inst.event_store.total_events


# ---------------------------------------------------------------------------
# phases
# ---------------------------------------------------------------------------

def phase_chain_fault(root, check):
    """Transient chained-dispatch fault → re-park, re-lease, zero loss."""
    inst = _make_instance(os.path.join(root, "chain"),
                          ring_depth=2, deadline_ms=200.0)
    # exercise the REAL donated-carry protocol (lease_packed + donated
    # chain); the CPU backend ignores the donation itself but the
    # lease/re-lease bookkeeping is identical to the TPU path
    inst.dispatcher._ring_donate = True
    inst.start()
    _register(inst)
    traffic = _Traffic()
    d = inst.dispatcher
    sm = inst.device_state

    # warm: one clean chained ring (2 fill plans = ring_depth)
    d.ingest_wire_lines(traffic.payload())
    d.ingest_wire_lines(traffic.payload())
    ingested = 2 * WIDTH
    stored0 = _settle(inst)
    check(stored0 >= ingested, "warm ring lost rows "
          f"({stored0} stored of {ingested})")
    gen0 = sm.lease_generation
    check(gen0 > 0, "donated ring never leased the packed carry")

    # the fault: first chained dispatch dies once, then the chip is fine
    faults.device_inject("device.dispatch", times=1)
    d.ingest_wire_lines(traffic.payload())
    d.ingest_wire_lines(traffic.payload())   # ring full -> chain -> fault
    ingested += 2 * WIDTH
    stored = _settle(inst)
    faults.device_clear()

    c = _counters(inst)
    check(c.get("device.fault.chain_faults", 0) == 1,
          f"expected 1 chain fault, saw {c.get('device.fault.chain_faults')}")
    check(c.get("device.fault.releases", 0) == 1,
          "the faulted chain's donated lease was not re-leased")
    check(stored >= ingested,
          f"chain fault lost rows: {ingested} ingested, {stored} stored")
    check(d.breaker.snapshot()["level"] == 0,
          "a single transient fault must not trip the breaker")

    # recovery without restart: the SAME live manager leases the carry
    # again for the next chained ring (lease_generation advances)
    d.ingest_wire_lines(traffic.payload())
    d.ingest_wire_lines(traffic.payload())
    ingested += 2 * WIDTH
    stored = _settle(inst)
    check(sm.lease_generation > gen0,
          "lease_generation did not advance across the fault "
          "(re-lease on the same live manager)")
    check(sm is d.state_manager, "state manager identity changed")
    check(stored >= ingested,
          f"post-recovery ring lost rows: {ingested} in, {stored} stored")

    report = {
        "ingested": ingested,
        "stored": int(stored),
        "chain_faults": int(c.get("device.fault.chain_faults", 0)),
        "releases": int(c.get("device.fault.releases", 0)),
        "lease_generation": int(sm.lease_generation),
        "breaker": d.breaker.snapshot(),
    }
    inst.stop()
    inst.terminate()
    return report


def phase_breaker(root, check):
    """Repeated faults demote chained → single-step → cpu-fallback; a
    cooldown probe restores chained dispatch and releases the ladder."""
    from sitewhere_tpu.runtime.overload import OverloadState

    inst = _make_instance(os.path.join(root, "breaker"),
                          ring_depth=2, deadline_ms=200.0)
    inst.start()
    _register(inst)
    traffic = _Traffic()
    d = inst.dispatcher
    d.breaker.cooldown_s = 3600.0     # no accidental half-open mid-phase
    ingested = 0

    def fault_cycle():
        nonlocal ingested
        faults.device_inject("device.dispatch", times=1)
        d.ingest_wire_lines(traffic.payload())
        d.ingest_wire_lines(traffic.payload())
        ingested += 2 * WIDTH
        _settle(inst)
        faults.device_clear()

    # three distinct-batch faults: chained -> single-step
    for _ in range(d.breaker.threshold):
        fault_cycle()
    snap = d.breaker.snapshot()
    check(snap["level"] == 1 and snap["trips"] == 1,
          f"breaker did not demote to single-step: {snap}")
    check(inst.overload.state == OverloadState.DEGRADED,
          "breaker trip did not ride the overload ladder to DEGRADED")
    check(inst.overload.last_driver == "device-breaker",
          "forced DEGRADED lost its driver attribution")

    # three more: single-step -> cpu-fallback
    for _ in range(d.breaker.threshold):
        fault_cycle()
    snap = d.breaker.snapshot()
    check(snap["level"] == 2 and snap["trips"] == 2,
          f"breaker did not demote to cpu-fallback: {snap}")

    # at FALLBACK a clean dispatch routes to the CPU device
    d.ingest_wire_lines(traffic.payload())
    ingested += WIDTH
    _settle(inst)
    c = _counters(inst)
    check(c.get("device.fault.cpu_fallback_steps", 0) >= 1,
          "FALLBACK level never routed a step to the CPU fallback")

    # recovery: cooldown elapses -> half-open probe -> chained success
    d.breaker.cooldown_s = 0.0
    d.ingest_wire_lines(traffic.payload())
    d.ingest_wire_lines(traffic.payload())
    ingested += 2 * WIDTH
    stored = _settle(inst)
    snap = d.breaker.snapshot()
    check(snap["level"] == 0 and snap["restores"] == 1,
          f"probe did not restore chained dispatch: {snap}")
    check(inst.overload.state == OverloadState.NORMAL,
          "breaker restore did not release the forced DEGRADED")
    check(stored >= ingested,
          f"breaker ladder lost rows: {ingested} ingested, {stored} stored")

    c = _counters(inst)
    report = {
        "ingested": ingested,
        "stored": int(stored),
        "trips": snap["trips"],
        "restores": snap["restores"],
        "breaker_trips_metric": int(c.get("device.fault.breaker_trips", 0)),
        "cpu_fallback_steps": int(c.get("device.fault.cpu_fallback_steps", 0)),
        "overload": inst.overload.state.name,
    }
    inst.stop()
    inst.terminate()
    return report


def _clean_state(inst):
    """Exported state rows of every clean device, keyed by token."""
    out = {}
    for i in range(N_DEVICES - 1):
        tok = f"d-{i}"
        out[tok] = inst.device_state.get_device_state(tok)
    return out


def phase_poison(root, check, smoke):
    """Poison rows bisect to dead letters; clean rows commit bit-identical
    to a fault-free control run; requeue replays into the quarantine."""
    inst = _make_instance(os.path.join(root, "poison"))
    control = _make_instance(os.path.join(root, "control"))
    inst.start()
    control.start()
    _register(inst)
    _register(control)
    d = inst.dispatcher
    d.breaker.threshold = 99   # this phase proves bisect, not the ladder
    n_poison = 3
    n_clean_payloads = 2 if smoke else 4
    ingested_clean = 0

    # identical clean traffic to both runs (same values, same timestamps)
    t_fault = _Traffic(clean_devices=N_DEVICES - 1)
    t_ctl = _Traffic(clean_devices=N_DEVICES - 1)
    for _ in range(n_clean_payloads):
        p = t_fault.payload()
        d.ingest_wire_lines(p)
        control.dispatcher.ingest_wire_lines(t_ctl.payload())
        ingested_clean += WIDTH

    # the poison payload: same clean rows to both; the faulted run
    # additionally carries NaN rows that make the device fault
    faults.device_inject("device.dispatch", times=None,
                         when_nonfinite=True)
    d.ingest_wire_lines(t_fault.payload(poison_rows=n_poison))
    control.dispatcher.ingest_wire_lines(
        t_ctl.payload(rows=WIDTH - n_poison))
    t_ctl.ts += n_poison          # keep the clocks aligned
    ingested_clean += WIDTH - n_poison
    stored = _settle(inst)
    stored_ctl = _settle(control)
    faults.device_clear()

    c = _counters(inst)
    check(c.get("device.fault.poison_rows", 0) == n_poison,
          f"bisect isolated {c.get('device.fault.poison_rows')} rows, "
          f"expected exactly {n_poison}")
    check(c.get("device.fault.bisect_rounds", 0) > 0, "bisect never ran")
    letters = [l for l in inst.list_dead_letters(limit=50)
               if l.get("kind") == "device-poison"]
    check(len(letters) >= 1, "no device-poison dead letters")
    dl_rows = sum(int(l.get("count", 0)) for l in letters)
    check(dl_rows == n_poison,
          f"dead letters carry {dl_rows} rows, expected {n_poison}")
    for letter in letters:
        vals = letter.get("columns", {}).get("value", [])
        check(all(not math.isfinite(v) for v in vals),
              "a dead-lettered poison row has a finite value")
    check(stored >= ingested_clean,
          f"poison containment lost clean rows: {ingested_clean} clean "
          f"ingested, {stored} stored")

    # bit-identical surviving state vs the fault-free control run
    st, st_ctl = _clean_state(inst), _clean_state(control)
    mismatched = [tok for tok in st if st[tok] != st_ctl[tok]]
    check(not mismatched,
          f"unpoisoned device state diverged from the fault-free run: "
          f"{mismatched}")

    # goodput recovers: the next clean payload lands in full
    d.ingest_wire_lines(t_fault.payload())
    ingested_clean += WIDTH
    stored_after = _settle(inst)
    check(stored_after >= stored + WIDTH,
          "goodput did not recover after containment")

    # --- quarantine via replay: requeue the poison letters ------------
    g0 = _gauges(inst)
    check(g0.get("pipeline.quarantine.devices", 0) == 0,
          "device quarantined before any nonfinite row ever egressed")
    requeued_rows = 0
    for letter in letters:
        res = inst.requeue_dead_letter(int(letter["offset"]))
        check(res.get("requeued") is True,
              f"device-poison requeue refused: {res}")
        requeued_rows += int(res.get("rows", 0))
    check(requeued_rows == n_poison,
          f"requeue replayed {requeued_rows} rows, expected {n_poison}")
    _settle(inst)
    c = _counters(inst)
    g = _gauges(inst)
    check(c.get("pipeline.quarantine.rows_nonfinite", 0) >= n_poison,
          "device-counted nonfinite telemetry never surfaced")
    check(g.get("pipeline.quarantine.devices", 0) == 1,
          f"expected 1 quarantined device, gauge says "
          f"{g.get('pipeline.quarantine.devices')}")
    check(c.get("pipeline.quarantine.state_changes", 0) == 1,
          "quarantine did not emit exactly one STATE_CHANGE")
    check(d.metrics_snapshot()["device_fault"]["quarantined_devices"] == 1,
          "dispatcher snapshot disagrees on quarantined devices")

    report = {
        "clean_rows": ingested_clean,
        "stored": int(stored_after),
        "control_stored": int(stored_ctl),
        "poison_rows": n_poison,
        "dead_letters": len(letters),
        "bisect_rounds": int(c.get("device.fault.bisect_rounds", 0)),
        "requeued_rows": requeued_rows,
        "quarantined_devices": int(g.get("pipeline.quarantine.devices", 0)),
        "state_bit_identical": not mismatched,
    }
    inst.stop()
    inst.terminate()
    control.stop()
    control.terminate()
    return report


def phase_watchdog(root, check):
    """A stalled dispatch trips soft then hard budgets, goes unhealthy
    for peers, and self-clears when the dispatch drains."""
    inst = _make_instance(os.path.join(root, "watchdog"))
    inst.start()
    _register(inst)
    d = inst.dispatcher
    d.watchdog.soft_s = 0.05
    d.watchdog.hard_s = 0.2
    traffic = _Traffic()

    unhealthy_seen = []
    stop = threading.Event()

    def sampler():
        while not stop.is_set():
            if d.device_unhealthy:
                unhealthy_seen.append(True)
            time.sleep(0.01)

    t = threading.Thread(target=sampler, daemon=True)
    t.start()
    anomalies0 = int(_counters(inst).get("flightrec.anomalies", 0))
    faults.device_inject("device.dispatch", exc=None, stall_s=0.6)
    d.ingest_wire_lines(traffic.payload())   # stalls 0.6 s on this thread
    faults.device_clear()
    stored = _settle(inst)
    stop.set()
    t.join(timeout=2)

    wd = d.watchdog.snapshot()
    c = _counters(inst)
    check(wd["softTrips"] >= 1, "soft budget never tripped")
    check(wd["hardTrips"] >= 1, "hard budget never tripped")
    check(bool(unhealthy_seen),
          "device_unhealthy was never observable while wedged")
    check(not wd["unhealthy"],
          "unhealthy flag did not self-clear after the dispatch drained")
    check(c.get("device.fault.watchdog_soft_trips", 0) >= 1
          and c.get("device.fault.watchdog_hard_trips", 0) >= 1,
          "watchdog trip counters missing")
    anomalies = int(c.get("flightrec.anomalies", 0))
    check(anomalies > anomalies0,
          "no flight-recorder anomaly for the hung step")
    check(stored >= WIDTH, "stalled dispatch lost rows")

    report = {
        "soft_trips": wd["softTrips"],
        "hard_trips": wd["hardTrips"],
        "unhealthy_observed": bool(unhealthy_seen),
        "self_cleared": not wd["unhealthy"],
        "anomalies": anomalies - anomalies0,
        "stored": int(stored),
    }
    inst.stop()
    inst.terminate()
    return report


def phase_shard_containment(root, check):
    """Fused mesh ring under a one-shard poison storm: only the sick
    shard's breaker demotes, the healthy shards keep chaining, no clean
    row is lost, and the episode dumps the flight recorder."""
    n_shards, K, cap = 4, 2, 32
    seg = WIDTH // n_shards
    rps = cap // n_shards
    inst = _make_instance(os.path.join(root, "shards"),
                          n_shards=n_shards, ring_depth=K,
                          deadline_ms=200.0, registry_capacity=cap)
    inst.start()
    dm = inst.device_management
    dm.create_device_type(token="sensor", name="Sensor")
    for i in range(cap):
        dm.create_device(token=f"d-{i}", device_type="sensor")
        dm.create_device_assignment(device=f"d-{i}")
    handles = np.asarray(
        inst.identity.device.lookup_many([f"d-{i}" for i in range(cap)]),
        np.int32)
    by_shard = [handles[(handles // rps) == s] for s in range(n_shards)]
    rng = np.random.default_rng(5)
    poison_rounds, clean_rounds, ppr = 2 * K, 2 * K, 2

    faults.device_inject("device.dispatch", times=None,
                         when_nonfinite=True)
    try:
        for r in range(poison_rounds + clean_rounds):
            # balanced shard-block-ordered full rounds: every emission
            # is ring-eligible on every shard
            dev = np.concatenate([
                rng.choice(by_shard[s], seg) for s in range(n_shards)
            ]).astype(np.int32)
            value = rng.uniform(0, 100, WIDTH).astype(np.float32)
            if r < poison_rounds:
                value[2 * seg:2 * seg + ppr] = np.nan   # shard 2 only
            inst.dispatcher.ingest_arrays(
                device_id=dev,
                event_type=np.zeros(WIDTH, np.int32),
                ts_s=np.full(WIDTH, TS0 + r, np.int32),
                mtype_id=np.zeros(WIDTH, np.int32),
                value=value)
    finally:
        faults.device_clear()
    stored = _settle(inst)

    snap = inst.dispatcher.metrics_snapshot()
    br = snap["device_fault"]["breaker"]
    check(br["shards"][2]["level"] >= 1,
          f"poisoned shard 2 never demoted: {br}")
    for s in (0, 1, 3):
        check(br["shards"][s]["level"] == 0,
              f"healthy shard {s} was demoted with the sick one: {br}")
    npoison = poison_rounds * ppr
    letters = [l for l in inst.list_dead_letters(limit=100)
               if l.get("kind") == "device-poison"]
    dl_rows = sum(int(l.get("count", 0)) for l in letters)
    check(dl_rows == npoison,
          f"dead letters carry {dl_rows} rows, expected {npoison}")
    total = (poison_rounds + clean_rounds) * WIDTH
    check(stored == total - npoison,
          f"clean-row loss: {total - npoison} expected, {stored} stored")
    check(snap["ring_chains"] >= 1,
          "healthy shards never chained while shard 2 was demoted")
    dump = (inst.flightrec.snapshot("shard-containment")
            if inst.flightrec is not None else None)
    check(dump is not None, "no flight-recorder dump for the episode")

    report = {
        "n_shards": n_shards,
        "ring_depth": K,
        "poison_rows": npoison,
        "stored": int(stored),
        "expected_stored": total - npoison,
        "shard_levels": [int(sh["level"]) for sh in br["shards"]],
        "ring_chains": int(snap["ring_chains"]),
        "dead_letter_rows": dl_rows,
        "flightrec_dump": dump,
    }
    inst.stop()
    inst.terminate()
    return report


# ---------------------------------------------------------------------------

def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="reduced volumes (CI gate)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON on stdout")
    args = ap.parse_args()

    failures = []

    def check(ok, msg):
        if not ok and msg:
            failures.append(msg)

    root = tempfile.mkdtemp(prefix="devfault-bench-")
    report = {"smoke": bool(args.smoke), "width": WIDTH, "phases": {}}
    t0 = time.monotonic()
    try:
        report["phases"]["chain_fault"] = phase_chain_fault(root, check)
        report["phases"]["breaker"] = phase_breaker(root, check)
        report["phases"]["poison"] = phase_poison(root, check, args.smoke)
        report["phases"]["watchdog"] = phase_watchdog(root, check)
        report["phases"]["shard_containment"] = phase_shard_containment(
            root, check)
    finally:
        faults.device_clear()
        faults.clear()
        shutil.rmtree(root, ignore_errors=True)
    report["wall_s"] = round(time.monotonic() - t0, 2)
    report["ok"] = not failures
    if failures:
        report["failures"] = failures

    if args.json:
        print(json.dumps(report, indent=2))
    else:
        for name, ph in report["phases"].items():
            print(f"{name}: {json.dumps(ph)}")
        print(f"wall: {report['wall_s']}s")
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("devfault_bench: containment contract held",
          file=sys.stderr if args.json else sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
