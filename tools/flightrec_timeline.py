#!/usr/bin/env python
"""Render a flight-recorder snapshot as a per-batch stage timeline.

A snapshot (``<data_dir>/flightrec/NNNNNN-<reason>.jsonl``, or the
``/api/instance/flightrecorder/snapshots/{name}`` download) holds the
last N per-batch records the dispatcher appended before an anomaly
fired.  This renders them as one line per batch — sequence, commit
outcome, overload state — plus a proportional ASCII bar splitting the
end-to-end latency into wait / dispatch / egress, so "what was the
pipeline doing when it broke" reads at a glance instead of as raw JSON.

Usage::

    python tools/flightrec_timeline.py path/to/000003-egress-crash.jsonl
    python tools/flightrec_timeline.py snap.jsonl --limit 40
    python tools/flightrec_timeline.py --url \\
        http://127.0.0.1:8080/api/instance/flightrecorder/snapshots/000003-egress-crash.jsonl

Failed commits render with a ``!!`` marker and their error; the bar
legend is ``w`` batcher wait, ``d`` step dispatch, ``e`` egress, ``·``
unattributed (device dwell + queueing between stages).

Besides per-batch rows the ring also holds EVENT records carrying a
``kind`` field — the device-fault containment plane appends them on its
cold paths (``hung-step`` plans caught in flight by the watchdog,
``quarantine`` strikes from the nonfinite scan).  These render as
``**``-marked event lines in sequence with the batches instead of being
dropped as unknown records, so a ``device-hung-step`` /
``device-quarantine`` / ``device-fault`` snapshot shows WHAT tripped
amid the batches around it.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BAR_WIDTH = 40


def _bar(rec: dict) -> str:
    """Proportional stage bar over the record's e2e latency."""
    e2e = max(float(rec.get("e2e_ms", 0.0)), 1e-9)
    cells = []
    for key, ch in (("wait_ms", "w"), ("dispatch_ms", "d"),
                    ("egress_ms", "e")):
        n = int(round(min(1.0, float(rec.get(key, 0.0)) / e2e) * BAR_WIDTH))
        cells.append(ch * n)
    bar = "".join(cells)[:BAR_WIDTH]
    return bar + "·" * (BAR_WIDTH - len(bar))


def _event_line(rec: dict) -> str:
    """One ``**`` event line for a kind-style ring record (hung-step,
    quarantine, …): seq/slot/rows columns stay aligned with the batch
    rows; everything else folds into a key=value tail so unknown kinds
    still render complete instead of being dropped."""
    kind = rec["kind"]
    slot = rec.get("slot")
    extras = {k: v for k, v in rec.items()
              if k not in ("kind", "seq", "slot", "rows", "ts")}
    tail = " ".join(f"{k}={v}" for k, v in sorted(extras.items()))
    return (f"{rec.get('seq', -1):>6} "
            f"{'-' if slot is None else slot:>4} "
            f"{rec.get('rows', 0):>6} "
            f"{'':>9} {'':>9}  ** {kind}"
            + (f": {tail}" if tail else ""))


def render(snapshot: dict, limit: int = 100, out=sys.stdout) -> None:
    header = snapshot["header"]
    records = snapshot["records"][-limit:]
    print(f"flight-recorder snapshot: reason={header.get('reason')} "
          f"records={header.get('records')} "
          f"{('detail=' + str(header.get('detail'))) if header.get('detail') else ''}",
          file=out)
    print(f"{'seq':>6} {'slot':>4} {'rows':>6} {'ovl':<9} "
          f"{'e2e_ms':>9}  {'timeline (w=wait d=dispatch e=egress)':<{BAR_WIDTH}}"
          f"  commit", file=out)
    events = 0
    for rec in records:
        if rec.get("kind"):
            events += 1
            print(_event_line(rec), file=out)
            continue
        slot = rec.get("slot")
        mark = "!!" if rec.get("commit") != "ok" else "  "
        line = (f"{rec.get('seq', -1):>6} "
                f"{'-' if slot is None else slot:>4} "
                f"{rec.get('rows', 0):>6} "
                f"{str(rec.get('overload', '?')):<9} "
                f"{float(rec.get('e2e_ms', 0.0)):>9.3f}  "
                f"{_bar(rec)}  {mark}{rec.get('commit', '?')}")
        if rec.get("error"):
            line += f"  [{rec['error']}]"
        print(line, file=out)
    batches = len(records) - events
    failed = sum(1 for r in records
                 if not r.get("kind") and r.get("commit") != "ok")
    tail = f", {events} events" if events else ""
    print(f"{batches} batches shown, {failed} failed commits{tail}",
          file=out)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="render a flight-recorder JSONL snapshot as a timeline")
    parser.add_argument("path", nargs="?",
                        help="snapshot .jsonl file")
    parser.add_argument("--url",
                        help="fetch the snapshot over HTTP instead "
                             "(the REST download endpoint)")
    parser.add_argument("--limit", type=int, default=100,
                        help="newest N records to render")
    args = parser.parse_args(argv)

    from sitewhere_tpu.runtime.flightrec import parse_snapshot

    if args.url:
        import urllib.request

        with urllib.request.urlopen(args.url, timeout=10) as resp:
            data = resp.read()
    elif args.path:
        with open(args.path, "rb") as f:
            data = f.read()
    else:
        parser.error("pass a snapshot path or --url")
        return 2
    try:
        snapshot = parse_snapshot(data)
    except ValueError as e:
        print(f"not a valid flight-recorder snapshot: {e}",
              file=sys.stderr)
        return 1
    render(snapshot, limit=args.limit)
    return 0


if __name__ == "__main__":
    sys.exit(main())
