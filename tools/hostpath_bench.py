#!/usr/bin/env python
"""Host-path micro-benchmark: decode / batch / dispatch / egress in
isolation, with a printed stage breakdown.

The overlapped host pipeline (README "Performance") only pays off when
the slowest stage — not the SUM of stages — bounds throughput.  This
tool measures each stage alone, on the same synthetic fleet traffic the
bench uses, so a regression localizes to one stage instead of hiding in
an end-to-end number:

- **decode**   — ``decode_json_lines`` over an NDJSON measurement
  payload (the decode-pool worker's unit of work);
- **batch**    — ``Batcher.add_arrays`` intake + packed emission (the
  dispatch thread's assembly stage);
- **h2d**      — ``device_put`` staging of one packed batch (the
  double-buffer front half — hidden behind compute when staged ahead);
- **dispatch** — the jitted packed pipeline step, post-warmup (h2d sync
  + device dwell + output allocation: the single-step host view);
- **dwell**    — the DEVICE-side step time alone, from a chained
  ``ring_k``-step program (one host round-trip covers the chain, the
  measured RTT is subtracted — the phase-C methodology, and the cost a
  ring slot actually pays on device);
- **d2h**      — blocking fetch of one step's output block + metrics
  (what egress pays when the async copy did NOT land in time);
- **egress**   — ``SegmentStore.append_columns`` of one batch (the
  offload worker's unit of work: a shard-routed packed row copy);
- **seal split** — the segment store's hand-off vs background seal:
  ``seal_perceived_s`` is the hot path's whole per-batch seal cost
  (row copy + O(1) job enqueue with the worker pool live) and
  ``seal_background_s`` the per-segment build+write wall time on the
  background workers (the ``store.seal_s`` stage timer).

Also reports ``host_rtt_s`` (trivial-program round-trip: the per-sync
floor on a network-attached chip) and ``host_syncs_per_batch`` for the
single-step (1.0) vs ring (1/ring_k) dispatch paths — every remaining
millisecond of config-2 latency attributes to exactly one of these
rows.

Prints one line per stage (per-batch host ms + events/s), the serial
sum, and the pipeline bound (the max stage — what the overlapped
dispatcher can approach).

Usage::

    python tools/hostpath_bench.py                 # defaults
    python tools/hostpath_bench.py --width 4096 --iters 32
    python tools/hostpath_bench.py --json          # machine-readable
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time_stage(fn, iters: int) -> float:
    """Median-of-iters wall seconds for one call of ``fn``."""
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return samples[len(samples) // 2]


def _payload(width: int) -> bytes:
    lines = [
        json.dumps({
            "deviceToken": f"dev-{i}", "type": "Measurement",
            "request": {"name": "temp", "value": 20.0 + (i % 7),
                        "eventDate": 1_753_800_000 + i},
        })
        for i in range(width)
    ]
    return ("\n".join(lines)).encode()


def _measure_rtt() -> float:
    """Median dispatch round-trip of a trivial jitted program (seconds)
    — the shared probe from the telemetry library, so the tool, the
    bench, and the production calibration subtract the same floor."""
    from sitewhere_tpu.pipeline.telemetry import measure_rtt

    return measure_rtt()


def run(width: int = 2048, iters: int = 16, capacity: int = 16_384,
        ring_k: int = 8, data_dir: str | None = None) -> dict:
    import numpy as np

    from sitewhere_tpu.ids import NULL_ID, HandleSpace
    from sitewhere_tpu.ingest.batcher import Batcher
    from sitewhere_tpu.ingest.columnar import decode_json_lines, space_of

    results: dict = {"width": width, "iters": iters}

    # -- decode --------------------------------------------------------------
    # Three-way A/B over the same payload (the zero-copy ingest story):
    #   fill    — fill-direct C scan straight into a batcher reservation
    #             (the production hot path; zero intermediate copies)
    #   native  — the classic C scanners returning intermediate buffers
    #             that Python re-materializes (the pre-fill-direct path,
    #             still the fallback; SW_NATIVE_FILL=0 forces it live)
    #   python  — the pure-Python columnar decoder (SW_NATIVE=0 behavior)
    from sitewhere_tpu.ingest.columnar import (
        CopyTally,
        _decode_lines_inner,
        decode_fill_direct,
        parse_envelopes,
    )

    devices = HandleSpace("device", capacity)
    for i in range(width):
        devices.mint(f"dev-{i}")
    payload = _payload(width)
    results["payload_bytes"] = len(payload)
    space = space_of(devices.lookup)
    decode_json_lines(payload, device_space=space)  # warm (native build)
    results["decode_native_s"] = _time_stage(
        lambda: decode_json_lines(payload, device_space=space), iters)
    native_tally = CopyTally()
    decode_json_lines(payload, device_space=space, copied=native_tally)
    results["bytes_copied_per_event_native"] = native_tally.n / width
    results["decode_python_s"] = _time_stage(
        lambda: _decode_lines_inner(parse_envelopes(payload)), iters)

    fill_batcher = Batcher(
        width=width, n_shards=1, registry_capacity=capacity,
        resolve_device=devices.lookup, resolve_mtype=lambda n: 0,
        resolve_alert=lambda n: 0, deadline_ms=1e9, emit_packed=True)
    cap = payload.count(b"\n") + 1

    def decode_fill_once():
        res = fill_batcher.reserve(cap)
        if res is None or decode_fill_direct(
                payload, space, res, lambda n: 0) is None:
            raise RuntimeError("fill-direct path unavailable")
        res.abort()

    try:
        decode_fill_once()
        results["decode_s"] = results["decode_fill_s"] = _time_stage(
            decode_fill_once, iters)
        results["fill_direct"] = True
    except RuntimeError:
        # no native toolchain: the production decode stage IS the
        # classic path — keep the A/B keys meaningful
        results["decode_s"] = results["decode_fill_s"] = \
            results["decode_native_s"]
        results["fill_direct"] = False
    results["bytes_copied_per_event_fill"] = 0.0 if results["fill_direct"] \
        else results["bytes_copied_per_event_native"]
    results["decode_speedup_fill_vs_native"] = (
        results["decode_native_s"] / results["decode_fill_s"]
        if results["decode_fill_s"] else 0.0)

    # full fill-direct ingest (decode + commit + ADOPTED zero-copy
    # emission — what the dispatcher's hot path pays per payload)
    if results["fill_direct"]:
        def ingest_fill_once():
            res = fill_batcher.reserve(cap)
            n = decode_fill_direct(payload, space, res, lambda n: 0)
            res.set_const(tenant_id=0, payload_ref=1)
            plans = res.commit()
            if n != width or len(plans) != 1:
                raise RuntimeError("adoption did not engage")

        ingest_fill_once()
        before = fill_batcher.copied_bytes
        ingest_fill_once()
        results["bytes_copied_per_event_fill_ingest"] = (
            fill_batcher.copied_bytes - before) / width
        results["ingest_fill_s"] = _time_stage(ingest_fill_once, iters)

    # -- batch (packed emission, the dispatch-thread assembly) ---------------
    batcher = Batcher(
        width=width, n_shards=1, registry_capacity=capacity,
        resolve_device=devices.lookup, resolve_mtype=lambda n: 0,
        resolve_alert=lambda n: 0, deadline_ms=1e9, emit_packed=True)
    ids = np.arange(width, dtype=np.int32) % capacity
    vals = np.linspace(0.0, 1.0, width).astype(np.float32)

    def batch_once():
        plans = batcher.add_arrays(_copy=False, device_id=ids.copy(),
                                   value=vals)
        if not plans:
            batcher.flush()

    batch_once()
    before = batcher.copied_bytes
    batch_once()
    results["bytes_copied_per_event_batch"] = \
        (batcher.copied_bytes - before) / width
    results["batch_s"] = _time_stage(batch_once, iters)

    # end-to-end copy accounting (decode + batch assembly), the
    # "bytes copied per event" acceptance column: the classic path pays
    # intermediate decode buffers + the emission memcpy; the fill path
    # pays zero on both (adopted full-width reservation)
    native_total = (results["bytes_copied_per_event_native"]
                    + results["bytes_copied_per_event_batch"])
    fill_total = results.get("bytes_copied_per_event_fill_ingest",
                             results["bytes_copied_per_event_fill"])
    results["bytes_copied_per_event_native_total"] = native_total
    results["bytes_copied_per_event_fill_total"] = fill_total
    results["bytes_copied_reduction"] = (
        native_total / fill_total if fill_total > 0 else None)
    results["bytes_copied_3x"] = bool(
        fill_total == 0 or native_total / fill_total >= 3.0)

    # -- dispatch (the jitted packed step, post-warmup) ----------------------
    import jax

    from sitewhere_tpu.pipeline.packed import (
        pack_batch_host,
        pack_state,
        pack_tables,
        packed_pipeline_step,
    )
    from sitewhere_tpu.schema import (
        DeviceState,
        Registry,
        RuleTable,
        ZoneTable,
    )

    registry = Registry.empty(capacity).replace(
        active=(np.arange(capacity) < width),
        assignment_status=np.ones(capacity, np.int32))
    tables = pack_tables(registry, RuleTable.empty(8), ZoneTable.empty(8))
    state = pack_state(DeviceState.empty(capacity))
    plan = batcher.add_arrays(_copy=False, device_id=ids.copy(),
                              value=vals) or [batcher.flush()]
    bi, bf = plan[0].packed_i, plan[0].packed_f
    step = jax.jit(packed_pipeline_step)
    out = step(tables, state, bi, bf)  # warm (compile)
    jax.block_until_ready(out)

    def dispatch_once():
        jax.block_until_ready(step(tables, state, bi, bf))

    results["dispatch_s"] = _time_stage(dispatch_once, iters)

    # -- h2d (device_put staging of one packed batch, the ring slot fill) ----
    def h2d_once():
        jax.block_until_ready((jax.device_put(bi), jax.device_put(bf)))

    h2d_once()
    results["h2d_stage_s"] = _time_stage(h2d_once, iters)

    # -- dwell (device-side step time from a chained ring_k-step program) ----
    from sitewhere_tpu.pipeline.packed import build_packed_chain

    rtt = _measure_rtt()
    results["host_rtt_s"] = rtt
    staged_bi = jax.device_put(bi)
    staged_bf = jax.device_put(bf)
    chain = build_packed_chain(ring_k, donate=True)
    carry = pack_state(DeviceState.empty(capacity))
    slots = [staged_bi] * ring_k + [staged_bf] * ring_k
    carry, ois, mets, present = chain(tables, carry, *slots)  # compile
    jax.block_until_ready(mets)
    samples = []
    for _ in range(max(2, iters // 4)):
        t0 = time.perf_counter()
        carry, ois, mets, present = chain(tables, carry, *slots)
        int(jax.device_get(mets)[0][0])  # force the whole chain
        samples.append(max(0.0, time.perf_counter() - t0 - rtt) / ring_k)
    samples.sort()
    results["device_dwell_s"] = samples[len(samples) // 2]
    results["ring_chain_k"] = ring_k
    # how often the host must touch the device per dispatched batch
    results["host_syncs_per_batch_single"] = 1.0
    results["host_syncs_per_batch_ring"] = 1.0 / ring_k

    # -- d2h (blocking fetch of one step's outputs — the per-sync cost) ------
    # fresh outputs per sample: jax caches a fetched array's host copy,
    # so re-fetching the same buffer would measure a dict lookup
    outs = []
    for _ in range(iters):
        o = step(tables, state, bi, bf)
        outs.append((o[1], o[2]))
    jax.block_until_ready(outs)
    samples = []
    for oi_dev, met_dev in outs:
        t0 = time.perf_counter()
        jax.device_get((oi_dev, met_dev))
        samples.append(time.perf_counter() - t0)
    samples.sort()
    results["d2h_fetch_s"] = samples[len(samples) // 2]

    # -- egress (segment-store append: the hot path's whole seal cost) -------
    from sitewhere_tpu.runtime.metrics import MetricsRegistry
    from sitewhere_tpu.store.segmented import SegmentStore

    tmp = data_dir or tempfile.mkdtemp(prefix="hostpath-bench-")
    try:
        store_metrics = MetricsRegistry()
        store = SegmentStore(tmp, flush_rows=1 << 30, flush_interval_s=1e9,
                             compact_interval_s=0.0, metrics=store_metrics)
        cols = {
            "device_id": ids, "tenant_id": np.zeros(width, np.int32),
            "event_type": np.zeros(width, np.int32),
            "ts_s": np.full(width, 1_753_800_000, np.int32),
            "ts_ns": np.zeros(width, np.int32),
            "mtype_id": np.zeros(width, np.int32), "value": vals,
            "lat": np.zeros(width, np.float32),
            "lon": np.zeros(width, np.float32),
            "elevation": np.zeros(width, np.float32),
            "alert_code": np.full(width, NULL_ID, np.int32),
            "alert_level": np.zeros(width, np.int32),
            "command_id": np.full(width, NULL_ID, np.int32),
            "payload_ref": np.full(width, NULL_ID, np.int32),
            "device_type_id": np.zeros(width, np.int32),
            "assignment_id": ids, "area_id": np.zeros(width, np.int32),
            "customer_id": np.zeros(width, np.int32),
            "asset_id": np.zeros(width, np.int32),
        }
        mask = np.ones(width, bool)

        def egress_once():
            # the offload worker's per-batch work is the append: a
            # shard-routed packed row copy (segment seal happens on the
            # background worker pool, off this path)
            store.append_columns(cols, mask=mask)

        egress_once()
        results["egress_s"] = _time_stage(egress_once, iters)
        t0 = time.perf_counter()
        store.flush()
        results["seal_s"] = time.perf_counter() - t0

        # -- seal hand-off vs background seal (the segment-store split) ------
        # perceived: a store whose buffers fill EVERY batch, with the
        # worker pool live — each append closes a shard buffer and
        # enqueues a seal job, so this measures the full hot-path seal
        # cost (copy + O(1) enqueue), never the npz write/fsync.
        seal_dir = os.path.join(tmp, "seal-split")
        pool_metrics = MetricsRegistry()
        pool_store = SegmentStore(
            seal_dir, flush_rows=width, flush_interval_s=1e9,
            compact_interval_s=0.0, metrics=pool_metrics)
        pool_store.sealer.start()
        try:
            pool_store.append_columns(cols, mask=mask)  # warm buffers
            results["seal_perceived_s"] = _time_stage(
                lambda: pool_store.append_columns(cols, mask=mask), iters)
            pool_store.flush()
            # the background stage timer: store.seal_s observes each
            # worker's build+write wall time, off the perceived path
            hist = pool_metrics.histogram("store.seal_s")
            results["seal_background_s"] = (
                hist.total / hist.count if hist.count else 0.0)
            results["seal_background_segments"] = int(hist.count)
        finally:
            pool_store.sealer.stop()
    finally:
        if data_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)

    # -- flight recorder (the always-on per-batch record cost) ---------------
    # The recorder's acceptance bar is <1% of per-batch host budget: one
    # dict build + deque append, memory-only here (snapshot I/O happens
    # only on anomaly, off the steady-state path).
    from sitewhere_tpu.runtime.flightrec import FlightRecorder

    rec = FlightRecorder(data_dir=None, capacity=2048)

    def record_once():
        rec.record(seq=1, reason="fill", rows=width, fill=1.0, slot=0,
                   replay_depth=0, wait_ms=0.1, dispatch_ms=0.2,
                   egress_ms=0.3, e2e_ms=1.0, overload="NORMAL",
                   trace_id=None, commit="ok")

    record_once()
    results["flightrec_record_s"] = _time_stage(
        record_once, max(iters, 256))

    # -- tenant metering (the per-plan ledger charge cost) -------------------
    # Same acceptance bar as the recorder: <1% of the per-batch host
    # budget.  The device already bucketed rows/writes/nonfinite per
    # tenant inside the compiled step (zero extra syncs); the host-side
    # residue measured here is one bucket→tenant attribution over the
    # retained tenant column plus the sketch/window fold.
    from sitewhere_tpu.pipeline.packed import (
        TENANT_METER_COUNTERS,
        TENANT_METER_SLOTS,
    )
    from sitewhere_tpu.runtime.metering import UsageLedger

    ledger = UsageLedger()
    meter_tenants = (np.arange(width, dtype=np.int32) % 7).astype(np.int32)
    meter_block = np.zeros(
        (len(TENANT_METER_COUNTERS), TENANT_METER_SLOTS), np.int64)
    counts = np.bincount(meter_tenants % TENANT_METER_SLOTS,
                         minlength=TENANT_METER_SLOTS)
    meter_block[0] = counts          # rows
    meter_block[1] = counts          # state_writes

    def meter_once():
        ledger.charge_device_block(meter_block, meter_tenants,
                                   decode_s=1e-4)

    meter_once()
    results["metering_charge_s"] = _time_stage(meter_once, max(iters, 256))

    serial = sum(results[k] for k in
                 ("decode_s", "batch_s", "dispatch_s", "egress_s"))
    bound = max(results[k] for k in
                ("decode_s", "batch_s", "dispatch_s", "egress_s"))
    results["serial_s"] = serial
    results["pipeline_bound_s"] = bound
    results["serial_events_per_s"] = width / serial if serial else 0.0
    results["overlapped_events_per_s"] = width / bound if bound else 0.0
    # per-batch recorder cost over the stage that bounds throughput —
    # the "<1% throughput delta" acceptance number
    results["flightrec_overhead_frac"] = (
        results["flightrec_record_s"] / bound if bound else 0.0)
    results["metering_overhead_frac"] = (
        results["metering_charge_s"] / bound if bound else 0.0)
    return results


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="host-path stage breakdown (decode/batch/dispatch/egress)")
    parser.add_argument("--width", type=int, default=2048,
                        help="events per payload/batch")
    parser.add_argument("--iters", type=int, default=16,
                        help="timing iterations per stage (median)")
    parser.add_argument("--capacity", type=int, default=16_384)
    parser.add_argument("--ring-k", type=int, default=8,
                        help="chain depth for the device-dwell probe "
                             "(the dispatcher ring's K)")
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend")
    parser.add_argument("--json", action="store_true",
                        help="print the raw results dict as JSON")
    args = parser.parse_args(argv)

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    r = run(width=args.width, iters=args.iters, capacity=args.capacity,
            ring_k=args.ring_k)
    if args.json:
        print(json.dumps(r, indent=2))
        return 0
    print(f"host-path stage breakdown  (width={r['width']}, "
          f"iters={r['iters']}, median)")
    for stage, key in (("decode", "decode_s"), ("batch", "batch_s"),
                       ("h2d", "h2d_stage_s"), ("dispatch", "dispatch_s"),
                       ("dwell", "device_dwell_s"), ("d2h", "d2h_fetch_s"),
                       ("egress", "egress_s")):
        s = r[key]
        rate = r["width"] / s if s else float("inf")
        print(f"  {stage:<9} {s * 1e3:9.3f} ms/batch   {rate:12,.0f} events/s")
    # zero-copy ingest A/B (decode stage + copy accounting)
    mode = "fill-direct" if r.get("fill_direct") else "no native toolchain"
    print(f"  decode A/B ({mode}): fill {r['decode_fill_s'] * 1e3:.3f} ms"
          f" | native {r['decode_native_s'] * 1e3:.3f} ms"
          f" | python {r['decode_python_s'] * 1e3:.3f} ms"
          f"  → {r['decode_speedup_fill_vs_native']:.2f}x vs native")
    red = r.get("bytes_copied_reduction")
    print(f"  bytes copied/event: fill "
          f"{r['bytes_copied_per_event_fill_total']:.1f} B"
          f" | native {r['bytes_copied_per_event_native_total']:.1f} B"
          f" ({'∞' if red is None else f'{red:.1f}x'} reduction)")
    print(f"  {'serial':<9} {r['serial_s'] * 1e3:9.3f} ms/batch   "
          f"{r['serial_events_per_s']:12,.0f} events/s")
    print(f"  pipeline bound (max stage): "
          f"{r['pipeline_bound_s'] * 1e3:.3f} ms/batch → "
          f"{r['overlapped_events_per_s']:,.0f} events/s overlapped")
    print(f"  host sync floor: rtt {r['host_rtt_s'] * 1e3:.3f} ms — "
          f"host_syncs/batch 1.0 single-step, "
          f"{r['host_syncs_per_batch_ring']:.3f} ring "
          f"(K={r['ring_chain_k']} chained)")
    print(f"  flight recorder: {r['flightrec_record_s'] * 1e6:.2f} "
          f"µs/batch record — "
          f"{r['flightrec_overhead_frac'] * 100:.4f}% of the pipeline "
          f"bound (<1% = always-on is free)")
    print(f"  tenant metering: {r['metering_charge_s'] * 1e6:.2f} "
          f"µs/batch charge — "
          f"{r['metering_overhead_frac'] * 100:.4f}% of the pipeline "
          f"bound (<1% = metering-on is free)")
    print(f"  (one-time seal of {r['iters'] + 1} buffered batches: "
          f"{r['seal_s'] * 1e3:.3f} ms — amortized at commit points)")
    print(f"  seal split: perceived {r['seal_perceived_s'] * 1e3:.3f} "
          f"ms/batch on the hot path (copy + enqueue) | background "
          f"{r['seal_background_s'] * 1e3:.3f} ms/segment on the worker "
          f"pool ({r['seal_background_segments']} segments sealed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
