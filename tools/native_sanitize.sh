#!/usr/bin/env bash
# ASan/UBSan build of the C wire scanner + the fill-direct test suite
# run under the instrumented build.
#
#   tools/native_sanitize.sh           # build + run tests/test_native_fill.py
#   tools/native_sanitize.sh --build   # build only, print the .so path
#
# The production build (native/__init__.py) compiles swwire.c with -O2 on
# first use; memory bugs in the scanner — the code that parses HOSTILE
# wire bytes straight into the batcher's buffers — would corrupt the
# packed columns silently.  This target rebuilds it with
# AddressSanitizer + UndefinedBehaviorSanitizer (no recover: any finding
# aborts the test run) and executes the full fill-direct suite against
# it via SW_NATIVE_LIB, with the sanitizer runtime LD_PRELOADed into the
# (uninstrumented) CPython host.
#
# Wired into the verify flow as the slow-marked tests/test_native_sanitize.py
# (pytest -m slow) and runnable standalone from any checkout.
set -euo pipefail

REPO="$(cd "$(dirname "$0")/.." && pwd)"
SRC="$REPO/sitewhere_tpu/native/swwire.c"
OUT_DIR="${TMPDIR:-/tmp}/sw_native_sanitize"
OUT="$OUT_DIR/_swwire_sanitized.so"
CC="${CC:-cc}"

command -v "$CC" >/dev/null || { echo "native_sanitize: no C compiler" >&2; exit 3; }

INCLUDE="$(python -c 'import sysconfig; print(sysconfig.get_paths()["include"])')"
LIBASAN="$("$CC" -print-file-name=libasan.so)"
if [ ! -e "$LIBASAN" ]; then
    echo "native_sanitize: libasan runtime not found ($LIBASAN)" >&2
    exit 3
fi

mkdir -p "$OUT_DIR"
echo "native_sanitize: building $OUT"
"$CC" -O1 -g -fno-omit-frame-pointer \
    -fsanitize=address,undefined -fno-sanitize-recover=all \
    -shared -fPIC -pthread -I"$INCLUDE" "$SRC" -o "$OUT" -lm

if [ "${1:-}" = "--build" ]; then
    echo "$OUT"
    exit 0
fi

# detect_leaks=0: CPython itself "leaks" interned objects at exit — leak
# checking an embedded interpreter is all noise; ASan's real value here
# is overflow/UAF/UB detection during the scan.
# verify_asan_link_order=0: the host python is uninstrumented, the
# runtime arrives via LD_PRELOAD — that inversion is exactly what the
# check would (falsely) reject.
cd "$REPO"
# Preflight: the instrumented .so must actually LOAD in the child
# environment — native/__init__.py swallows import failures into a
# Python-path fallback, and the native test suites skip wholesale when
# the module is absent, so a dlopen failure (ASan runtime mismatch,
# stripped LD_PRELOAD) would otherwise read as a vacuously green gate.
echo "native_sanitize: preflighting instrumented load"
env LD_PRELOAD="$LIBASAN" \
    ASAN_OPTIONS="detect_leaks=0,verify_asan_link_order=0" \
    SW_NATIVE_LIB="$OUT" SW_SANITIZED_SO="$OUT" JAX_PLATFORMS=cpu \
    python -c 'import os, sys
from sitewhere_tpu import native
mod = native.load_swwire()
want = os.environ["SW_SANITIZED_SO"]
origin = getattr(getattr(mod, "__spec__", None), "origin", None)
if origin != want:
    print("native_sanitize: instrumented .so did not load "
          "(got %r, wanted %r)" % (origin, want), file=sys.stderr)
    sys.exit(1)'

echo "native_sanitize: running tests/test_native_fill.py under ASan/UBSan"
env LD_PRELOAD="$LIBASAN" \
    ASAN_OPTIONS="detect_leaks=0,abort_on_error=1,verify_asan_link_order=0" \
    UBSAN_OPTIONS="print_stacktrace=1,halt_on_error=1" \
    SW_NATIVE_LIB="$OUT" \
    JAX_PLATFORMS=cpu \
    python -m pytest tests/test_native_fill.py tests/test_native_wire.py \
        tests/test_native_resolved.py -q -p no:cacheprovider "$@"
echo "native_sanitize: OK (ASan/UBSan clean)"
