"""Device-tier fault containment: breaker ladder, hung-step watchdog,
health-plane propagation, and the dispatcher's containment protocol.

Unit half: :mod:`sitewhere_tpu.runtime.devguard` under a fake clock —
distinct-batch strike counting, the chained → single-step →
cpu-fallback ladder, half-open probe semantics, soft/hard watchdog
budgets with parts-refcounted entries.  Integration half: a live
instance driven through the ``device.dispatch`` injection seam
(``runtime/faults.py``) — containment WITHOUT restart, poison-row
bisect to replayable dead letters, NaN quarantine via the packed
telemetry scalar, and the unhealthy flag riding the fleet heartbeat.
"""

import json
import time

import numpy as np
import pytest

from sitewhere_tpu.runtime import faults
from sitewhere_tpu.runtime.devguard import (
    CHAINED,
    FALLBACK,
    SINGLE_STEP,
    DeviceBreaker,
    DeviceWatchdog,
)


@pytest.fixture(autouse=True)
def _clean_device_faults():
    faults.device_clear()
    yield
    faults.device_clear()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# DeviceBreaker
# ---------------------------------------------------------------------------

class TestDeviceBreaker:
    def test_distinct_batches_trip_same_batch_does_not(self):
        clock = FakeClock()
        b = DeviceBreaker(threshold=3, clock=clock)
        # the bisect protocol re-faults ONE batch many times: one strike
        for _ in range(10):
            b.record_fault(seq=7)
        assert b.level == CHAINED and b.trips == 0
        b.record_fault(seq=8)
        assert b.level == CHAINED
        assert b.record_fault(seq=9)          # third DISTINCT batch
        assert b.level == SINGLE_STEP and b.trips == 1

    def test_strikes_age_out_of_the_window(self):
        clock = FakeClock()
        b = DeviceBreaker(threshold=2, window_s=60.0, clock=clock)
        b.record_fault(1)
        clock.advance(61.0)
        b.record_fault(2)                      # the first strike expired
        assert b.level == CHAINED
        b.record_fault(3)
        assert b.level == SINGLE_STEP

    def test_ladder_stops_at_fallback(self):
        clock = FakeClock()
        b = DeviceBreaker(threshold=1, clock=clock)
        b.record_fault(1)
        assert b.level == SINGLE_STEP
        b.record_fault(2)
        assert b.level == FALLBACK
        b.record_fault(3)
        assert b.level == FALLBACK             # no rung below fallback

    def test_cooldown_probe_then_chained_success_restores(self):
        clock = FakeClock()
        trips, restores = [], []
        b = DeviceBreaker(threshold=1, cooldown_s=30.0, clock=clock,
                          on_trip=trips.append,
                          on_restore=lambda: restores.append(True))
        b.record_fault(1)
        assert trips == [SINGLE_STEP]
        assert not b.allow_chain()             # cooling down
        clock.advance(31.0)
        assert b.allow_chain()                 # half-open probe admitted
        b.record_success(chained=True)
        assert b.level == CHAINED and restores == [True]
        assert b.allow_chain()

    def test_probe_failure_recloses_and_restarts_cooldown(self):
        clock = FakeClock()
        b = DeviceBreaker(threshold=1, cooldown_s=30.0, clock=clock)
        b.record_fault(1)
        clock.advance(31.0)
        assert b.allow_chain()                 # probing
        b.record_fault(2)                      # probe chain died
        assert b.level == FALLBACK             # and the strike escalated
        assert not b.allow_chain()
        clock.advance(29.0)
        assert not b.allow_chain()             # cooldown restarted
        clock.advance(2.0)
        assert b.allow_chain()

    def test_non_chained_success_does_not_restore(self):
        clock = FakeClock()
        b = DeviceBreaker(threshold=1, clock=clock)
        b.record_fault(1)
        b.record_success(chained=False)        # a single-step drain
        assert b.level == SINGLE_STEP

    def test_snapshot_shape(self):
        b = DeviceBreaker()
        snap = b.snapshot()
        assert snap["levelName"] == "chained"
        assert {"level", "strikes", "probing", "trips",
                "restores"} <= set(snap)


# ---------------------------------------------------------------------------
# DeviceWatchdog
# ---------------------------------------------------------------------------

class TestDeviceWatchdog:
    def test_soft_once_per_entry_hard_once_per_episode(self):
        clock = FakeClock()
        soft, hard = [], []
        wd = DeviceWatchdog(soft_s=1.0, hard_s=5.0, clock=clock,
                            on_soft=lambda r, e: soft.append((r, e)),
                            on_unhealthy=lambda r, e: hard.append((r, e)))
        token = wd.begin("plan-A")
        clock.advance(1.5)
        assert not wd.check()
        assert len(soft) == 1 and soft[0][0] == "plan-A"
        wd.check()
        assert len(soft) == 1                  # once per entry
        clock.advance(4.0)
        assert wd.check()                      # past hard: unhealthy
        assert len(hard) == 1 and wd.unhealthy
        wd.check()
        assert len(hard) == 1                  # once per episode
        wd.end(token)
        assert not wd.unhealthy                # self-clears on drain

    def test_parts_refcount_drains_on_last_end(self):
        clock = FakeClock()
        recovered = []
        wd = DeviceWatchdog(soft_s=1.0, hard_s=2.0, clock=clock,
                            on_recovered=lambda: recovered.append(True))
        token = wd.begin(["p1", "p2", "p3"], parts=3)
        clock.advance(3.0)
        assert wd.check() and wd.unhealthy
        wd.end(token)
        wd.end(token)
        assert wd.unhealthy                    # two of three parts done
        wd.end(token)
        assert not wd.unhealthy and recovered == [True]
        wd.end(token)                          # idempotent
        wd.end(None)                           # None-safe

    def test_opaque_records_hand_back_verbatim(self):
        clock = FakeClock()
        seen = []
        wd = DeviceWatchdog(soft_s=0.5, hard_s=9.0, clock=clock,
                            on_soft=lambda r, e: seen.append(r))
        payload = [object(), object()]
        wd.begin(payload, parts=2)
        clock.advance(1.0)
        wd.check()
        assert seen and seen[0] is payload     # no copy, no render

    def test_calibrate_floors_protect_cpu_hosts(self):
        wd = DeviceWatchdog()
        wd.calibrate(stage_ms=0.2)             # a fast chip
        assert wd.soft_s == pytest.approx(0.25)   # floored
        assert wd.hard_s == pytest.approx(2.0)    # floored
        wd.calibrate(stage_ms=30.0)            # a real TPU step
        assert wd.soft_s == pytest.approx(1.5)    # 50x stage
        assert wd.hard_s == pytest.approx(12.0)   # 400x stage

    def test_snapshot_tracks_oldest(self):
        clock = FakeClock()
        wd = DeviceWatchdog(clock=clock)
        wd.begin("x")
        clock.advance(2.0)
        snap = wd.snapshot()
        assert snap["inflight"] == 1
        assert snap["oldestS"] == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# health-plane propagation: the unhealthy flag rides the heartbeat
# ---------------------------------------------------------------------------

class TestDeviceUnhealthyPropagation:
    def _table(self):
        from sitewhere_tpu.rpc.health import PeerHealthTable

        clock = FakeClock()
        return PeerHealthTable([1], clock=clock), clock

    def test_unhealthy_peer_parks_drain_then_recovers(self):
        table, clock = self._table()
        table.observe_heartbeat(1, now=clock())
        assert table.can_drain(1)
        table.observe_heartbeat(1, device_unhealthy=True, now=clock())
        assert not table.can_drain(1)          # RPC alive, chip wedged
        assert table.snapshot()["1"]["device_unhealthy"] is True
        table.observe_heartbeat(1, device_unhealthy=False, now=clock())
        assert table.can_drain(1)

    def test_heartbeat_body_carries_the_dispatcher_flag(self, tmp_path):
        from sitewhere_tpu.rpc.forward import HostForwarder

        wedged = [False]
        fwd = HostForwarder(None, 0, {0: None},
                            data_dir=str(tmp_path / "spool"),
                            heartbeat_interval_s=0,
                            device_unhealthy=lambda: wedged[0])
        try:
            assert fwd.heartbeat_body(0)["deviceUnhealthy"] is False
            wedged[0] = True
            assert fwd.heartbeat_body(0)["deviceUnhealthy"] is True
        finally:
            fwd.stop()


# ---------------------------------------------------------------------------
# dispatcher integration: containment through the device seam
# ---------------------------------------------------------------------------

def _instance_config(tmp_path, **pipeline):
    from sitewhere_tpu.runtime.config import Config

    return Config({
        "instance": {"id": "devguard-inst",
                     "data_dir": str(tmp_path / "data")},
        "pipeline": {"width": 64, "registry_capacity": 128,
                     "mtype_slots": 4, "deadline_ms": 5.0, "n_shards": 1,
                     **pipeline},
        "overload": {"cooldown_s": 3600.0},
        "presence": {"scan_interval_s": 3600.0, "missing_after_s": 1800},
    }, apply_env=False)


def _seed_devices(inst, n=4):
    inst.device_management.create_device_type(token="sensor", name="S")
    for i in range(n):
        inst.device_management.create_device(token=f"d-{i}",
                                             device_type="sensor")
        inst.device_management.create_device_assignment(device=f"d-{i}")


def _lines(values, ts0=1_754_600_000, token="d-0"):
    return "\n".join(json.dumps({
        "deviceToken": token, "type": "Measurement",
        "request": {"name": "temp", "value": v, "eventDate": ts0 + i},
    }) for i, v in enumerate(values)).encode()


class TestDispatcherContainment:
    def test_device_fault_contained_without_restart(self, tmp_path):
        """A transient device fault is contained IN PROCESS: the full-set
        retry re-dispatches from the last committed epoch, every row
        commits, and the journal offset advances — no restart, no
        replay, no dead letters."""
        from sitewhere_tpu.instance import Instance

        inst = Instance(_instance_config(tmp_path))
        inst.start()
        try:
            _seed_devices(inst)
            gen0 = inst.device_state.lease_generation
            faults.device_inject("device.dispatch", times=1)
            inst.dispatcher.ingest_wire_lines(_lines([1.0, 2.0, 3.0]))
            inst.dispatcher.flush()
            inst.event_store.flush()
            assert faults.device_fired("device.dispatch") == 1
            assert inst.event_store.total_events == 3
            # the gate reopened: the offset committed past the record
            assert inst.dispatcher.journal_reader.committed == 1
            c = inst.metrics.snapshot()["counters"]
            assert c.get("device.fault.step_faults", 0) == 1
            assert c.get("device.fault.poison_rows", 0) == 0
            assert inst.dead_letters.end_offset == 0
            # same live manager throughout (no restart, no re-build)
            assert inst.device_state.lease_generation >= gen0
        finally:
            inst.stop()
            inst.terminate()

    def test_poison_rows_bisect_to_replayable_dead_letters(self, tmp_path):
        """Only the poison rows leave the pipeline — isolated by bisect,
        dead-lettered with their raw columns, and replayable through
        ``requeue_dead_letter`` into the quarantine path."""
        from sitewhere_tpu.instance import Instance

        inst = Instance(_instance_config(tmp_path,
                                         quarantine_after=2))
        inst.start()
        try:
            _seed_devices(inst)
            faults.device_inject("device.dispatch", times=None,
                                 when_nonfinite=True)
            inst.dispatcher.ingest_wire_lines(
                _lines([1.0, float("nan"), 3.0, float("nan"), 5.0]))
            inst.dispatcher.flush()
            faults.device_clear()
            inst.event_store.flush()
            # the three clean rows committed; the two poison rows left
            assert inst.event_store.total_events == 3
            letters = [d for d in inst.list_dead_letters(limit=10)
                       if d.get("kind") == "device-poison"]
            assert sum(d["count"] for d in letters) == 2
            vals = [v for d in letters for v in d["columns"]["value"]]
            assert all(not np.isfinite(v) for v in vals)

            # replay: the rows re-enter, the device masks + counts them,
            # and the host attribution quarantines the offender
            for d in letters:
                res = inst.requeue_dead_letter(int(d["offset"]))
                assert res["requeued"] and res["kind"] == "device-poison"
            inst.dispatcher.flush()
            snap = inst.metrics.snapshot()
            assert snap["counters"].get(
                "pipeline.quarantine.rows_nonfinite", 0) == 2
            assert snap["gauges"].get(
                "pipeline.quarantine.devices", 0) == 1
            assert snap["counters"].get(
                "pipeline.quarantine.state_changes", 0) == 1
            df = inst.dispatcher.metrics_snapshot()["device_fault"]
            assert df["quarantined_devices"] == 1
        finally:
            inst.stop()
            inst.terminate()

    def test_watchdog_trips_and_recovers_on_live_instance(self, tmp_path):
        """A stalled dispatch trips soft then hard from the LOOP thread
        (the dispatch thread is the one wedged), and the tier recovers
        when the dispatch drains."""
        from sitewhere_tpu.instance import Instance

        inst = Instance(_instance_config(tmp_path))
        inst.start()
        try:
            _seed_devices(inst)
            inst.dispatcher.watchdog.soft_s = 0.03
            inst.dispatcher.watchdog.hard_s = 0.12
            faults.device_inject("device.dispatch", exc=None,
                                 stall_s=0.4)
            inst.dispatcher.ingest_wire_lines(_lines([1.0]))
            inst.dispatcher.flush()
            wd = inst.dispatcher.watchdog.snapshot()
            assert wd["softTrips"] >= 1 and wd["hardTrips"] >= 1
            assert not wd["unhealthy"]         # self-cleared on drain
            assert not inst.dispatcher.device_unhealthy
            c = inst.metrics.snapshot()["counters"]
            assert c.get("device.fault.watchdog_soft_trips", 0) >= 1
            assert c.get("device.fault.watchdog_hard_trips", 0) >= 1
            # zero loss: the stalled rows still landed
            inst.event_store.flush()
            assert inst.event_store.total_events == 1
        finally:
            inst.stop()
            inst.terminate()

    def test_breaker_trip_rides_and_releases_the_overload_ladder(
            self, tmp_path):
        """The breaker trip forces DEGRADED with its own driver tag; the
        restore releases ONLY its own demotion."""
        from sitewhere_tpu.instance import Instance
        from sitewhere_tpu.runtime.overload import OverloadState

        inst = Instance(_instance_config(tmp_path))
        inst.start()
        try:
            _seed_devices(inst)
            d = inst.dispatcher
            d.breaker.cooldown_s = 3600.0
            for seq in range(d.breaker.threshold):
                faults.device_inject("device.dispatch", times=1)
                d.ingest_wire_lines(_lines([float(seq)],
                                           ts0=1_754_700_000 + 10 * seq))
                d.flush()
                faults.device_clear()
            assert d.breaker.level == SINGLE_STEP
            assert inst.overload.state == OverloadState.DEGRADED
            assert inst.overload.last_driver == "device-breaker"
            # restore via the breaker's own path releases the force
            d.breaker.record_success(chained=True)
            assert d.breaker.level == CHAINED
            assert inst.overload.state == OverloadState.NORMAL
        finally:
            inst.stop()
            inst.terminate()
