"""Driver-contract smoke tests for __graft_entry__ (CPU mesh)."""

import sys

sys.path.insert(0, "/root/repo")

import jax

import __graft_entry__ as graft


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    _, out = jax.jit(fn)(*args)
    assert int(out.metrics.processed) == args[-1].width


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)
