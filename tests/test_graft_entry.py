"""Driver-contract smoke tests for __graft_entry__ (CPU mesh)."""

import sys

sys.path.insert(0, "/root/repo")

import jax

import __graft_entry__ as graft


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    _, out = jax.jit(fn)(*args)
    assert int(out.metrics.processed) == args[-1].width


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


def test_dryrun_mesh_ring_4():
    """Tier-1 gate for the fused chained mesh: the K-deep packed chain
    under shard_map on a 4-way forced-CPU mesh, host_syncs == steps/K.
    Guarded like the other sharded tests: if the shard_map shim cannot
    import, skip rather than re-joining the old ImportError set."""
    import pytest

    try:
        from sitewhere_tpu.pipeline.sharded import (  # noqa: F401
            build_sharded_packed_chain,
        )
    except Exception as e:  # pragma: no cover - environment-dependent
        pytest.skip(f"sharded pipeline unavailable: {e}")
    graft.dryrun_mesh_ring(4, ring_depth=4)
