"""Tenant metering plane: sketches, attribution, ledger, ladder hook.

The accuracy contract is tested against exact counting on a skewed
O(10k)-tenant stream: space-saving reports every count within its own
error bound (≤ N/k) and never loses a tenant above the threshold, and
count-min point reads never underestimate.  The ledger tests cover the
deferred device-block fold (segment-sum blocks are additive, so reads
must flush pending accumulation), decode-time apportionment, eviction
folding into the long-tail aggregate, the checkpoint round-trip (window
deliberately restarts empty), and the overload-ladder integration: a
heavy tenant's DEGRADED budget tightens from its MEASURED share while a
quiet tenant keeps the uniform one — all on injected clocks, no sleeps.
"""

import json

import numpy as np
import pytest

from sitewhere_tpu.pipeline.packed import TENANT_METER_SLOTS
from sitewhere_tpu.runtime.metering import (
    CountMin,
    SpaceSaving,
    UsageLedger,
    attribute_block,
)
from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.runtime.overload import (
    OverloadController,
    OverloadState,
    PriorityClass,
)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _skewed_stream(n_tenants=10_000, base=3_000, seed=7):
    """Zipf-ish per-tenant true counts and a shuffled offer order."""
    true = np.maximum(1, base // np.arange(1, n_tenants + 1))
    stream = np.repeat(np.arange(n_tenants), true)
    rng = np.random.default_rng(seed)
    rng.shuffle(stream)
    return true, stream


class TestSketches:
    def test_space_saving_error_bound_on_skewed_fleet(self):
        k = 128
        true, stream = _skewed_stream()
        ss = SpaceSaving(k)
        for t in stream.tolist():
            ss.offer(t)
        n = len(stream)
        ranked = ss.topk()
        assert len(ranked) == k
        for key, count, error in ranked:
            # reported ∈ [true, true + error], error ≤ N/k
            assert count >= true[key]
            assert count - error <= true[key]
            assert error <= n / k
        # guaranteed capture: every tenant above N/k is tracked
        tracked = {key for key, _, _ in ranked}
        for t in np.nonzero(true > n / k)[0].tolist():
            assert t in tracked, f"tenant {t} (true={true[t]}) lost"
        # and the rank order surfaces the actual heaviest tenant first
        assert ranked[0][0] == 0

    def test_count_min_never_underestimates(self):
        true, stream = _skewed_stream(n_tenants=5_000, base=2_000)
        cm = CountMin(width=1024, depth=4)
        cm.add_many(stream, np.ones(len(stream), np.int64))
        assert cm.total == len(stream)
        bound = 2 * len(stream) / cm.width
        over = []
        for t in range(0, 5_000, 97):
            est = cm.estimate(t)
            assert est >= true[t]
            over.append(est - true[t])
        # expected overestimate ≤ 2N/width with prob ≥ 1-(1/2)^depth;
        # deterministic stream + fixed salts, so assert the mean holds
        assert np.mean(over) <= bound

    def test_add_many_matches_scalar_add(self):
        a, b = CountMin(width=64, depth=4), CountMin(width=64, depth=4)
        keys = [3, 99, 123_456, 3, 2**40 + 5]
        amounts = [5, 2, 9, 4, 1]
        for k, amt in zip(keys, amounts):
            a.add(k, amt)
        b.add_many(keys, amounts)
        np.testing.assert_array_equal(a.table, b.table)
        assert a.total == b.total


class TestAttributeBlock:
    def _block(self, ids):
        block = np.zeros((3, TENANT_METER_SLOTS), np.int64)
        block[0] = np.bincount(ids % TENANT_METER_SLOTS,
                               minlength=TENANT_METER_SLOTS)
        block[1] = block[0]
        return block

    def test_single_owner_buckets_attribute_exactly(self):
        ids = np.array([1] * 30 + [2] * 10 + [5] * 3, np.int32)
        out, collided = attribute_block(self._block(ids), ids)
        assert collided == 0
        assert {t: v["rows"] for t, v in out.items()} == {1: 30, 2: 10, 5: 3}
        assert out[1]["state_writes"] == 30

    def test_collision_apportions_by_row_share(self):
        # 1 and 1 + slots land in the same bucket
        ids = np.array([1] * 30 + [1 + TENANT_METER_SLOTS] * 10, np.int32)
        out, collided = attribute_block(self._block(ids), ids)
        assert collided == 1
        assert out[1]["rows"] == pytest.approx(30)
        assert out[1 + TENANT_METER_SLOTS]["rows"] == pytest.approx(10)
        # mass conserved across the split
        assert sum(v["rows"] for v in out.values()) == pytest.approx(40)

    def test_padding_rows_ignored(self):
        ids = np.array([-1] * 8 + [5] * 4, np.int32)
        block = np.zeros((3, TENANT_METER_SLOTS), np.int64)
        block[0, 5] = 4
        out, collided = attribute_block(block, ids)
        assert {t: v["rows"] for t, v in out.items()} == {5: 4}
        assert collided == 0

    def test_empty_block_and_empty_ids(self):
        zeros = np.zeros((3, TENANT_METER_SLOTS), np.int64)
        assert attribute_block(zeros, np.array([1, 2])) == ({}, 0)
        block = np.zeros((3, TENANT_METER_SLOTS), np.int64)
        block[0, 3] = 7
        assert attribute_block(block, np.array([], np.int32)) == ({}, 0)


class TestUsageLedger:
    def _charge(self, led, ids, decode_s=0.0):
        block = np.zeros((3, TENANT_METER_SLOTS), np.int64)
        block[0] = np.bincount(ids % TENANT_METER_SLOTS,
                               minlength=TENANT_METER_SLOTS)
        led.charge_device_block(block, ids, decode_s=decode_s)

    def test_deferred_fold_flushes_on_read(self):
        led = UsageLedger(fold_every=8, clock=FakeClock())
        ids = np.array([1] * 30 + [2] * 10, np.int32)
        for _ in range(3):          # below the fold cadence
            self._charge(led, ids, decode_s=0.01)
        u = led.usage_of(1)         # read surface flushes pending
        assert u["tracked"]
        assert u["usage"]["rows"] == 90
        # decode time apportioned by accepted-row share: 30/40 of 0.03
        assert u["usage"]["decode_s"] == pytest.approx(0.0225)
        assert led.usage_of(2)["usage"]["rows"] == 30
        assert led.snapshot()["totals"]["rows"] == 120

    def test_fold_cadence_triggers_without_reads(self):
        clock = FakeClock()
        led = UsageLedger(fold_every=4, clock=clock)
        ids = np.array([3] * 10, np.int32)
        for _ in range(4):
            self._charge(led, ids)
        # folded by cadence alone — inspect without the flushing readers
        with led._lock:
            assert led._totals["rows"] == 40

    def test_eviction_folds_exact_row_into_other(self):
        led = UsageLedger(top_k=2, fold_every=1, clock=FakeClock())
        self._charge(led, np.full(100, 1, np.int32))
        self._charge(led, np.full(50, 2, np.int32))
        self._charge(led, np.full(60, 3, np.int32))   # evicts tenant 2
        snap = led.snapshot()
        assert {t["tenant_id"] for t in snap["tenants"]} == {1, 3}
        assert snap["other"]["rows"] == 50
        assert snap["totals"]["rows"] == 210
        u = led.usage_of(2)
        assert not u["tracked"] and u["estimated"]
        assert u["rows_estimate"] >= 50    # count-min floor
        # space-saving overestimate carries the evicted floor as error
        top = {key: (count, err) for key, count, err in led.topk()}
        assert top[3] == (110, 50)

    def test_window_shares_and_rate_scale(self):
        clock = FakeClock()
        led = UsageLedger(window_s=60.0, fold_every=1, clock=clock,
                          fair_share_frac=0.25, min_rate_frac=0.1)
        self._charge(led, np.full(75, 1, np.int32))
        self._charge(led, np.full(25, 2, np.int32))
        shares = led.shares()
        assert shares[1] == pytest.approx(0.75)
        assert shares[2] == pytest.approx(0.25)
        assert led.rate_scale(1) == pytest.approx(0.25 / 0.75)
        assert led.rate_scale(2) == 1.0          # at fair share: untouched
        # window expiry: shares describe CURRENT load only
        clock.t += 120.0
        assert led.shares() == {}
        assert led.rate_scale(1) == 1.0
        # lifetime usage is NOT windowed
        assert led.usage_of(1)["usage"]["rows"] == 75

    def test_checkpoint_round_trip(self):
        clock = FakeClock()
        led = UsageLedger(top_k=4, fold_every=1, clock=clock)
        self._charge(led, np.array([1] * 30 + [2] * 10, np.int32),
                     decode_s=0.02)
        led.charge(1, "shed_rows", 5)
        led.charge_rows_host(np.full(6, 2, np.int32), "outbound_rows")
        payload, header = led.snapshot_payload()
        json.loads(payload.decode())    # checkpoint body is valid JSON

        led2 = UsageLedger(top_k=4, clock=FakeClock())
        led2.restore_payload(header or {}, payload)
        assert led2.usage_of(1) == led.usage_of(1)
        assert led2.usage_of(2) == led.usage_of(2)
        assert led2.snapshot()["totals"] == led.snapshot()["totals"]
        assert led2._cm.estimate(1) == led._cm.estimate(1)
        # the sliding window restarts empty: pre-crash load is not
        # evidence about the post-restart stream
        assert led2.shares() == {}
        # and the restored ledger keeps charging correctly
        self._charge(led2, np.full(10, 1, np.int32))
        assert led2.usage_of(1)["usage"]["rows"] == 40

    def test_restore_drops_stale_geometry_sketch(self):
        led = UsageLedger(sketch_width=64, sketch_depth=2, fold_every=1,
                          clock=FakeClock())
        self._charge(led, np.full(10, 1, np.int32))
        payload, header = led.snapshot_payload()
        led2 = UsageLedger(sketch_width=128, sketch_depth=2,
                           clock=FakeClock())
        led2.restore_payload(header or {}, payload)
        # exact rows restore; the mis-shaped sketch starts fresh rather
        # than mis-hash restored cells
        assert led2.usage_of(1)["usage"]["rows"] == 10
        assert led2._cm.total == 0


class TestLadderIntegration:
    def test_heavy_tenant_degraded_rate_tightens(self):
        clock = FakeClock()
        led = UsageLedger(fold_every=1, clock=clock,
                          fair_share_frac=0.25, min_rate_frac=0.1)
        # measured window: heavy=75% of rows, quiet=25%
        block = np.zeros((3, TENANT_METER_SLOTS), np.int64)
        ids = np.array([1] * 75 + [2] * 25, np.int32)
        block[0] = np.bincount(ids % TENANT_METER_SLOTS,
                               minlength=TENANT_METER_SLOTS)
        led.charge_device_block(block, ids)

        dense = {"heavy": 1, "quiet": 2}
        c = OverloadController(clock=clock, metrics=MetricsRegistry(),
                               degraded_telemetry_rate_per_s=12.0,
                               degraded_telemetry_burst=6.0)
        c.set_usage_ledger(led, resolve=dense.__getitem__)
        c.force(OverloadState.DEGRADED)

        # quiet tenant keeps the full uniform burst of 6
        assert c.admit(PriorityClass.TELEMETRY, tenant="quiet", n=6)
        assert not c.admit(PriorityClass.TELEMETRY, tenant="quiet", n=1)
        # heavy tenant's budget scales by fair/share = 1/3: burst 2
        assert c.admit(PriorityClass.TELEMETRY, tenant="heavy", n=2)
        assert not c.admit(PriorityClass.TELEMETRY, tenant="heavy", n=1)
        # refill follows the scaled rate (12/3 = 4/s) but is capped at
        # the scaled burst of 2 — a half second already tops it up
        clock.t += 0.5
        assert c.admit(PriorityClass.TELEMETRY, tenant="heavy", n=2)
        assert not c.admit(PriorityClass.TELEMETRY, tenant="heavy", n=1)

    def test_shed_charges_ledger_and_unknown_tenant_is_safe(self):
        clock = FakeClock()
        led = UsageLedger(fold_every=1, clock=clock)
        dense = {"acme": 9}
        c = OverloadController(clock=clock, metrics=MetricsRegistry())
        c.set_usage_ledger(led, resolve=dense.__getitem__)
        c.force(OverloadState.SHEDDING)
        assert not c.admit(PriorityClass.TELEMETRY, tenant="acme", n=7)
        assert led.snapshot()["totals"]["shed_rows"] == 7
        # an unmapped tenant sheds without charging (resolve raises)
        assert not c.admit(PriorityClass.TELEMETRY, tenant="ghost", n=3)
        assert led.snapshot()["totals"]["shed_rows"] == 7
