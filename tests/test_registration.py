"""Auto-registration: unknown devices become registered + replayable.

Reference parity: DeviceRegistrationManager defaults/switches and the
reprocess replay path (SURVEY.md §3.5).
"""

import pytest

from sitewhere_tpu.ids import NULL_ID, IdentityMap
from sitewhere_tpu.ingest.decoders import DecodedRequest, RequestKind
from sitewhere_tpu.services.device_management import DeviceManagement, RegistryMirror
from sitewhere_tpu.services.registration import RegistrationManager


@pytest.fixture()
def dm():
    identity = IdentityMap(capacity=1024)
    mirror = RegistryMirror(capacity=1024)
    svc = DeviceManagement("default", identity, mirror)
    svc.create_device_type(token="thermo", name="Thermostat")
    svc.create_area_type(token="site", name="Site")
    svc.create_area(token="hq", area_type="site", name="HQ")
    return svc


def reg_req(token, **kw):
    return DecodedRequest(
        kind=RequestKind.REGISTRATION, device_token=token, ts_s=1000, **kw
    )


def test_explicit_registration_with_defaults(dm):
    mgr = RegistrationManager(dm, default_device_type="thermo", default_area="hq")
    assert mgr.handle_registration(reg_req("new-dev"))
    dev = dm.get_device("new-dev")
    assert dev.device_type == "thermo"
    a = dm.get_active_assignment("new-dev")
    assert a is not None and a.area == "hq"
    did = dm.identity.device.lookup("new-dev")
    assert dm.mirror.active[did]
    assert mgr.registered == 1
    # idempotent re-registration
    assert mgr.handle_registration(reg_req("new-dev"))
    assert mgr.registered == 1


def test_registration_names_its_own_type(dm):
    dm.create_device_type(token="meter", name="Meter")
    mgr = RegistrationManager(dm, default_device_type="thermo")
    assert mgr.handle_registration(reg_req("m-1", device_type_token="meter"))
    assert dm.get_device("m-1").device_type == "meter"


def test_rejection_paths(dm):
    mgr = RegistrationManager(dm, default_device_type=None)
    assert not mgr.handle_registration(reg_req("no-type"))  # no type known
    assert mgr.rejected == 1

    closed = RegistrationManager(dm, default_device_type="thermo", allow_new_devices=False)
    assert not closed.handle_registration(reg_req("blocked"))
    assert "blocked" not in dm.devices


def test_unregistered_events_replay(dm):
    mgr = RegistrationManager(dm, default_device_type="thermo")
    events = [
        DecodedRequest(
            kind=RequestKind.MEASUREMENT, device_token="d-x", ts_s=5, mtype="t", value=1.0
        ),
        DecodedRequest(
            kind=RequestKind.MEASUREMENT, device_token="d-y", ts_s=6, mtype="t", value=2.0
        ),
    ]
    replay = mgr.process_unregistered(events)
    assert len(replay) == 2
    assert replay[0] is events[0]  # original event returned for re-injection
    assert "d-x" in dm.devices and "d-y" in dm.devices
    assert dm.get_active_assignment("d-x") is not None
