"""STOMP receiver (ActiveMQ/RabbitMQ analog), HTTP webhook connector
(InitialState/dweet analog), and HTTP SMS gateway delivery (Twilio analog).

Reference files these mirror:
``service-event-sources/.../activemq/ActiveMQClientEventReceiver.java``,
``.../rabbitmq/RabbitMqInboundEventReceiver.java``,
``service-outbound-connectors/.../initialstate``/``dweetio``,
``service-command-delivery/.../twilio/TwilioCommandDeliveryProvider.java``.
"""

import http.server
import json
import socket
import socketserver
import threading
import time

import numpy as np
import pytest

from sitewhere_tpu.commands.destinations import (
    DeliveryError,
    HttpDeliveryProvider,
    SmsParameterExtractor,
)
from sitewhere_tpu.commands.model import CommandExecution, CommandInvocation
from sitewhere_tpu.ingest.stomp import (
    FrameReader,
    StompError,
    StompReceiver,
    encode_frame,
)
from sitewhere_tpu.outbound.connectors import HttpConnector


# ---------------------------------------------------------------------------
# frame codec
# ---------------------------------------------------------------------------

def test_frame_roundtrip_with_escapes_and_binary_body():
    body = b"\x00\x01binary\nbody\x00"
    raw = encode_frame("SEND", {"destination": "/queue/a:b\nc"}, body)
    frames = FrameReader().feed(raw)
    assert len(frames) == 1
    command, headers, got = frames[0]
    assert command == "SEND"
    assert headers["destination"] == "/queue/a:b\nc"
    assert got == body
    assert headers["content-length"] == str(len(body))


def test_reader_handles_heartbeats_split_frames_and_crlf():
    r = FrameReader()
    raw = b"\n\n" + encode_frame("MESSAGE", {"ack": "m1"}, b"hello")
    # feed one byte at a time: the parser must buffer partial frames
    frames = []
    for i in range(len(raw)):
        frames += r.feed(raw[i:i + 1])
    assert [f[0] for f in frames] == ["MESSAGE"]
    assert frames[0][2] == b"hello"
    # CRLF head form
    crlf = b"MESSAGE\r\nack:m2\r\n\r\nworld\x00"
    (cmd, headers, body), = r.feed(crlf)
    assert (cmd, headers["ack"], body) == ("MESSAGE", "m2", b"world")


def test_reader_first_header_occurrence_wins_and_bad_escape_raises():
    (_, headers, _), = FrameReader().feed(
        b"MESSAGE\nfoo:one\nfoo:two\n\n\x00")
    assert headers["foo"] == "one"
    with pytest.raises(StompError):
        FrameReader().feed(b"MESSAGE\nbad:\\x\n\n\x00")


# ---------------------------------------------------------------------------
# mini broker: scripted STOMP server for end-to-end receiver tests
# ---------------------------------------------------------------------------

class MiniBroker:
    """Single-session scripted broker: CONNECT→CONNECTED, records
    SUBSCRIBE/ACK frames, pushes queued MESSAGEs."""

    def __init__(self, drop_first_session=False, heartbeat="0,0",
                 raw_capture=None, go_silent_after_subscribe=False):
        self.acks = []
        self.subscribes = []
        self.sessions = 0
        self.drop_first_session = drop_first_session
        self.heartbeat = heartbeat
        self.raw_capture = raw_capture
        self.go_silent_after_subscribe = go_silent_after_subscribe
        self._to_send = []
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(4)
        self.port = self._srv.getsockname()[1]
        self._alive = True
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def push(self, ack_id, body):
        with self._lock:
            self._to_send.append((ack_id, body))

    def close(self):
        self._alive = False
        try:
            self._srv.close()
        except OSError:
            pass

    def _loop(self):
        while self._alive:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            self.sessions += 1
            if self.drop_first_session and self.sessions == 1:
                conn.close()  # force the receiver's reconnect path
                continue
            threading.Thread(
                target=self._session, args=(conn,), daemon=True).start()

    def _session(self, conn):
        reader = FrameReader()
        conn.settimeout(0.05)
        subscribed = False
        try:
            while self._alive:
                if subscribed:  # a real broker never delivers pre-SUBSCRIBE
                    if self.go_silent_after_subscribe:
                        # stop answering entirely (still RECORDING what
                        # the client sends): the client's dead-connection
                        # cutoff must fire
                        try:
                            got = conn.recv(65536)
                            if not got:
                                return  # client cut the connection
                            if self.raw_capture is not None:
                                self.raw_capture.append(got)
                        except socket.timeout:
                            pass
                        continue
                    with self._lock:
                        pending, self._to_send = self._to_send, []
                    for ack_id, body in pending:
                        conn.sendall(encode_frame("MESSAGE", {
                            "destination": "/queue/q", "message-id": ack_id,
                            "subscription": "0", "ack": ack_id,
                        }, body))
                try:
                    data = conn.recv(65536)
                except socket.timeout:
                    continue
                if not data:
                    return
                if self.raw_capture is not None:
                    self.raw_capture.append(data)
                for cmd, headers, _ in reader.feed(data):
                    if cmd == "CONNECT":
                        conn.sendall(encode_frame(
                            "CONNECTED",
                            {"version": "1.2",
                             "heart-beat": self.heartbeat},
                            escape=False))
                    elif cmd == "SUBSCRIBE":
                        subscribed = True
                        self.subscribes.append(headers)
                    elif cmd == "ACK":
                        self.acks.append(headers["id"])
        except OSError:
            pass
        finally:
            conn.close()


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def test_stomp_receiver_subscribes_delivers_and_acks():
    broker = MiniBroker()
    got = []
    rx = StompReceiver("127.0.0.1", broker.port, destination="/queue/q",
                       heartbeat_ms=0)
    rx.sink = got.append
    rx.start()
    try:
        assert _wait(lambda: broker.subscribes)
        assert broker.subscribes[0]["destination"] == "/queue/q"
        assert broker.subscribes[0]["ack"] == "client-individual"
        broker.push("m-1", b'{"device":"d-1"}')
        broker.push("m-2", b'{"device":"d-2"}')
        assert _wait(lambda: len(got) == 2)
        assert got == [b'{"device":"d-1"}', b'{"device":"d-2"}']
        # per-message acks arrive only after the sink accepted the payload
        assert _wait(lambda: broker.acks == ["m-1", "m-2"])
    finally:
        rx.stop()
        broker.close()


def test_stomp_receiver_reconnects_after_dropped_session():
    broker = MiniBroker(drop_first_session=True)
    got = []
    rx = StompReceiver("127.0.0.1", broker.port, destination="/queue/q",
                       heartbeat_ms=0, reconnect_delay_s=0.05)
    rx.sink = got.append
    rx.start()
    try:
        assert _wait(lambda: broker.subscribes)  # second session made it
        assert broker.sessions >= 2
        broker.push("m-9", b"payload")
        assert _wait(lambda: got == [b"payload"])
    finally:
        rx.stop()
        broker.close()


# ---------------------------------------------------------------------------
# HTTP webhook connector + SMS gateway provider
# ---------------------------------------------------------------------------

class _CaptureHandler(http.server.BaseHTTPRequestHandler):
    status = 200

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        self.server.requests.append(
            (self.path, dict(self.headers), body))
        self.send_response(self.server.status)
        self.end_headers()

    def log_message(self, *args):
        pass


def _http_server(status=200):
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _CaptureHandler)
    srv.requests = []
    srv.status = status
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _cols(n):
    return {
        "event_type": np.zeros(n, np.int32),
        "device_id": np.arange(n, dtype=np.int32),
        "tenant_id": np.zeros(n, np.int32),
        "ts_s": np.full(n, 1_753_800_000, np.int32),
        "ts_ns": np.zeros(n, np.int32),
        "mtype_id": np.zeros(n, np.int32),
        "value": np.linspace(1.0, 2.0, n).astype(np.float32),
    }


def test_http_connector_posts_surviving_rows_as_json_array():
    srv = _http_server()
    try:
        c = HttpConnector(
            "webhook", f"http://127.0.0.1:{srv.server_address[1]}/hook",
            headers={"X-Api-Key": "k1"})
        mask = np.array([True, False, True])
        assert c.process_batch(_cols(3), mask) == 2
        assert len(srv.requests) == 1
        path, headers, body = srv.requests[0]
        assert path == "/hook"
        assert headers["X-Api-Key"] == "k1"
        docs = json.loads(body)
        assert [d["deviceId"] for d in docs] == [0, 2]
        # keep-alive: second batch reuses the connection
        assert c.process_batch(_cols(3), mask) == 2
        assert len(srv.requests) == 2
        assert c.errors == 0
    finally:
        srv.shutdown()
        srv.server_close()


def test_http_connector_counts_rejections():
    srv = _http_server(status=503)
    try:
        c = HttpConnector(
            "webhook", f"http://127.0.0.1:{srv.server_address[1]}/hook")
        # a rejected POST now RAISES (so an attached breaker sees the
        # failing sink; the manager isolates it) and is counted once
        from sitewhere_tpu.outbound.connectors import DeliveryFailed

        with pytest.raises(DeliveryFailed):
            c.process_batch(_cols(2), np.array([True, True]))
        assert c.errors == 1
    finally:
        srv.shutdown()
        srv.server_close()


def _execution(metadata):
    inv = CommandInvocation(
        command_token="reboot", target_assignment="a-1",
        device_token="d-1", tenant="t0")
    return CommandExecution(
        invocation=inv, command_name="reboot", namespace="sw",
        device_metadata=metadata)


def test_http_sms_gateway_delivery_and_missing_phone_dead_letters():
    srv = _http_server()
    try:
        provider = HttpDeliveryProvider(
            f"http://127.0.0.1:{srv.server_address[1]}/2010-04-01/Messages",
            field_map={"To": "{phone}", "From": "+15550100",
                       "Body": "{payload}"})
        extractor = SmsParameterExtractor()
        ex = _execution({"phone_number": "+15550123"})
        provider.deliver(ex, b"reboot now", extractor(ex))
        path, headers, body = srv.requests[0]
        assert path == "/2010-04-01/Messages"
        fields = dict(p.split("=", 1) for p in body.decode().split("&"))
        assert fields["To"] == "%2B15550123"
        assert fields["Body"] == "reboot+now"
        # device without a phone number → DeliveryError → undelivered
        ex2 = _execution({})
        with pytest.raises(DeliveryError):
            provider.deliver(ex2, b"x", extractor(ex2))
    finally:
        srv.shutdown()
        srv.server_close()


def test_http_sms_gateway_error_status_raises():
    srv = _http_server(status=401)
    try:
        provider = HttpDeliveryProvider(
            f"http://127.0.0.1:{srv.server_address[1]}/msg")
        extractor = SmsParameterExtractor()
        ex = _execution({"phone_number": "+15550123"})
        with pytest.raises(DeliveryError):
            provider.deliver(ex, b"x", extractor(ex))
    finally:
        srv.shutdown()
        srv.server_close()


def test_stomp_poison_message_left_unacked_and_receiver_survives():
    broker = MiniBroker()
    got = []

    def sink(payload):
        if payload == b"poison":
            raise ValueError("bad payload")
        got.append(payload)

    rx = StompReceiver("127.0.0.1", broker.port, destination="/queue/q",
                       heartbeat_ms=0)
    rx.sink = sink
    rx.start()
    try:
        assert _wait(lambda: broker.subscribes)
        broker.push("m-1", b"poison")
        broker.push("m-2", b"fine")
        assert _wait(lambda: got == [b"fine"])
        assert _wait(lambda: broker.acks == ["m-2"])  # poison NOT acked
        assert rx.emit_errors == 1
    finally:
        rx.stop()
        broker.close()


def test_stomp_heartbeats_negotiated_and_sent():
    """CONNECTED advertising heart-beats makes the client emit LF frames
    on the negotiated cadence and CUT a connection to a silent broker
    (then retry after the reconnect backoff)."""
    raw_frames = []
    broker = MiniBroker(heartbeat="100,100", raw_capture=raw_frames,
                        go_silent_after_subscribe=True)
    rx = StompReceiver("127.0.0.1", broker.port, destination="/queue/q",
                       heartbeat_ms=100, reconnect_delay_s=0.2)
    rx.sink = lambda p: None
    rx.start()
    try:
        assert _wait(lambda: broker.subscribes)
        # client LF heart-beats arrive (boundary-insensitive: a chunk of
        # nothing but LFs, however many coalesced)
        assert _wait(lambda: any(
            d and d.strip(b"\n") == b"" for d in raw_frames), timeout=2.0)
        # silent broker -> heart-beat cutoff -> reconnect attempt: the
        # broker sees a SECOND session (would never happen if the
        # dead-connection detection in _session were removed)
        assert _wait(lambda: broker.sessions >= 2, timeout=5.0)
    finally:
        rx.stop()
        broker.close()


# ---------------------------------------------------------------------------
# index-push connector (SolrOutboundConnector analog)
# ---------------------------------------------------------------------------

def test_index_push_accumulates_and_flushes_bulk():
    """Events accumulate across pipeline batches and flush as ONE bulk
    request at the row threshold."""
    from sitewhere_tpu.outbound import IndexPushConnector

    srv = _http_server()
    try:
        c = IndexPushConnector(
            "solr", f"http://127.0.0.1:{srv.server_address[1]}/update",
            bulk_rows=5, bulk_interval_s=3600.0)
        # 3 rows: below threshold — nothing posted yet
        c.process_batch(_cols(3), np.ones(3, np.bool_))
        assert len(srv.requests) == 0
        # 3 more: threshold crossed — one bulk of all 6
        c.process_batch(_cols(3), np.ones(3, np.bool_))
        assert len(srv.requests) == 1
        docs = json.loads(srv.requests[0][2])
        assert len(docs) == 6
        assert c.indexed == 6 and c.errors == 0
        c.stop()
    finally:
        srv.shutdown()
        srv.server_close()


def test_index_push_interval_flush_and_final_flush_on_stop():
    from sitewhere_tpu.outbound import IndexPushConnector

    srv = _http_server()
    try:
        c = IndexPushConnector(
            "solr", f"http://127.0.0.1:{srv.server_address[1]}/update",
            bulk_rows=1000, bulk_interval_s=0.1)
        c.start()
        c.process_batch(_cols(2), np.ones(2, np.bool_))
        deadline = time.time() + 5
        while not srv.requests and time.time() < deadline:
            time.sleep(0.02)
        assert len(srv.requests) == 1  # interval flush
        c.process_batch(_cols(1), np.ones(1, np.bool_))
        c.stop()  # final best-effort flush
        assert sum(len(json.loads(b)) for _, _, b in srv.requests) == 3
    finally:
        srv.shutdown()
        srv.server_close()


def test_index_push_retries_with_backoff_without_loss():
    """A failed bulk is retained and re-sent once the sink recovers."""
    from sitewhere_tpu.outbound import IndexPushConnector

    srv = _http_server(status=500)
    try:
        c = IndexPushConnector(
            "solr", f"http://127.0.0.1:{srv.server_address[1]}/update",
            bulk_rows=2, bulk_interval_s=3600.0, backoff_s=0.05)
        c.process_batch(_cols(2), np.ones(2, np.bool_))
        assert c.errors == 1 and c.indexed == 0
        assert len(c._pending) == 2  # retained for retry
        srv.status = 200
        time.sleep(0.06)  # let the backoff window pass
        c.process_batch(_cols(1), np.ones(1, np.bool_))
        assert c.indexed == 3
        assert len(c._pending) == 0
        # everything arrived exactly once after recovery
        ok = [b for _, _, b in srv.requests if len(json.loads(b)) == 3]
        assert len(ok) == 1
        c.stop()
    finally:
        srv.shutdown()
        srv.server_close()


def test_index_push_bounded_buffer_drops_oldest():
    from sitewhere_tpu.outbound import IndexPushConnector

    srv = _http_server(status=500)
    try:
        c = IndexPushConnector(
            "solr", f"http://127.0.0.1:{srv.server_address[1]}/update",
            bulk_rows=100, bulk_interval_s=3600.0, max_buffer_rows=4,
            backoff_s=3600.0)
        c.process_batch(_cols(3), np.ones(3, np.bool_))
        c.process_batch(_cols(3), np.ones(3, np.bool_))
        assert c.dropped == 2
        assert len(c._pending) == 4
        # the RETAINED docs are the newest ones
        vals = [d["deviceId"] for d in c._pending]
        assert vals == [2, 0, 1, 2]
        c.stop()
    finally:
        srv.shutdown()
        srv.server_close()


def test_index_push_custom_bulk_format():
    """An Elasticsearch-style _bulk NDJSON builder plugs in unchanged."""
    from sitewhere_tpu.outbound import IndexPushConnector

    def es_bulk(docs):
        lines = []
        for d in docs:
            lines.append(json.dumps({"index": {"_index": "events"}}))
            lines.append(json.dumps(d))
        return ("\n".join(lines) + "\n").encode()

    srv = _http_server()
    try:
        c = IndexPushConnector(
            "es", f"http://127.0.0.1:{srv.server_address[1]}/_bulk",
            bulk_rows=2, bulk_interval_s=3600.0, bulk_format=es_bulk)
        c.process_batch(_cols(2), np.ones(2, np.bool_))
        assert len(srv.requests) == 1
        body = srv.requests[0][2].decode().strip().split("\n")
        assert len(body) == 4  # action+doc per event
        assert json.loads(body[0]) == {"index": {"_index": "events"}}
        assert json.loads(body[1])["deviceId"] == 0
        c.stop()
    finally:
        srv.shutdown()
        srv.server_close()
