"""Streaming media: durable chunked device streams + request manager.

Reference behaviors covered (service-streaming-media):
- stream create per active assignment, duplicate → EXISTS ack
  (DeviceStreamManager.handleDeviceStreamRequest)
- stream ids scoped per assignment (IDeviceStreamManagement SPI) — no
  cross-device access or squatting
- sequence-numbered chunk append / point get / ordered list,
  last-write-wins per sequence
- send-back request answers empty payload for missing chunks
- durability: chunks and descriptors survive restart; torn tail writes
  dropped, mid-file corruption refused loudly (Journal semantics)
"""

import os

import pytest

from sitewhere_tpu.ids import IdentityMap
from sitewhere_tpu.ingest.journal import CorruptJournal
from sitewhere_tpu.services.common import (
    DuplicateToken,
    EntityNotFound,
    InvalidReference,
    SearchCriteria,
    ValidationError,
)
from sitewhere_tpu.services.device_management import DeviceManagement, RegistryMirror
from sitewhere_tpu.services.streams import (
    DeviceStreamManagement,
    DeviceStreamManager,
    DeviceStreamStatus,
)


@pytest.fixture()
def dm():
    svc = DeviceManagement(
        "default", IdentityMap(capacity=256),
        RegistryMirror(capacity=256, max_zones=8, max_verts=8),
    )
    svc.create_device_type(token="cam", name="Camera")
    svc.create_device(token="cam-1", device_type="cam")
    svc.create_device_assignment(device="cam-1")
    svc.create_device(token="cam-2", device_type="cam")  # unassigned
    svc.create_device(token="cam-3", device_type="cam")
    svc.create_device_assignment(device="cam-3")
    return svc


@pytest.fixture()
def streams(tmp_path):
    svc = DeviceStreamManagement(str(tmp_path))
    svc.start()
    yield svc
    svc.stop()
    svc.terminate()


class TestStreamStore:
    def test_create_get_list(self, streams):
        s1 = streams.create_device_stream("a-1", "video-1", "video/mp4")
        streams.create_device_stream("a-1", "video-2")
        streams.create_device_stream("a-2", "audio-1")
        assert streams.get_device_stream(s1.token).content_type == "video/mp4"
        assert streams.list_device_streams("a-1").total == 2
        assert streams.list_device_streams().total == 3
        with pytest.raises(DuplicateToken):
            streams.create_device_stream("a-1", "video-1")
        # same device-chosen id under a DIFFERENT assignment is fine
        streams.create_device_stream("a-2", "video-1")
        with pytest.raises(EntityNotFound):
            streams.get_device_stream("nope")

    def test_chunks_ordered_and_point_reads(self, streams):
        s = streams.create_device_stream("a-1", "s")
        for seq in (2, 0, 1):  # out-of-order arrival
            streams.add_device_stream_data(s.token, seq, f"chunk{seq}".encode())
        listed = streams.list_device_stream_data(s.token)
        assert [c.sequence_number for c in listed] == [0, 1, 2]
        assert [c.data for c in listed] == [b"chunk0", b"chunk1", b"chunk2"]
        assert streams.get_device_stream_data(s.token, 1).data == b"chunk1"
        assert streams.get_device_stream_data(s.token, 9) is None
        assert streams.stream_content(s.token) == b"chunk0chunk1chunk2"

    def test_last_write_wins_per_sequence(self, streams):
        s = streams.create_device_stream("a-1", "s")
        streams.add_device_stream_data(s.token, 0, b"old")
        streams.add_device_stream_data(s.token, 0, b"new")
        assert streams.get_device_stream_data(s.token, 0).data == b"new"
        assert streams.list_device_stream_data(s.token).total == 1
        assert streams.stream_content(s.token) == b"new"

    def test_seq_bounds_validated(self, streams):
        s = streams.create_device_stream("a-1", "s")
        with pytest.raises(ValidationError):
            streams.add_device_stream_data(s.token, -1, b"x")
        with pytest.raises(ValidationError):
            streams.add_device_stream_data(s.token, 1 << 64, b"x")

    def test_paging(self, streams):
        s = streams.create_device_stream("a-1", "s")
        for seq in range(10):
            streams.add_device_stream_data(s.token, seq, bytes([seq]))
        page = streams.list_device_stream_data(
            s.token, SearchCriteria(page=2, page_size=4)
        )
        assert [c.sequence_number for c in page.results] == [4, 5, 6, 7]
        assert [c.data for c in page.results] == [b"\x04", b"\x05", b"\x06", b"\x07"]
        assert page.total == 10

    def test_interleaved_streams_stay_separate(self, streams):
        sa = streams.create_device_stream("a-1", "sa")
        sb = streams.create_device_stream("a-1", "sb")
        for i in range(5):
            streams.add_device_stream_data(sa.token, i, b"A%d" % i)
            streams.add_device_stream_data(sb.token, i, b"B%d" % i)
        assert streams.stream_content(sa.token) == b"A0A1A2A3A4"
        assert streams.stream_content(sb.token) == b"B0B1B2B3B4"

    def test_durability_across_restart(self, tmp_path):
        svc = DeviceStreamManagement(str(tmp_path))
        svc.start()
        s = svc.create_device_stream("a-1", "s", "image/png", metadata={"k": "v"})
        svc.add_device_stream_data(s.token, 0, b"\x00" * 1000)
        svc.add_device_stream_data(s.token, 1, b"tail")
        svc.stop()
        svc.terminate()

        svc2 = DeviceStreamManagement(str(tmp_path))
        svc2.start()
        stream = svc2.get_device_stream(s.token)
        assert stream.content_type == "image/png"
        assert stream.metadata == {"k": "v"}
        assert svc2.get_assignment_stream("a-1", "s").token == s.token
        assert svc2.stream_content(s.token) == b"\x00" * 1000 + b"tail"

    def test_torn_tail_dropped_on_recovery(self, tmp_path):
        svc = DeviceStreamManagement(str(tmp_path))
        svc.start()
        s = svc.create_device_stream("a-1", "s")
        svc.add_device_stream_data(s.token, 0, b"good")
        svc.add_device_stream_data(s.token, 1, b"willtear")
        svc.stop()
        svc.terminate()
        # tear the final record (crash mid-append)
        seg = sorted(
            p for p in os.listdir(os.path.join(svc.dir, "media"))
            if p.endswith(".log")
        )[-1]
        full = os.path.join(svc.dir, "media", seg)
        with open(full, "r+b") as f:
            f.seek(-3, os.SEEK_END)
            f.truncate()

        svc2 = DeviceStreamManagement(str(tmp_path))
        svc2.start()
        assert svc2.get_device_stream_data(s.token, 0).data == b"good"
        assert svc2.get_device_stream_data(s.token, 1) is None
        # appends continue cleanly after truncation
        svc2.add_device_stream_data(s.token, 1, b"retry")
        assert svc2.stream_content(s.token) == b"goodretry"

    def test_mid_file_corruption_is_loud(self, tmp_path):
        """Valid data after a corrupt record must not be silently dropped —
        the store refuses to open (Journal CorruptJournal semantics)."""
        svc = DeviceStreamManagement(str(tmp_path))
        svc.start()
        s = svc.create_device_stream("a-1", "s")
        first = svc.add_device_stream_data(s.token, 0, b"AAAAAAAA")
        svc.add_device_stream_data(s.token, 1, b"BBBBBBBB")
        svc.stop()
        svc.terminate()
        seg = sorted(
            p for p in os.listdir(os.path.join(svc.dir, "media"))
            if p.endswith(".log")
        )[0]
        full = os.path.join(svc.dir, "media", seg)
        with open(full, "r+b") as f:
            data = f.read()
            f.seek(data.index(b"AAAAAAAA"))
            f.write(b"XXXX")
        with pytest.raises(CorruptJournal):
            DeviceStreamManagement(str(tmp_path))
        assert first.sequence_number == 0  # silence unused warning


class TestStreamManager:
    def test_create_ack_and_duplicate(self, dm, streams):
        acks = []
        mgr = DeviceStreamManager(
            dm, streams, deliver_command=lambda tok, cmd: acks.append((tok, cmd))
        )
        mgr.start()
        assert (
            mgr.handle_device_stream_request("cam-1", "rec-1", "video/mp4")
            == DeviceStreamStatus.CREATED
        )
        assert (
            mgr.handle_device_stream_request("cam-1", "rec-1")
            == DeviceStreamStatus.EXISTS
        )
        assert [c["status"] for _, c in acks] == ["created", "exists"]
        # stream is attached to the device's active assignment
        a = dm.get_active_assignment("cam-1")
        assert streams.get_assignment_stream(a.token, "rec-1") is not None

    def test_unassigned_device_rejected(self, dm, streams):
        mgr = DeviceStreamManager(dm, streams)
        with pytest.raises(InvalidReference):
            mgr.handle_device_stream_request("cam-2", "s")
        with pytest.raises(EntityNotFound):
            mgr.handle_device_stream_request("ghost", "s")

    def test_cross_device_streams_isolated(self, dm, streams):
        """cam-3 creating/writing 'rec-1' must not touch cam-1's 'rec-1'."""
        mgr = DeviceStreamManager(dm, streams)
        mgr.handle_device_stream_request("cam-1", "rec-1")
        mgr.handle_device_stream_data_request("cam-1", "rec-1", 0, b"cam1-data")
        # same id from another device: CREATED (own scope), not EXISTS
        assert (
            mgr.handle_device_stream_request("cam-3", "rec-1")
            == DeviceStreamStatus.CREATED
        )
        mgr.handle_device_stream_data_request("cam-3", "rec-1", 0, b"cam3-data")
        assert (
            mgr.handle_send_device_stream_data_request("cam-1", "rec-1", 0)
            == b"cam1-data"
        )
        assert (
            mgr.handle_send_device_stream_data_request("cam-3", "rec-1", 0)
            == b"cam3-data"
        )
        # writing to a stream id that only exists under ANOTHER assignment
        with pytest.raises(EntityNotFound):
            mgr.handle_device_stream_data_request("cam-3", "only-cam1", 0, b"x")

    def test_data_and_send_back(self, dm, streams):
        sent = []
        mgr = DeviceStreamManager(
            dm, streams, deliver_command=lambda tok, cmd: sent.append(cmd)
        )
        mgr.handle_device_stream_request("cam-1", "s")
        mgr.handle_device_stream_data_request("cam-1", "s", 0, b"frame0")
        assert mgr.handle_send_device_stream_data_request("cam-1", "s", 0) == b"frame0"
        # missing chunk answers empty (reference: new byte[0])
        assert mgr.handle_send_device_stream_data_request("cam-1", "s", 5) == b""
        data_cmds = [c for c in sent if c["type"] == "stream_data"]
        assert data_cmds[0]["data"] == b"frame0"
        assert data_cmds[1]["data"] == b""


def test_create_failure_acks_failed(dm, streams):
    """Invalid create requests ack FAILED instead of erroring the device
    (reference DeviceStreamManager.java:62-66)."""
    acks = []
    mgr = DeviceStreamManager(
        dm, streams, deliver_command=lambda tok, cmd: acks.append(cmd)
    )
    assert (
        mgr.handle_device_stream_request("cam-1", "")  # empty id
        == DeviceStreamStatus.FAILED
    )
    assert acks[-1]["status"] == "failed"


def test_device_streams_over_wire_source(tmp_path):
    """A device creates a stream and uploads chunks through a protocol
    source (reference: stream requests flow event-sources →
    DeviceStreamManager, media/DeviceStreamManager.java) — no
    programmatic stream calls, just wire payloads."""
    import base64
    import json as _json
    import socket
    import struct
    import time as _time

    from sitewhere_tpu.instance import Instance
    from sitewhere_tpu.runtime.config import Config

    cfg = Config({
        "instance": {"id": "stream-wire", "data_dir": str(tmp_path / "d")},
        "pipeline": {"width": 64, "registry_capacity": 256, "mtype_slots": 4,
                     "deadline_ms": 5.0, "n_shards": 1},
        "presence": {"scan_interval_s": 3600.0, "missing_after_s": 1800},
        "sources": [{"id": "wire", "decoder": "json",
                     "receivers": [{"type": "tcp", "port": 0}]}],
    }, apply_env=False)
    inst = Instance(cfg)
    inst.start()
    try:
        dm = inst.device_management
        dm.create_device_type(token="cam", name="Cam")
        dm.create_device(token="cam-1", device_type="cam")
        a = dm.create_device_assignment(device="cam-1")

        rx = inst.sources[0].receivers[0]

        def send(*docs):
            # ONE connection for ordered payloads: separate connections
            # land on separate ThreadingTCPServer handler threads, whose
            # scheduling does not preserve send order (a chunk racing
            # ahead of its stream-create dead-letters) — per-connection
            # ordering is the contract a streaming device actually has
            payload = b"".join(
                struct.pack(">I", len(p)) + p
                for p in (_json.dumps(d).encode() for d in docs))
            with socket.create_connection(("127.0.0.1", rx.port),
                                          timeout=5) as s:
                s.sendall(payload)

        send({"deviceToken": "cam-1", "type": "DeviceStream",
              "request": {"streamId": "clip-1", "contentType": "video/mp4"}},
             {"deviceToken": "cam-1", "type": "StreamData",
              "request": {"streamId": "clip-1", "sequenceNumber": 0,
                          "data": base64.b64encode(b"AB").decode()}},
             {"deviceToken": "cam-1", "type": "StreamData",
              "request": {"streamId": "clip-1", "sequenceNumber": 1,
                          "data": base64.b64encode(b"CD").decode()}})

        deadline = _time.monotonic() + 5
        stream = None
        while _time.monotonic() < deadline:
            stream = inst.streams.get_assignment_stream(a.token, "clip-1")
            if stream is not None and \
                    inst.streams.stream_content(stream.token) == b"ABCD":
                break
            _time.sleep(0.05)
        assert stream is not None
        assert inst.streams.stream_content(stream.token) == b"ABCD"
        assert stream.content_type == "video/mp4"
        # a chunk for an unknown stream dead-letters, doesn't explode
        send({"deviceToken": "cam-1", "type": "StreamData",
              "request": {"streamId": "nope", "sequenceNumber": 0,
                          "data": "AAAA"}})
        deadline = _time.monotonic() + 5
        while _time.monotonic() < deadline:
            if any(d["kind"] == "failed-stream-request"
                   for d in inst.list_dead_letters(limit=20)):
                break
            _time.sleep(0.05)
        assert any(d["kind"] == "failed-stream-request"
                   for d in inst.list_dead_letters(limit=20))
    finally:
        inst.stop()
        inst.terminate()
