"""Multitenant isolation under load (ROADMAP item 5's fairness story).

Deterministic (fake-clock) coverage of the four isolation planes:

- **Budget overlays** (``runtime/overload.py`` TenantBudgets): the
  configured per-tenant DEGRADED ceiling COMPOSES with the ledger's
  measured-share scaling — effective rate = min of the two — so a
  configured budget can only tighten, a noisy tenant can never push a
  quiet one below its fairness floor, and stale buckets re-derive their
  rate within ``budget_refresh_s``.
- **Metered quotas** (``runtime/metering.py`` QuotaTable): windowed
  ``eval_s`` consumption walks the ok → deprioritized → refused ladder,
  429s are retryable because the refusal clears when the window
  rotates, and the ingest hot path never consults the table.
- **Partitioned state** (``state/manager.py`` TenantPartitions): pow2
  rung ladders with shrink-at-quarter hysteresis; one tenant's
  registration churn bumps only ITS ``compile_count``.
- **Budget dead-letters**: budget-bound sheds carry their own
  replayable kind ``tenant-budget`` and the requeue path re-checks the
  tenant's CURRENT budget.
"""

import json

import numpy as np
import pytest

from sitewhere_tpu.pipeline.packed import TENANT_METER_SLOTS
from sitewhere_tpu.runtime.metering import QuotaTable, UsageLedger
from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.runtime.overload import (
    OverloadController,
    OverloadShed,
    OverloadState,
    PriorityClass,
    TenantBudgets,
    TokenBucket,
)
from sitewhere_tpu.services.common import QuotaExceeded
from sitewhere_tpu.state.manager import TenantPartitions, _next_pow2


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _charge_rows(led, ids):
    """Bill a device-block of accepted rows (the windowed-share path)."""
    block = np.zeros((3, TENANT_METER_SLOTS), np.int64)
    block[0] = np.bincount(np.asarray(ids) % TENANT_METER_SLOTS,
                           minlength=TENANT_METER_SLOTS)
    led.charge_device_block(block, np.asarray(ids, np.int32))


def _ledger(clock, **kw):
    kw.setdefault("fold_every", 1)
    kw.setdefault("fair_share_frac", 0.25)
    kw.setdefault("min_rate_frac", 0.1)
    return UsageLedger(clock=clock, **kw)


# ---------------------------------------------------------------------------
# fairness floor: the measured-share half of the composition
# ---------------------------------------------------------------------------

class TestFairnessFloor:
    @pytest.mark.parametrize("noisy_rows", [400, 4_000, 40_000])
    def test_noisy_volume_never_penalizes_quiet_tenant(self, noisy_rows):
        """Property: however loud the noisy tenant gets, a tenant at or
        under ``fair_share_frac`` keeps scale 1.0 — and the noisy one
        is floored at ``min_rate_frac``, never starved to zero."""
        clock = FakeClock()
        led = _ledger(clock)
        _charge_rows(led, np.full(noisy_rows, 1, np.int32))
        _charge_rows(led, np.full(100, 2, np.int32))
        assert led.shares()[2] <= led.fair_share_frac
        assert led.rate_scale(2) == 1.0
        assert led.min_rate_frac <= led.rate_scale(1) < 1.0

    def test_scale_tracks_share_then_floors(self):
        clock = FakeClock()
        led = _ledger(clock)
        _charge_rows(led, np.full(500, 1, np.int32))
        _charge_rows(led, np.full(500, 2, np.int32))
        # both at 2× fair share: both clipped to half the uniform budget
        assert led.rate_scale(1) == pytest.approx(0.5)
        assert led.rate_scale(2) == pytest.approx(0.5)
        # a monopolist's scale is floored at min_rate_frac, not zero
        # (fair/share can only undercut the floor when fair < floor)
        led2 = _ledger(clock, fair_share_frac=0.05, min_rate_frac=0.1)
        _charge_rows(led2, np.full(1_000, 1, np.int32))
        assert led2.shares()[1] == pytest.approx(1.0)
        assert led2.rate_scale(1) == pytest.approx(0.1)

    def test_topk_rotation_under_tenant_churn(self):
        """A churning long tail rotates through the top-K without
        losing mass: evicted tenants fold into ``other`` and totals
        stay conserved."""
        clock = FakeClock()
        led = _ledger(clock, top_k=4)
        total = 0
        for t in range(1, 33):         # 32 tenants through a K=4 sketch
            n = 10 + t
            _charge_rows(led, np.full(n, t, np.int32))
            total += n
        snap = led.snapshot()
        assert len(snap["tenants"]) <= 4
        tracked = sum(t["usage"]["rows"] for t in snap["tenants"])
        assert tracked + snap["other"]["rows"] == pytest.approx(total)
        assert snap["totals"]["rows"] == pytest.approx(total)
        # the heaviest recent tenants are the survivors
        survivors = {t["tenant_id"] for t in snap["tenants"]}
        assert 32 in survivors


# ---------------------------------------------------------------------------
# budget overlays: composition, attribution, refresh
# ---------------------------------------------------------------------------

def _controller(clock, **kw):
    kw.setdefault("metrics", MetricsRegistry())
    kw.setdefault("cooldown_s", 2.0)
    return OverloadController(clock=clock, **kw)


class TestBudgetComposition:
    def test_from_config_parses_overlay_sections(self):
        budgets = TenantBudgets.from_config({
            "t-a": {"overload": {"degraded_telemetry_rate_per_s": 50.0,
                                 "degraded_telemetry_burst": 10.0}},
            "t-b": {"quota": {"eval_s_per_window": 1.0}},   # no overload
            "t-c": "garbage",
        })
        assert budgets.get("t-a") == (50.0, 10.0)
        assert budgets.get("t-b") is None
        assert budgets.overlay("t-a") == {
            "degraded_telemetry_rate_per_s": 50.0,
            "degraded_telemetry_burst": 10.0}
        assert len(budgets) == 1

    def test_effective_rate_is_min_of_configured_and_measured(self):
        clock = FakeClock()
        led = _ledger(clock)
        _charge_rows(led, np.full(50, 1, np.int32))   # share 0.5 → ×0.5
        _charge_rows(led, np.full(50, 2, np.int32))
        c = _controller(clock, degraded_telemetry_rate_per_s=1000.0,
                        degraded_telemetry_burst=2000.0)
        c.set_usage_ledger(led, resolve={"noisy": 1, "quiet": 2}.get)
        # measured binds: configured 800 > measured 1000×0.5
        c.tenant_budgets.set_budget("noisy", rate_per_s=800.0)
        rate, burst, bound = c._telemetry_rate("noisy")
        assert rate == pytest.approx(500.0)
        assert not bound
        # configured binds: 200 < 500
        c.tenant_budgets.set_budget("noisy", rate_per_s=200.0, burst=100.0)
        rate, burst, bound = c._telemetry_rate("noisy")
        assert (rate, burst) == (pytest.approx(200.0), pytest.approx(100.0))
        assert bound

    def test_configured_overlay_only_ever_tightens(self):
        clock = FakeClock()
        c = _controller(clock, degraded_telemetry_rate_per_s=100.0,
                        degraded_telemetry_burst=50.0)
        # a generous overlay can never loosen the uniform budget
        c.tenant_budgets.set_budget("vip", rate_per_s=1e6, burst=1e6)
        rate, burst, bound = c._telemetry_rate("vip")
        assert (rate, burst) == (100.0, 50.0)
        assert not bound

    def test_admit_detail_attributes_budget_vs_overload(self):
        clock = FakeClock()
        c = _controller(clock, degraded_telemetry_rate_per_s=1000.0,
                        degraded_telemetry_burst=1000.0)
        c.tenant_budgets.set_budget("capped", rate_per_s=0.0, burst=2.0)
        c.force(OverloadState.DEGRADED)
        ok, reason = c.admit_detail(PriorityClass.TELEMETRY,
                                    tenant="capped", n=2)
        assert ok and reason == ""
        ok, reason = c.admit_detail(PriorityClass.TELEMETRY,
                                    tenant="capped", n=1)
        assert not ok and reason == "budget"
        assert c._metrics.counter("tenant.budget.clipped_rows").value == 1
        # a tenant WITHOUT an overlay refusing on the uniform bucket is
        # plain overload, not a budget clip
        c2 = _controller(clock, degraded_telemetry_rate_per_s=0.0,
                         degraded_telemetry_burst=1.0)
        c2.force(OverloadState.DEGRADED)
        assert c2.admit_detail(PriorityClass.TELEMETRY, tenant="t")[0]
        ok, reason = c2.admit_detail(PriorityClass.TELEMETRY, tenant="t")
        assert not ok and reason == "overload"

    def test_quiet_tenant_keeps_uniform_budget_while_noisy_clipped(self):
        """The fairness invariant end to end: DEGRADED admission clips
        the budgeted tenant while the quiet one rides the uniform
        bucket untouched."""
        clock = FakeClock()
        c = _controller(clock, degraded_telemetry_rate_per_s=0.0,
                        degraded_telemetry_burst=10.0)
        c.tenant_budgets.set_budget("noisy", rate_per_s=0.0, burst=2.0)
        c.force(OverloadState.DEGRADED)
        noisy_ok = sum(
            c.admit_detail(PriorityClass.TELEMETRY, tenant="noisy")[0]
            for _ in range(10))
        quiet_ok = sum(
            c.admit_detail(PriorityClass.TELEMETRY, tenant="quiet")[0]
            for _ in range(10))
        assert noisy_ok == 2          # clipped to the configured burst
        assert quiet_ok == 10         # full uniform burst

    def test_stale_bucket_reprices_within_refresh_interval(self):
        clock = FakeClock()
        c = _controller(clock, degraded_telemetry_rate_per_s=10.0,
                        degraded_telemetry_burst=100.0,
                        budget_refresh_s=5.0)
        c.tenant_budgets.set_budget("t", rate_per_s=0.0, burst=1.0)
        c.force(OverloadState.DEGRADED)
        assert c.admit(PriorityClass.TELEMETRY, tenant="t")
        assert not c.admit(PriorityClass.TELEMETRY, tenant="t")
        # operator loosens the budget mid-episode (still ≤ the uniform
        # ceiling — overlays only tighten): the already-built bucket
        # does NOT reprice until the refresh interval elapses...
        c.tenant_budgets.set_budget("t", rate_per_s=10.0, burst=50.0)
        assert not c.admit(PriorityClass.TELEMETRY, tenant="t")
        clock.t += 5.0
        # ...then reprices in place: 10/s over 5s accrued 50 tokens
        assert c.admit(PriorityClass.TELEMETRY, tenant="t", n=10)

    def test_set_rate_clamps_tokens_no_fresh_burst(self):
        clock = FakeClock()
        b = TokenBucket(rate_per_s=0.0, burst=100.0, clock=clock)
        assert b.try_take(40)                      # 60 tokens left
        b.set_rate(0.0, 10.0)                      # tightened: clamp to 10
        assert not b.try_take(11)
        assert b.try_take(10)
        # loosening never grants a fresh full burst mid-episode
        b2 = TokenBucket(rate_per_s=0.0, burst=5.0, clock=clock)
        assert b2.try_take(5)
        b2.set_rate(0.0, 1000.0)
        assert not b2.try_take(1)


# ---------------------------------------------------------------------------
# metered quotas: the ok → deprioritized → refused ladder
# ---------------------------------------------------------------------------

class TestQuotaLadder:
    def test_ladder_states_and_429(self):
        clock = FakeClock()
        led = _ledger(clock, window_s=60.0)
        quotas = QuotaTable(led, soft_frac=0.8, metrics=MetricsRegistry())
        quotas.set_quota(7, 1.0)
        assert quotas.state_of(7) == "ok"
        led.charge(7, "eval_s", 0.5)
        assert quotas.state_of(7) == "ok"
        led.charge(7, "eval_s", 0.35)             # 0.85 ≥ 0.8 × quota
        assert quotas.state_of(7) == "deprioritized"
        quotas.check_eval(7)                      # deprioritized ≠ refused
        led.charge(7, "eval_s", 0.2)              # 1.05 ≥ quota
        assert quotas.state_of(7) == "refused"
        with pytest.raises(QuotaExceeded) as exc:
            quotas.check_eval(7)
        assert exc.value.http_status == 429       # retryable, not a 403
        assert "retry" in str(exc.value)
        body = quotas.consumption(7)
        assert body["state"] == "refused"
        assert body["eval_s_remaining"] == 0.0
        # an unquota'd tenant is unlimited
        led.charge(9, "eval_s", 100.0)
        assert quotas.state_of(9) == "ok"
        assert quotas.consumption(9)["eval_s_quota"] is None

    def test_refusal_clears_when_window_rotates(self):
        clock = FakeClock()
        led = _ledger(clock, window_s=60.0, window_slices=12)
        quotas = QuotaTable(led)
        quotas.set_quota(3, 1.0)
        led.charge(3, "eval_s", 2.0)
        assert quotas.state_of(3) == "refused"
        clock.t += 61.0                           # window rotates off
        assert led.windowed_eval_s(3) == 0.0
        assert quotas.state_of(3) == "ok"
        quotas.check_eval(3)                      # no raise: retry worked

    def test_skip_mask_targets_only_throttled_tenants(self):
        clock = FakeClock()
        led = _ledger(clock)
        metrics = MetricsRegistry()
        quotas = QuotaTable(led, metrics=metrics)
        ids = np.array([1, 2, 1, 3, 2], np.int32)
        # fast path: no quota configured anywhere → None, zero work
        assert quotas.skip_mask(ids) is None
        quotas.set_quota(2, 1.0)
        assert quotas.skip_mask(ids) is None      # tenant 2 still ok
        led.charge(2, "eval_s", 5.0)
        mask = quotas.skip_mask(ids)
        assert mask.tolist() == [False, True, False, False, True]
        assert metrics.counter(
            "tenant.quota.eval_rows_skipped").value == 2

    def test_default_quota_applies_to_every_tenant(self):
        clock = FakeClock()
        led = _ledger(clock)
        quotas = QuotaTable(led, default_eval_s=0.5)
        led.charge(11, "eval_s", 0.6)
        assert quotas.state_of(11) == "refused"
        quotas.set_quota(11, 10.0)                # override loosens
        assert quotas.state_of(11) == "ok"


# ---------------------------------------------------------------------------
# partitioned device state: rung ladder, hysteresis, compile_count
# ---------------------------------------------------------------------------

class TestTenantPartitions:
    def _parts(self, column, min_capacity=4, metrics=None):
        return TenantPartitions(lambda: column, min_capacity=min_capacity,
                                metrics=metrics)

    def test_rung_ladder_grows_by_pow2(self):
        col = np.full(64, -1, np.int32)
        col[:3] = 1
        p = self._parts(col)
        p.refresh()
        assert p.partition_of(1) == {"count": 3, "rung": 4,
                                     "compile_count": 1}
        col[:9] = 1                               # 9 > rung 4 → grow
        p.refresh()
        assert p.partition_of(1)["rung"] == 16
        assert p.compile_count(1) == 2

    def test_shrink_only_at_quarter_occupancy(self):
        col = np.full(64, -1, np.int32)
        col[:32] = 5
        p = self._parts(col)
        p.refresh()
        assert p.partition_of(5)["rung"] == 32
        col[9:] = -1                              # 9 devices: > 32//4
        p.refresh()
        assert p.partition_of(5)["rung"] == 32    # hysteresis holds
        assert p.compile_count(5) == 1
        col[8:] = -1                              # 8 ≤ 32//4 → shrink
        p.refresh()
        assert p.partition_of(5)["rung"] == 8
        assert p.compile_count(5) == 2

    def test_untouched_tenant_compile_count_stays_flat_under_churn(self):
        """The churn-storm invariant: tenant 1's view never recompiles
        while tenant 2 registers and drops devices in waves."""
        col = np.full(256, -1, np.int32)
        col[:10] = 1
        metrics = MetricsRegistry()
        p = self._parts(col, metrics=metrics)
        p.refresh()
        baseline = p.compile_count(1)
        rng = np.random.default_rng(7)
        for _ in range(20):                       # churn waves: tenant 2
            col[10:] = -1
            n = int(rng.integers(1, 200))
            col[10:10 + n] = 2
            p.refresh()
        assert p.compile_count(1) == baseline == 1
        assert p.compile_count(2) > 1             # the churner DID resize
        assert metrics.gauge("tenant.partition.tracked").value == 2

    def test_padded_view_gathers_only_owned_rows(self):
        col = np.array([3, -1, 3, 9, 3, -1], np.int32)
        p = self._parts(col)
        p.refresh()
        idx, valid = p.indices_of(3)
        assert len(idx) == 4 and valid.sum() == 3
        state = {"x": np.arange(6) * 10.0}
        rows, vmask = p.view(state, 3)
        got = np.asarray(rows["x"])[np.asarray(vmask)]
        assert sorted(got.tolist()) == [0.0, 20.0, 40.0]
        assert p.view(state, 999) is None         # unknown tenant

    def test_gather_kernel_shared_per_rung(self):
        from sitewhere_tpu.state.manager import _partition_gather

        assert _partition_gather(16) is _partition_gather(16)
        assert _next_pow2(1) == 1 and _next_pow2(5) == 8
        assert _next_pow2(64) == 64


# ---------------------------------------------------------------------------
# tenant-budget dead-letters + replay re-checks the CURRENT budget
# ---------------------------------------------------------------------------

def _instance_config(tmp_path, tenants=None, overload=None):
    from sitewhere_tpu.runtime.config import Config

    return Config({
        "instance": {"id": "iso-inst", "data_dir": str(tmp_path / "data")},
        "pipeline": {"width": 64, "registry_capacity": 128,
                     "mtype_slots": 4, "deadline_ms": 5.0, "n_shards": 1},
        "presence": {"scan_interval_s": 3600.0, "missing_after_s": 1800},
        "overload": {"enabled": True, **(overload or {})},
        "tenants": tenants or {},
    }, apply_env=False)


def _measurement(token, value, ts=1_753_800_000):
    return json.dumps({
        "deviceToken": token, "type": "Measurement",
        "request": {"name": "temp", "value": value, "eventDate": ts},
    })


class TestTenantBudgetDeadLetter:
    def _decoded(self, inst, token, tenant, n):
        from sitewhere_tpu.ingest.decoders import JsonLinesDecoder

        payload = "\n".join(
            _measurement(token, float(i)) for i in range(n)).encode()
        reqs = JsonLinesDecoder()(payload)
        for r in reqs:
            r.metadata = dict(r.metadata or {}, tenant=tenant)
        return payload, reqs

    def test_budget_shed_kind_and_replay_recheck(self, tmp_path):
        from sitewhere_tpu.instance import Instance

        inst = Instance(_instance_config(
            tmp_path,
            tenants={"t-noisy": {"overload": {
                "degraded_telemetry_rate_per_s": 0.0,
                "degraded_telemetry_burst": 0.0}}},
            # refresh every admit: budget changes reprice immediately
            overload={"budget_refresh_s": 0.0}))
        inst.start()
        try:
            inst.device_management.create_device_type(token="sensor",
                                                      name="Sensor")
            inst.device_management.create_device(token="d-0",
                                                 device_type="sensor")
            inst.device_management.create_device_assignment(device="d-0")
            inst.overload.force(OverloadState.DEGRADED)

            # quiet tenant sails through DEGRADED on the uniform bucket
            qp, qreqs = self._decoded(inst, "d-0", "t-quiet", 2)
            inst.dispatcher.ingest_many(qreqs, qp, "src-q")

            payload, reqs = self._decoded(inst, "d-0", "t-noisy", 3)
            with pytest.raises(OverloadShed):
                inst.dispatcher.ingest_many(reqs, payload, "src-n")
            letters = [d for d in inst.list_dead_letters(limit=50)
                       if d.get("kind") == "tenant-budget"]
            assert len(letters) == 1
            doc = letters[0]
            assert doc["tenant"] == "t-noisy"
            assert doc["reason"] == "tenant budget exceeded"
            assert doc["classes"] == {"telemetry": 3}
            assert doc["budget"] == {
                "degraded_telemetry_rate_per_s": 0.0,
                "degraded_telemetry_burst": 0.0}
            # distinct kind: nothing landed under the generic intake-shed
            assert not [d for d in inst.list_dead_letters(limit=50)
                        if d.get("kind") == "intake-shed"]

            # replay while STILL over budget: refused, record retryable
            refused = inst.requeue_dead_letter(doc["offset"])
            assert refused["requeued"] is False
            assert refused["reason"].startswith("still over tenant budget")

            # operator raises the budget: the SAME record replays, the
            # composed admission re-checking the CURRENT budget
            inst.overload.tenant_budgets.set_budget(
                "t-noisy", rate_per_s=1e6, burst=1e6)
            result = inst.requeue_dead_letter(doc["offset"])
            assert result["requeued"] is True and result["rows"] == 3
            inst.dispatcher.flush()
            inst.event_store.flush()
            assert inst.event_store.total_events == 5   # 2 quiet + 3 replay
            # the original shed AND the refused replay attempt both
            # count as budget clips (3 rows each)
            clipped = inst.metrics.counter(
                "tenant.budget.clipped_rows").value
            assert clipped == 6
        finally:
            inst.stop()
            inst.terminate()

    def test_usage_drilldown_carries_budget_and_quota(self, tmp_path):
        """Satellite: GET /api/tenants/usage/{token} explains WHY a
        tenant is throttled — live rate_scale + configured budget +
        quota consumption in one body."""
        from sitewhere_tpu.instance import Instance

        inst = Instance(_instance_config(
            tmp_path,
            tenants={"t-metered": {
                "overload": {"degraded_telemetry_rate_per_s": 123.0},
                "quota": {"eval_s_per_window": 2.0}}}))
        inst.start()
        try:
            from sitewhere_tpu.web.controllers import register_routes
            from sitewhere_tpu.web.http import RestGateway

            inst.tenants.create_tenant(token="t-metered", name="Metered")
            tid = inst.identity.tenant.lookup("t-metered")
            inst.usage_ledger.charge(int(tid), "eval_s", 1.9)

            gw = RestGateway()
            register_routes(gw, inst)
            handler, params, _, _ = gw.router.route(
                "GET", "/api/tenants/usage/t-metered")

            class _Q:
                def __init__(self, p):
                    self.params = p

                def q1(self, k, default=None):
                    return default

            body = handler(_Q(params))
            assert body["budget"] == {
                "degraded_telemetry_rate_per_s": 123.0}
            assert body["quota"]["eval_s_quota"] == 2.0
            assert body["quota"]["state"] == "deprioritized"
            assert body["quota"]["eval_s_remaining"] == pytest.approx(
                0.1, abs=1e-6)
            assert body["rate_scale"] == 1.0
        finally:
            inst.stop()
            inst.terminate()
