"""Tenant CRUD, templates, and the per-tenant engine manager."""

import pytest

from sitewhere_tpu.services.common import (
    DuplicateToken,
    EntityNotFound,
    InvalidReference,
    ValidationError,
)
from sitewhere_tpu.services.tenants import (
    DatasetTemplate,
    MultitenantEngineManager,
    TenantManagement,
    TenantTemplate,
)


@pytest.fixture
def tm():
    return TenantManagement()


class TestTenantCrud:
    def test_create_get_update_delete(self, tm):
        t = tm.create_tenant("acme", name="Acme Corp")
        assert t.auth_token  # generated
        assert tm.get_tenant("acme").name == "Acme Corp"
        tm.update_tenant("acme", name="Acme Inc", logo_url="http://x/logo.png")
        assert tm.get_tenant("acme").name == "Acme Inc"
        tm.delete_tenant("acme")
        with pytest.raises(EntityNotFound):
            tm.get_tenant("acme")

    def test_validation(self, tm):
        with pytest.raises(ValidationError):
            tm.create_tenant("t1")  # no name
        tm.create_tenant("t1", name="One")
        with pytest.raises(DuplicateToken):
            tm.create_tenant("t1", name="Again")
        with pytest.raises(InvalidReference):
            tm.create_tenant("t2", name="Two", tenant_template_id="nope")
        with pytest.raises(ValidationError):
            tm.update_tenant("t1", bogus_field=1)

    def test_auth_token_lookup(self, tm):
        t = tm.create_tenant("acme", name="Acme", auth_token="sekrit")
        assert tm.get_tenant_by_auth_token("sekrit") is t
        assert tm.get_tenant_by_auth_token("nope") is None

    def test_authorized_users(self, tm):
        tm.create_tenant("acme", name="Acme", authorized_user_ids=["ada"])
        assert tm.authorized_for("acme", "ada")
        assert not tm.authorized_for("acme", "eve")
        tm.create_tenant("open", name="Open")  # empty list = everyone
        assert tm.authorized_for("open", "anyone")

    def test_paging(self, tm):
        for i in range(5):
            tm.create_tenant(f"t{i}", name=f"T{i}")
        from sitewhere_tpu.services.common import SearchCriteria

        page = tm.list_tenants(SearchCriteria(page=2, page_size=2))
        assert page.total == 5 and [t.token for t in page] == ["t2", "t3"]


class TestTemplates:
    def test_catalog(self, tm):
        tm.add_tenant_template(TenantTemplate(id="big", name="Big", config={"registry_capacity": 128}))
        ids = [t.id for t in tm.list_tenant_templates()]
        assert ids == ["big", "empty"]
        assert tm.get_tenant_template("big").config["registry_capacity"] == 128
        with pytest.raises(EntityNotFound):
            tm.get_dataset_template("nope")


class TestEngineManager:
    def test_engines_follow_tenant_lifecycle(self, tm):
        mgr = MultitenantEngineManager(tm)
        tm.create_tenant("pre", name="Pre-existing")
        mgr.start()
        assert mgr.get_engine("pre").state.name == "STARTED"
        # Engines spin up on create and down on delete (the
        # tenant-model-updates topic analog).
        tm.create_tenant("live", name="Created live")
        engine = mgr.get_engine("live")
        assert engine.state.name == "STARTED"
        assert engine.device_management.tenant == "live"
        tm.delete_tenant("live")
        with pytest.raises(EntityNotFound):
            mgr.get_engine("live")
        mgr.stop()
        assert mgr.get_engine("pre").state.name == "STOPPED"

    def test_template_config_applies(self, tm):
        tm.add_tenant_template(
            TenantTemplate(id="tiny", name="Tiny", config={"registry_capacity": 64})
        )
        mgr = MultitenantEngineManager(tm)
        mgr.start()
        tm.create_tenant("small", name="S", tenant_template_id="tiny")
        engine = mgr.get_engine("small")
        assert engine.mirror.capacity == 64

    def test_dataset_initializer_runs_once(self, tm):
        calls = []

        def seed(engine):
            calls.append(engine.tenant.token)
            engine.device_management.create_device_type("default-type", name="Default")

        tm.add_dataset_template(DatasetTemplate(id="seeded", name="Seeded", initialize=seed))
        mgr = MultitenantEngineManager(tm)
        mgr.start()
        tm.create_tenant("acme", name="Acme", dataset_template_id="seeded")
        engine = mgr.get_engine("acme")
        assert engine.device_management.get_device_type("default-type").name == "Default"
        mgr.restart_engine("acme")
        assert calls == ["acme"]  # bootstrapped marker prevents re-seeding

    def test_dense_tenant_ids_stable_across_restart(self, tm):
        mgr = MultitenantEngineManager(tm)
        mgr.start()
        tm.create_tenant("a", name="A")
        tm.create_tenant("b", name="B")
        id_a = mgr.get_engine("a").tenant_id
        id_b = mgr.get_engine("b").tenant_id
        assert id_a != id_b
        assert mgr.restart_engine("a").tenant_id == id_a

    def test_manager_restart_restarts_engines(self, tm):
        mgr = MultitenantEngineManager(tm)
        mgr.start()
        tm.create_tenant("a", name="A")
        mgr.stop()
        assert mgr.get_engine("a").state.name == "STOPPED"
        mgr.start()
        assert mgr.get_engine("a").state.name == "STARTED"

    def test_failed_bootstrap_is_retryable(self, tm):
        attempts = []

        def flaky(engine):
            attempts.append(1)
            if len(attempts) == 1:
                raise RuntimeError("transient bootstrap failure")

        tm.add_dataset_template(DatasetTemplate(id="flaky", name="Flaky", initialize=flaky))
        mgr = MultitenantEngineManager(tm)
        mgr.start()
        tm.create_tenant("acme", name="Acme", dataset_template_id="flaky")
        # Listener swallowed the failure: no engine registered, none leaked.
        with pytest.raises(EntityNotFound):
            mgr.get_engine("acme")
        # Manager restart retries the bootstrap and succeeds.
        mgr.stop()
        mgr.start()
        engine = mgr.get_engine("acme")
        assert engine.state.name == "STARTED"
        assert len(attempts) == 2

    def test_attach_extra_component(self, tm):
        from sitewhere_tpu.runtime.lifecycle import LifecycleComponent

        mgr = MultitenantEngineManager(tm)
        mgr.start()
        tm.create_tenant("a", name="A")
        engine = mgr.get_engine("a")
        comp = LifecycleComponent("extra")
        engine.attach("extra", comp)
        assert comp.state.name == "STARTED"  # started because engine is live
        engine.stop()
        assert comp.state.name == "STOPPED"


def test_manager_restart_covers_all_tenants_beyond_one_page(tm):
    """start() must bring up every tenant engine, not just the default
    first page of 100 (regression: restart left tenants 101+ parked)."""
    from sitewhere_tpu.runtime.lifecycle import LifecycleState

    for i in range(120):
        tm.create_tenant(token=f"t-{i}", name=f"Tenant {i}")
    mgr = MultitenantEngineManager(tm)
    mgr.start()
    assert len(mgr.list_engines()) == 120
    mgr.stop()
    mgr.start()
    states = {e.state for e in mgr.list_engines()}
    assert states == {LifecycleState.STARTED}
    mgr.stop()
