"""Overlapped host pipeline: decode pool ordering, egress offload, and
the stage-overlap acceptance proof (stubbed slow step).

The tentpole claim: with the host loop split into overlapped stages, the
only work left on the critical dispatch thread is batch assembly + step
launch — decode (window N+1) and egress (window N-1) run concurrently
with the device step of window N.  The proof here uses a stubbed slow
step and slow egress sink: wall clock stays near N×step while the
per-stage timers (``pipeline.stage_*_s``) show the full egress cost was
paid — their totals exceed wall elapsed, which is only possible when
the stages overlap.
"""

import threading
import time

import numpy as np
import pytest

from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.ingest.batcher import Batcher
from sitewhere_tpu.ingest.sources import DecodePool, InboundEventSource
from sitewhere_tpu.pipeline.step import StepMetrics
from sitewhere_tpu.runtime import faults
from sitewhere_tpu.runtime.dispatcher import PipelineDispatcher
from sitewhere_tpu.runtime.metrics import MetricsRegistry

WIDTH = 8


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.002)
    return cond()


# ---------------------------------------------------------------------------
# decode pool: parallel decode, ordered delivery
# ---------------------------------------------------------------------------

class TestDecodePool:
    def test_parallel_decode_delivers_in_submission_order(self):
        pool = DecodePool(workers=4, max_pending=64)
        try:
            delivered = []
            done = threading.Event()
            n = 12

            def work(i):
                # later jobs finish FIRST (reverse sleep) — only the
                # ordered-delivery lane keeps the output in order
                time.sleep(0.002 * (n - i))
                return i

            def deliver(result, exc):
                assert exc is None
                delivered.append(result)
                if len(delivered) == n:
                    done.set()

            t0 = time.perf_counter()
            for i in range(n):
                pool.submit("src", lambda i=i: work(i), deliver)
            assert done.wait(10.0)
            wall = time.perf_counter() - t0
            assert delivered == list(range(n))
            # 4 workers: wall must beat the serial sum (overlap proof)
            serial = sum(0.002 * (n - i) for i in range(n))
            assert wall < serial
        finally:
            pool.stop()

    def test_independent_keys_do_not_serialize(self):
        pool = DecodePool(workers=2, max_pending=64)
        try:
            got = []
            evt = threading.Event()

            def deliver(result, exc):
                got.append(result)
                if len(got) == 2:
                    evt.set()

            # "a" blocks until "b" has started: deliverable only if the
            # two keys decode concurrently (serialized lanes would leave
            # "a" waiting out the timeout and return the failure marker)
            b_started = threading.Event()
            pool.submit(
                "a", lambda: "a" if b_started.wait(5.0) else "a-stalled",
                deliver)
            pool.submit("b", lambda: b_started.set() or "b", deliver)
            assert evt.wait(10.0)
            assert sorted(got) == ["a", "b"]
        finally:
            pool.stop()

    def test_decode_error_routes_to_deliver_in_order(self):
        pool = DecodePool(workers=2, max_pending=8)
        try:
            seen = []
            done = threading.Event()

            def deliver(result, exc):
                seen.append((result, type(exc).__name__ if exc else None))
                if len(seen) == 3:
                    done.set()

            def boom():
                raise ValueError("bad payload")

            pool.submit("k", lambda: 1, deliver)
            pool.submit("k", boom, deliver)
            pool.submit("k", lambda: 3, deliver)
            assert done.wait(5.0)
            assert seen == [(1, None), (None, "ValueError"), (3, None)]
        finally:
            pool.stop()

    def test_submit_backpressure_blocks_at_max_pending(self):
        pool = DecodePool(workers=1, max_pending=2)
        try:
            release = threading.Event()
            pool.submit("k", lambda: release.wait(10), lambda r, e: None)
            pool.submit("k", lambda: None, lambda r, e: None)
            # budget exhausted: the third submit must block until a slot
            # frees — the receiver-thread backpressure contract
            unblocked = threading.Event()

            def third():
                pool.submit("k", lambda: None, lambda r, e: None)
                unblocked.set()

            t = threading.Thread(target=third, daemon=True)
            t.start()
            assert not unblocked.wait(0.15)
            release.set()
            assert unblocked.wait(5.0)
            assert pool.flush(5.0)
        finally:
            release.set()
            pool.stop()

    def test_stopped_pool_degrades_to_synchronous(self):
        pool = DecodePool(workers=1, max_pending=2)
        pool.stop()
        got = []
        pool.submit("k", lambda: 41, lambda r, e: got.append((r, e)))
        assert got == [(41, None)]

    def test_deliver_raising_base_exception_does_not_kill_worker(self):
        pool = DecodePool(workers=1, max_pending=8)
        try:
            got = []
            done = threading.Event()

            def bad_deliver(result, exc):
                raise SystemExit(3)  # a deliver re-raising a decode-stage
                # BaseException must not end the worker thread

            pool.submit("k", lambda: 1, bad_deliver)
            pool.submit("k", lambda: 2,
                        lambda r, e: (got.append(r), done.set()))
            assert done.wait(5.0)
            assert got == [2]
            assert pool.delivery_errors == 1
        finally:
            pool.stop()


# ---------------------------------------------------------------------------
# dispatcher fixture with a stubbed (slow) step
# ---------------------------------------------------------------------------

class FakeOut:
    """Duck-types the slice of PipelineOutputs the egress path consumes."""

    def __init__(self, n):
        z = np.zeros(n, np.int32)
        self.accepted = np.ones(n, bool)
        self.unregistered = np.zeros(n, bool)
        self.present_now = None
        self.device_type_id = z
        self.assignment_id = z
        self.area_id = z
        self.customer_id = z
        self.asset_id = z
        self.metrics = StepMetrics(
            processed=np.int32(n), accepted=np.int32(n),
            unregistered=np.int32(0), unassigned=np.int32(0),
            threshold_alerts=np.int32(0), zone_alerts=np.int32(0),
            by_type=np.zeros(6, np.int32))


class FakeStateManager:
    current = None
    current_packed = None

    def commit(self, new_state, present_now=None):
        pass

    def commit_packed(self, new_packed, present_now=None,
                      read_epoch=None, lease_token=None):
        pass

    def lease_packed(self):
        return None, None


class SlowStore:
    """Event-store stand-in whose append costs ``delay_s`` host time."""

    def __init__(self, delay_s=0.0):
        self.delay_s = delay_s
        self.rows = 0
        self.batches = 0
        self.append_threads = set()
        self.first_ids = []  # first device_id of each appended batch
        # (egress-order probe for the ring's ordering barrier)

    def append_columns(self, cols, mask=None):
        self.append_threads.add(threading.current_thread().name)
        if self.delay_s:
            time.sleep(self.delay_s)
        self.rows += int(mask.sum()) if mask is not None \
            else len(cols["device_id"])
        self.batches += 1
        self.first_ids.append(int(np.asarray(cols["device_id"])[0]))

    def flush(self):
        pass


def make_dispatcher(step_s=0.0, egress_s=0.0, egress_offload=True,
                    inflight_depth=1, **kw):
    metrics = MetricsRegistry()
    batcher = Batcher(
        width=WIDTH, n_shards=1, registry_capacity=64,
        resolve_device=lambda t: NULL_ID, resolve_mtype=lambda n: 0,
        resolve_alert=lambda n: 0, deadline_ms=60_000.0)
    store = SlowStore(egress_s)
    disp = PipelineDispatcher(
        batcher=batcher,
        registry_provider=lambda: None,
        state_manager=FakeStateManager(),
        rules_provider=lambda: None,
        zones_provider=lambda: None,
        event_store=store,
        inflight_depth=inflight_depth,
        egress_offload=egress_offload,
        metrics=metrics,
        **kw,
    )

    def slow_step(registry, state, rules, zones, batch):
        if step_s:
            time.sleep(step_s)  # the stubbed "device step"
        return state, FakeOut(WIDTH)

    disp._step = slow_step
    return disp, store, metrics


def ingest_window(disp):
    disp.ingest_arrays(device_id=np.arange(WIDTH, dtype=np.int32))


def make_ring_dispatcher(ring_depth=2, egress_s=0.0, egress_offload=True,
                         **kw):
    """Dispatcher on the device-resident ring path with a STUBBED chain:
    packed plans from an emit_packed batcher, a fake K-step chain whose
    stacked outputs accept every row, and no real jax dispatch — the
    ring's windowing/commit/ordering semantics in isolation."""
    from sitewhere_tpu.pipeline.packed import METRIC_SCALARS

    metrics = MetricsRegistry()
    batcher = Batcher(
        width=WIDTH, n_shards=1, registry_capacity=64,
        resolve_device=lambda t: NULL_ID, resolve_mtype=lambda n: 0,
        resolve_alert=lambda n: 0, deadline_ms=60_000.0, emit_packed=True)
    store = SlowStore(egress_s)
    disp = PipelineDispatcher(
        batcher=batcher,
        registry_provider=lambda: None,
        state_manager=FakeStateManager(),
        rules_provider=lambda: None,
        zones_provider=lambda: None,
        event_store=store,
        egress_offload=egress_offload,
        ring_depth=ring_depth,
        metrics=metrics,
        **kw,
    )
    disp._tables_packed = lambda: None
    chain_calls = []

    def _step_out(bi):
        valid = (np.asarray(bi)[0] != 0).astype(np.int32)
        oi = np.zeros((10, WIDTH), np.int32)
        oi[0] = valid  # flags row: F_ACCEPTED for every valid row
        mets = np.zeros(len(METRIC_SCALARS) + 6, np.int32)
        mets[0] = mets[1] = int(valid.sum())  # processed / accepted
        return oi, mets

    def fake_chain(tables, ps, *slots):
        k = len(slots) // 2
        chain_calls.append(k)
        outs = [_step_out(slots[i]) for i in range(k)]
        return (ps, np.stack([o for o, _ in outs]),
                np.stack([m for _, m in outs]), np.zeros(64, bool))

    def fake_packed_step(tables, ps, bi, bf):
        oi, mets = _step_out(bi)
        return ps, oi, mets, np.zeros(64, bool)

    for k in range(1, ring_depth + 1):
        disp._ring_chains[k] = fake_chain
    disp._packed_step = fake_packed_step
    disp._chain_calls = chain_calls
    return disp, store, metrics


# ---------------------------------------------------------------------------
# egress offload semantics
# ---------------------------------------------------------------------------

class TestEgressOffload:
    def test_flush_drains_the_offload_queue(self):
        disp, store, _ = make_dispatcher(egress_s=0.01)
        disp.start()
        try:
            for _ in range(4):
                ingest_window(disp)
            disp.flush()
            # flush's contract: every row ingested BEFORE the call has
            # completed egress on return — offloaded or not
            assert store.rows == 4 * WIDTH
            assert not disp._inflight
            with disp._lock:
                assert disp._plans_outstanding == 0
        finally:
            disp.stop()

    def test_egress_runs_off_the_dispatch_thread(self):
        disp, store, _ = make_dispatcher(egress_s=0.0)
        disp.start()
        try:
            ingest_window(disp)
            disp.flush()
            assert store.rows == WIDTH
            # offloaded: the append ran on the supervised egress worker,
            # not on this (ingesting) thread and not on the loop thread
            assert all("egress" in t for t in store.append_threads)
        finally:
            disp.stop()

    def test_offload_disabled_is_inline_and_needs_no_threads(self):
        disp, store, _ = make_dispatcher(egress_offload=False)
        # no start(): the inline path must work exactly as before
        ingest_window(disp)
        disp.flush()
        assert store.rows == WIDTH
        assert all("egress" not in t for t in store.append_threads)

    def test_unstarted_dispatcher_degrades_to_inline(self):
        disp, store, _ = make_dispatcher(egress_offload=True)
        ingest_window(disp)
        disp.flush()
        assert store.rows == WIDTH

    def test_backpressure_bounds_the_window(self):
        disp, store, _ = make_dispatcher(egress_s=0.05, inflight_depth=1)
        disp.start()
        try:
            for _ in range(6):
                ingest_window(disp)
                # the dispatch side may run ahead of egress by at most
                # the bounded window (queued) + one in-progress item
                assert len(disp._inflight) <= disp.egress_queue_depth
            disp.flush()
            assert store.rows == 6 * WIDTH
        finally:
            disp.stop()

    def test_egress_crash_fails_closed_and_worker_recovers(self):
        """An egress fault kills the WORKER mid-window: its supervisor
        restarts the loop, sibling plans still drain, and the dead
        plan's accounting keeps the commit gate closed forever (the
        at-least-once rule: never commit past an un-egressed plan)."""
        faults.clear()
        disp, store, _ = make_dispatcher(egress_s=0.0)
        disp.start()
        try:
            faults.inject("dispatcher.egress", times=1)
            ingest_window(disp)           # this plan's egress dies
            assert _wait(lambda: faults.fired("dispatcher.egress") == 1)
            ingest_window(disp)           # sibling must still egress
            disp.flush(timeout_s=1.0)
            assert store.rows == WIDTH    # only the sibling landed
            assert disp.egress_failures == 1
            assert _wait(lambda: disp._egress_super.restarts >= 1)
            assert not disp._egress_super.escalated
            with disp._lock:
                # the dead plan is still outstanding: gate failed closed
                assert disp._plans_outstanding == 1
        finally:
            faults.clear()
            disp.stop()


# ---------------------------------------------------------------------------
# device-resident dispatch ring: multi-step in-flight semantics
# ---------------------------------------------------------------------------

def ingest_window_at(disp, base):
    """One full-width fill window with device ids base..base+WIDTH-1
    (distinguishable in the store's egress-order probe)."""
    disp.ingest_arrays(
        device_id=(base + np.arange(WIDTH)).astype(np.int32))


class TestDeviceResidentRing:
    def test_full_windows_chain_k_steps_one_sync_per_chain(self):
        disp, store, metrics = make_ring_dispatcher(ring_depth=2)
        disp.start()
        try:
            for i in range(4):
                ingest_window_at(disp, i * WIDTH % 64)
            disp.flush()
            assert store.rows == 4 * WIDTH
            # first call is the boot-time warm-up (all-invalid ring)
            assert disp._chain_calls == [2, 2, 2]
            # the whole point: ONE blocking host sync per K-step chain
            assert metrics.counter("pipeline.host_syncs").value == 2
            assert metrics.counter("pipeline.ring_chains").value == 2
            assert not disp._ring
            with disp._lock:
                assert disp._plans_outstanding == 0
        finally:
            disp.stop()

    def test_flush_drains_partial_ring_no_lost_commits(self):
        disp, store, metrics = make_ring_dispatcher(ring_depth=2)
        disp.start()
        try:
            for i in range(3):   # one chain + one plan stranded in ring
                ingest_window_at(disp, i * WIDTH)
            disp.flush()
            # flush's contract holds through the ring: every row
            # ingested before the call completed egress on return
            assert store.rows == 3 * WIDTH
            assert not disp._ring
            with disp._lock:
                assert disp._plans_outstanding == 0
            assert metrics.counter("pipeline.ring_flushes").value == 1
        finally:
            disp.stop()

    def test_stop_drains_ring(self):
        disp, store, _ = make_ring_dispatcher(ring_depth=4)
        disp.start()
        ingest_window_at(disp, 0)   # sits in the ring, chain never fills
        disp.stop()                 # shutdown flush must not strand it
        assert store.rows == WIDTH
        with disp._lock:
            assert disp._plans_outstanding == 0

    def test_non_ring_plan_drains_ring_first_in_order(self):
        """A deadline/flush partial must not overtake ring-held
        predecessors: per-device event order across plans is preserved
        by the ordering barrier (ring drains single-step first)."""
        disp, store, _ = make_ring_dispatcher(ring_depth=3)
        disp.start()
        try:
            ingest_window_at(disp, 0)    # ring slot 0
            ingest_window_at(disp, 8)    # ring slot 1 (chain needs 3)
            disp.ingest_arrays(
                device_id=np.full(4, 16, np.int32))  # partial, pending
            disp.flush()                 # emits the partial (reason=flush)
            assert store.rows == 2 * WIDTH + 4
            assert store.first_ids == [0, 8, 16]
        finally:
            disp.stop()

    def test_barrier_drains_only_predecessors_by_seq(self):
        """The ordering barrier is seq-bounded: ring plans emitted AFTER
        the non-ring plan are successors — draining them would reorder
        them ahead of it (and starve it under sustained fill traffic)."""
        disp, store, _ = make_ring_dispatcher(ring_depth=4)
        disp.start()
        try:
            ingest_window_at(disp, 0)    # seq 0 → ring
            ingest_window_at(disp, 8)    # seq 1 → ring
            disp.ingest_arrays(device_id=np.full(4, 16, np.int32))
            partial = disp._take(disp.batcher.flush)[0]   # seq 2
            ingest_window_at(disp, 24)   # seq 3 → ring (a successor)
            disp._run_plan(partial)
            # predecessors stepped, then the partial; successor stays
            with disp._step_lock:
                assert [p.seq for p in disp._ring] == [3]
            disp.flush()
            assert store.first_ids == [0, 8, 16, 24]
            assert store.rows == 3 * WIDTH + 4
        finally:
            disp.stop()

    def test_egress_crash_mid_ring_fails_closed_on_dead_step_only(self):
        """An egress fault on slot 0 of a chained dispatch kills the
        worker; the supervisor restarts it, slot 1 still drains, and
        ONLY the dead step stays outstanding — the commit gate fails
        closed on exactly the uncommitted slice of the ring."""
        faults.clear()
        disp, store, _ = make_ring_dispatcher(ring_depth=2)
        disp.start()
        try:
            faults.inject("dispatcher.egress", times=1)
            ingest_window_at(disp, 0)
            ingest_window_at(disp, 8)   # chain of 2 dispatches here
            assert _wait(lambda: faults.fired("dispatcher.egress") == 1)
            disp.flush(timeout_s=1.0)
            assert store.rows == WIDTH          # only the sibling landed
            assert disp.egress_failures == 1
            assert _wait(lambda: disp._egress_super.restarts >= 1)
            assert not disp._egress_super.escalated
            with disp._lock:
                assert disp._plans_outstanding == 1
        finally:
            faults.clear()
            disp.stop()

    def test_overload_signal_reflects_oldest_ring_plan(self):
        """The seal-lag watermark must see plans buffered for a chain:
        with steps in flight beyond the windowed FIFO, the signal is the
        age of the OLDEST in-flight batch, not the last fetched one."""
        disp, _, _ = make_ring_dispatcher(ring_depth=4)
        # no start(): plans stay in the ring (no loop thread to age them
        # out), which is exactly the wedged state the signal must see
        disp.steps = 1  # past the warm-up gate
        ingest_window_at(disp, 0)
        ingest_window_at(disp, 8)
        assert len(disp._ring) == 2
        time.sleep(0.05)
        assert disp.oldest_unsealed_wait_s() >= 0.04
        disp._flush_ring()

    def test_ring_ineligible_plans_take_the_single_step_path(self):
        """Re-injected (replay-depth) plans and deadline partials never
        wait in the ring."""
        disp, store, _ = make_ring_dispatcher(ring_depth=2)
        # depth > 0 == egress-worker context: must dispatch immediately
        plan = disp._take(lambda: disp.batcher.add_arrays(
            device_id=np.arange(WIDTH, dtype=np.int32)))[0]
        assert not disp._ring_eligible(plan, replay_depth=1)
        assert disp._ring_eligible(plan, replay_depth=0)
        disp._run_plan(plan, replay_depth=1)
        assert not disp._ring   # never waited for a chain
        disp.flush()
        assert store.rows == WIDTH


# ---------------------------------------------------------------------------
# start_host_copy: only the deleted-buffer race is silent
# ---------------------------------------------------------------------------

class _FakeDeviceArray:
    def __init__(self, exc=None):
        self.exc = exc
        self.calls = 0

    def copy_to_host_async(self):
        self.calls += 1
        if self.exc is not None:
            raise self.exc


class TestStartHostCopy:
    @pytest.fixture(autouse=True)
    def _force_capability(self, monkeypatch):
        from sitewhere_tpu.pipeline import packed

        monkeypatch.setattr(packed, "_ASYNC_HOST_COPY", True)
        yield

    def test_deleted_buffer_race_stays_silent(self):
        from sitewhere_tpu.pipeline import packed

        before = packed.host_copy_errors
        errors = []
        packed.start_host_copy(
            _FakeDeviceArray(RuntimeError("Array has been deleted.")),
            on_error=errors.append)
        assert packed.host_copy_errors == before
        assert errors == []

    def test_unexpected_error_is_counted_and_does_not_stop_siblings(self):
        from sitewhere_tpu.pipeline import packed

        before = packed.host_copy_errors
        errors = []
        ok = _FakeDeviceArray()
        packed.start_host_copy(
            _FakeDeviceArray(RuntimeError("transfer engine wedged")),
            ok, on_error=errors.append)
        assert packed.host_copy_errors == before + 1
        assert len(errors) == 1
        # the failure must not abort the remaining arrays' copies
        # (the old bare guard returned on ANY error)
        assert ok.calls == 1

    def test_host_arrays_are_skipped(self):
        from sitewhere_tpu.pipeline import packed

        before = packed.host_copy_errors
        packed.start_host_copy(np.zeros(4), object())
        assert packed.host_copy_errors == before


# ---------------------------------------------------------------------------
# tier-1 CPU smoke: the ring end-to-end through a real Instance
# ---------------------------------------------------------------------------

class TestRingEndToEnd:
    def test_forced_ring_runs_journal_to_egress_on_cpu(self, tmp_path):
        """The device-resident dispatch loop exercised on EVERY tier-1
        run, not only on TPU: a real Instance with forced ``ring_depth=2``
        drives NDJSON wire payloads journal→dispatch(chained)→egress, and
        the host-sync counter proves the amortization (1 blocking sync
        per 2-step chain)."""
        import json as _json

        from sitewhere_tpu.instance import Instance
        from sitewhere_tpu.runtime.config import Config

        width = 64
        inst = Instance(Config({
            "instance": {"id": "ring-smoke",
                         "data_dir": str(tmp_path / "data")},
            "pipeline": {"width": width, "registry_capacity": 128,
                         "mtype_slots": 4, "deadline_ms": 60_000.0,
                         "n_shards": 1, "ring_depth": 2},
            "presence": {"scan_interval_s": 3600.0,
                         "missing_after_s": 1800},
        }, apply_env=False))
        inst.start()
        try:
            inst.device_management.create_device_type(
                token="sensor", name="Sensor")
            for i in range(width):
                inst.device_management.create_device(
                    token=f"d-{i}", device_type="sensor")
                inst.device_management.create_device_assignment(
                    device=f"d-{i}")

            def payload(r):
                return "\n".join(_json.dumps({
                    "deviceToken": f"d-{i}", "type": "Measurement",
                    "request": {"name": "temp", "value": 1.0 + i,
                                "eventDate": 1_753_800_000 + r},
                }) for i in range(width)).encode()

            for r in range(4):
                inst.dispatcher.ingest_wire_lines(payload(r))
            inst.dispatcher.flush()
            snap = inst.dispatcher.metrics_snapshot()
            assert snap["ring_depth"] == 2
            assert snap["ring_chains"] == 2          # 4 steps, 2 chains
            assert snap["accepted"] == 4 * width     # no lost commits
            # host syncs amortized to 1 per K steps (the tentpole claim)
            assert snap["host_syncs"] == 2
            assert snap["steps"] == 4
            # egress really landed (journal→dispatch→egress, not a stub)
            inst.event_store.flush()
            assert inst.event_store.total_events == 4 * width
            # chained commits merged state correctly
            row = inst.device_state.get_device_state("d-5")
            assert row["last_event_ts_s"] == 1_753_800_003
            # commit gate advanced past every journaled record
            assert inst.dispatcher.journal_reader.committed == 4
        finally:
            inst.stop()
            inst.terminate()


# ---------------------------------------------------------------------------
# the overlap acceptance proof
# ---------------------------------------------------------------------------

class TestHostpathBenchSmoke:
    def test_tool_reports_every_stage(self, tmp_path):
        """tools/hostpath_bench.py must run end-to-end and report a
        positive per-stage breakdown (tier-1 smoke: the tool is how a
        stage regression localizes)."""
        import importlib.util
        import os

        path = os.path.join(os.path.dirname(__file__), os.pardir,
                            "tools", "hostpath_bench.py")
        spec = importlib.util.spec_from_file_location("hostpath_bench", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        r = mod.run(width=128, iters=2, capacity=1024, ring_k=2,
                    data_dir=str(tmp_path))
        for key in ("decode_s", "batch_s", "dispatch_s", "egress_s",
                    "h2d_stage_s", "d2h_fetch_s", "host_rtt_s",
                    "seal_s", "serial_s", "pipeline_bound_s",
                    "seal_perceived_s", "seal_background_s"):
            assert r[key] > 0.0, key
        # ISSUE 13 acceptance: the hot path's perceived seal cost is a
        # packed row copy + enqueue (segment writes run on the worker
        # pool, attributed to their own background stage timer)
        assert r["seal_background_segments"] > 0
        assert r["seal_perceived_s"] < r["seal_s"]
        # dwell is RTT-clamped: ≥ 0, and positive wherever the chain
        # outruns the trivial-program probe (every real backend)
        assert r["device_dwell_s"] >= 0.0
        assert r["ring_chain_k"] == 2
        assert r["host_syncs_per_batch_ring"] == 0.5
        assert r["pipeline_bound_s"] <= r["serial_s"]
        assert r["overlapped_events_per_s"] >= r["serial_events_per_s"]
        # ISSUE 9 acceptance: the always-on flight recorder's per-batch
        # record cost stays under 1% of the throughput-bounding stage
        assert r["flightrec_record_s"] > 0.0
        assert r["flightrec_overhead_frac"] < 0.01
        # ISSUE 17 acceptance: per-tenant usage attribution rides the
        # same bar — the per-plan ledger charge (bucket→tenant resolve +
        # sketch/window fold) stays under 1% of the bounding stage
        assert r["metering_charge_s"] > 0.0
        assert r["metering_overhead_frac"] < 0.01
        # ISSUE 10 acceptance: the decode A/B + bytes-copied columns are
        # recorded, and with the native toolchain the fill-direct path
        # copies ZERO bytes per event (3x-fewer bar trivially cleared)
        for key in ("decode_fill_s", "decode_native_s", "decode_python_s",
                    "decode_speedup_fill_vs_native",
                    "bytes_copied_per_event_native_total",
                    "bytes_copied_per_event_fill_total"):
            assert key in r, key
        from sitewhere_tpu.native import load_swwire
        if load_swwire() is not None:
            assert r["fill_direct"] is True
            assert r["bytes_copied_per_event_fill_total"] == 0.0
            assert r["bytes_copied_per_event_native_total"] > 0.0
            assert r["bytes_copied_3x"] is True
            assert r["ingest_fill_s"] > 0.0


class TestFillDirectEndToEnd:
    def test_fill_path_runs_wire_to_egress_with_zero_copies(self, tmp_path):
        """Tier-1 fill-direct smoke: a real Instance (native build
        forced by the module-level skip in test_native_fill; here we
        just require it) ingests full-width NDJSON payloads through the
        zero-copy path — decode writes straight into adopted packed
        buffers — and the bytes-copied counters prove it: zero decode
        bytes, zero batch bytes, all rows accepted and egressed."""
        import json as _json

        from sitewhere_tpu.instance import Instance
        from sitewhere_tpu.native import load_swwire
        from sitewhere_tpu.runtime.config import Config

        if load_swwire() is None:
            pytest.skip("native toolchain unavailable")
        width = 64
        inst = Instance(Config({
            "instance": {"id": "fill-smoke",
                         "data_dir": str(tmp_path / "data")},
            "pipeline": {"width": width, "registry_capacity": 128,
                         "mtype_slots": 4, "deadline_ms": 60_000.0,
                         "n_shards": 1},
            "presence": {"scan_interval_s": 3600.0,
                         "missing_after_s": 1800},
        }, apply_env=False))
        inst.start()
        try:
            dm = inst.device_management
            dm.create_device_type(token="sensor", name="Sensor")
            for i in range(width):
                dm.create_device(token=f"d-{i}", device_type="sensor")
                dm.create_device_assignment(device=f"d-{i}")

            def payload(r):
                return "\n".join(_json.dumps({
                    "deviceToken": f"d-{i}", "type": "Measurement",
                    "request": {"name": "temp", "value": 1.0 + i,
                                "eventDate": 1_753_800_000 + r},
                }) for i in range(width)).encode()

            for r in range(3):
                n = inst.dispatcher.ingest_wire_lines(payload(r))
                assert n == width
            inst.dispatcher.flush()
            snap = inst.dispatcher.metrics_snapshot()
            assert snap["accepted"] == 3 * width
            reg = inst.metrics
            # the zero-copy proof: the hot path materialized NOTHING
            assert reg.counter("pipeline.bytes_copied.decode").value == 0
            assert reg.counter("pipeline.bytes_copied.batch").value == 0
            inst.event_store.flush()
            assert inst.event_store.total_events == 3 * width
            # journal carries the payloads (replayability unchanged)
            assert inst.ingest_journal.end_offset == 3
            # A/B: the same wire bytes through the classic path land the
            # same rows, with nonzero copies — the counters discriminate
            inst.dispatcher._fill_enabled = False
            assert inst.dispatcher.ingest_wire_lines(payload(3)) == width
            inst.dispatcher.flush()
            assert reg.counter("pipeline.bytes_copied.decode").value > 0
            snap = inst.dispatcher.metrics_snapshot()
            assert snap["accepted"] == 4 * width
        finally:
            inst.stop()
            inst.terminate()

    def test_fill_path_through_decode_pool_source(self, tmp_path):
        """The pooled wire lane: reservations are filled on decode-pool
        workers and committed in delivery order — per-source ordering
        and the journal offset↔row correspondence survive."""
        import json as _json

        from sitewhere_tpu.instance import Instance
        from sitewhere_tpu.native import load_swwire
        from sitewhere_tpu.runtime.config import Config

        if load_swwire() is None:
            pytest.skip("native toolchain unavailable")
        width = 32
        inst = Instance(Config({
            "instance": {"id": "fill-pool",
                         "data_dir": str(tmp_path / "data")},
            "pipeline": {"width": width, "registry_capacity": 128,
                         "mtype_slots": 4, "deadline_ms": 60_000.0,
                         "n_shards": 1},
            "ingest": {"decode_workers": 2},
            "presence": {"scan_interval_s": 3600.0,
                         "missing_after_s": 1800},
        }, apply_env=False))
        inst.start()
        try:
            dm = inst.device_management
            dm.create_device_type(token="sensor", name="Sensor")
            for i in range(width):
                dm.create_device(token=f"d-{i}", device_type="sensor")
                dm.create_device_assignment(device=f"d-{i}")
            src = InboundEventSource("pool-wire", [], decoder=lambda b: [],
                                     raw_wire=True)
            src.decode_pool = inst.decode_pool
            src.on_wire_payload = lambda p, s: \
                inst.dispatcher.ingest_wire_lines(p, source_id=s)
            src.on_wire_decode = inst.dispatcher.decode_wire_lines
            src.on_wire_decoded = inst.dispatcher.ingest_wire_decoded

            def payload(r):
                return "\n".join(_json.dumps({
                    "deviceToken": f"d-{i}", "type": "Measurement",
                    "request": {"name": "temp", "value": float(r),
                                "eventDate": 1_753_800_000 + r},
                }) for i in range(width)).encode()

            for r in range(4):
                src.on_encoded_payload(payload(r))
            assert inst.decode_pool.flush(5.0)
            inst.dispatcher.flush()
            snap = inst.dispatcher.metrics_snapshot()
            assert snap["accepted"] == 4 * width
            assert inst.metrics.counter(
                "pipeline.bytes_copied.decode").value == 0
            # delivery order held: the last value committed per device
            # is the LAST payload's
            row = inst.device_state.get_device_state("d-3")
            assert row["last_event_ts_s"] == 1_753_800_003
        finally:
            inst.stop()
            inst.terminate()


class TestStageOverlap:
    def test_host_step_p50_below_2x_device_step_and_stages_overlap(self):
        """Acceptance: with fault injection off, host_step p50 drops
        below 2× device_step — egress demonstrably overlaps the stubbed
        slow step (stage timers sum past wall clock)."""
        assert not faults.active()
        step_s, egress_s, n = 0.05, 0.04, 5
        disp, store, metrics = make_dispatcher(
            step_s=step_s, egress_s=egress_s)
        disp.start()
        try:
            # warm the numpy→jax conversion in batch emission: the
            # first call initializes the backend (~100ms) and would
            # otherwise be charged to the measured window
            ingest_window(disp)
            disp.flush()
            dispatch = metrics.timer("pipeline.stage_dispatch_s")
            egress = metrics.timer("pipeline.stage_egress_s")
            d_total0, e_total0 = dispatch.total, egress.total

            t0 = time.perf_counter()
            for _ in range(n):
                ingest_window(disp)
            disp.flush()
            wall = time.perf_counter() - t0
            assert store.rows == (n + 1) * WIDTH

            # host_step (the per-plan time the dispatch thread spends) ≈
            # the device step alone, NOT step + egress: below 2× device
            assert dispatch.count == n + 1
            assert dispatch.percentile(0.5) < 2 * step_s

            # the egress cost was actually paid — just elsewhere
            e_spent = egress.total - e_total0
            assert egress.count == n + 1
            assert e_spent >= n * egress_s * 0.9

            # serial execution would need ≥ n*(step+egress); the
            # pipeline finished well under it, and the stages' summed
            # host time exceeds wall clock — only possible overlapped.
            # (margin absorbs scheduler noise on a loaded CI machine)
            serial = n * (step_s + egress_s)
            assert wall < serial * 0.9
            assert (dispatch.total - d_total0) + e_spent > wall * 0.9
        finally:
            disp.stop()
