"""Overlapped host pipeline: decode pool ordering, egress offload, and
the stage-overlap acceptance proof (stubbed slow step).

The tentpole claim: with the host loop split into overlapped stages, the
only work left on the critical dispatch thread is batch assembly + step
launch — decode (window N+1) and egress (window N-1) run concurrently
with the device step of window N.  The proof here uses a stubbed slow
step and slow egress sink: wall clock stays near N×step while the
per-stage timers (``pipeline.stage_*_s``) show the full egress cost was
paid — their totals exceed wall elapsed, which is only possible when
the stages overlap.
"""

import threading
import time

import numpy as np
import pytest

from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.ingest.batcher import Batcher
from sitewhere_tpu.ingest.sources import DecodePool
from sitewhere_tpu.pipeline.step import StepMetrics
from sitewhere_tpu.runtime import faults
from sitewhere_tpu.runtime.dispatcher import PipelineDispatcher
from sitewhere_tpu.runtime.metrics import MetricsRegistry

WIDTH = 8


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.002)
    return cond()


# ---------------------------------------------------------------------------
# decode pool: parallel decode, ordered delivery
# ---------------------------------------------------------------------------

class TestDecodePool:
    def test_parallel_decode_delivers_in_submission_order(self):
        pool = DecodePool(workers=4, max_pending=64)
        try:
            delivered = []
            done = threading.Event()
            n = 12

            def work(i):
                # later jobs finish FIRST (reverse sleep) — only the
                # ordered-delivery lane keeps the output in order
                time.sleep(0.002 * (n - i))
                return i

            def deliver(result, exc):
                assert exc is None
                delivered.append(result)
                if len(delivered) == n:
                    done.set()

            t0 = time.perf_counter()
            for i in range(n):
                pool.submit("src", lambda i=i: work(i), deliver)
            assert done.wait(10.0)
            wall = time.perf_counter() - t0
            assert delivered == list(range(n))
            # 4 workers: wall must beat the serial sum (overlap proof)
            serial = sum(0.002 * (n - i) for i in range(n))
            assert wall < serial
        finally:
            pool.stop()

    def test_independent_keys_do_not_serialize(self):
        pool = DecodePool(workers=2, max_pending=64)
        try:
            got = []
            evt = threading.Event()

            def deliver(result, exc):
                got.append(result)
                if len(got) == 2:
                    evt.set()

            # "a" blocks until "b" has started: deliverable only if the
            # two keys decode concurrently (serialized lanes would leave
            # "a" waiting out the timeout and return the failure marker)
            b_started = threading.Event()
            pool.submit(
                "a", lambda: "a" if b_started.wait(5.0) else "a-stalled",
                deliver)
            pool.submit("b", lambda: b_started.set() or "b", deliver)
            assert evt.wait(10.0)
            assert sorted(got) == ["a", "b"]
        finally:
            pool.stop()

    def test_decode_error_routes_to_deliver_in_order(self):
        pool = DecodePool(workers=2, max_pending=8)
        try:
            seen = []
            done = threading.Event()

            def deliver(result, exc):
                seen.append((result, type(exc).__name__ if exc else None))
                if len(seen) == 3:
                    done.set()

            def boom():
                raise ValueError("bad payload")

            pool.submit("k", lambda: 1, deliver)
            pool.submit("k", boom, deliver)
            pool.submit("k", lambda: 3, deliver)
            assert done.wait(5.0)
            assert seen == [(1, None), (None, "ValueError"), (3, None)]
        finally:
            pool.stop()

    def test_submit_backpressure_blocks_at_max_pending(self):
        pool = DecodePool(workers=1, max_pending=2)
        try:
            release = threading.Event()
            pool.submit("k", lambda: release.wait(10), lambda r, e: None)
            pool.submit("k", lambda: None, lambda r, e: None)
            # budget exhausted: the third submit must block until a slot
            # frees — the receiver-thread backpressure contract
            unblocked = threading.Event()

            def third():
                pool.submit("k", lambda: None, lambda r, e: None)
                unblocked.set()

            t = threading.Thread(target=third, daemon=True)
            t.start()
            assert not unblocked.wait(0.15)
            release.set()
            assert unblocked.wait(5.0)
            assert pool.flush(5.0)
        finally:
            release.set()
            pool.stop()

    def test_stopped_pool_degrades_to_synchronous(self):
        pool = DecodePool(workers=1, max_pending=2)
        pool.stop()
        got = []
        pool.submit("k", lambda: 41, lambda r, e: got.append((r, e)))
        assert got == [(41, None)]

    def test_deliver_raising_base_exception_does_not_kill_worker(self):
        pool = DecodePool(workers=1, max_pending=8)
        try:
            got = []
            done = threading.Event()

            def bad_deliver(result, exc):
                raise SystemExit(3)  # a deliver re-raising a decode-stage
                # BaseException must not end the worker thread

            pool.submit("k", lambda: 1, bad_deliver)
            pool.submit("k", lambda: 2,
                        lambda r, e: (got.append(r), done.set()))
            assert done.wait(5.0)
            assert got == [2]
            assert pool.delivery_errors == 1
        finally:
            pool.stop()


# ---------------------------------------------------------------------------
# dispatcher fixture with a stubbed (slow) step
# ---------------------------------------------------------------------------

class FakeOut:
    """Duck-types the slice of PipelineOutputs the egress path consumes."""

    def __init__(self, n):
        z = np.zeros(n, np.int32)
        self.accepted = np.ones(n, bool)
        self.unregistered = np.zeros(n, bool)
        self.present_now = None
        self.device_type_id = z
        self.assignment_id = z
        self.area_id = z
        self.customer_id = z
        self.asset_id = z
        self.metrics = StepMetrics(
            processed=np.int32(n), accepted=np.int32(n),
            unregistered=np.int32(0), unassigned=np.int32(0),
            threshold_alerts=np.int32(0), zone_alerts=np.int32(0),
            by_type=np.zeros(6, np.int32))


class FakeStateManager:
    current = None
    current_packed = None

    def commit(self, new_state, present_now=None):
        pass


class SlowStore:
    """Event-store stand-in whose append costs ``delay_s`` host time."""

    def __init__(self, delay_s=0.0):
        self.delay_s = delay_s
        self.rows = 0
        self.batches = 0
        self.append_threads = set()

    def append_columns(self, cols, mask=None):
        self.append_threads.add(threading.current_thread().name)
        if self.delay_s:
            time.sleep(self.delay_s)
        self.rows += int(mask.sum()) if mask is not None \
            else len(cols["device_id"])
        self.batches += 1

    def flush(self):
        pass


def make_dispatcher(step_s=0.0, egress_s=0.0, egress_offload=True,
                    inflight_depth=1, **kw):
    metrics = MetricsRegistry()
    batcher = Batcher(
        width=WIDTH, n_shards=1, registry_capacity=64,
        resolve_device=lambda t: NULL_ID, resolve_mtype=lambda n: 0,
        resolve_alert=lambda n: 0, deadline_ms=60_000.0)
    store = SlowStore(egress_s)
    disp = PipelineDispatcher(
        batcher=batcher,
        registry_provider=lambda: None,
        state_manager=FakeStateManager(),
        rules_provider=lambda: None,
        zones_provider=lambda: None,
        event_store=store,
        inflight_depth=inflight_depth,
        egress_offload=egress_offload,
        metrics=metrics,
        **kw,
    )

    def slow_step(registry, state, rules, zones, batch):
        if step_s:
            time.sleep(step_s)  # the stubbed "device step"
        return state, FakeOut(WIDTH)

    disp._step = slow_step
    return disp, store, metrics


def ingest_window(disp):
    disp.ingest_arrays(device_id=np.arange(WIDTH, dtype=np.int32))


# ---------------------------------------------------------------------------
# egress offload semantics
# ---------------------------------------------------------------------------

class TestEgressOffload:
    def test_flush_drains_the_offload_queue(self):
        disp, store, _ = make_dispatcher(egress_s=0.01)
        disp.start()
        try:
            for _ in range(4):
                ingest_window(disp)
            disp.flush()
            # flush's contract: every row ingested BEFORE the call has
            # completed egress on return — offloaded or not
            assert store.rows == 4 * WIDTH
            assert not disp._inflight
            with disp._lock:
                assert disp._plans_outstanding == 0
        finally:
            disp.stop()

    def test_egress_runs_off_the_dispatch_thread(self):
        disp, store, _ = make_dispatcher(egress_s=0.0)
        disp.start()
        try:
            ingest_window(disp)
            disp.flush()
            assert store.rows == WIDTH
            # offloaded: the append ran on the supervised egress worker,
            # not on this (ingesting) thread and not on the loop thread
            assert all("egress" in t for t in store.append_threads)
        finally:
            disp.stop()

    def test_offload_disabled_is_inline_and_needs_no_threads(self):
        disp, store, _ = make_dispatcher(egress_offload=False)
        # no start(): the inline path must work exactly as before
        ingest_window(disp)
        disp.flush()
        assert store.rows == WIDTH
        assert all("egress" not in t for t in store.append_threads)

    def test_unstarted_dispatcher_degrades_to_inline(self):
        disp, store, _ = make_dispatcher(egress_offload=True)
        ingest_window(disp)
        disp.flush()
        assert store.rows == WIDTH

    def test_backpressure_bounds_the_window(self):
        disp, store, _ = make_dispatcher(egress_s=0.05, inflight_depth=1)
        disp.start()
        try:
            for _ in range(6):
                ingest_window(disp)
                # the dispatch side may run ahead of egress by at most
                # the bounded window (queued) + one in-progress item
                assert len(disp._inflight) <= disp.egress_queue_depth
            disp.flush()
            assert store.rows == 6 * WIDTH
        finally:
            disp.stop()

    def test_egress_crash_fails_closed_and_worker_recovers(self):
        """An egress fault kills the WORKER mid-window: its supervisor
        restarts the loop, sibling plans still drain, and the dead
        plan's accounting keeps the commit gate closed forever (the
        at-least-once rule: never commit past an un-egressed plan)."""
        faults.clear()
        disp, store, _ = make_dispatcher(egress_s=0.0)
        disp.start()
        try:
            faults.inject("dispatcher.egress", times=1)
            ingest_window(disp)           # this plan's egress dies
            assert _wait(lambda: faults.fired("dispatcher.egress") == 1)
            ingest_window(disp)           # sibling must still egress
            disp.flush(timeout_s=1.0)
            assert store.rows == WIDTH    # only the sibling landed
            assert disp.egress_failures == 1
            assert _wait(lambda: disp._egress_super.restarts >= 1)
            assert not disp._egress_super.escalated
            with disp._lock:
                # the dead plan is still outstanding: gate failed closed
                assert disp._plans_outstanding == 1
        finally:
            faults.clear()
            disp.stop()


# ---------------------------------------------------------------------------
# the overlap acceptance proof
# ---------------------------------------------------------------------------

class TestHostpathBenchSmoke:
    def test_tool_reports_every_stage(self, tmp_path):
        """tools/hostpath_bench.py must run end-to-end and report a
        positive per-stage breakdown (tier-1 smoke: the tool is how a
        stage regression localizes)."""
        import importlib.util
        import os

        path = os.path.join(os.path.dirname(__file__), os.pardir,
                            "tools", "hostpath_bench.py")
        spec = importlib.util.spec_from_file_location("hostpath_bench", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        r = mod.run(width=128, iters=2, capacity=1024,
                    data_dir=str(tmp_path))
        for key in ("decode_s", "batch_s", "dispatch_s", "egress_s",
                    "seal_s", "serial_s", "pipeline_bound_s"):
            assert r[key] > 0.0, key
        assert r["pipeline_bound_s"] <= r["serial_s"]
        assert r["overlapped_events_per_s"] >= r["serial_events_per_s"]


class TestStageOverlap:
    def test_host_step_p50_below_2x_device_step_and_stages_overlap(self):
        """Acceptance: with fault injection off, host_step p50 drops
        below 2× device_step — egress demonstrably overlaps the stubbed
        slow step (stage timers sum past wall clock)."""
        assert not faults.active()
        step_s, egress_s, n = 0.05, 0.04, 5
        disp, store, metrics = make_dispatcher(
            step_s=step_s, egress_s=egress_s)
        disp.start()
        try:
            # warm the numpy→jax conversion in batch emission: the
            # first call initializes the backend (~100ms) and would
            # otherwise be charged to the measured window
            ingest_window(disp)
            disp.flush()
            dispatch = metrics.timer("pipeline.stage_dispatch_s")
            egress = metrics.timer("pipeline.stage_egress_s")
            d_total0, e_total0 = dispatch.total, egress.total

            t0 = time.perf_counter()
            for _ in range(n):
                ingest_window(disp)
            disp.flush()
            wall = time.perf_counter() - t0
            assert store.rows == (n + 1) * WIDTH

            # host_step (the per-plan time the dispatch thread spends) ≈
            # the device step alone, NOT step + egress: below 2× device
            assert dispatch.count == n + 1
            assert dispatch.percentile(0.5) < 2 * step_s

            # the egress cost was actually paid — just elsewhere
            e_spent = egress.total - e_total0
            assert egress.count == n + 1
            assert e_spent >= n * egress_s * 0.9

            # serial execution would need ≥ n*(step+egress); the
            # pipeline finished well under it, and the stages' summed
            # host time exceeds wall clock — only possible overlapped.
            # (margin absorbs scheduler noise on a loaded CI machine)
            serial = n * (step_s + egress_s)
            assert wall < serial * 0.9
            assert (dispatch.total - d_total0) + e_spent > wall * 0.9
        finally:
            disp.stop()
