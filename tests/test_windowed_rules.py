"""Windowed (EWMA) + rate-of-change rules — round-2 verdict item #7.

Reference SPI surface: ``service-rule-processing/.../spi/IRuleProcessor.
java:50-97`` (per-event callbacks; windowed logic would be host-side
processor state).  Here the trailing stats are DeviceState tensors and
every rule kind evaluates in the same fused [B, R] pass.
"""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from sitewhere_tpu.pipeline.step import pipeline_step
from sitewhere_tpu.schema import (
    AssignmentStatus,
    ComparisonOp,
    DeviceState,
    EventBatch,
    Registry,
    RuleKind,
    RuleTable,
    ZoneTable,
)

CAP = 32
T0 = 1_753_800_000


def _tables():
    idx = jnp.arange(CAP)
    on = idx < 8
    registry = Registry.empty(CAP).replace(
        active=on,
        tenant_id=jnp.where(on, 0, -1),
        device_type_id=jnp.where(on, 0, -1),
        assignment_id=jnp.where(on, idx, -1),
        assignment_status=jnp.where(on, AssignmentStatus.ACTIVE, 0),
    )
    state = DeviceState.empty(CAP, num_mtype_slots=4, num_ewma_scales=3)
    zones = ZoneTable.empty(4, max_verts=8)
    return registry, state, zones


def _rule(kind, op, threshold, window_s=None, taus=(60.0, 600.0, 3600.0)):
    rules = RuleTable.empty(8, ewma_taus=taus)
    widx = 0
    if window_s is not None:
        widx = int(np.argmin(np.abs(np.asarray(taus) - window_s)))
    return rules.replace(
        active=rules.active.at[0].set(True),
        mtype_id=rules.mtype_id.at[0].set(0),
        op=rules.op.at[0].set(int(op)),
        threshold=rules.threshold.at[0].set(threshold),
        alert_code=rules.alert_code.at[0].set(7),
        kind=rules.kind.at[0].set(int(kind)),
        window_idx=rules.window_idx.at[0].set(widx),
    )


def _batch(device_id, value, ts_s):
    n = len(device_id)
    return EventBatch.empty(n).replace(
        valid=jnp.ones(n, bool),
        device_id=jnp.asarray(device_id, jnp.int32),
        tenant_id=jnp.zeros(n, jnp.int32),
        event_type=jnp.zeros(n, jnp.int32),  # MEASUREMENT
        ts_s=jnp.asarray(ts_s, jnp.int32),
        mtype_id=jnp.zeros(n, jnp.int32),
        value=jnp.asarray(value, jnp.float32),
        update_state=jnp.ones(n, bool),
    )


def test_window_mean_rule_smooths_spikes():
    """One spike does not move a long EWMA past the threshold; a sustained
    elevation does."""
    registry, state, zones = _tables()
    rules = _rule(RuleKind.WINDOW_MEAN, ComparisonOp.GT, 50.0,
                  window_s=600.0)

    # seed: steady 10.0
    state, out = pipeline_step(registry, state, rules, zones,
                               _batch([0], [10.0], [T0]))
    assert int(out.metrics.threshold_alerts) == 0

    # a single 1000.0 spike after 1s: alpha = 1-exp(-1/600) ≈ 0.0017 →
    # ewma ≈ 11.7, far below 50 (an INSTANT rule would have fired)
    state, out = pipeline_step(registry, state, rules, zones,
                               _batch([0], [1000.0], [T0 + 1]))
    assert int(out.metrics.threshold_alerts) == 0

    # sustained 100.0 for ~3 windows pushes the EWMA over 50
    t = T0 + 1
    fired = 0
    for i in range(6):
        t += 300
        state, out = pipeline_step(registry, state, rules, zones,
                                   _batch([0], [100.0], [t]))
        fired += int(out.metrics.threshold_alerts)
    assert fired >= 1


def test_ewma_matches_closed_form():
    registry, state, zones = _tables()
    rules = _rule(RuleKind.WINDOW_MEAN, ComparisonOp.GT, 1e9,
                  window_s=60.0)
    state, _ = pipeline_step(registry, state, rules, zones,
                             _batch([0], [10.0], [T0]))
    state, _ = pipeline_step(registry, state, rules, zones,
                             _batch([0], [20.0], [T0 + 30]))
    alpha = 1.0 - math.exp(-30.0 / 60.0)
    expect = 10.0 + alpha * (20.0 - 10.0)
    got = float(np.asarray(state.ewma_values)[0, 0, 0])
    assert got == pytest.approx(expect, rel=1e-5)


def test_rate_rule_fires_on_fast_change_only():
    registry, state, zones = _tables()
    # fire when value rises faster than 5 units/second
    rules = _rule(RuleKind.RATE_PER_S, ComparisonOp.GT, 5.0)

    # first sample: no previous → cannot fire
    state, out = pipeline_step(registry, state, rules, zones,
                               _batch([0], [10.0], [T0]))
    assert int(out.metrics.threshold_alerts) == 0

    # +4 units over 2s = 2/s → below
    state, out = pipeline_step(registry, state, rules, zones,
                               _batch([0], [14.0], [T0 + 2]))
    assert int(out.metrics.threshold_alerts) == 0

    # +40 units over 2s = 20/s → fires
    state, out = pipeline_step(registry, state, rules, zones,
                               _batch([0], [54.0], [T0 + 4]))
    assert int(out.metrics.threshold_alerts) == 1
    assert int(np.asarray(out.rule_id)[0]) == 0


def test_instant_rules_unchanged():
    registry, state, zones = _tables()
    rules = _rule(RuleKind.INSTANT, ComparisonOp.GT, 90.0)
    state, out = pipeline_step(registry, state, rules, zones,
                               _batch([0, 1], [95.0, 10.0], [T0, T0]))
    assert int(out.metrics.threshold_alerts) == 1


def test_rule_manager_publishes_kinds(tmp_path):
    from sitewhere_tpu.ids import IdentityMap
    from sitewhere_tpu.pipeline.rules import RuleManager

    rm = RuleManager(IdentityMap(64),
                     ewma_halflives_s=(60.0, 600.0, 3600.0))
    rm.create_rule(mtype="temp", op=ComparisonOp.GT, threshold=50.0,
                   alert_type="hot", kind=RuleKind.WINDOW_MEAN,
                   window_s=500.0, token="w")
    rm.create_rule(mtype="temp", op=ComparisonOp.GT, threshold=5.0,
                   alert_type="spike", kind=RuleKind.RATE_PER_S, token="r")
    table = rm.publish()
    slots = {t: rm._slots[t] for t in ("w", "r")}
    kinds = np.asarray(table.kind)
    widx = np.asarray(table.window_idx)
    assert kinds[slots["w"]] == int(RuleKind.WINDOW_MEAN)
    assert widx[slots["w"]] == 1  # 500s snaps to the 600s scale
    assert kinds[slots["r"]] == int(RuleKind.RATE_PER_S)

    from sitewhere_tpu.services.common import ValidationError
    with pytest.raises(ValidationError):
        rm.create_rule(mtype="x", op=ComparisonOp.GT, threshold=1.0,
                       alert_type="a", kind=RuleKind.WINDOW_MEAN)


def test_windowed_rule_through_instance(tmp_path):
    """End-to-end: a rate rule created through the instance rule manager
    fires a derived alert through the live dispatcher."""
    from sitewhere_tpu.instance import Instance
    from sitewhere_tpu.runtime.config import Config

    cfg = Config({
        "instance": {"id": "wr-e2e", "data_dir": str(tmp_path / "d")},
        "pipeline": {"width": 32, "registry_capacity": 64,
                     "mtype_slots": 4, "deadline_ms": 5.0, "n_shards": 1},
        "presence": {"scan_interval_s": 3600.0, "missing_after_s": 1800},
    }, apply_env=False)
    inst = Instance(cfg)
    inst.start()
    try:
        dm = inst.device_management
        dm.create_device_type(token="sensor", name="S")
        dm.create_device(token="d-0", device_type="sensor")
        dm.create_device_assignment(device="d-0")
        inst.rules.create_rule(mtype="temp", op=ComparisonOp.GT,
                               threshold=5.0, alert_type="spike",
                               kind=RuleKind.RATE_PER_S, token="rr")
        h = inst.identity.device.lookup("d-0")
        m = inst.identity.mtype.mint("temp")

        def send(value, ts):
            inst.dispatcher.ingest_arrays(
                device_id=np.asarray([h], np.int32),
                event_type=np.zeros(1, np.int32),
                ts_s=np.asarray([ts], np.int32),
                mtype_id=np.asarray([m], np.int32),
                value=np.asarray([value], np.float32),
            )
            inst.dispatcher.flush()
            inst.dispatcher.flush()

        send(10.0, T0)
        send(11.0, T0 + 10)   # 0.1/s — quiet
        assert inst.dispatcher.metrics_snapshot()["threshold_alerts"] == 0
        send(200.0, T0 + 12)  # 94.5/s — fires
        snap = inst.dispatcher.metrics_snapshot()
        assert snap["threshold_alerts"] == 1
        assert snap["derived_alerts"] == 1
    finally:
        inst.stop()
        inst.terminate()
