"""Mesh topology + routing tests (8 virtual CPU devices)."""

import jax
import numpy as np
import pytest

from sitewhere_tpu.parallel import MeshSpec, make_mesh, shard_for_device
from sitewhere_tpu.parallel.mesh import (
    SHARD_AXIS,
    MODEL_AXIS,
    event_sharding,
    registry_sharding,
    replicated,
)


def test_cpu_backend_has_8_devices(devices):
    assert len(devices) == 8
    assert all(d.platform == "cpu" for d in devices)


def test_make_mesh_shapes(mesh8):
    assert mesh8.shape[SHARD_AXIS] == 8
    assert mesh8.shape[MODEL_AXIS] == 1


def test_make_mesh_model_parallel():
    m = make_mesh(8, model_parallel=2)
    assert m.shape[SHARD_AXIS] == 4
    assert m.shape[MODEL_AXIS] == 2
    with pytest.raises(ValueError):
        make_mesh(8, model_parallel=3)


def test_mesh_spec():
    spec = MeshSpec(n_shards=4, model_parallel=2)
    assert spec.n_devices == 8


def test_sharding_placement(mesh8):
    import jax.numpy as jnp

    x = jnp.zeros((1024,))
    xs = jax.device_put(x, event_sharding(mesh8))
    # block-sharded: each device holds 128 contiguous rows
    assert xs.sharding.shard_shape(x.shape) == (128,)
    r = jax.device_put(jnp.zeros((64,)), replicated(mesh8))
    assert r.sharding.shard_shape((64,)) == (64,)


def test_shard_for_device_matches_block_sharding(mesh8):
    """Host routing must agree with XLA's block-sharding of the registry."""
    import jax.numpy as jnp

    capacity, n_shards = 4096, 8
    reg_col = jax.device_put(
        jnp.arange(capacity, dtype=jnp.int32), registry_sharding(mesh8)
    )
    # For each shard, the device rows XLA placed there:
    for shard_idx, piece in enumerate(reg_col.addressable_shards):
        rows = np.asarray(piece.data)
        for d in (int(rows[0]), int(rows[-1])):
            assert shard_for_device(d, capacity, n_shards) == piece.index[0].start // (
                capacity // n_shards
            ) == shard_idx
