"""Overload control: watermark state machine, priority admission,
protocol-native backpressure, and the degradation ladder.

The controller itself is verified deterministically (injected clock, no
sleeps): hysteresis keeps the state while signals sit between the exit
and enter watermarks, and de-escalation lands within exactly ONE
cooldown of the load dropping.  The integration tests force states
through the ops hook and prove the layer contracts: CRITICAL events
always reach seal, telemetry sheds are counted + dead-lettered +
signalled natively (HTTP 429/Retry-After, CoAP 5.03/Max-Age, withheld
PUBACK, unacked STOMP/AMQP deliveries), and shed payloads are
replayable through the dead-letter requeue path.
"""

import json
import socket
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.runtime.overload import (
    OverloadController,
    OverloadShed,
    OverloadSignals,
    OverloadState,
    PriorityClass,
    TokenBucket,
    Watermarks,
    classify_event_type,
)


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.005)
    return cond()


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _controller(clock, **kw):
    kw.setdefault("cooldown_s", 2.0)
    kw.setdefault("metrics", MetricsRegistry())
    return OverloadController(clock=clock, **kw)


# ---------------------------------------------------------------------------
# the state machine: escalation, hysteresis, cooldown — deterministic
# ---------------------------------------------------------------------------

class TestStateMachine:
    def test_escalates_immediately_on_enter_watermark(self):
        clock = FakeClock()
        c = _controller(clock)
        assert c.state == OverloadState.NORMAL
        assert c.observe(OverloadSignals(batcher_backlog=1.5)) \
            == OverloadState.DEGRADED
        # jumps straight to the justified level, no rung-by-rung climb
        assert c.observe(OverloadSignals(seal_lag_s=3.0)) \
            == OverloadState.EMERGENCY
        assert c.transitions == 2
        assert c.last_driver == "seal_lag_s"

    def test_hysteresis_holds_state_between_exit_and_enter(self):
        clock = FakeClock()
        c = _controller(clock, hysteresis=0.7)
        c.observe(OverloadSignals(batcher_backlog=4.5))
        assert c.state == OverloadState.SHEDDING
        # 3.0 is below the SHEDDING enter (4.0) but above its exit
        # (4.0 * 0.7 = 2.8): the state must HOLD however long it lasts
        for _ in range(10):
            clock.t += 10.0
            assert c.observe(OverloadSignals(batcher_backlog=3.0)) \
                == OverloadState.SHEDDING

    def test_deescalates_within_one_cooldown_of_load_drop(self):
        clock = FakeClock()
        c = _controller(clock, cooldown_s=2.0)
        c.observe(OverloadSignals(egress_inflight=2.0))
        assert c.state == OverloadState.EMERGENCY
        calm = OverloadSignals()
        clock.t += 0.5
        assert c.observe(calm) == OverloadState.EMERGENCY  # cooldown starts
        clock.t += 1.9
        assert c.observe(calm) == OverloadState.EMERGENCY  # 1.9s < 2.0s
        clock.t += 0.2
        # one cooldown after the drop: straight to NORMAL, not one rung
        assert c.observe(calm) == OverloadState.NORMAL

    def test_spike_during_cooldown_restarts_it(self):
        clock = FakeClock()
        c = _controller(clock, cooldown_s=2.0)
        c.observe(OverloadSignals(decode_backlog=0.9))
        assert c.state == OverloadState.SHEDDING
        clock.t += 1.9
        c.observe(OverloadSignals())          # almost recovered...
        c.observe(OverloadSignals(decode_backlog=0.9))  # ...spike
        clock.t += 1.9
        # the spike restarted the cooldown: 1.9s below is not enough
        assert c.observe(OverloadSignals()) == OverloadState.SHEDDING
        clock.t += 2.1
        assert c.observe(OverloadSignals()) == OverloadState.NORMAL

    def test_confirm_samples_rejects_one_sample_spikes(self):
        """A single slow plan pinning a last-value gauge (a jit
        compile, one disk stall) is a spike, not sustained overload:
        with confirm_samples=2 the enter watermark must hold for two
        consecutive samples before the ladder moves."""
        clock = FakeClock()
        c = _controller(clock, confirm_samples=2)
        hot = OverloadSignals(seal_lag_s=3.0)
        assert c.observe(hot) == OverloadState.NORMAL   # 1st: pending
        assert c.observe(OverloadSignals()) == OverloadState.NORMAL
        assert c.observe(hot) == OverloadState.NORMAL   # count restarted
        assert c.observe(hot) == OverloadState.EMERGENCY  # confirmed
        # a streak whose level varies escalates to the MINIMUM level it
        # sustained — every sample justified at least DEGRADED
        c2 = _controller(clock, confirm_samples=2)
        c2.observe(OverloadSignals(seal_lag_s=3.0))     # EMERGENCY-level
        assert c2.observe(OverloadSignals(seal_lag_s=0.2)) \
            == OverloadState.DEGRADED                   # confirmed at min

    def test_flapping_signal_still_escalates_to_sustained_level(self):
        """Regression: a noisy signal straddling one watermark boundary
        (levels 1,2,1,2,…) used to restart the confirmation count on
        every sample and NEVER escalate, leaving admission off under
        genuine sustained overload."""
        c = _controller(FakeClock(), confirm_samples=3)
        for i in range(3):
            level = c.observe(OverloadSignals(
                seal_lag_s=0.55 if i % 2 else 0.12))
        assert level == OverloadState.DEGRADED   # min sustained level

    def test_pending_escalation_restarts_the_cooldown(self):
        """Regression: an above-watermark sample that merely ARMED the
        escalation confirmation (without transitioning) must still
        restart the de-escalation cooldown — the contract is cooldown_s
        of CONTINUOUS calm."""
        clock = FakeClock()
        c = _controller(clock, cooldown_s=2.0, confirm_samples=2)
        c.force(OverloadState.DEGRADED)
        c.observe(OverloadSignals())              # calm: cooldown starts
        clock.t += 1.95
        # one spike above the SHEDDING enter watermark — not confirmed,
        # no transition, but it breaks the continuous calm
        c.observe(OverloadSignals(seal_lag_s=0.55))
        clock.t += 0.05
        assert c.observe(OverloadSignals()) == OverloadState.DEGRADED
        clock.t += 2.1   # a FULL cooldown after the spike
        assert c.observe(OverloadSignals()) == OverloadState.NORMAL

    def test_transition_metrics_and_snapshot(self):
        clock = FakeClock()
        reg = MetricsRegistry()
        c = _controller(clock, metrics=reg)
        seen = []
        c.on_transition(lambda old, new, sig: seen.append((old, new)))
        c.observe(OverloadSignals(fsync_latency_s=0.3))
        assert seen == [(OverloadState.NORMAL, OverloadState.SHEDDING)]
        assert reg.gauge("overload.state").value == 2
        assert reg.counter("overload.transitions.to_shedding").value == 1
        snap = c.snapshot()
        assert snap["state"] == "SHEDDING"
        assert snap["driver"] == "fsync_latency_s"
        assert snap["signals"]["fsync_latency_s"] == 0.3

    def test_watermark_overrides_validate(self):
        w = Watermarks().replace({"batcher_backlog": [0.1, 0.2, 0.3]})
        assert w.batcher_backlog == (0.1, 0.2, 0.3)
        with pytest.raises(ValueError):
            Watermarks().replace({"nope": [1, 2, 3]})
        with pytest.raises(ValueError):
            Watermarks().replace({"seal_lag_s": [3, 2, 1]})


# ---------------------------------------------------------------------------
# admission: priority classes + token buckets
# ---------------------------------------------------------------------------

class TestAdmission:
    def test_classification(self):
        from sitewhere_tpu.schema import EventType

        assert classify_event_type(EventType.MEASUREMENT) \
            == PriorityClass.TELEMETRY
        assert classify_event_type(EventType.LOCATION) \
            == PriorityClass.TELEMETRY
        assert classify_event_type(EventType.ALERT) == PriorityClass.CRITICAL
        assert classify_event_type(EventType.COMMAND_RESPONSE) \
            == PriorityClass.CRITICAL
        assert classify_event_type(EventType.COMMAND_INVOCATION) \
            == PriorityClass.COMMAND
        assert classify_event_type(99) == PriorityClass.COMMAND

    def test_critical_never_shed_even_in_emergency(self):
        clock = FakeClock()
        c = _controller(clock)
        c.force(OverloadState.EMERGENCY)
        for _ in range(100):
            assert c.admit(PriorityClass.CRITICAL)
        assert c.shed_total == 0

    def test_telemetry_rate_limited_in_degraded(self):
        clock = FakeClock()
        c = _controller(clock, degraded_telemetry_rate_per_s=10.0,
                        degraded_telemetry_burst=5.0)
        c.force(OverloadState.DEGRADED)
        assert c.admit(PriorityClass.TELEMETRY, n=5)   # burst
        assert not c.admit(PriorityClass.TELEMETRY, n=5)  # bucket empty
        clock.t += 0.5   # refill 5 tokens at 10/s
        assert c.admit(PriorityClass.TELEMETRY, n=5)

    def test_telemetry_refused_outright_in_shedding(self):
        c = _controller(FakeClock())
        c.force(OverloadState.SHEDDING)
        assert not c.admit(PriorityClass.TELEMETRY)
        assert c.admit(PriorityClass.COMMAND)   # bucket still has burst
        c.force(OverloadState.EMERGENCY)
        assert not c.admit(PriorityClass.COMMAND)

    def test_per_tenant_buckets_isolate(self):
        clock = FakeClock()
        c = _controller(clock, degraded_telemetry_rate_per_s=1.0,
                        degraded_telemetry_burst=2.0)
        c.force(OverloadState.DEGRADED)
        assert c.admit(PriorityClass.TELEMETRY, tenant="a", n=2)
        assert not c.admit(PriorityClass.TELEMETRY, tenant="a", n=1)
        # tenant b's bucket is untouched by a's exhaustion
        assert c.admit(PriorityClass.TELEMETRY, tenant="b", n=2)

    def test_shed_counters_per_class_and_tenant(self):
        reg = MetricsRegistry()
        c = _controller(FakeClock(), metrics=reg)
        c.force(OverloadState.SHEDDING)
        c.admit(PriorityClass.TELEMETRY, tenant="acme", n=7)
        assert reg.counter("overload.shed.telemetry").value == 7
        assert reg.counter("tenant.shed.acme").value == 7
        assert c.shed_total == 7

    def test_buckets_reset_on_return_to_normal(self):
        clock = FakeClock()
        c = _controller(clock, degraded_telemetry_rate_per_s=1.0,
                        degraded_telemetry_burst=1.0)
        c.force(OverloadState.DEGRADED)
        assert c.admit(PriorityClass.TELEMETRY)
        assert not c.admit(PriorityClass.TELEMETRY)
        c.force(OverloadState.NORMAL)
        c.force(OverloadState.DEGRADED)
        assert c.admit(PriorityClass.TELEMETRY)   # fresh burst

    def test_retry_after_scales_with_severity(self):
        c = _controller(FakeClock(), retry_after_s=2.0)
        c.force(OverloadState.DEGRADED)
        assert c.retry_after() == 2.0
        c.force(OverloadState.EMERGENCY)
        assert c.retry_after() == 6.0

    def test_token_bucket_refill(self):
        clock = FakeClock()
        b = TokenBucket(rate_per_s=2.0, burst=4.0, clock=clock)
        assert b.try_take(4)
        assert not b.try_take(1)
        clock.t += 1.0
        assert b.try_take(2)


# ---------------------------------------------------------------------------
# degradation ladder
# ---------------------------------------------------------------------------

class TestDegradationLadder:
    def test_optional_off_from_degraded(self):
        c = _controller(FakeClock())
        assert c.allow_optional("labels")
        c.force(OverloadState.DEGRADED)
        assert not c.allow_optional("labels")

    def test_fanout_sheds_non_priority_from_shedding(self):
        c = _controller(FakeClock())
        c.force(OverloadState.DEGRADED)
        assert c.allow_fanout(priority=False)   # DEGRADED keeps fan-out
        c.force(OverloadState.SHEDDING)
        assert not c.allow_fanout(priority=False)
        assert c.allow_fanout(priority=True)    # alert notifiers flow

    def test_outbound_manager_sheds_only_non_priority(self):
        from sitewhere_tpu.outbound.connectors import CallbackConnector
        from sitewhere_tpu.outbound.manager import OutboundConnectorsManager

        c = _controller(FakeClock())
        bulk_got, alert_got = [], []
        bulk = CallbackConnector(
            "bulk-indexer", lambda cols, m: bulk_got.append(int(m.sum())))
        alerts = CallbackConnector(
            "alert-notifier", lambda cols, m: alert_got.append(int(m.sum())),
            priority=True)
        mgr = OutboundConnectorsManager([bulk, alerts], overload=c)
        mgr.start()
        try:
            cols = {"device_id": np.arange(4, dtype=np.int32)}
            mask = np.ones(4, bool)
            c.force(OverloadState.SHEDDING)
            mgr.submit(cols, mask)
            mgr.drain(5.0)
            assert alert_got == [4]
            assert bulk_got == []
            assert mgr._workers["bulk-indexer"].overload_shed == 1
            c.force(OverloadState.NORMAL)
            mgr.submit(cols, mask)
            mgr.drain(5.0)
            assert bulk_got == [4]
        finally:
            mgr.stop()

    def test_label_generation_refuses_under_load(self):
        from sitewhere_tpu.labels.manager import LabelGeneratorManager
        from sitewhere_tpu.services.common import ServiceUnavailable

        c = _controller(FakeClock())
        mgr = LabelGeneratorManager()
        mgr.load_gate = c.allow_optional
        assert mgr.generate_png("default", "device", "d-1")
        c.force(OverloadState.DEGRADED)
        with pytest.raises(ServiceUnavailable):
            mgr.generate_png("default", "device", "d-1")
        assert mgr.refused_under_load == 1
        c.force(OverloadState.NORMAL)
        assert mgr.generate_png("default", "device", "d-1")

    def test_outbound_drain_wakes_without_polling(self):
        """Satellite regression: drain used to spin on unfinished_tasks
        at 5ms; it now blocks on the queue's all_tasks_done condition —
        a finished batch wakes it immediately and an unmet deadline
        returns on time."""
        from sitewhere_tpu.outbound.connectors import CallbackConnector
        from sitewhere_tpu.outbound.manager import OutboundConnectorsManager

        release = []

        def slow(cols, mask):
            _wait(lambda: release, timeout=5.0)

        mgr = OutboundConnectorsManager([CallbackConnector("slow", slow)])
        mgr.start()
        try:
            cols = {"device_id": np.arange(2, dtype=np.int32)}
            mgr.submit(cols, np.ones(2, bool))
            t0 = time.monotonic()
            mgr.drain(timeout=0.2)           # deadline honored...
            assert time.monotonic() - t0 < 1.0
            release.append(True)
            mgr.drain(timeout=5.0)           # ...and completion wakes it
            assert mgr._workers["slow"].q.unfinished_tasks == 0
        finally:
            mgr.stop()


# ---------------------------------------------------------------------------
# dispatcher admission: dead-letter audit + replayability
# ---------------------------------------------------------------------------

def _instance_config(tmp_path, overload=None, **pipeline):
    from sitewhere_tpu.runtime.config import Config

    return Config({
        "instance": {"id": "ov-inst", "data_dir": str(tmp_path / "data")},
        "pipeline": {"width": 64, "registry_capacity": 128,
                     "mtype_slots": 4, "deadline_ms": 5.0, "n_shards": 1,
                     **pipeline},
        "presence": {"scan_interval_s": 3600.0, "missing_after_s": 1800},
        "overload": {"enabled": True, **(overload or {})},
    }, apply_env=False)


def _seed_device(inst, token="d-0"):
    inst.device_management.create_device_type(token="sensor", name="Sensor")
    inst.device_management.create_device(token=token, device_type="sensor")
    inst.device_management.create_device_assignment(device=token)


def _measurement(token, value, ts=1_753_800_000):
    return json.dumps({
        "deviceToken": token, "type": "Measurement",
        "request": {"name": "temp", "value": value, "eventDate": ts},
    })


def _alert(token, ts=1_753_800_000):
    return json.dumps({
        "deviceToken": token, "type": "Alert",
        "request": {"type": "overheat", "level": "warning",
                    "message": "hot", "eventDate": ts},
    })


def _dead_letters(inst, kind):
    return [d for d in inst.list_dead_letters(limit=100)
            if d.get("kind") == kind]


class TestDispatcherAdmission:
    def test_full_shed_raises_dead_letters_and_skips_journal(self, tmp_path):
        from sitewhere_tpu.instance import Instance

        inst = Instance(_instance_config(tmp_path))
        inst.start()
        try:
            _seed_device(inst)
            inst.overload.force(OverloadState.SHEDDING)
            payload = "\n".join(
                [_measurement("d-0", i) for i in range(3)]).encode()
            with pytest.raises(OverloadShed) as exc:
                inst.dispatcher.ingest_wire_lines(payload, "src-1")
            assert exc.value.retry_after_s > 0
            # shed ≠ journaled: the offset space holds only admitted work
            assert inst.ingest_journal.end_offset == 0
            letters = _dead_letters(inst, "intake-shed")
            assert len(letters) == 1
            assert letters[0]["classes"] == {"telemetry": 3}
            assert letters[0]["state"] == "SHEDDING"
            assert letters[0]["source"] == "src-1"
            assert bytes.fromhex(letters[0]["payload"]) == payload
        finally:
            inst.stop()
            inst.terminate()

    def test_partial_shed_admits_critical_rows_to_seal(self, tmp_path):
        from sitewhere_tpu.instance import Instance

        inst = Instance(_instance_config(tmp_path))
        inst.start()
        try:
            _seed_device(inst)
            inst.overload.force(OverloadState.SHEDDING)
            payload = "\n".join([
                _measurement("d-0", 1.0),
                _alert("d-0"),
                _measurement("d-0", 2.0),
            ]).encode()
            n = inst.dispatcher.ingest_wire_lines(payload, "src-1")
            assert n == 1   # the alert row
            inst.dispatcher.flush()
            inst.event_store.flush()
            # CRITICAL reached seal even while SHEDDING
            assert inst.event_store.total_events == 1
            assert inst.dispatcher.totals["accepted"] == 1
            assert inst.metrics.counter(
                "overload.shed.telemetry").value == 2
            assert inst.metrics.counter(
                "overload.shed.critical").value == 0
            letters = _dead_letters(inst, "intake-shed")
            assert letters[0]["classes"] == {"telemetry": 2}
        finally:
            inst.stop()
            inst.terminate()

    def test_scalar_ingest_many_partial_shed(self, tmp_path):
        from sitewhere_tpu.ingest.decoders import JsonLinesDecoder
        from sitewhere_tpu.instance import Instance

        inst = Instance(_instance_config(tmp_path))
        inst.start()
        try:
            _seed_device(inst)
            inst.overload.force(OverloadState.EMERGENCY)
            decoder = JsonLinesDecoder()
            mixed = decoder("\n".join(
                [_measurement("d-0", 1.0), _alert("d-0")]).encode())
            inst.dispatcher.ingest_many(mixed, b"raw", source_id="s")
            inst.dispatcher.flush()
            inst.event_store.flush()
            assert inst.event_store.total_events == 1
            with pytest.raises(OverloadShed):
                inst.dispatcher.ingest_many(
                    decoder(_measurement("d-0", 3.0).encode()), b"raw2",
                    source_id="s")
        finally:
            inst.stop()
            inst.terminate()

    def test_journal_replay_bypasses_admission(self, tmp_path):
        """Already-journaled work is NEVER shed: replay is how the
        fail-closed durability contract recovers, and shedding it would
        turn an overload into data loss."""
        from sitewhere_tpu.instance import Instance

        inst = Instance(_instance_config(tmp_path))
        inst.start()
        try:
            _seed_device(inst)
            # a journaled-but-unprocessed record, as a crash leaves it
            inst.ingest_journal.append(_measurement("d-0", 7.0).encode())
            inst.overload.force(OverloadState.EMERGENCY)
            replayed = inst.dispatcher.replay_journal(upto=1)
            assert replayed == 1   # telemetry replayed even in EMERGENCY
            inst.event_store.flush()
            assert inst.event_store.total_events == 1
        finally:
            inst.stop()
            inst.terminate()

    def test_shed_payload_is_requeueable_after_recovery(self, tmp_path):
        from sitewhere_tpu.instance import Instance

        inst = Instance(_instance_config(tmp_path))
        inst.start()
        try:
            _seed_device(inst)
            inst.overload.force(OverloadState.SHEDDING)
            payload = _measurement("d-0", 9.0).encode()
            with pytest.raises(OverloadShed):
                inst.dispatcher.ingest_wire_lines(payload)
            offset = _dead_letters(inst, "intake-shed")[0]["offset"]
            # still overloaded: the requeue is refused, not re-shed
            refused = inst.requeue_dead_letter(offset)
            assert refused["requeued"] is False
            # recovered: the audited payload replays into the pipeline
            inst.overload.force(OverloadState.NORMAL)
            result = inst.requeue_dead_letter(offset)
            assert result["requeued"] is True and result["rows"] == 1
            inst.dispatcher.flush()
            inst.event_store.flush()
            assert inst.event_store.total_events == 1
        finally:
            inst.stop()
            inst.terminate()


# ---------------------------------------------------------------------------
# protocol-native backpressure: shed ≠ silent drop, per transport
# ---------------------------------------------------------------------------

class TestProtocolBackpressure:
    def test_http_answers_429_with_retry_after(self, tmp_path):
        from sitewhere_tpu.ingest.decoders import JsonLinesDecoder
        from sitewhere_tpu.ingest.sources import (
            HttpReceiver,
            InboundEventSource,
        )
        from sitewhere_tpu.instance import Instance

        inst = Instance(_instance_config(
            tmp_path, overload={"retry_after_s": 3.0}))
        rx = HttpReceiver(port=0)
        src = InboundEventSource("http-src", [rx], JsonLinesDecoder())
        inst.add_source(src)
        inst.start()
        try:
            _seed_device(inst)
            url = f"http://127.0.0.1:{rx.port}/events"

            def post(body):
                return urllib.request.urlopen(urllib.request.Request(
                    url, data=body, method="POST"), timeout=10)

            assert post(_measurement("d-0", 1.0).encode()).status == 202
            inst.overload.force(OverloadState.SHEDDING)
            with pytest.raises(urllib.error.HTTPError) as exc:
                post(_measurement("d-0", 2.0).encode())
            assert exc.value.code == 429
            assert exc.value.headers["Retry-After"] == "6"  # 3.0 * state 2
            # CRITICAL still flows over the same connection path
            assert post(_alert("d-0").encode()).status == 202
            assert src.shed_count == 1
            assert rx.sheds == 1
            inst.overload.force(OverloadState.NORMAL)
            assert post(_measurement("d-0", 3.0).encode()).status == 202
        finally:
            inst.stop()
            inst.terminate()

    def test_coap_answers_503_with_max_age(self, tmp_path):
        from sitewhere_tpu.ingest.coap import (
            ACK,
            OPT_MAX_AGE,
            UNAVAILABLE_503,
            CHANGED_204,
            CoapServerReceiver,
            encode_post,
            parse_message,
        )
        from sitewhere_tpu.ingest.decoders import JsonLinesDecoder
        from sitewhere_tpu.ingest.sources import InboundEventSource
        from sitewhere_tpu.instance import Instance

        inst = Instance(_instance_config(
            tmp_path, overload={"retry_after_s": 2.0}))
        rx = CoapServerReceiver(port=0)
        src = InboundEventSource("coap-src", [rx], JsonLinesDecoder())
        inst.add_source(src)
        inst.start()
        try:
            _seed_device(inst)
            client = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            client.settimeout(5.0)

            def post(body, mid):
                client.sendto(
                    encode_post("/events", body, message_id=mid),
                    ("127.0.0.1", rx.port))
                data, _ = client.recvfrom(65536)
                return parse_message(data)

            ok = post(_measurement("d-0", 1.0).encode(), 1)
            assert (ok.mtype, ok.code) == (ACK, CHANGED_204)
            inst.overload.force(OverloadState.SHEDDING)
            shed = post(_measurement("d-0", 2.0).encode(), 2)
            assert (shed.mtype, shed.code) == (ACK, UNAVAILABLE_503)
            max_age = shed.option(OPT_MAX_AGE)
            assert int.from_bytes(max_age, "big") == 4  # 2.0 * state 2
            # the alert POST still gets its 2.04 while SHEDDING
            hot = post(_alert("d-0").encode(), 3)
            assert (hot.mtype, hot.code) == (ACK, CHANGED_204)
            client.close()
        finally:
            inst.stop()
            inst.terminate()

    def test_mqtt_broker_withholds_puback_and_keeps_session(self, tmp_path):
        from sitewhere_tpu.ingest.decoders import JsonLinesDecoder
        from sitewhere_tpu.ingest.mqtt import MqttClient
        from sitewhere_tpu.ingest.mqtt_broker import MqttBrokerReceiver
        from sitewhere_tpu.ingest.sources import InboundEventSource
        from sitewhere_tpu.instance import Instance

        inst = Instance(_instance_config(tmp_path))
        rx = MqttBrokerReceiver(topic_filter="sitewhere/input/#")
        src = InboundEventSource("mqtt-src", [rx], JsonLinesDecoder())
        inst.add_source(src)
        inst.start()
        try:
            _seed_device(inst)
            dev = MqttClient("127.0.0.1", rx.port, client_id="dev-ov")
            dev.connect()
            inst.overload.force(OverloadState.SHEDDING)
            dev.publish("sitewhere/input/dev-ov",
                        _measurement("d-0", 1.0).encode(), qos=1)
            # the PUBACK is WITHHELD (the device's redelivery cue)...
            assert not dev.drain_publishes(timeout=1.0)
            assert _wait(lambda: rx.broker.sheds == 1)
            # ...but the session survives: shedding is flow control
            assert rx.broker.session_count == 1
            assert rx.broker.tap_failures == 0   # shed ≠ fault
            inst.overload.force(OverloadState.NORMAL)
            # device-side at-least-once: reconnect and redeliver (the
            # withheld PUBACK is what makes the device do this)
            dev2 = MqttClient("127.0.0.1", rx.port, client_id="dev-ov")
            dev2.connect()
            dev2.publish("sitewhere/input/dev-ov",
                         _measurement("d-0", 1.0).encode(), qos=1)
            assert dev2.drain_publishes(timeout=10.0)
            dev2.disconnect()
        finally:
            inst.stop()
            inst.terminate()

    def test_stomp_leaves_message_unacked(self):
        from sitewhere_tpu.ingest.stomp import StompReceiver

        from test_stomp_http import MiniBroker

        broker = MiniBroker()
        got = []
        shedding = [True]

        def sink(payload):
            if shedding[0]:
                raise OverloadShed(PriorityClass.TELEMETRY,
                                   OverloadState.SHEDDING, 1.0)
            got.append(payload)

        rx = StompReceiver("127.0.0.1", broker.port,
                           destination="/queue/q", heartbeat_ms=0,
                           reconnect_delay_s=0.05)
        rx.sink = sink
        rx.start()
        try:
            assert _wait(lambda: broker.subscribes)
            broker.push("m-1", b"ev-1")
            assert _wait(lambda: rx.sheds == 1)
            time.sleep(0.05)
            assert broker.acks == []       # unacked → broker redelivers
            assert rx.emit_errors == 0     # shed is not a fault
            shedding[0] = False
            broker.push("m-1", b"ev-1")    # broker-side redelivery
            assert _wait(lambda: got == [b"ev-1"])
            assert _wait(lambda: broker.acks == ["m-1"])
        finally:
            rx.stop()
            broker.close()

    def test_amqp_sheds_with_paced_nack_requeue(self):
        """A shed delivery is nacked with requeue after a pacing pause
        (never acked, never logged as a fault): leaving it unacked
        would strand it in the prefetch window of a heartbeat-healthy
        session and wedge the consumer forever.  The broker redelivers
        the requeued message and it lands once admission reopens."""
        from sitewhere_tpu.ingest.amqp import AmqpReceiver

        from test_amqp import MiniAmqpBroker

        broker = MiniAmqpBroker()
        got = []
        shedding = [True]

        def sink(payload):
            if shedding[0]:
                raise OverloadShed(PriorityClass.TELEMETRY,
                                   OverloadState.SHEDDING, 1.0)
            got.append(payload)

        rx = AmqpReceiver("127.0.0.1", broker.port, queue="q1")
        rx.sink = sink
        rx.start()
        try:
            assert _wait(lambda: broker.sessions == 1)
            broker.push(b"telemetry-1")
            assert _wait(lambda: rx.sheds >= 1)
            # nacked with the requeue bit — broker-native redelivery
            assert _wait(lambda: len(broker.nacks) >= 1)
            assert broker.nacks[0][1] == 0x02
            assert rx.emit_errors == 0     # shed is not a fault
            assert rx.nacked == 0          # ...and not a sink failure
            shedding[0] = False            # overload clears
            # the requeued redelivery lands and acks
            assert _wait(lambda: b"telemetry-1" in got)
            assert _wait(lambda: len(broker.acks) >= 1)
        finally:
            rx.stop()
            broker.close()

    def test_ackless_receivers_swallow_shed(self):
        """UDP (and TCP/WS/poll) have no ack channel: a shed must NOT
        crash the supervised loop — it was already counted +
        dead-lettered at the admission edge."""
        from sitewhere_tpu.ingest.sources import UdpReceiver

        rx = UdpReceiver(port=0)
        rx.sink = lambda payload: (_ for _ in ()).throw(
            OverloadShed(PriorityClass.TELEMETRY, OverloadState.SHEDDING))
        rx.start()
        try:
            client = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            client.sendto(b"telemetry", ("127.0.0.1", rx.port))
            assert _wait(lambda: rx.sheds == 1)
            assert rx.supervisor.restarts == 0   # not treated as a crash
            client.close()
        finally:
            rx.stop()


# ---------------------------------------------------------------------------
# tools/overload_bench.py smoke — the tool is how a regression in the
# goodput curve (collapse instead of graceful shedding) localizes
# ---------------------------------------------------------------------------

class TestOverloadBenchSmoke:
    def test_tool_reports_curve_and_never_sheds_alerts(self):
        import importlib.util
        import os

        path = os.path.join(os.path.dirname(__file__), os.pardir,
                            "tools", "overload_bench.py")
        spec = importlib.util.spec_from_file_location("overload_bench", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        # alert_every=2: even a heavily contended box that only gets a
        # handful of paced sends through per phase still offers alerts
        result = mod.run(width=64, duration_s=0.2, multipliers=(1.0, 4.0),
                         alert_every=2)
        assert result["capacity_rows_per_s"] > 0
        assert len(result["rows"]) == 2
        for row in result["rows"]:
            assert row["goodput_rows_per_s"] > 0
            # the acceptance invariant: alert-class events never shed
            assert row["alert_sheds"] == 0
            assert row["alerts_offered"] > 0
        # the rendered table includes every multiplier
        table = mod._render(result)
        assert "(1.0x)" in table and "(4.0x)" in table


# ---------------------------------------------------------------------------
# the RPC fabric leg: a shedding owner answers a RETRYABLE code
# ---------------------------------------------------------------------------

class TestRpcBackpressure:
    def test_shed_maps_to_retryable_overloaded_code(self):
        """Cross-host forwarding: the owning host's admission refusal
        must reach the forwarding peer as ``overloaded`` — retryable,
        like an unreachable peer (the spool redelivers) — never as an
        opaque ``internal`` error that dead-letters rows the owner
        will accept once it recovers."""
        from sitewhere_tpu.rpc.channel import RpcChannel, RpcError
        from sitewhere_tpu.rpc.server import RpcServer

        srv = RpcServer(port=0)

        def shedding_ingest(ctx, body):
            raise OverloadShed(PriorityClass.TELEMETRY,
                               OverloadState.SHEDDING, 1.0)

        srv.register("events.ingest", shedding_ingest, auth_required=False)
        srv.start()
        try:
            chan = RpcChannel(srv.endpoint)
            with pytest.raises(RpcError) as exc:
                chan.call("events.ingest", {}, attachment=b"{}")
            assert exc.value.error == "overloaded"
            chan.close()
        finally:
            srv.stop()
