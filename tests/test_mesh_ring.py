"""Mesh-fused ring dispatch: the K-deep packed chain under shard_map.

Four contracts of the fused chained mesh (conftest forces an 8-device
virtual CPU mesh, so a 4-way mesh is always available):

- host-sync amortization: K chained steps cost ONE device round-trip,
  so ``host_syncs == steps / K`` when every emission chains;
- split invariance: the mesh chain is bit-identical to (a) the same
  mesh stepping one batch at a time and (b) the single-chip chain —
  sharding and chaining are pure execution strategies, never semantics;
- per-shard containment: poison rows on one shard demote ONLY that
  shard's breaker; the other shards keep chaining and no clean row is
  lost;
- zero-copy sharded ingest: a segment-ordered full-width reservation is
  ADOPTED by the sharded batcher — ``pipeline.bytes_copied.batch``
  stays 0 end-to-end.
"""

import numpy as np
import pytest

try:
    from sitewhere_tpu.pipeline.sharded import (  # noqa: F401
        build_sharded_packed_chain,
    )
    _SHARDED_ERR = None
except Exception as e:  # pragma: no cover - environment-dependent
    _SHARDED_ERR = e

pytestmark = pytest.mark.skipif(
    _SHARDED_ERR is not None,
    reason=f"sharded pipeline unavailable: {_SHARDED_ERR}")

WIDTH = 128
CAP = 256
N_SHARDS = 4
K = 4
SEG = WIDTH // N_SHARDS       # rows per shard per full batch
RPS = CAP // N_SHARDS         # device handles per registry block


def _config(tmp_path, name, *, n_shards, ring_depth, **extra):
    from sitewhere_tpu.runtime.config import Config

    pipeline = {"width": WIDTH, "registry_capacity": CAP,
                "mtype_slots": 4, "deadline_ms": 200.0}
    if n_shards > 1:
        pipeline["n_shards"] = n_shards
    if ring_depth:
        pipeline["ring_depth"] = ring_depth
    cfg = {
        "instance": {"id": name, "data_dir": str(tmp_path / name)},
        "pipeline": pipeline,
        "presence": {"scan_interval_s": 3600.0, "missing_after_s": 1800},
        "registration": {"default_device_type": "sensor"},
    }
    cfg.update(extra)
    return Config(cfg, apply_env=False)


def _start(cfg, *, rule=False):
    from sitewhere_tpu.instance import Instance

    inst = Instance(cfg)
    inst.start()
    dm = inst.device_management
    dm.create_device_type(token="sensor", name="Sensor")
    if rule:
        from sitewhere_tpu.schema import AlertLevel, ComparisonOp

        inst.rules.create_rule(mtype=None, op=ComparisonOp.GT,
                               threshold=90.0, alert_type="hot",
                               alert_level=AlertLevel.WARNING)
    for i in range(CAP):
        dm.create_device(token=f"d-{i}", device_type="sensor")
        dm.create_device_assignment(device=f"d-{i}")
    handles = np.asarray(
        inst.identity.device.lookup_many([f"d-{i}" for i in range(CAP)]),
        np.int32)
    by_shard = [handles[(handles // RPS) == s] for s in range(N_SHARDS)]
    assert all(len(b) >= SEG for b in by_shard), [len(b) for b in by_shard]
    return inst, by_shard


def _balanced_round(rng, by_shard):
    """Exactly SEG rows per shard, shard-block ordered — every emission
    is a full-width fill batch whose layout is identical on the sharded
    and single-shard batchers (segment s == arrival block s)."""
    return np.concatenate([
        rng.choice(by_shard[s], SEG) for s in range(N_SHARDS)
    ]).astype(np.int32)


def _ingest_rounds(inst, by_shard, rounds, seed, poison=None,
                   values=None):
    rng = np.random.default_rng(seed)
    for r in range(rounds):
        dev = _balanced_round(rng, by_shard)
        if values is None:
            value = rng.uniform(0, 100, WIDTH).astype(np.float32)
        else:
            value = values(r, rng)
        if poison is not None:
            poison(r, value)
        inst.dispatcher.ingest_arrays(
            device_id=dev,
            event_type=np.zeros(WIDTH, np.int32),
            ts_s=np.full(WIDTH, 1_753_800_000 + r, np.int32),
            mtype_id=np.zeros(WIDTH, np.int32),
            value=value,
            lat=rng.uniform(-20, 20, WIDTH).astype(np.float32),
            lon=rng.uniform(-20, 20, WIDTH).astype(np.float32),
        )
    inst.dispatcher.flush()
    inst.dispatcher.flush()   # drain re-injected derived alerts


def test_mesh_chain_amortizes_host_syncs(tmp_path):
    """K fused steps, one D2H fetch: host_syncs == steps / K."""
    rounds = 2 * K
    inst, by_shard = _start(
        _config(tmp_path, "mesh-ring", n_shards=N_SHARDS, ring_depth=K))
    try:
        _ingest_rounds(inst, by_shard, rounds, seed=7)
        snap = inst.dispatcher.metrics_snapshot()
        assert snap["processed"] == rounds * WIDTH
        assert snap["steps"] == rounds, snap
        assert snap["ring_chains"] == rounds // K, snap
        assert snap["host_syncs"] == snap["steps"] // K, snap
        assert inst.event_store.total_events == rounds * WIDTH
        st = inst.device_state.current
        assert len(st.last_event_ts_s.sharding.device_set) == N_SHARDS
    finally:
        inst.stop()
        inst.terminate()


def test_mesh_chain_matches_single_chip_and_split(tmp_path):
    """Shard-split AND batch-split invariance, bit-for-bit: the fused
    4-way mesh chain == the same mesh stepping batch-by-batch == the
    single-chip chain, on identical traffic (rule leg included, so the
    all-gathered rule eval is part of the equality).

    Alerts fire only in the LAST round: derived-alert re-injection is
    deliberately deferred past every full batch, because mid-stream
    alerts join LATER batches at dispatch-timing-dependent points —
    fused mode egresses (and so re-injects) K batches at a time — which
    legitimately regroups intra-batch dedup winners without changing
    any aggregate.  The invariance contract is over execution strategy,
    not over re-injection arrival timing."""
    import jax

    rounds = 2 * K

    def _values(r, rng):
        lo, hi = ((80.0, 100.0) if r == rounds - 1 else (0.0, 50.0))
        return rng.uniform(lo, hi, WIDTH).astype(np.float32)
    variants = {
        "mesh-fused": _config(tmp_path, "g-mesh-fused",
                              n_shards=N_SHARDS, ring_depth=K),
        "mesh-step": _config(tmp_path, "g-mesh-step",
                             n_shards=N_SHARDS, ring_depth=0),
        "single-chip": _config(tmp_path, "g-single",
                               n_shards=1, ring_depth=K),
    }
    states, metrics, stored = {}, {}, {}
    for name, cfg in variants.items():
        inst, by_shard = _start(cfg, rule=True)
        try:
            _ingest_rounds(inst, by_shard, rounds, seed=3,
                           values=_values)
            snap = inst.dispatcher.metrics_snapshot()
            states[name] = [
                np.asarray(leaf) for leaf in
                jax.tree_util.tree_leaves(inst.device_state.current)
            ]
            metrics[name] = {key: snap[key] for key in
                             ("processed", "accepted", "threshold_alerts")}
            stored[name] = inst.event_store.total_events
        finally:
            inst.stop()
            inst.terminate()
    ref = states["mesh-fused"]
    for other in ("mesh-step", "single-chip"):
        assert metrics[other] == metrics["mesh-fused"], (other, metrics)
        assert stored[other] == stored["mesh-fused"], (other, stored)
        assert len(states[other]) == len(ref)
        for i, (a, b) in enumerate(zip(ref, states[other])):
            np.testing.assert_array_equal(
                a, b, err_msg=f"state leaf {i} differs vs {other}")
    assert metrics["mesh-fused"]["threshold_alerts"] > 0, metrics


@pytest.mark.chaos
def test_single_shard_fault_contained(tmp_path):
    """Poison rows on shard 2 demote ONLY shard 2's breaker: its rows
    dead-letter row-by-row, shards 0/1/3 never strike and keep
    chaining, and every clean row lands in the store."""
    from sitewhere_tpu.runtime import faults

    inst, by_shard = _start(_config(
        tmp_path, "shard-contain", n_shards=N_SHARDS, ring_depth=K,
        overload={"cooldown_s": 3600.0}))
    poison_rounds, clean_rounds, ppr = 2 * K, 2 * K, 2

    def _poison(r, value):
        if r < poison_rounds:
            value[2 * SEG:2 * SEG + ppr] = np.nan

    try:
        faults.device_inject("device.dispatch", times=None,
                             when_nonfinite=True)
        _ingest_rounds(inst, by_shard, poison_rounds + clean_rounds,
                       seed=7, poison=_poison)
        faults.device_clear()
        inst.event_store.flush()
        snap = inst.dispatcher.metrics_snapshot()
        br = snap["device_fault"]["breaker"]
        assert br["shards"][2]["level"] >= 1, br
        for s in (0, 1, 3):
            assert br["shards"][s]["level"] == 0, (s, br)
        npoison = poison_rounds * ppr
        letters = [d for d in inst.list_dead_letters(limit=100)
                   if d.get("kind") == "device-poison"]
        assert sum(d["count"] for d in letters) == npoison, letters
        total = (poison_rounds + clean_rounds) * WIDTH
        assert inst.event_store.total_events == total - npoison
        assert snap["ring_chains"] >= 1, "healthy shards stopped chaining"
    finally:
        faults.device_clear()
        inst.stop()
        inst.terminate()


def test_sharded_reservation_adopts_zero_copy(tmp_path):
    """Fill-direct on the mesh: segment-ordered full-width reservations
    are adopted by the sharded batcher, chain through the fused ring,
    and the batch-assembly copy counter stays at ZERO."""
    inst, by_shard = _start(
        _config(tmp_path, "mesh-adopt", n_shards=N_SHARDS, ring_depth=K))
    rounds = K
    try:
        rng = np.random.default_rng(11)
        for r in range(rounds):
            res = inst.dispatcher.batcher.reserve(WIDTH)
            assert res is not None
            dev = _balanced_round(rng, by_shard)
            res.device_id[:WIDTH] = dev
            res.mtype_id[:WIDTH] = 0
            res.value[:WIDTH] = rng.uniform(0, 50, WIDTH).astype(np.float32)
            res.ts_s[:WIDTH] = 1_753_800_000 + r
            res.ts_ns[:WIDTH] = 0
            res.update_state[:WIDTH] = 1
            res.n = WIDTH
            inst.dispatcher.ingest_wire_decoded(b"", res, [],
                                                source_id="test")
        inst.dispatcher.flush()
        snap = inst.dispatcher.metrics_snapshot()
        assert snap["processed"] == rounds * WIDTH
        assert snap["ring_chains"] == rounds // K, snap
        counters = inst.metrics.snapshot()["counters"]
        assert counters.get("pipeline.bytes_copied.batch", 0) == 0, counters
        assert inst.dispatcher.batcher.copied_bytes == 0
        assert inst.event_store.total_events == rounds * WIDTH
    finally:
        inst.stop()
        inst.terminate()
