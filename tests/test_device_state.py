"""Device-state manager + presence detection.

Reference behaviors covered: last-known-state merge visibility through the
query surface (DeviceStateImpl RPC analogs), presence sweep marking
overdue devices (DevicePresenceManager), send-once notification semantics,
and re-arming when a device comes back.
"""

import numpy as np
import pytest

from sitewhere_tpu.ids import IdentityMap, NULL_ID
from sitewhere_tpu.pipeline import pipeline_step
from sitewhere_tpu.schema import DeviceState, EventType, RuleTable, ZoneTable
from sitewhere_tpu.services.common import EntityNotFound
from sitewhere_tpu.state import DeviceStateManager, PresenceManager, presence_sweep

from helpers import make_batch, make_registry, measurement, location


CAP = 64


@pytest.fixture
def identity():
    im = IdentityMap(capacity=CAP)
    for i in range(8):
        assert im.device.mint(f"dev-{i}") == i
    return im


@pytest.fixture
def manager(identity):
    return DeviceStateManager(CAP, identity)


def run_step(manager, rows):
    registry = make_registry(capacity=CAP, n_devices=8)
    rules = RuleTable.empty(4)
    zones = ZoneTable.empty(4)
    new_state, out = pipeline_step(
        registry, manager.current, rules, zones, make_batch(rows)
    )
    manager.commit(new_state)
    return out


class TestStateManager:
    def test_merge_visible_through_queries(self, manager):
        run_step(
            manager,
            [
                measurement(0, mtype=1, value=42.5, ts=5000),
                location(3, lat=10.0, lon=20.0, ts=6000),
            ],
        )
        s0 = manager.get_device_state("dev-0")
        assert s0["last_event_type"] == EventType.MEASUREMENT
        assert s0["last_event_ts_s"] == 5000
        assert s0["last_values"][1] == 42.5
        s3 = manager.get_device_state("dev-3")
        assert s3["last_location"]["lat"] == 10.0
        assert s3["last_location"]["lon"] == 20.0
        # Device with no events yet.
        assert manager.get_device_state("dev-7")["last_event_type"] is None

    def test_unknown_device(self, manager):
        with pytest.raises(EntityNotFound):
            manager.get_device_state("nope")

    def test_seen_since_and_summary(self, manager):
        run_step(manager, [measurement(0, ts=1000), measurement(1, ts=9000)])
        assert manager.seen_since(5000) == [1]
        assert manager.summary()["devices_with_state"] == 2


class TestPresenceSweep:
    def test_overdue_devices_marked(self, manager):
        run_step(manager, [measurement(0, ts=1000), measurement(1, ts=50_000)])
        batch = manager.apply_presence_sweep(now_s=60_000, missing_after_s=30_000)
        # dev-0 is 59k stale (> 30k) → missing; dev-1 is 10k stale → present.
        assert manager.missing_device_ids() == [0]
        assert batch is not None
        ids = np.asarray(batch.device_id)[np.asarray(batch.valid)]
        assert list(ids) == [0]
        assert int(np.asarray(batch.event_type)[0]) == EventType.STATE_CHANGE

    def test_devices_without_events_ignored(self, manager):
        batch = manager.apply_presence_sweep(now_s=10**9, missing_after_s=1)
        assert batch is None
        assert manager.missing_device_ids() == []

    def test_send_once(self, manager):
        run_step(manager, [measurement(0, ts=1000)])
        assert manager.apply_presence_sweep(50_000, 30_000) is not None
        # Second sweep: still missing, but not NEWLY missing → no batch.
        assert manager.apply_presence_sweep(60_000, 30_000) is None

    def test_rearm_on_return(self, manager):
        run_step(manager, [measurement(0, ts=1000)])
        manager.apply_presence_sweep(50_000, 30_000)
        assert manager.missing_device_ids() == [0]
        # Device comes back: pipeline step clears the flag...
        run_step(manager, [measurement(0, ts=55_000)])
        assert manager.missing_device_ids() == []
        # ...and a later lapse notifies again.
        assert manager.apply_presence_sweep(100_000, 30_000) is not None


class TestPresenceManager:
    def test_sweep_once_and_counters(self, manager):
        run_step(manager, [measurement(0, ts=1000)])
        emitted = []
        pm = PresenceManager(
            manager,
            missing_after_s=30_000,
            on_state_changes=emitted.append,
            clock=lambda: 50_000,
        )
        assert pm.sweep_once() == 1
        assert pm.total_marked_missing == 1
        assert len(emitted) == 1
        assert pm.sweep_once() == 0  # send-once

    def test_background_thread(self, manager):
        import time as _time

        run_step(manager, [measurement(0, ts=1000)])
        pm = PresenceManager(
            manager,
            check_interval_s=0.02,
            missing_after_s=30_000,
            clock=lambda: 50_000,
        )
        pm.start()
        deadline = _time.time() + 2
        while pm.sweeps == 0 and _time.time() < deadline:
            _time.sleep(0.01)
        pm.stop()
        assert pm.sweeps >= 1
        assert manager.missing_device_ids() == [0]

    def test_tenant_ids_in_state_changes(self, identity):
        tenants = np.full(CAP, 3, np.int32)
        mgr = DeviceStateManager(
            CAP, identity, tenant_id_of_device=lambda ids: tenants[ids]
        )
        run_step(mgr, [measurement(0, ts=1000, tenant=0)])
        # run_step's registry uses tenant 0; the emission callback uses the
        # injected mapping (tenant 3) — verifying the hook is honored.
        batch = mgr.apply_presence_sweep(50_000, 30_000)
        assert int(np.asarray(batch.tenant_id)[0]) == 3


def test_presence_sweep_is_jittable_and_pure():
    import jax.numpy as jnp

    state = DeviceState.empty(16)
    state = state.replace(
        last_event_type=state.last_event_type.at[2].set(EventType.MEASUREMENT),
        last_event_ts_s=state.last_event_ts_s.at[2].set(100),
    )
    new_state, newly = presence_sweep(state, jnp.int32(10_000), jnp.int32(500))
    assert bool(newly[2]) and not bool(newly[0])
    # Input untouched (functional update).
    assert not bool(state.presence_missing[2])
    assert bool(new_state.presence_missing[2])


class TestCommitMergeRace:
    def test_concurrent_sweep_flags_survive_commit(self, manager):
        """A sweep that lands between the dispatcher's state read and its
        commit must not be clobbered (lost-update race): flags for devices
        the batch did not touch are preserved when the batch is passed."""
        # dev-0 and dev-5 have old events
        run_step(manager, [measurement(0, ts=1000), measurement(5, ts=1000)])
        base = manager.current  # dispatcher snapshot S0

        # slow pipeline step computes from S0...
        registry = make_registry(capacity=CAP, n_devices=8)
        batch = make_batch([measurement(0, ts=90_000)])
        new_state, out = pipeline_step(
            registry, base, RuleTable.empty(4), ZoneTable.empty(4), batch
        )

        # ...meanwhile the presence sweep marks both 0 and 5 missing
        swept = manager.apply_presence_sweep(now_s=80_000, missing_after_s=10_000)
        assert sorted(manager.missing_device_ids()) == [0, 5]
        assert swept is not None

        # dispatcher commits: dev-0 (touched, fresh event) cleared;
        # dev-5 (untouched) keeps the sweep's flag
        manager.commit(new_state, batch=batch, accepted=out.accepted)
        assert manager.missing_device_ids() == [5]
        # and the next sweep does NOT re-mark dev-5 (send-once holds)
        assert manager.apply_presence_sweep(80_000, 10_000) is None

    def test_rejected_rows_do_not_clear_sweep_flags(self, manager):
        """A batch row the step REJECTED (e.g. unregistered device id) must
        not count as touched — its sweep flag survives the commit."""
        run_step(manager, [measurement(0, ts=1000), measurement(5, ts=1000)])
        base = manager.current

        registry = make_registry(capacity=CAP, n_devices=8)
        # row for dev-5 arrives but its registry slot is inactive → rejected
        import numpy as np

        from sitewhere_tpu.schema import AssignmentStatus

        registry = registry.replace(
            active=registry.active.at[5].set(False)
        )
        batch = make_batch([measurement(0, ts=90_000), measurement(5, ts=90_000)])
        new_state, out = pipeline_step(
            registry, base, RuleTable.empty(4), ZoneTable.empty(4), batch
        )
        assert not bool(np.asarray(out.accepted)[1])

        manager.apply_presence_sweep(now_s=80_000, missing_after_s=10_000)
        assert sorted(manager.missing_device_ids()) == [0, 5]

        manager.commit(new_state, batch=batch, accepted=out.accepted)
        # dev-0 cleared (accepted fresh event); dev-5's flag survives even
        # though a (rejected) row named it
        assert manager.missing_device_ids() == [5]

    def test_present_now_commit_path_matches_batch_path(self, manager):
        """The dispatcher's hot path passes the step's present_now output
        instead of re-deriving touched rows from the batch; both forms
        must reconcile a concurrent sweep identically."""
        import numpy as np

        run_step(manager, [measurement(0, ts=1000), measurement(5, ts=1000)])
        base = manager.current
        registry = make_registry(capacity=CAP, n_devices=8)
        batch = make_batch([measurement(0, ts=90_000)])
        new_state, out = pipeline_step(
            registry, base, RuleTable.empty(4), ZoneTable.empty(4), batch
        )
        # present_now marks exactly the merged device
        pn = np.asarray(out.present_now)
        assert pn[0] and not pn[5] and pn.sum() == 1

        manager.apply_presence_sweep(now_s=80_000, missing_after_s=10_000)
        assert sorted(manager.missing_device_ids()) == [0, 5]
        manager.commit(new_state, present_now=out.present_now)
        # dev-0 (merged) cleared; dev-5 (untouched) keeps the sweep flag —
        # identical to the batch/accepted re-derive form above
        assert manager.missing_device_ids() == [5]


class TestLeasePacked:
    """The donated-chain hand-off (``lease_packed`` → chain →
    ``commit_packed(lease_token=...)``) — the dispatcher ring's
    production path wherever donation is real (TPU).  Donation is a
    no-op on CPU, but the token protocol, the reader-safety twin
    materialization, and the sweep-intervened merge all run fully."""

    def _packed_step(self, manager_ps, rows):
        import jax

        from sitewhere_tpu.pipeline.packed import (
            BATCH_F,
            BATCH_I,
            pack_batch_host,
            pack_tables,
            packed_pipeline_step,
        )
        from sitewhere_tpu.schema import as_numpy

        registry = make_registry(capacity=CAP, n_devices=8)
        tables = pack_tables(registry, RuleTable.empty(4), ZoneTable.empty(4))
        host = as_numpy(make_batch(rows))
        cols = {f: np.asarray(getattr(host, f)) for f in BATCH_I + BATCH_F}
        bi, bf = pack_batch_host(cols, len(rows))
        return jax.jit(packed_pipeline_step)(tables, manager_ps, bi, bf)

    def test_fast_path_and_reader_survives_donation(self, manager):
        run_step(manager, [measurement(0, ts=1000)])
        ps, token = manager.lease_packed()
        new_ps, _oi, _mets, present = self._packed_step(
            ps, [measurement(0, ts=5000)])
        # simulate the donation: the chain consumed the leased buffers
        ps.si.delete()
        ps.sf.delete()
        # a reader arriving mid-chain sees the pre-chain epoch from the
        # materialized twin — never the deleted/donated buffers
        assert manager.get_device_state("dev-0")["last_event_ts_s"] == 1000
        manager.commit_packed(new_ps, present_now=present,
                              lease_token=token)
        assert manager.get_device_state("dev-0")["last_event_ts_s"] == 5000

    def test_sweep_during_lease_merges_at_commit(self, manager):
        """A presence sweep landing mid-chain invalidates the lease
        token: the commit must re-apply the sweep's flags for devices
        the chain did not merge (same lost-update rule as the unpacked
        commit race)."""
        run_step(manager, [measurement(0, ts=1000), measurement(5, ts=1000)])
        ps, token = manager.lease_packed()
        new_ps, _oi, _mets, present = self._packed_step(
            ps, [measurement(0, ts=90_000)])
        swept = manager.apply_presence_sweep(
            now_s=80_000, missing_after_s=10_000)
        assert swept is not None
        assert sorted(manager.missing_device_ids()) == [0, 5]
        manager.commit_packed(new_ps, present_now=present,
                              lease_token=token)
        # dev-0 (chain-merged, fresh event) cleared; dev-5 keeps the flag
        assert manager.missing_device_ids() == [5]


def test_update_state_false_rows_do_not_touch_state(manager):
    """System-generated events (presence STATE_CHANGEs, derived alerts)
    carry update_state=False: persisted/fanned out but never merged —
    reference IDeviceEvent.isUpdateState() semantics."""
    import jax.numpy as jnp

    run_step(manager, [measurement(0, ts=1000)])
    manager.apply_presence_sweep(now_s=80_000, missing_after_s=10_000)
    assert manager.missing_device_ids() == [0]

    registry = make_registry(capacity=CAP, n_devices=8)
    batch = make_batch([
        dict(device_id=0, tenant_id=0, event_type=EventType.STATE_CHANGE,
             ts_s=80_000, update_state=False),
    ])
    base = manager.current
    new_state, out = pipeline_step(
        registry, base, RuleTable.empty(4), ZoneTable.empty(4), batch
    )
    manager.commit(new_state, batch=batch, accepted=out.accepted)
    # still missing, last_event_ts unchanged — the STATE_CHANGE about the
    # device did not make it look alive
    assert manager.missing_device_ids() == [0]
    assert manager.get_device_state("dev-0")["last_event_ts_s"] == 1000
