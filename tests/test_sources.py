"""Protocol frontends: drive real sockets end-to-end into decoded requests."""

import json
import socket
import struct
import threading
import time
import urllib.request

import pytest

from sitewhere_tpu.ingest.decoders import JsonDecoder, RequestKind
from sitewhere_tpu.ingest.dedup import AlternateIdDeduplicator
from sitewhere_tpu.ingest.sources import (
    HttpReceiver,
    InboundEventSource,
    TcpReceiver,
    UdpReceiver,
    newline_frames,
)


def wait_for(predicate, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return False


def meas_payload(token="dev-1", value=1.0, alt=None):
    req = {"name": "temp", "value": value, "eventDate": 1000}
    if alt:
        req["alternateId"] = alt
    return json.dumps({"deviceToken": token, "type": "Measurement",
                       "request": req}).encode()


def make_source(receivers, dedup=None):
    events, regs, failures = [], [], []
    src = InboundEventSource(
        "test", receivers, JsonDecoder(), deduplicator=dedup,
        on_event=lambda req, raw: events.append(req),
        on_registration=lambda req, raw: regs.append(req),
        on_failed_decode=lambda raw, sid, e: failures.append((raw, str(e))),
    )
    return src, events, regs, failures


def test_tcp_receiver_length_prefixed():
    src, events, _, failures = make_source([TcpReceiver(port=0)])
    src.start()
    try:
        port = src.receivers[0].port
        with socket.create_connection(("127.0.0.1", port)) as s:
            for v in (1.0, 2.0):
                payload = meas_payload(value=v)
                s.sendall(struct.pack(">I", len(payload)) + payload)
            bad = b"this is not json"
            s.sendall(struct.pack(">I", len(bad)) + bad)
        assert wait_for(lambda: len(events) == 2 and len(failures) == 1)
        assert [e.value for e in events] == [1.0, 2.0]
        assert events[0].kind == RequestKind.MEASUREMENT
    finally:
        src.stop()


def test_tcp_receiver_newline_framing():
    src, events, _, _ = make_source(
        [TcpReceiver(port=0, framing=newline_frames)]
    )
    src.start()
    try:
        port = src.receivers[0].port
        with socket.create_connection(("127.0.0.1", port)) as s:
            s.sendall(meas_payload(value=5.0) + b"\n" + meas_payload(value=6.0) + b"\n")
        assert wait_for(lambda: len(events) == 2)
        assert {e.value for e in events} == {5.0, 6.0}
    finally:
        src.stop()


def test_udp_receiver():
    src, events, _, _ = make_source([UdpReceiver(port=0)])
    src.start()
    try:
        port = src.receivers[0].port
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.sendto(meas_payload(value=9.0), ("127.0.0.1", port))
        assert wait_for(lambda: len(events) == 1)
        assert events[0].value == 9.0
    finally:
        src.stop()


def test_http_receiver_and_registration_routing():
    src, events, regs, _ = make_source([HttpReceiver(port=0)])
    src.start()
    try:
        port = src.receivers[0].port
        url = f"http://127.0.0.1:{port}/events"
        reg = json.dumps({"deviceToken": "new-dev", "type": "RegisterDevice",
                          "request": {"deviceTypeToken": "pi"}}).encode()
        for body in (meas_payload(), reg):
            r = urllib.request.urlopen(urllib.request.Request(
                url, data=body, method="POST"))
            assert r.status == 202
        assert wait_for(lambda: len(events) == 1 and len(regs) == 1)
        assert regs[0].device_type_token == "pi"
        # wrong path -> 404, no event
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/nope", data=b"x", method="POST"))
    finally:
        src.stop()


def test_source_dedups_across_receivers():
    dedup = AlternateIdDeduplicator()
    src, events, _, _ = make_source([UdpReceiver(port=0)], dedup=dedup)
    src.start()
    try:
        port = src.receivers[0].port
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        for _ in range(3):
            s.sendto(meas_payload(alt="same-msg"), ("127.0.0.1", port))
        assert wait_for(lambda: src.receivers[0].received_count == 3)
        assert wait_for(lambda: len(events) == 1)
        assert src.duplicate_count == 2
    finally:
        src.stop()


def test_lifecycle_status_tree():
    src, *_ = make_source([UdpReceiver(port=0), HttpReceiver(port=0)])
    src.start()
    try:
        tree = src.status_tree()
        assert tree["state"] == "started"
        assert len(tree["children"]) == 2
        assert all(c["state"] == "started" for c in tree["children"])
    finally:
        src.stop()
    assert src.status_tree()["state"] == "stopped"


def test_host_plane_request_does_not_kill_receiver():
    src, events, _, _ = make_source([UdpReceiver(port=0)])
    # wire on_host_request absent: stream data should be counted, dropped
    src.start()
    try:
        port = src.receivers[0].port
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.sendto(json.dumps({"deviceToken": "d", "type": "StreamData",
                             "request": {"streamId": "s1",
                                         "sequenceNumber": 0,
                                         "data": "AAAA"}}).encode(),
                 ("127.0.0.1", port))
        # malformed stream request (no streamId) dead-letters as a
        # failed decode rather than killing the receiver
        s.sendto(json.dumps({"deviceToken": "d", "type": "StreamData",
                             "request": {}}).encode(), ("127.0.0.1", port))
        s.sendto(meas_payload(value=3.0), ("127.0.0.1", port))
        assert wait_for(lambda: len(events) == 1)  # receiver survived
        assert src.dropped_host_requests == 1
        assert src.failed_count == 1
    finally:
        src.stop()


def test_broken_sink_does_not_kill_receiver():
    def exploding_sink(req, raw):
        raise RuntimeError("sink bug")

    src = InboundEventSource("t", [UdpReceiver(port=0)], JsonDecoder(),
                             on_event=exploding_sink)
    src.start()
    try:
        port = src.receivers[0].port
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.sendto(meas_payload(value=1.0), ("127.0.0.1", port))
        s.sendto(meas_payload(value=2.0), ("127.0.0.1", port))
        assert wait_for(lambda: src.failed_count == 2)  # both logged, thread alive
        assert src.receivers[0].received_count == 2
    finally:
        src.stop()
