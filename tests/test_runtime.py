"""Runtime kernel tests: lifecycle, config, metrics."""

import json

import pytest

from sitewhere_tpu.runtime.config import Config
from sitewhere_tpu.runtime.lifecycle import (
    LifecycleComponent,
    LifecycleError,
    LifecycleState,
)
from sitewhere_tpu.runtime.metrics import MetricsRegistry


class Probe(LifecycleComponent):
    def __init__(self, name, fail_on=None):
        super().__init__(name=name)
        self.calls = []
        self.fail_on = fail_on

    def start(self):
        self.calls.append("start")
        if self.fail_on == "start":
            raise RuntimeError("boom")
        super().start()

    def stop(self):
        self.calls.append("stop")
        if self.fail_on == "stop":
            raise RuntimeError("boom")
        super().stop()


def test_lifecycle_order_and_reverse_stop():
    root = LifecycleComponent("root")
    a, b = Probe("a"), Probe("b")
    root.add_child(a)
    root.add_child(b)
    root.start()
    assert root.state == LifecycleState.STARTED
    assert a.state == b.state == LifecycleState.STARTED
    root.stop()
    # children stopped in reverse order
    assert b.calls.index("stop") <= a.calls.index("stop")
    assert root.state == LifecycleState.STOPPED


def test_lifecycle_child_failure_marks_error():
    root = LifecycleComponent("root")
    root.add_child(Probe("ok"))
    root.add_child(Probe("bad", fail_on="start"))
    with pytest.raises(RuntimeError):
        root.start()
    assert root.state == LifecycleState.ERROR


def test_lifecycle_stop_failure_still_stops_others():
    root = LifecycleComponent("root")
    a = Probe("a")
    bad = Probe("bad", fail_on="stop")
    root.add_child(a)
    root.add_child(bad)
    root.start()
    with pytest.raises(LifecycleError):
        root.stop()
    assert "stop" in a.calls  # earlier sibling still stopped


def test_config_defaults_env_and_tenant(monkeypatch, tmp_path):
    monkeypatch.setenv("SW_TPU_PIPELINE__WIDTH", "1024")
    monkeypatch.setenv("SW_TPU_API__HOST", "0.0.0.0")
    cfg = Config()
    assert cfg["pipeline.width"] == 1024     # env override, coerced to int
    assert cfg["api.host"] == "0.0.0.0"
    assert cfg["journal.fsync_every"] == 256  # default intact

    tenant = cfg.for_tenant({"pipeline": {"deadline_ms": 2.0}})
    assert tenant["pipeline.deadline_ms"] == 2.0
    assert tenant["pipeline.width"] == 1024   # inherits

    with pytest.raises(KeyError):
        cfg["nope.nope"]


def test_config_file_load_and_reload(tmp_path, monkeypatch):
    path = tmp_path / "cfg.json"
    path.write_text(json.dumps({"pipeline": {"width": 512}}))
    cfg = Config.load(str(path), apply_env=False)
    assert cfg["pipeline.width"] == 512

    seen = []
    cfg.on_change(lambda c: seen.append(c["pipeline.width"]))
    path.write_text(json.dumps({"pipeline": {"width": 2048}}))
    cfg.reload()
    assert seen == [2048]
    assert cfg["pipeline.width"] == 2048


def test_metrics_registry():
    m = MetricsRegistry()
    m.counter("events.processed").inc(5)
    m.counter("events.processed").inc(2)
    m.gauge("journal.lag").set(17)
    t = m.timer("step.latency")
    for v in (0.001, 0.002, 0.003, 0.100):
        t.observe(v)
    snap = m.snapshot()
    assert snap["counters"]["events.processed"] == 7
    assert snap["gauges"]["journal.lag"] == 17
    assert snap["timers"]["step.latency"]["count"] == 4
    assert snap["timers"]["step.latency"]["p99_ms"] >= 2.9
