"""Native C wire decoder (_swwire): equivalence + strict-bail contract.

The native tier is PURELY an accelerator: for any payload it accepts, the
result must be identical to the pure-Python columnar decoder; anything
else must bail to Python (never diverge, never crash).
"""

import json

import numpy as np
import pytest

from sitewhere_tpu.ingest import columnar
from sitewhere_tpu.native import load_swwire

pytestmark = pytest.mark.skipif(
    load_swwire() is None, reason="native toolchain unavailable")


def _line(token, value, ts=1_753_800_000, name="temp", extra=None):
    req = {"name": name, "value": value, "eventDate": ts}
    req.update(extra or {})
    return json.dumps({"deviceToken": token, "type": "Measurement",
                       "request": req}, separators=(",", ":"))


def _python_decode(payload):
    return columnar._decode_lines_inner(
        __import__("sitewhere_tpu.ingest.decoders",
                   fromlist=["parse_envelopes"]).parse_envelopes(payload))


def test_native_matches_python_columnar():
    rng = np.random.default_rng(0)
    lines = [
        _line(f"dev-{i}", float(rng.uniform(-50, 150)),
              ts=1_753_800_000 + i, name=("temp" if i % 3 else "rpm"))
        for i in range(200)
    ]
    # sprinkle updateState and epoch-millis timestamps
    lines.append(_line("dev-x", 1.0, extra={"updateState": False}))
    lines.append(_line("dev-y", 2.0, ts=1_753_800_000_123))
    payload = "\n".join(lines).encode()

    native, host_n = columnar.decode_json_lines(payload)
    py, host_p = _python_decode(payload)
    assert host_n == host_p == []
    assert native["device_token"] == py["device_token"]
    assert native["mtype"] == py["mtype"]
    for k in ("event_type", "ts_s", "ts_ns", "alert_level"):
        np.testing.assert_array_equal(native[k], py[k], err_msg=k)
    np.testing.assert_allclose(native["value"], py["value"], rtol=1e-6)
    np.testing.assert_array_equal(native["update_state"],
                                  py["update_state"])


@pytest.mark.parametrize("payload", [
    b'{"deviceToken":"d","type":"Alert","request":{"type":"x"}}',
    b'{"deviceToken":"d\\u0041","type":"Measurement","request":{"name":"t","value":1}}',
    b'{"deviceToken":"d","type":"Measurement","request":{"name":"t","value":1,"metadata":{}}}',
    b'{"deviceToken":"d","type":"Measurement","unknown":1,"request":{"name":"t","value":1}}',
])
def test_native_bails_to_python(payload):
    mod = load_swwire()
    assert mod.decode_measurement_lines(payload) is None


def test_native_bail_still_decodes_through_python():
    """A payload the native scanner rejects (escape sequence) must still
    decode via the Python fallback with identical semantics."""
    payload = (b'{"deviceToken":"d\\u0041","type":"Measurement",'
               b'"request":{"name":"t","value":3.5}}')
    cols, _ = columnar.decode_json_lines(payload)
    assert cols["device_token"] == ["dA"]
    assert cols["value"].tolist() == pytest.approx([3.5])


def test_native_disabled_by_env(monkeypatch):
    monkeypatch.setenv("SW_NATIVE", "0")
    import importlib

    import sitewhere_tpu.native as nat
    importlib.reload(nat)
    try:
        assert nat.load_swwire() is None
    finally:
        monkeypatch.delenv("SW_NATIVE")
        importlib.reload(nat)


def test_malformed_numbers_and_truncation_bail():
    mod = load_swwire()
    assert mod.decode_measurement_lines(
        b'{"deviceToken":"d","type":"Measurement","request":{"name":"t","value":"hot"}}') is None
    assert mod.decode_measurement_lines(
        b'{"deviceToken":"d","type":"Measurement","request":{"name":"t"') is None
    assert mod.decode_measurement_lines(b'not json at all') is None


def test_alias_precedence_matches_python():
    """name/measurementId, eventDate/timestamp, deviceToken/hardwareId
    precedence must be identical on both paths regardless of key order."""
    mod = load_swwire()
    line = (b'{"type":"Measurement","hardwareId":"hw","deviceToken":"dt",'
            b'"request":{"measurementId":"alt","name":"main","value":1,'
            b'"timestamp":111,"eventDate":222}}')
    out = mod.decode_measurement_lines(line)
    assert out is not None
    tokens, names, _, ts_b, _ = out
    assert tokens == ["dt"]       # deviceToken wins over hardwareId
    assert names == ["main"]      # name wins over measurementId
    assert np.frombuffer(ts_b, np.float64).tolist() == [222.0]
    # reversed order — same result
    line2 = (b'{"deviceToken":"dt","hardwareId":"hw","type":"Measurement",'
             b'"request":{"name":"main","measurementId":"alt","value":1,'
             b'"eventDate":222,"timestamp":111}}')
    out2 = mod.decode_measurement_lines(line2)
    assert out2[0] == ["dt"] and out2[1] == ["main"]
    assert np.frombuffer(out2[3], np.float64).tolist() == [222.0]


def test_non_json_numbers_bail():
    mod = load_swwire()
    for bad in (b'.5', b'+1', b'0x10', b'nan', b'Infinity', b'1.', b'01'):
        line = (b'{"deviceToken":"d","type":"Measurement",'
                b'"request":{"name":"t","value":' + bad + b'}}')
        assert mod.decode_measurement_lines(line) is None, bad


# ---------------------------------------------------------------------------
# split_owner_lines: the multi-host routing edge must agree with the
# Python splitter byte-for-byte (ownership is a cluster-wide contract)
# ---------------------------------------------------------------------------

def _python_owner(line: bytes, n: int) -> int:
    from sitewhere_tpu.rpc.forward import owning_process

    try:
        env = json.loads(line)
        token = (env.get("deviceToken") or env.get("hardwareId")
                 if isinstance(env, dict) else None)
        if token:
            return owning_process(str(token), n)
    except (ValueError, UnicodeDecodeError):
        pass
    return -1


def test_split_owner_lines_matches_python():
    sw = load_swwire()
    if not hasattr(sw, "split_owner_lines"):
        pytest.skip("split_owner_lines not built")
    lines = [
        _line(f"dev-{i}", 1.0).encode() for i in range(50)
    ] + [
        b'{"hardwareId": "hw-1", "type": "Location"}',     # alias
        b'{"deviceToken": "", "hardwareId": "hw-2"}',      # falsy -> alias
        b'{"deviceToken": "a", "deviceToken": "b"}',       # dup: last wins
        b'{"noToken": 5}',                                 # tokenless -> -1
        b'not json',                                       # malformed -> -1
        b'[1, 2, 3]',                                      # non-dict -> -1
        b'{"deviceToken": "t", "extra": {"deviceToken": "nested"}}',
        b'{"deviceToken": "t2", "arr": [1, "x", {"a": null}], "n": -1.5e3}',
        b'  {"deviceToken": "sp"}  ',                      # padded line
        '{"deviceToken": "ütf-8"}'.encode(),               # non-ascii utf8
        b'\x0b',                                  # NOT blank to json/native
        b'{"deviceToken": "t", "x": bogus}',      # bare word -> -1 both
        b'{"deviceToken": "t", "n": 01}',         # leading zero -> -1 both
        b'{"deviceToken": "\xff"}',               # invalid utf-8 -> -1 both
        b'{"deviceToken": "ok", "b": true, "c": null, "d": false}',
        b'{"deviceToken": "t", "v": NaN}',          # json.loads accepts
        b'{"deviceToken": "t", "v": Infinity}',
        b'{"deviceToken": "t", "v": -Infinity}',
        b'{"deviceToken": "t", "x": {bogus}}',      # invalid nested -> -1
        b'{"deviceToken": "t", "x": "a\\qb"}',      # bad escape -> -1
        b'{"deviceToken": "t", "x": "a\\u00e9\\n"}',  # valid escapes -> ok
        b'{"deviceToken": "t", "x": [1, {"k": "v"}, [true]]}',
        b'{"deviceToken": "t", "x": [1, 2}',        # mismatched -> -1
        b'{"deviceToken": "t", "x": {"a": 1,}}',    # trailing comma -> -1
    ]
    payload = b"\n".join(lines) + b"\n\n  \r\n"           # blank tails
    for n in (2, 3, 8):
        owners = sw.split_owner_lines(payload, n)
        assert owners is not None
        expected = [_python_owner(ln, n) for ln in lines]
        assert owners == expected


@pytest.mark.parametrize("line", [
    b'{"device\\u0054oken": "x"}',        # escaped KEY could be the token
    b'{"deviceToken": "a\\nb"}',          # escaped token value
    b'{"deviceToken": 42}',               # non-string token
    b'{"hardwareId": null}',              # non-string alias
])
def test_split_owner_lines_bails_on_ambiguity(line):
    sw = load_swwire()
    if not hasattr(sw, "split_owner_lines"):
        pytest.skip("split_owner_lines not built")
    payload = b'{"deviceToken": "ok"}\n' + line
    assert sw.split_owner_lines(payload, 4) is None
    # and the public splitter still routes every line via the Python path
    from sitewhere_tpu.rpc.forward import split_lines

    by_owner = split_lines(payload, 4)
    assert sum(len(v) for v in by_owner.values()) == 2


def test_split_lines_uses_same_enumeration_as_native():
    """Blank-line skipping and \\n-splitting must align between the
    native owner array and the Python-side line list they zip with."""
    from sitewhere_tpu.rpc.forward import split_lines

    payload = (b'\n  \n{"deviceToken": "a"}\r\n\n'
               b'{"deviceToken": "b"}\n\t\n')
    by_owner = split_lines(payload, 1)
    lines = [ln for v in by_owner.values() for ln in v]
    assert sorted(lines) == sorted(
        [b'{"deviceToken": "a"}\r', b'{"deviceToken": "b"}'])


# ---- decode_event_lines: the full wire family --------------------------

def _loc_line(token, lat, lon, ts=1_753_800_000, extra=None):
    req = {"latitude": lat, "longitude": lon, "eventDate": ts}
    req.update(extra or {})
    return json.dumps({"deviceToken": token, "type": "Location",
                       "request": req}, separators=(",", ":"))


def _alert_line(token, atype="overheat", level="warning",
                ts=1_753_800_000, extra=None):
    req = {"type": atype, "level": level, "message": "hot!",
           "eventDate": ts}
    req.update(extra or {})
    return json.dumps({"deviceToken": token, "type": "Alert",
                       "request": req}, separators=(",", ":"))


def test_native_mixed_family_matches_python():
    """Measurements + locations + alerts in one payload decode natively
    and bit-match the pure-Python columnar decoder."""
    rng = np.random.default_rng(1)
    lines = []
    for i in range(300):
        k = i % 3
        if k == 0:
            lines.append(_line(f"dev-{i}", float(rng.uniform(0, 100)),
                               ts=1_753_800_000 + i))
        elif k == 1:
            lines.append(_loc_line(f"dev-{i}", float(rng.uniform(-80, 80)),
                                   float(rng.uniform(-170, 170)),
                                   ts=1_753_800_000 + i,
                                   extra={"elevation": float(i)}))
        else:
            lines.append(_alert_line(
                f"dev-{i}",
                level=("critical" if i % 2 else 2),
                ts=1_753_800_000 + i,
                extra=({"latitude": 1.5, "longitude": 2.5}
                       if i % 6 == 2 else {})))
    payload = "\n".join(lines).encode()

    native, host_n = columnar.decode_json_lines(payload)
    py, host_p = _python_decode(payload)
    assert host_n == host_p == []
    assert native["device_token"] == py["device_token"]
    assert native["mtype"] == py["mtype"]
    assert native["alert_type"] == py["alert_type"]
    for k in ("event_type", "ts_s", "ts_ns", "alert_level"):
        np.testing.assert_array_equal(np.asarray(native[k]),
                                      np.asarray(py[k]), err_msg=k)
    for k in ("value", "lat", "lon", "elevation"):
        np.testing.assert_allclose(np.asarray(native[k]),
                                   np.asarray(py[k]), rtol=1e-6, err_msg=k)
    np.testing.assert_array_equal(native["update_state"],
                                  py["update_state"])


def test_native_alert_precedence_matches_python():
    """Alert 'type' is get-with-default (present wins even empty);
    'alertType' is the fallback; missing both defaults to "alert"."""
    lines = [
        json.dumps({"deviceToken": "d1", "type": "Alert",
                    "request": {"type": "", "alertType": "x",
                                "eventDate": 1000}}),
        json.dumps({"deviceToken": "d2", "type": "Alert",
                    "request": {"alertType": "fallback",
                                "eventDate": 1000}}),
        json.dumps({"deviceToken": "d3", "type": "Alert",
                    "request": {"eventDate": 1000}}),
    ]
    payload = "\n".join(lines).encode()
    native, _ = columnar.decode_json_lines(payload)
    py, _ = _python_decode(payload)
    assert native["alert_type"] == py["alert_type"] == ["", "fallback", "alert"]


def test_native_splits_registration_lines():
    """Registrations split out as host-plane requests; event rows keep
    decoding natively — same result as the pure path."""
    from sitewhere_tpu.ingest.decoders import RequestKind

    lines = [
        _line("dev-1", 42.0),
        json.dumps({"deviceToken": "ghost", "type": "RegisterDevice",
                    "request": {"deviceTypeToken": "sensor"}}),
        _loc_line("dev-2", 1.0, 2.0),
    ]
    payload = "\n".join(lines).encode()
    sw = load_swwire()
    out = sw.decode_event_lines(payload)
    assert out is not None
    assert len(out[0]) == 2          # two event rows
    assert len(out[11]) == 1         # one host line
    cols, host = columnar.decode_json_lines(payload)
    assert cols["device_token"] == ["dev-1", "dev-2"]
    assert len(host) == 1
    assert host[0].kind == RequestKind.REGISTRATION
    assert host[0].device_token == "ghost"


def test_native_registration_bad_json_deadletters_whole_payload():
    """Native accepts the split, but a registration line json.loads
    rejects must dead-letter the whole payload like the pure path.
    (The native scanner validates lines, so craft one IT passes but
    json.loads refuses: impossible by design — instead verify a
    malformed registration line bails the whole payload natively.)"""
    payload = (_line("dev-1", 1.0) + "\n" +
               '{"deviceToken":"g","type":"RegisterDevice","request":{'
               ).encode()
    sw = load_swwire()
    assert sw.decode_event_lines(payload) is None


@pytest.mark.parametrize("line,why", [
    ('{"deviceToken":"d","type":"Alert","request":{"level":"Warning"}}',
     "level casing needs Python .lower()"),
    ('{"deviceToken":"d","type":"Location","request":{"latitude":1.0}}',
     "location missing longitude -> DecodeError in Python"),
    ('{"deviceToken":"d","type":"StateChange","request":{}}',
     "unsupported kind natively"),
    ('{"deviceToken":"","type":"Measurement","request":{"name":"t","value":1},"hardwareId":"h"}',
     "present-but-empty deviceToken is an error, not a fallthrough"),
    ('{"deviceToken":"d\\u0041","type":"Location","request":{"latitude":1,"longitude":2}}',
     "escaped token"),
])
def test_native_event_lines_bail_cases(line, why):
    sw = load_swwire()
    assert sw.decode_event_lines(line.encode()) is None, why


def test_native_event_extras_are_skipped_like_python():
    """Unknown envelope/request keys are ignored by the Python decoder,
    so the native scanner skips (and validates) them too."""
    line = ('{"deviceToken":"d","meta":{"a":[1,2,{"b":"c\\n"}]},'
            '"type":"Measurement",'
            '"request":{"name":"t","value":3.5,"weird":null,"arr":[true]}}')
    payload = line.encode()
    native, _ = columnar.decode_json_lines(payload)
    py, _ = _python_decode(payload)
    assert native["device_token"] == py["device_token"]
    np.testing.assert_allclose(native["value"], py["value"])


def test_fuzz_mutated_payloads_never_crash_and_never_diverge():
    """Randomized mutation fuzz over the C scanners: for any byte
    soup, the native tier must either BAIL (None) or produce exactly
    what the pure-Python columnar decoder produces — and never crash.
    Mutations: byte flips, truncations, splices of valid JSON lines,
    duplicated keys, random unicode, deep nesting."""
    import json as _json

    rng = np.random.default_rng(0xC0FFEE)
    mod = load_swwire()
    table = mod.TokenTable()
    for i in range(64):
        table.set(f"dev-{i}", i)

    def valid_line():
        kind = rng.choice(["Measurement", "Location", "Alert",
                           "RegisterDevice"])
        req = {"eventDate": int(rng.integers(0, 2_000_000_000))}
        if kind == "Measurement":
            req.update(name="m" + str(rng.integers(0, 5)),
                       value=float(rng.normal()))
        elif kind == "Location":
            req.update(latitude=float(rng.uniform(-90, 90)),
                       longitude=float(rng.uniform(-180, 180)))
        elif kind == "Alert":
            req.update(type="t", level=str(rng.choice(
                ["info", "warning", "error", "critical"])))
        else:
            req.update(deviceTypeToken="sensor")
        return _json.dumps({
            "deviceToken": f"dev-{rng.integers(0, 64)}",
            "type": str(kind), "request": req})

    def mutate(payload: bytes) -> bytes:
        b = bytearray(payload)
        op = rng.integers(0, 6)
        if op == 0 and b:  # flip random bytes
            for _ in range(int(rng.integers(1, 8))):
                b[int(rng.integers(0, len(b)))] = int(rng.integers(0, 256))
        elif op == 1 and b:  # truncate
            del b[int(rng.integers(0, len(b))):]
        elif op == 2:  # splice random bytes in
            pos = int(rng.integers(0, len(b) + 1))
            b[pos:pos] = bytes(rng.integers(0, 256, int(rng.integers(1, 16)),
                                            dtype=np.uint8))
        elif op == 3:  # duplicate a random slice (repeated keys etc.)
            if len(b) > 4:
                lo = int(rng.integers(0, len(b) - 2))
                hi = int(rng.integers(lo + 1, len(b)))
                b[hi:hi] = b[lo:hi]
        elif op == 4:  # deep nesting injection
            b += b'\n{"deviceToken":"d","type":"Measurement","request":' \
                 + b'{' * int(rng.integers(1, 40)) + b'}'
        # op 5: leave as-is
        return bytes(b)

    checked = accepted = 0
    for trial in range(400):
        lines = [valid_line() for _ in range(int(rng.integers(1, 6)))]
        payload = "\n".join(lines).encode()
        if trial % 3:
            payload = mutate(payload)
        # 1. must never crash — all three scanners over arbitrary bytes
        mod.decode_measurement_lines(payload)
        mod.decode_event_lines(payload)
        mod.decode_measurement_lines_resolved(payload, table)
        checked += 1
        # 2. whatever the PRODUCTION native tier accepts — measurement
        # scanner first, family scanner second, exactly as
        # _native_decode tries them — must match the pure-Python decode
        # (None = bail is always allowed; a DecodeError means the C scan
        # accepted the shape but a shared value check rejected it — the
        # Python path must then reject the payload too)
        from sitewhere_tpu.ingest.decoders import DecodeError
        try:
            native, host_n = columnar._native_decode(payload) or (None, None)
        except DecodeError:
            with pytest.raises(Exception):
                T_py, _ = _python_decode(payload)
            continue
        if native is None:
            continue
        try:
            py, host_p = _python_decode(payload)
        except Exception as e:
            raise AssertionError(
                f"native accepted what python rejects: {payload!r}: {e}")
        assert len(host_n) == len(host_p)
        assert native["device_token"] == py["device_token"], payload
        if not native["device_token"]:
            continue  # host-only payload: no event columns to compare
        assert native["mtype"] == py["mtype"], payload
        assert native["alert_type"] == py["alert_type"], payload
        for col in ("event_type", "ts_s", "ts_ns", "alert_level",
                    "update_state"):
            np.testing.assert_array_equal(
                np.asarray(native[col]), np.asarray(py[col]),
                err_msg=f"{col}: {payload!r}")
        for col in ("value", "lat", "lon", "elevation"):
            np.testing.assert_allclose(
                np.asarray(native[col], np.float64),
                np.asarray(py[col], np.float64), rtol=1e-6,
                err_msg=f"{col}: {payload!r}")
        accepted += 1
    assert checked == 400 and accepted > 30  # fuzz actually exercised both
