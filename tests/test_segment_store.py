"""Log-structured sharded segment store (sitewhere_tpu/store).

Invariant suite for ISSUE 13: parallel background seal off the hot
path, catalog-governed retention/compaction, packed hot tier, and the
retrospective scan lane — golden live≡retro equivalence through
segments, catalog pruning correctness (zone-map/Bloom
false-negative-free), compaction idempotence, tiering
demotion/promotion round-trips, and the prune-vs-concurrent-seal
regression.
"""

import os
import threading
import time

import numpy as np
import pytest

from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.schema import EventType
from sitewhere_tpu.services.common import EntityNotFound
from sitewhere_tpu.store.segment import (
    COLUMNS,
    COLUMN_NAMES,
    Segment,
    event_id,
    pack_cols,
    split_event_id,
    unpack_cols,
    write_segment_file,
)
from sitewhere_tpu.store.segmented import SegmentStore

M = int(EventType.MEASUREMENT)
A = int(EventType.ALERT)
T0 = 1_753_900_000


def make_cols(n, *, device=None, tenant=None, etype=M, ts0=T0, value=None):
    cols = {}
    for name, dtype in COLUMNS:
        if name == "received_s":
            continue
        cols[name] = np.full(
            n, NULL_ID if np.issubdtype(dtype, np.integer) else 0.0, dtype)
    cols["device_id"] = np.asarray(
        device if device is not None else np.arange(n), np.int32)
    cols["tenant_id"] = np.asarray(
        tenant if tenant is not None else np.zeros(n), np.int32)
    cols["event_type"] = np.full(n, etype, np.int32)
    cols["ts_s"] = np.arange(ts0, ts0 + n, dtype=np.int32)
    cols["value"] = (np.linspace(0, 1, n).astype(np.float32)
                     if value is None else np.asarray(value, np.float32))
    return cols


def make_store(root, *, flush_rows=64, n_shards=4, workers=2,
               hot_bytes=64 << 20, compact_interval_s=0.0, **kw):
    return SegmentStore(
        str(root), flush_rows=flush_rows, flush_interval_s=10.0,
        n_shards=n_shards, seal_workers=workers, hot_bytes=hot_bytes,
        compact_interval_s=compact_interval_s, **kw)


def scan_rows(store, **filters):
    """(device_id, ts_s, value) tuples in scan order."""
    out = []
    for cols in store.iter_chunks(**filters):
        out.extend(zip(cols["device_id"].tolist(),
                       cols["ts_s"].tolist(),
                       np.round(cols["value"], 5).tolist()))
    return out


# ---------------------------------------------------------------------------
# parallel seal off the hot path
# ---------------------------------------------------------------------------


class TestBackgroundSeal:
    def test_append_seals_on_workers_not_caller(self, tmp_path):
        store = make_store(tmp_path, flush_rows=32)
        store.sealer.start()
        try:
            for k in range(8):
                store.append_columns(make_cols(64, ts0=T0 + 64 * k))
            store.flush(sync=True)
        finally:
            store.sealer.stop()
        assert store.total_events == 512
        assert store.sealer.sealed_segments > 0
        # every sealed segment is a durable file the catalog lists
        assert store.verify_catalog() == []

    def test_buffers_grow_on_demand_not_eagerly(self, tmp_path):
        """A huge flush_rows (the benches' 'never auto-seal' idiom)
        must not eagerly allocate gigabytes per shard buffer."""
        store = make_store(tmp_path, flush_rows=1 << 30, n_shards=2)
        store.append_columns(make_cols(10))
        bufs = [b for b in store._open_bufs if b is not None]
        assert bufs
        for b in bufs:
            assert b.alloc <= b.INITIAL_ROWS  # lazy, not cap-sized
        # growth past the initial allocation keeps every row
        store.append_columns(make_cols(9_000))
        store.flush(sync=True)
        assert store.total_events == 9_010

    def test_unstarted_store_still_seals_inline(self, tmp_path):
        store = make_store(tmp_path, flush_rows=16)
        store.append_columns(make_cols(64))
        store.flush(sync=True)
        assert store.total_events == 64
        assert store.verify_catalog() == []

    def test_reads_see_queued_and_buffered_rows(self, tmp_path):
        # with no workers running, filled buffers sit in the seal queue:
        # queries and ids must still resolve (fail-closed visibility)
        store = make_store(tmp_path, flush_rows=16, n_shards=1)
        rec = store.add_event(device_id=3, tenant_id=0, event_type=M,
                              ts_s=T0, mtype_id=1, value=2.5)
        store.append_columns(make_cols(40, ts0=T0 + 1))
        assert store.total_events == 41
        got = store.get_event(rec.event_id)
        assert got.value == pytest.approx(2.5)
        assert store.query(device_id=3).total >= 1
        store.flush(sync=True)
        assert store.get_event(rec.event_id).value == pytest.approx(2.5)

    def test_event_ids_stable_across_background_seal(self, tmp_path):
        store = make_store(tmp_path, flush_rows=8, n_shards=2)
        recs = [store.add_event(device_id=i % 4, tenant_id=0, event_type=M,
                                ts_s=T0 + i, mtype_id=1, value=float(i))
                for i in range(32)]
        store.sealer.start()
        try:
            store.flush(sync=True)
        finally:
            store.sealer.stop()
        for i, rec in enumerate(recs):
            assert store.get_event(rec.event_id).value == float(i)

    def test_flush_contract_restart_recovers(self, tmp_path):
        store = make_store(tmp_path, flush_rows=16)
        store.append_columns(make_cols(100))
        store.flush(sync=True)
        before = sorted(scan_rows(store))
        # restart: catalog rebuilds from segment files + manifest marker
        store2 = make_store(tmp_path, flush_rows=16)
        assert store2.total_events == 100
        assert sorted(scan_rows(store2)) == before
        assert store2.verify_catalog() == []


# ---------------------------------------------------------------------------
# golden live ≡ retro equivalence through segments
# ---------------------------------------------------------------------------


class TestLiveRetroEquivalence:
    def _feed(self, store, batches):
        for cols in batches:
            store.append_columns(cols)

    def _batches(self):
        rng = np.random.default_rng(11)
        batches = []
        for k in range(12):
            n = 48
            dev = rng.integers(0, 16, n).astype(np.int32)
            cols = make_cols(n, device=dev, ts0=T0 + k * n,
                             value=rng.random(n).astype(np.float32) * 50)
            batches.append(cols)
        return batches

    def test_per_device_order_survives_seal_and_compaction(self, tmp_path):
        batches = self._batches()
        live = {}  # device -> [(ts, value)] in arrival order
        for cols in batches:
            for d, t, v in zip(cols["device_id"].tolist(),
                               cols["ts_s"].tolist(),
                               np.round(cols["value"], 5).tolist()):
                live.setdefault(d, []).append((t, v))
        store = make_store(tmp_path, flush_rows=32, n_shards=4,
                           compact_min_rows=128)
        self._feed(store, batches)
        store.flush(sync=True)

        def retro_per_device():
            retro = {}
            for d, t, v in scan_rows(store):
                retro.setdefault(d, []).append((t, v))
            return retro

        assert retro_per_device() == live
        # ...and again through compaction (order_key keeps scan order)
        merged = store.compactor.drain()
        assert merged > 0
        assert retro_per_device() == live
        assert store.verify_catalog() == []
        # ...and across a restart of the compacted store
        store2 = make_store(tmp_path, flush_rows=32, n_shards=4)
        retro2 = {}
        for d, t, v in scan_rows(store2):
            retro2.setdefault(d, []).append((t, v))
        assert retro2 == live

    def test_compiled_query_matches_live_evaluation(self, tmp_path):
        """The H-STREAM claim: ONE compiled operator, fed live batches
        or sealed segments, produces identical matches."""
        from sitewhere_tpu.analytics.query import WindowQuery, compile_query

        batches = self._batches()
        q = WindowQuery(name="w", threshold=25.0, agg="mean", window_s=64)
        live_op = compile_query(q, capacity=16)
        live_matches = []
        for cols in batches:
            live_matches.extend(live_op.eval_cols(cols))
        live_matches.extend(live_op.flush())

        store = make_store(tmp_path, flush_rows=32, n_shards=4,
                           compact_min_rows=128)
        self._feed(store, batches)
        store.flush(sync=True)
        store.compactor.drain()
        retro_op = compile_query(q, capacity=16)
        retro_matches = []
        for cols in store.iter_chunks(event_type=M):
            retro_matches.extend(retro_op.eval_cols(cols))
        retro_matches.extend(retro_op.flush())

        # value rounded like the golden crash harness: float32 window
        # sums accumulate in batch-split order, and live batches split
        # differently than sealed segments (ULP-level drift)
        key = lambda m: (m.device_id, m.start_ts_s, round(m.value, 3))
        assert sorted(map(key, retro_matches)) == \
            sorted(map(key, live_matches))
        assert live_matches  # the workload produces real matches


# ---------------------------------------------------------------------------
# catalog pruning correctness (false-negative-free)
# ---------------------------------------------------------------------------


class TestCatalogPruning:
    def test_filters_never_lose_rows(self, tmp_path):
        rng = np.random.default_rng(5)
        store = make_store(tmp_path, flush_rows=32, n_shards=4)
        all_rows = []
        for k in range(8):
            n = 40
            dev = rng.integers(0, 64, n).astype(np.int32)
            ten = (dev % 3).astype(np.int32)
            et = np.where(rng.random(n) < 0.7, M, A).astype(np.int32)
            cols = make_cols(n, device=dev, tenant=ten, ts0=T0 + k * n)
            cols["event_type"] = et
            cols["mtype_id"] = (dev % 5).astype(np.int32)
            store.append_columns(cols)
            all_rows.extend(zip(dev.tolist(), ten.tolist(), et.tolist(),
                                cols["mtype_id"].tolist(),
                                cols["ts_s"].tolist()))
        store.flush(sync=True)

        def brute(device_id=None, tenant_id=None, event_type=None,
                  mtype_id=None, start_s=None, end_s=None):
            out = []
            for d, t, e, m, ts in all_rows:
                if device_id is not None and d != device_id:
                    continue
                if tenant_id is not None and t != tenant_id:
                    continue
                if event_type is not None and e != event_type:
                    continue
                if mtype_id is not None and m != mtype_id:
                    continue
                if start_s is not None and ts < start_s:
                    continue
                if end_s is not None and ts > end_s:
                    continue
                out.append((d, ts))
            return sorted(out)

        def lane(**filters):
            out = []
            for cols in store.iter_chunks(**filters):
                out.extend(zip(cols["device_id"].tolist(),
                               cols["ts_s"].tolist()))
            return sorted(out)

        cases = [
            {"device_id": 7}, {"device_id": 63}, {"device_id": 1},
            {"tenant_id": 2}, {"event_type": A}, {"mtype_id": 4},
            {"device_id": 9, "event_type": M},
            {"start_s": T0 + 100, "end_s": T0 + 200},
            {"device_id": 3, "start_s": T0 + 50, "end_s": T0 + 290},
            {"device_id": 999},  # absent key: Bloom prunes, zero rows
        ]
        for filters in cases:
            assert lane(**filters) == brute(**filters), filters
        # pruning also holds after compaction rewrites the metadata
        store.compactor.drain()
        for filters in cases:
            assert lane(**filters) == brute(**filters), filters

    def test_absent_device_prunes_without_io(self, tmp_path):
        from sitewhere_tpu.runtime.metrics import MetricsRegistry

        metrics = MetricsRegistry()
        store = make_store(tmp_path, flush_rows=64, n_shards=2,
                           hot_bytes=0, metrics=metrics)
        store.append_columns(make_cols(256, device=np.arange(256) % 8))
        store.flush(sync=True)
        store._cache.loads = 0
        assert scan_rows(store, device_id=100_000) == []
        assert store._cache.loads == 0  # zone-map/Bloom skipped every file


# ---------------------------------------------------------------------------
# retention vs concurrent seal (the ISSUE 13 regression)
# ---------------------------------------------------------------------------


class TestRetentionVsSeal:
    def test_prune_cannot_dangle_a_stalled_seal(self, tmp_path, monkeypatch):
        """A retention pass running while a seal worker is stalled
        MID-WRITE must neither delist nor unlink the in-flight segment:
        pruning goes through the catalog, and an uncommitted job is not
        in the catalog yet."""
        import sitewhere_tpu.store.sealer as sealer_mod

        gate = threading.Event()
        entered = threading.Event()
        stall = {"active": False}
        real_write = sealer_mod.write_segment_file

        def stalled_write(path, cols, seg, **kw):
            if stall["active"]:
                entered.set()
                assert gate.wait(timeout=10.0)
            return real_write(path, cols, seg, **kw)

        monkeypatch.setattr(sealer_mod, "write_segment_file",
                            stalled_write)
        store = make_store(tmp_path, flush_rows=16, n_shards=1, workers=1)
        # one OLD committed segment (sealed inline before workers start)
        store.append_columns(make_cols(16, ts0=1000))
        store.flush(sync=False)
        store.sealer.drain()
        assert store.total_events == 16 and len(store._chunks) == 1
        # one NEW buffer worth of OLD-TIMESTAMPED rows, sealed by the
        # (stalled) worker — the adversarial case: its rows are below
        # the cutoff, so a row-level retention would want them gone
        stall["active"] = True
        store.sealer.start()
        try:
            store.append_columns(make_cols(16, ts0=2000))
            store.flush(sync=False)             # close buffer → enqueue
            assert entered.wait(timeout=10.0)   # worker is mid-write
            removed = store.prune_older_than(10_000)
            assert removed == 16  # ONLY the committed segment
            stall["active"] = False
            gate.set()
            store.flush(sync=True)
        finally:
            stall["active"] = False
            gate.set()
            store.sealer.stop()
        # the stalled job committed cleanly after the prune
        assert store.total_events == 16
        assert store.verify_catalog() == []
        rows = scan_rows(store)
        assert len(rows) == 16 and all(t >= 2000 for _, t, _ in rows)
        # the next retention pass collects it normally
        assert store.prune_older_than(10_000) == 16
        assert store.total_events == 0
        assert store.verify_catalog() == []

    def test_prune_goes_through_catalog(self, tmp_path):
        store = make_store(tmp_path, flush_rows=32, n_shards=2)
        store.append_columns(make_cols(64, ts0=1000))
        store.flush(sync=True)   # old rows seal into their own segments
        store.append_columns(make_cols(64, ts0=50_000))
        store.flush(sync=True)
        removed = store.prune_older_than(10_000)
        assert removed == 64
        assert store.total_events == 64
        assert store.verify_catalog() == []
        # restart: the marker kept seqs from regressing
        store2 = make_store(tmp_path)
        assert store2._next_seq >= store._next_seq
        assert store2.total_events == 64


# ---------------------------------------------------------------------------
# compaction: idempotence, crash recovery, id remap
# ---------------------------------------------------------------------------


class TestCompaction:
    def _small_segments(self, store, k=6, rows=8):
        for i in range(k):
            store.append_columns(make_cols(rows, ts0=T0 + i * rows,
                                           device=np.arange(rows) % 4))
            store.flush(sync=False)
        store.sealer.drain()
        store.flush(sync=True)

    def test_compaction_merges_and_is_idempotent(self, tmp_path):
        store = make_store(tmp_path, flush_rows=1024, n_shards=1,
                           compact_min_rows=64)
        self._small_segments(store)
        before = scan_rows(store)
        segs_before = len(store._chunks)
        merged = store.compactor.drain()
        assert merged >= 2
        assert len(store._chunks) < segs_before
        assert scan_rows(store) == before  # content and order unchanged
        # idempotent: nothing left to do
        assert store.compactor.drain() == 0
        assert store.verify_catalog() == []

    def test_event_ids_resolve_through_remap(self, tmp_path):
        store = make_store(tmp_path, flush_rows=1024, n_shards=1,
                           compact_min_rows=64)
        recs = []
        for i in range(4):
            r = store.add_event(device_id=1, tenant_id=0, event_type=M,
                                ts_s=T0 + i, mtype_id=1, value=float(i))
            recs.append(r)
            store.flush(sync=False)
        store.sealer.drain()
        store.flush(sync=True)
        assert store.compactor.drain() >= 2
        for i, rec in enumerate(recs):
            got = store.get_event(rec.event_id)
            assert got.value == float(i)
            # round-trippable: the record carries the REQUESTED id,
            # not the merged segment's internal (seq, row)
            assert got.event_id == rec.event_id
        # and across a restart (provenance re-derives the remap)
        store2 = make_store(tmp_path, flush_rows=1024, n_shards=1)
        for i, rec in enumerate(recs):
            got = store2.get_event(rec.event_id)
            assert got.value == float(i)
            assert got.event_id == rec.event_id

    def test_crashed_swap_resolves_tombstones_at_boot(self, tmp_path):
        """Crash between the merged write and the input unlink: both
        live on disk.  Boot must adopt the merged segment and drop the
        inputs — rows exactly once."""
        store = make_store(tmp_path, flush_rows=1024, n_shards=1,
                           compact_min_rows=64)
        self._small_segments(store, k=3, rows=8)
        before = sorted(scan_rows(store))
        inputs = list(store._chunks)
        merged_cols = {
            name: np.concatenate([c.materialize()[name] for c in inputs])
            for name in COLUMN_NAMES
        }
        seq = store._next_seq
        seg = Segment(seq, merged_cols, shard=inputs[0].shard)
        replaces, base = [], 0
        for c in inputs:
            replaces.append((int(c.seq), base, int(c.n)))
            base += int(c.n)
        seg.replaces = tuple(replaces)
        write_segment_file(store._segment_path(seq), merged_cols, seg)
        # "crash" here: restart on the directory with both generations
        store2 = make_store(tmp_path, flush_rows=1024, n_shards=1)
        assert store2.catalog.tombstones_resolved == len(inputs)
        assert sorted(scan_rows(store2)) == before
        assert store2.verify_catalog() == []
        # old event ids still resolve through recorded provenance
        old_id = event_id(inputs[0].seq, 3)
        assert store2.get_event(old_id).ts_s == T0 + 3

    def test_scan_survives_compaction_mid_scan(self, tmp_path):
        """A scan's snapshot races background compaction: inputs the
        scan has not reached yet get merged and their files unlinked.
        Their rows must be served from the merged segment's recorded
        row range — never silently dropped."""
        store = make_store(tmp_path, flush_rows=1024, n_shards=1,
                           compact_min_rows=64, hot_bytes=0)
        self._small_segments(store, k=4, rows=8)
        expected = scan_rows(store)
        gen = store.iter_chunks()
        first = next(gen)          # snapshot taken, segment 0 served
        got = list(zip(first["device_id"].tolist(),
                       first["ts_s"].tolist(),
                       np.round(first["value"], 5).tolist()))
        assert store.compactor.drain() >= 2   # inputs now unlinked
        for cols in gen:                      # remap serves the rest
            got.extend(zip(cols["device_id"].tolist(),
                           cols["ts_s"].tolist(),
                           np.round(cols["value"], 5).tolist()))
        assert got == expected

    def test_no_merge_across_shard_count_generations(self, tmp_path):
        """Segments sealed under different events.shards values must
        never merge: after a reshard a device can hash to a different
        shard, and a cross-generation merge (order_key = run minimum)
        could move its newer rows ahead of older ones in scan order."""
        store = make_store(tmp_path, flush_rows=1024, n_shards=1,
                           compact_min_rows=64)
        self._small_segments(store, k=2, rows=8)
        # "restart" with a different shard count on the same data dir
        store2 = make_store(tmp_path, flush_rows=1024, n_shards=2,
                            compact_min_rows=64)
        for i in range(2):
            store2.append_columns(make_cols(8, ts0=T0 + 1000 + i * 8,
                                            device=np.arange(8) % 4))
            store2.flush(sync=False)
        store2.sealer.drain()
        store2.flush(sync=True)
        per_device = {}
        for d, t, v in scan_rows(store2):
            per_device.setdefault(d, []).append(t)
        run = store2.compactor._candidates()
        assert run, "small segments should still be mergeable in-gen"
        assert len({(c.shard, c.shard_count) for c in run}) == 1
        store2.compactor.drain()
        after = {}
        for d, t, v in scan_rows(store2):
            after.setdefault(d, []).append(t)
        assert after == per_device  # per-device order survived
        assert store2.verify_catalog() == []

    def test_retention_race_aborts_swap(self, tmp_path):
        """Retention delisting an input mid-merge must abort the swap
        (resurrecting pruned rows would violate the contract)."""
        store = make_store(tmp_path, flush_rows=1024, n_shards=1,
                           compact_min_rows=64)
        self._small_segments(store, k=3, rows=8)
        run = store.compactor._candidates()
        assert len(run) >= 2
        # prune EVERYTHING while the merge would be in flight
        store.prune_older_than(T0 + 10_000)
        assert store.compactor.run_once() == 0
        assert store.total_events == 0
        assert store.verify_catalog() == []


# ---------------------------------------------------------------------------
# tiering: packed hot tier round-trips
# ---------------------------------------------------------------------------


class TestTiering:
    def test_adopt_demote_promote_round_trip(self, tmp_path):
        # tier budget fits ~2 segments of 64 rows (64*80 B each)
        store = make_store(tmp_path, flush_rows=64, n_shards=1,
                           hot_bytes=2 * 64 * 80)
        for k in range(6):
            store.append_columns(make_cols(64, ts0=T0 + 64 * k))
        store.flush(sync=True)
        assert store.hot.demotions > 0  # budget forced evictions
        assert len(store.hot) <= 2
        # the newest segment survived LRU adoption → direct hot hit
        assert store.hot.get(store._chunks[-1].seq) is not None
        before = scan_rows(store)       # UNFILTERED scan: no promotion
        assert store.hot.promotions == 0  # (would thrash the live tier)
        after = scan_rows(store)
        assert after == before          # content bit-identical
        # a WINDOWED query promotes what it materializes...
        old = scan_rows(store, start_s=T0, end_s=T0 + 64 * 2 - 1)
        assert len(old) == 128
        assert store.hot.promotions > 0
        # ...and a repeat of the same window is tier-served
        assert scan_rows(store, start_s=T0, end_s=T0 + 64 * 2 - 1) == old
        assert store.hot.hits > 0

    def test_hot_block_matches_file_contents(self, tmp_path):
        store = make_store(tmp_path, flush_rows=32, n_shards=1)
        store.append_columns(make_cols(32))
        store.flush(sync=True)
        seg = store._chunks[-1]
        pair = store.hot.get(seg.seq)
        assert pair is not None
        hot_cols = unpack_cols(pair[0], pair[1])
        file_cols = seg.materialize()
        for name in COLUMN_NAMES:
            assert np.array_equal(hot_cols[name], file_cols[name]), name

    def test_pack_unpack_round_trip(self):
        cols = make_cols(17)
        cols["received_s"] = np.full(17, 123, np.int32)
        ints, flts = pack_cols(cols)
        back = unpack_cols(ints, flts)
        for name in COLUMN_NAMES:
            assert np.array_equal(back[name], cols[name]), name

    def test_scan_packed_blocks(self, tmp_path):
        from sitewhere_tpu.store.scan import scan_packed

        store = make_store(tmp_path, flush_rows=32, n_shards=2)
        store.append_columns(make_cols(96))
        store.flush(sync=True)
        total = 0
        for ints, flts, seg in scan_packed(store, event_type=M):
            cols = unpack_cols(ints, flts)
            assert (cols["event_type"] == M).all()
            total += ints.shape[1]
        assert total == 96


# ---------------------------------------------------------------------------
# checkpoint section + metrics + misc
# ---------------------------------------------------------------------------


class TestCatalogCheckpoint:
    def test_manifest_snapshot_and_drift(self, tmp_path):
        import json

        store = make_store(tmp_path, flush_rows=32)
        store.append_columns(make_cols(64))
        store.flush(sync=True)
        doc = json.loads(store.catalog.snapshot())
        assert doc["next_seq"] == store._next_seq
        assert {e["seq"] for e in doc["segments"]} == \
            {c.seq for c in store._chunks}
        # an honest manifest restores drift-free
        assert store.catalog.note_restored(doc) == []
        # a manifest naming a segment that never existed reports drift
        stale = dict(doc)
        stale["segments"] = doc["segments"] + [
            {"seq": 9999, "order_key": 9999, "shard": 0, "n": 1,
             "min_ts": 0, "max_ts": 0}]
        drift = store.catalog.note_restored(stale)
        assert any("9999" in d for d in drift)


class TestStoreMetricsAndBench:
    def test_store_metric_family_lints_clean(self, tmp_path):
        from sitewhere_tpu.analysis.metric_names import lint_names
        from sitewhere_tpu.runtime.metrics import MetricsRegistry

        metrics = MetricsRegistry()
        store = make_store(tmp_path, flush_rows=32, metrics=metrics)
        store.append_columns(make_cols(64))
        store.flush(sync=True)
        list(store.iter_chunks(device_id=1))
        store.compactor.run_once()
        names = [n for n in metrics.names() if n.startswith("store.")]
        assert names, "store.* family never registered"
        assert lint_names(names) == []

    def test_store_bench_smoke(self, tmp_path):
        """tools/store_bench.py end-to-end at CI scale: runs, the scan
        lane beats the legacy row scan, and results are bit-identical
        (ISSUE 13 acceptance, scaled)."""
        import importlib.util

        path = os.path.join(os.path.dirname(__file__), os.pardir,
                            "tools", "store_bench.py")
        spec = importlib.util.spec_from_file_location("store_bench", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        r = mod.run(rows=24_000, batch_rows=2048, flush_rows=2048,
                    keep_dir=str(tmp_path))
        assert r["bit_identical"]
        assert r["retro_matched_rows"] > 0
        assert r["retro_speedup"] > 1.0
        assert r["retro_segments_pruned"] > 0
        assert r["store_seal_segments"] > 0
        assert r["store_append_p99_s"] > 0.0


class TestSealFailClosed:
    def test_sync_flush_raises_while_seal_fails_then_heals(self, tmp_path):
        from sitewhere_tpu.runtime import faults

        store = make_store(tmp_path, flush_rows=16, n_shards=1, workers=1,
                           max_seal_retries=1000)
        store.sealer.start()
        try:
            faults.inject("event_store.seal", exc=OSError("disk full"),
                          times=None)
            store.append_columns(make_cols(32))
            with pytest.raises(OSError):
                store.flush(sync=True)
            # fail-closed: rows still readable (parked, not dropped)
            assert store.total_events == 32
            faults.clear("event_store.seal")
            store.flush(sync=True)   # retry_parked + drain heals
            assert store.total_events == 32
            assert store.verify_catalog() == []
        finally:
            faults.clear()
            store.sealer.stop()

    def test_inline_pump_parks_job_on_non_oserror(self, tmp_path):
        """The drain fallback (no live workers) must park — never drop
        — a job that dies on a NON-OSError: a lost job would let the
        next sync flush commit a journal offset over rows that exist
        nowhere."""
        from sitewhere_tpu.runtime import faults

        store = make_store(tmp_path, flush_rows=16, n_shards=1)
        try:
            faults.inject("event_store.seal")  # FaultInjected, once
            store.append_columns(make_cols(32))
            with pytest.raises(Exception):
                store.flush(sync=False)        # inline pump raises
            assert store.sealer.parked_count() >= 1  # parked, not lost
            assert store.total_events == 32    # rows still visible
            store.flush(sync=True)             # retry heals (fault spent)
            assert store.total_events == 32
            assert store.sealer.parked_count() == 0
            assert store.verify_catalog() == []
        finally:
            faults.clear()

    def test_writer_valve_bounds_seal_backlog(self, tmp_path):
        """With no workers draining, the append-side valve seals
        inline once the queue falls behind — the legacy 4×-flush_rows
        memory bound, pool edition."""
        store = make_store(tmp_path, flush_rows=64, n_shards=1, workers=1)
        # sealer never started: queue only drains through the valve
        for k in range(20):
            store.append_columns(make_cols(64, ts0=T0 + 64 * k))
        bound = 4 + store.sealer.n_workers + 1
        assert store.sealer.queue_depth() <= bound
        assert store.sealer.sealed_segments > 0  # valve did real seals
        store.flush(sync=True)
        assert store.total_events == 20 * 64

    def test_terminal_failure_dead_letters_not_wedges(self, tmp_path):
        from sitewhere_tpu.runtime import faults

        store = make_store(tmp_path, flush_rows=16, n_shards=1, workers=1,
                           max_seal_retries=0, seal_retry_window_s=0.0)
        store.sealer.start()
        try:
            faults.inject("event_store.seal", exc=OSError("disk dead"),
                          times=None)
            store.append_columns(make_cols(32))
            store.flush(sync=True)   # dead-letter IS the durable trace
            assert store.sealed_dead_lettered == 32
            assert store.total_events == 0
            faults.clear("event_store.seal")
            store.append_columns(make_cols(8, ts0=T0 + 100))
            store.flush(sync=True)   # the store is not wedged
            assert store.total_events == 8
        finally:
            faults.clear()
            store.sealer.stop()


class TestEgressColumnsView:
    def test_lazy_enrichment_fetch(self):
        from sitewhere_tpu.runtime.dispatcher import EgressColumns

        host = {name: np.arange(4, dtype=np.int32)
                for name in EgressColumns.HOST_COLUMNS}
        fetches = []

        class Out:
            def __getattr__(self, name):
                fetches.append(name)
                return np.full(4, 7, np.int32)

        cols = EgressColumns(host, Out())
        assert not fetches                      # nothing eager
        assert cols["device_id"] is host["device_id"]
        assert not fetches                      # host access is free
        assert (cols["area_id"] == 7).all()
        # first enrichment touch fetches ALL five once (thread-safe
        # memo), then releases the step output
        assert sorted(fetches) == sorted(EgressColumns.ENRICHMENT_COLUMNS)
        assert cols._out is None                # device buffers released
        assert (cols["area_id"] == 7).all()
        assert len(fetches) == 5                # memoized, no refetch
        assert "payload_ref" in cols and "asset_id" in cols
        assert "nope" not in cols
        assert len(dict(cols.items())) == len(cols) == 19

    def test_append_columns_accepts_view(self, tmp_path):
        from sitewhere_tpu.runtime.dispatcher import EgressColumns

        n = 24
        host = {name: np.arange(n, dtype=np.int32)
                if name not in ("value", "lat", "lon", "elevation")
                else np.zeros(n, np.float32)
                for name in EgressColumns.HOST_COLUMNS}
        host["ts_s"] = np.arange(T0, T0 + n, dtype=np.int32)

        class Out:
            def __getattr__(self, name):
                return np.zeros(n, np.int32)

        store = make_store(tmp_path, flush_rows=16)
        added = store.append_columns(EgressColumns(host, Out()),
                                     mask=np.arange(n) % 2 == 0)
        assert added == n // 2
        store.flush(sync=True)
        assert store.total_events == n // 2
