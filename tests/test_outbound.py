"""Outbound connectors: vectorized filters, delivery, manager isolation,
and event search providers."""

import json
import threading
import time

import numpy as np
import pytest

from sitewhere_tpu.outbound import (
    AreaFilter,
    CallbackConnector,
    CallbackFilter,
    DeviceTypeFilter,
    EventSearchProvider,
    EventTypeFilter,
    FileConnector,
    MqttOutboundConnector,
    OutboundConnectorsManager,
    SearchProvidersManager,
)
from sitewhere_tpu.outbound.connectors import marshal_row
from sitewhere_tpu.services.common import EntityNotFound, SearchCriteria


def make_cols(n=8):
    return {
        "device_id": np.arange(n, dtype=np.int32),
        "tenant_id": np.zeros(n, np.int32),
        "event_type": np.asarray([i % 3 for i in range(n)], np.int32),
        "ts_s": np.arange(n, dtype=np.int32) + 1000,
        "ts_ns": np.zeros(n, np.int32),
        "mtype_id": np.zeros(n, np.int32),
        "value": np.linspace(0, 1, n).astype(np.float32),
        "lat": np.ones(n, np.float32),
        "lon": np.ones(n, np.float32),
        "elevation": np.zeros(n, np.float32),
        "alert_code": np.full(n, 7, np.int32),
        "alert_level": np.ones(n, np.int32),
        "command_id": np.full(n, -1, np.int32),
        "area_id": np.asarray([1, 1, 2, 2, 3, 3, 1, 1], np.int32)[:n],
        "customer_id": np.zeros(n, np.int32),
        "asset_id": np.zeros(n, np.int32),
        "assignment_id": np.arange(n, dtype=np.int32),
        "device_type_id": np.asarray([0, 1] * (n // 2), np.int32),
    }


def test_filters_compose():
    cols = make_cols()
    mask = np.ones(8, np.bool_)
    seen = []
    conn = CallbackConnector(
        "c", lambda c, m: seen.append(m.copy()),
        filters=[
            AreaFilter([1], include=True),          # rows 0,1,6,7
            DeviceTypeFilter([1], include=False),   # drop odd rows
            CallbackFilter(lambda c: c["value"] < 0.9),  # drop row 7 (value 1.0)
        ],
    )
    n = conn.process_batch(cols, mask)
    assert n == 2
    assert list(np.nonzero(seen[0])[0]) == [0, 6]
    assert conn.processed == 2


def test_event_type_filter_alerts_only():
    cols = make_cols()
    got = []
    conn = CallbackConnector(
        "alerts", lambda c, m: got.extend(np.nonzero(m)[0].tolist()),
        filters=[EventTypeFilter([2], include=True)],
    )
    conn.process_batch(cols, np.ones(8, np.bool_))
    assert got == [2, 5]


def test_file_connector_writes_jsonl(tmp_path):
    path = str(tmp_path / "out" / "events.jsonl")
    conn = FileConnector("file", path)
    conn.process_batch(make_cols(), np.ones(8, np.bool_))
    lines = [json.loads(line) for line in open(path)]
    assert len(lines) == 8
    assert lines[0]["eventType"] == "measurement"
    assert lines[1]["eventType"] == "location"
    assert lines[2]["eventType"] == "alert"
    assert lines[2]["alertCode"] == 7
    assert lines[0]["areaId"] == 1


def test_mqtt_connector_multicast_routes():
    published = []

    class FakeClient:
        def publish(self, topic, payload, qos=0):
            published.append((topic, json.loads(payload)))

    conn = MqttOutboundConnector(
        "mqtt", FakeClient(),
        multicaster=lambda doc: (
            ["alerts", "all"] if doc["eventType"] == "alert" else ["all"]
        ),
        route_builder=lambda route, doc: f"sw/{route}/{doc['deviceId']}",
    )
    conn.process_batch(make_cols(), np.ones(8, np.bool_))
    topics = [t for t, _ in published]
    assert "sw/all/0" in topics
    assert "sw/alerts/2" in topics
    assert len([t for t in topics if t.startswith("sw/alerts/")]) == 2


def test_filter_crash_counts_as_connector_error():
    conn = CallbackConnector(
        "broken-filter", lambda c, m: None,
        filters=[CallbackFilter(lambda c: c["no-such-column"] < 1)])
    with pytest.raises(KeyError):
        conn.process_batch(make_cols(), np.ones(8, np.bool_))
    assert conn.errors == 1


def test_mqtt_publish_failure_counted_not_raised():
    class BoomClient:
        def publish(self, *a, **k):
            raise OSError("down")

    conn = MqttOutboundConnector("mqtt", BoomClient())
    conn.process_batch(make_cols(), np.ones(8, np.bool_))
    assert conn.errors == 8


def test_manager_fans_out_and_isolates_failures():
    good, order = [], []

    def slow_deliver(c, m):
        time.sleep(0.01)
        good.append(int(m.sum()))

    def bad_deliver(c, m):
        raise RuntimeError("connector bug")

    mgr = OutboundConnectorsManager([
        CallbackConnector("good", slow_deliver),
        CallbackConnector("bad", bad_deliver),
    ])
    mgr.initialize()
    mgr.start()
    try:
        for _ in range(3):
            mgr.submit(make_cols(), np.ones(8, np.bool_))
        mgr.drain()  # accurate: returns only after in-flight batches finish
        stats = mgr.stats()
        assert sum(good) == 24
        assert stats["bad"]["errors"] == 3
        assert stats["good"]["processed"] == 24
    finally:
        mgr.stop()


def test_search_providers(tmp_path):
    from sitewhere_tpu.services.event_store import EventStore

    store = EventStore(str(tmp_path))
    store.add_event(device_id=4, tenant_id=0, event_type=2, ts_s=50, alert_code=9)
    mgr = SearchProvidersManager([EventSearchProvider("default", store)])
    res = mgr.get_provider("default").search(device_id=4)
    assert res.total == 1
    assert res.results[0].alert_code == 9
    assert len(mgr.list_providers()) == 1
    with pytest.raises(EntityNotFound):
        mgr.get_provider("solr")
