"""Cross-host RPC fabric: wire framing, channels, demux failover,
interceptors, domain services, and keyed event forwarding.

Reference behaviors pinned here: ApiDemux round-robin + failover +
waitForChannel backoff (ApiDemux.java:42-110), JWT/tenant interceptors
(JwtServerInterceptor, TenantTokenServerInterceptor.java:53-57), the
near-cached device lookups (CachedDeviceManagementApiChannel.java), and
Kafka's keyed-partition placement at the host boundary
(MicroserviceKafkaProducer.java:106) — two real Instances in one
process, rows crossing "DCN" (localhost TCP) to their owning host.
"""

import json
import socket
import threading
import time

import pytest

from sitewhere_tpu.rpc import (
    ChannelUnavailable,
    HostForwarder,
    RemoteDeviceManagement,
    RpcChannel,
    RpcDemux,
    RpcError,
    RpcServer,
    bind_instance,
    owning_process,
    split_lines,
    wire,
)
from sitewhere_tpu.security.jwt import TokenManagement


# ---------------------------------------------------------------------------
# wire framing
# ---------------------------------------------------------------------------

class TestWire:
    def test_round_trip(self):
        frame = wire.request_frame(
            7, "device.get", {"token": "dev-1"},
            {"authorization": "abc", "tenant": "t1"}, b"\x00\x01binary")
        a, b = socket.socketpair()
        try:
            a.sendall(wire.encode(frame))
            got = wire.read_frame(b)
        finally:
            a.close()
            b.close()
        assert got.request_id == 7
        assert got.method == "device.get"
        assert got.body == {"token": "dev-1"}
        assert got.headers["tenant"] == "t1"
        assert got.attachment == b"\x00\x01binary"
        assert not got.is_response and not got.is_error

    def test_response_and_error_flags(self):
        ok = wire.response_frame(1, {"x": 1})
        err = wire.response_frame(2, {"error": "boom"}, error=True)
        assert ok.is_response and not ok.is_error
        assert err.is_response and err.is_error

    def test_bad_magic_rejected(self):
        a, b = socket.socketpair()
        try:
            a.sendall(b"XXXX" + b"\x00" * 24)
            with pytest.raises(wire.WireError):
                wire.read_frame(b)
        finally:
            a.close()
            b.close()

    def test_undecodable_body_is_wire_error(self):
        # invalid JSON body must surface as WireError (protocol fault →
        # connection drop + failover), never escape as ValueError and
        # kill the reader thread silently
        import struct
        raw = (wire._HEADER.pack(wire.MAGIC, wire.FLAG_RESPONSE, 0, 1)
               + struct.pack(">H", 0)
               + struct.pack(">I", 2) + b"{}"
               + struct.pack(">I", 5) + b"{oops"
               + struct.pack(">I", 0))
        a, b = socket.socketpair()
        try:
            a.sendall(raw)
            with pytest.raises(wire.WireError):
                wire.read_frame(b)
        finally:
            a.close()
            b.close()

    def test_eof_mid_frame(self):
        a, b = socket.socketpair()
        try:
            a.sendall(wire.encode(wire.request_frame(1, "m", None))[:10])
            a.close()
            with pytest.raises(ConnectionError):
                wire.read_frame(b)
        finally:
            b.close()


# ---------------------------------------------------------------------------
# server + channel
# ---------------------------------------------------------------------------

@pytest.fixture()
def server():
    srv = RpcServer(port=0)
    srv.register("echo", lambda ctx, body: body, auth_required=False)
    srv.register("attach",
                 lambda ctx, body: ({"n": len(ctx.attachment)},
                                    ctx.attachment[::-1]),
                 auth_required=False)
    srv.start()
    yield srv
    srv.stop()


class TestServerChannel:
    def test_echo_and_attachment(self, server):
        chan = RpcChannel(server.endpoint)
        body, _ = chan.call("echo", {"hello": "world"})
        assert body == {"hello": "world"}
        body, attach = chan.call("attach", None, attachment=b"abc")
        assert body == {"n": 3}
        assert attach == b"cba"
        chan.close()

    def test_unknown_method_is_rpc_error(self, server):
        chan = RpcChannel(server.endpoint)
        with pytest.raises(RpcError) as exc:
            chan.call("nope", {})
        assert exc.value.error == "not_found"
        chan.close()

    def test_concurrent_calls_multiplex(self, server):
        chan = RpcChannel(server.endpoint)
        results = {}

        def worker(i):
            body, _ = chan.call("echo", {"i": i})
            results[i] = body["i"]

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == {i: i for i in range(16)}
        chan.close()

    def test_connection_refused_backoff(self):
        chan = RpcChannel("127.0.0.1:1")   # nothing listens on port 1
        with pytest.raises(ChannelUnavailable):
            chan.call("echo", {})
        # immediately retrying hits the backoff window, not the socket
        with pytest.raises(ChannelUnavailable) as exc:
            chan.call("echo", {})
        assert "backoff" in str(exc.value)
        chan.close()


# ---------------------------------------------------------------------------
# trace-context propagation across the RPC boundary
# ---------------------------------------------------------------------------

class TestTracePropagation:
    """The caller's trace context rides the frame headers; the server
    continues the SAME trace instead of starting a fresh one per call —
    the client/server tracing-interceptor pair."""

    def _server(self, tracer, fn=None):
        srv = RpcServer(port=0, tracer=tracer)
        srv.register("echo", fn or (lambda ctx, body: body),
                     auth_required=False)
        srv.start()
        return srv

    def test_same_trace_id_on_both_sides(self):
        from sitewhere_tpu.runtime.tracing import Tracer

        server_tracer = Tracer(sample_rate=1.0)
        srv = self._server(server_tracer)
        client_tracer = Tracer(sample_rate=1.0)
        try:
            chan = RpcChannel(srv.endpoint)
            trace = client_tracer.trace("forward.batch")
            body, _ = chan.call("echo", {"x": 1}, trace=trace)
            trace.end()
            chan.close()
            assert body == {"x": 1}
            client_spans = client_tracer.recent(10)
            server_spans = server_tracer.recent(10)
            assert [s["name"] for s in client_spans] == ["rpc.client.echo"]
            assert [s["name"] for s in server_spans] == ["rpc.server.echo"]
            # the acceptance criterion: one trace id across the boundary
            assert client_spans[0]["trace_id"] == server_spans[0]["trace_id"]
            # and the server span hangs off the client span
            assert server_spans[0]["parent_id"] == client_spans[0]["span_id"]
            assert server_tracer.joined == 1
        finally:
            srv.stop()

    def test_forced_error_retained_by_tail_sampler_on_both_sides(self):
        """A forced-error call with a 0% head rate: BOTH sides'
        tail samplers keep their half of the trace, same trace_id."""
        from sitewhere_tpu.runtime.tracing import Tracer

        def boom(ctx, body):
            raise ValueError("forced")

        server_tracer = Tracer(sample_rate=0.0, tail_errors=True)
        srv = self._server(server_tracer, fn=boom)
        client_tracer = Tracer(sample_rate=0.0, tail_errors=True)
        try:
            chan = RpcChannel(srv.endpoint)
            trace = client_tracer.trace("forward.batch")
            with pytest.raises(RpcError):
                chan.call("echo", {"x": 1}, trace=trace)
            trace.end()
            chan.close()
            assert server_tracer.retained_tail == 1
            assert client_tracer.retained_tail == 1
            client_spans = client_tracer.recent(10)
            server_spans = server_tracer.recent(10)
            assert client_spans[0]["trace_id"] == server_spans[0]["trace_id"]
            assert server_spans[0]["error"]
        finally:
            srv.stop()

    def test_no_trace_context_starts_fresh_server_trace(self):
        from sitewhere_tpu.runtime.tracing import Tracer

        server_tracer = Tracer(sample_rate=1.0)
        srv = self._server(server_tracer)
        try:
            chan = RpcChannel(srv.endpoint)
            chan.call("echo", {})
            chan.close()
            assert server_tracer.joined == 0
            assert server_tracer.sampled == 1
        finally:
            srv.stop()



class TestInterceptors:
    @pytest.fixture()
    def secured(self):
        tokens = TokenManagement()
        srv = RpcServer(port=0, tokens=tokens)
        srv.register("who", lambda ctx, body: {"user": ctx.username,
                                               "tenant": ctx.tenant})
        srv.register("admin.only", lambda ctx, body: {"ok": True},
                     authority="ROLE_ADMIN")
        srv.register("open", lambda ctx, body: {"ok": True},
                     auth_required=False)
        srv.start()
        yield srv, tokens
        srv.stop()

    def test_jwt_required(self, secured):
        srv, tokens = secured
        chan = RpcChannel(srv.endpoint)
        with pytest.raises(RpcError) as exc:
            chan.call("who", {})
        assert exc.value.error == "unauthorized"
        # open methods skip the interceptor (instance.ping analog)
        body, _ = chan.call("open", {})
        assert body == {"ok": True}
        chan.close()

    def test_jwt_and_tenant_headers_flow(self, secured):
        srv, tokens = secured
        jwt = tokens.mint("alice", ["ROLE_USER"])
        chan = RpcChannel(srv.endpoint, token_provider=lambda: jwt,
                          tenant="acme")
        body, _ = chan.call("who", {})
        assert body == {"user": "alice", "tenant": "acme"}
        chan.close()

    def test_authority_enforced(self, secured):
        srv, tokens = secured
        user = tokens.mint("bob", ["ROLE_USER"])
        admin = tokens.mint("root", ["ROLE_ADMIN"])
        chan = RpcChannel(srv.endpoint, token_provider=lambda: user)
        with pytest.raises(RpcError) as exc:
            chan.call("admin.only", {})
        assert exc.value.error == "forbidden"
        chan.close()
        chan = RpcChannel(srv.endpoint, token_provider=lambda: admin)
        body, _ = chan.call("admin.only", {})
        assert body == {"ok": True}
        chan.close()


# ---------------------------------------------------------------------------
# demux: round-robin, failover, recovery
# ---------------------------------------------------------------------------

class TestDemux:
    def _server(self, tag):
        srv = RpcServer(port=0)
        srv.register("which", lambda ctx, body: {"server": tag},
                     auth_required=False)
        srv.start()
        return srv

    def test_round_robin(self):
        a, b = self._server("a"), self._server("b")
        demux = RpcDemux([a.endpoint, b.endpoint])
        seen = {demux.call("which")[0]["server"] for _ in range(4)}
        assert seen == {"a", "b"}
        demux.close()
        a.stop()
        b.stop()

    def test_failover_when_replica_dies(self):
        a, b = self._server("a"), self._server("b")
        demux = RpcDemux([a.endpoint, b.endpoint])
        demux.call("which")   # connect both eventually
        a.stop()
        # every call still answers, from b
        for _ in range(4):
            assert demux.call("which")[0]["server"] == "b"
        demux.close()
        b.stop()

    def test_all_down_then_wait_for_channel(self):
        srv = self._server("a")
        endpoint = srv.endpoint
        srv.stop()
        demux = RpcDemux([endpoint])
        with pytest.raises(ChannelUnavailable):
            demux.call("which")
        # replica comes back on the same port; wait_for_channel reconnects
        host, port = endpoint.rsplit(":", 1)
        srv2 = RpcServer(host=host, port=int(port))
        srv2.register("which", lambda ctx, body: {"server": "a2"},
                      auth_required=False)
        srv2.start()
        demux.wait_for_channel(timeout_s=10)
        assert demux.call("which")[0]["server"] == "a2"
        demux.close()
        srv2.stop()

    def test_discovery_update_add_remove(self):
        a, b = self._server("a"), self._server("b")
        demux = RpcDemux([a.endpoint])
        assert demux.call("which")[0]["server"] == "a"
        demux.set_endpoints([b.endpoint])   # a removed, b added
        assert demux.endpoints == [b.endpoint]
        assert demux.call("which")[0]["server"] == "b"
        demux.close()
        a.stop()
        b.stop()


class TestWireLimits:
    def test_oversized_attachment_rejected_at_encode(self):
        frame = wire.request_frame(
            1, "m", None, attachment=b"x" * (wire.MAX_ATTACH + 1))
        with pytest.raises(wire.WireError):
            wire.encode(frame)

    def test_expired_token_unauthorized(self):
        tokens = TokenManagement()
        srv = RpcServer(port=0, tokens=tokens)
        srv.register("who", lambda ctx, body: {"user": ctx.username})
        srv.start()
        try:
            expired = tokens.mint("u", ["ROLE_USER"], expiration_min=-1)
            chan = RpcChannel(srv.endpoint,
                              token_provider=lambda: expired)
            with pytest.raises(RpcError) as exc:
                chan.call("who", {})
            assert exc.value.error == "unauthorized"
            chan.close()
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# domain services over the fabric + near-cache
# ---------------------------------------------------------------------------

from sitewhere_tpu.instance import Instance  # noqa: E402
from tests.test_instance import make_config, seed_device  # noqa: E402


@pytest.fixture()
def bound_instance(tmp_path):
    inst = Instance(make_config(tmp_path))
    inst.start()
    srv = RpcServer(port=0, tokens=inst.tokens, tracer=inst.tracer)
    bind_instance(srv, inst)
    srv.start()
    admin = inst.users.authenticate("admin", "password")
    jwt = inst.tokens.mint(admin.username, admin.authorities)
    yield inst, srv, jwt
    srv.stop()
    inst.stop()
    inst.terminate()


class TestDomainServices:
    def test_device_crud_and_events_over_fabric(self, bound_instance):
        inst, srv, jwt = bound_instance
        demux = RpcDemux([srv.endpoint], token_provider=lambda: jwt)
        demux.call("devicetype.create", {"token": "sensor", "name": "S"})
        demux.call("device.create", {"token": "dev-1",
                                     "device_type": "sensor"})
        demux.call("assignment.create", {"device": "dev-1"})
        body, _ = demux.call("device.get", {"token": "dev-1"})
        assert body["token"] == "dev-1"

        # event intake over the binary lane → owner's journaled wire path
        lines = b"\n".join(
            b'{"deviceToken": "dev-1", "type": "Measurement", "request":'
            b' {"name": "temp", "value": %d, "eventDate": 1000}}' % v
            for v in range(8))
        body, _ = demux.call("events.ingest", {"sourceId": "test"},
                             attachment=lines)
        assert body["accepted"] == 8
        inst.dispatcher.flush()
        body, _ = demux.call("events.query", {"deviceToken": "dev-1"})
        assert body["numResults"] == 8

        # state over the fabric
        body, _ = demux.call("state.get", {"deviceToken": "dev-1"})
        assert body["presence_missing"] in (True, False)
        demux.close()

    def test_mutations_need_admin(self, bound_instance):
        inst, srv, jwt = bound_instance
        inst.users.create_granted_authority("ROLE_USER")
        inst.users.create_user(username="viewer", password="pw",
                               authorities=["ROLE_USER"])
        weak = inst.tokens.mint("viewer", ["ROLE_USER"])
        demux = RpcDemux([srv.endpoint], token_provider=lambda: weak)
        with pytest.raises(RpcError) as exc:
            demux.call("device.create", {"token": "x",
                                         "device_type": "sensor"})
        assert exc.value.error == "forbidden"
        demux.close()

    def test_remote_device_management_cache(self, bound_instance):
        inst, srv, jwt = bound_instance
        seed_device(inst, "dev-c")
        demux = RpcDemux([srv.endpoint], token_provider=lambda: jwt)
        remote = RemoteDeviceManagement(demux, cache_ttl_s=60)
        first = remote.get_device("dev-c")
        again = remote.get_device("dev-c")
        assert first == again
        assert remote.hits == 1 and remote.misses == 1
        # write-through invalidation: update → next get refetches
        remote.update_device("dev-c", comments="updated")
        fresh = remote.get_device("dev-c")
        assert fresh["comments"] == "updated"
        assert remote.misses == 2
        # assignment near-cache
        a1 = remote.get_active_assignment("dev-c")
        a2 = remote.get_active_assignment("dev-c")
        assert a1 == a2 and remote.hits == 2
        demux.close()


# ---------------------------------------------------------------------------
# keyed cross-host forwarding (two Instances = two "hosts")
# ---------------------------------------------------------------------------

class TestForwarding:
    def test_owning_process_stable(self):
        assert owning_process("dev-1", 4) == owning_process("dev-1", 4)
        owners = {owning_process(f"dev-{i}", 4) for i in range(512)}
        assert owners == {0, 1, 2, 3}   # spreads over all processes

    def test_owning_process_rendezvous_elasticity(self):
        """Growing the fleet P -> P+1 remaps ~1/(P+1) of devices
        (rendezvous hashing; a modulo hash would remap ~P/(P+1)) and
        load stays balanced — including odd P, where a linear weight
        function (raw chained CRC32, the bug this test pins) skewed one
        process to 2× its share."""
        from collections import Counter

        tokens = [f"dev-{i}" for i in range(4000)]
        for P in (2, 3, 4, 5, 7, 8):
            counts = Counter(owning_process(t, P) for t in tokens)
            assert set(counts) == set(range(P))
            share = len(tokens) / P
            for p, n in counts.items():
                assert 0.8 * share < n < 1.2 * share, \
                    f"P={P}: process {p} holds {n} (fair share {share:.0f})"
            moved = sum(owning_process(t, P) != owning_process(t, P + 1)
                        for t in tokens)
            frac = moved / len(tokens)
            ideal = 1 / (P + 1)
            assert ideal / 1.5 < frac < ideal * 1.5, \
                f"P={P}: {frac:.2%} moved (ideal {ideal:.2%})"
            # devices that moved only ever move TO the new process
            for t in tokens:
                a, b = owning_process(t, P), owning_process(t, P + 1)
                assert a == b or b == P

    def test_split_lines_unparseable_stays_local(self):
        payload = (b'{"deviceToken": "d", "type": "Measurement"}\n'
                   b'not json at all\n'
                   b'{"noToken": 1}')
        by_owner = split_lines(payload, 2)
        locals_ = by_owner.get(-1, [])
        assert len(locals_) == 2   # bad line + tokenless line

    @pytest.fixture()
    def two_hosts(self, tmp_path):
        insts, servers = [], []
        for p in range(2):
            inst = Instance(make_config(tmp_path / f"host{p}"))
            inst.start()
            inst.device_management.create_device_type(token="sensor",
                                                      name="S")
            srv = RpcServer(port=0, tokens=inst.tokens, tracer=inst.tracer)
            bind_instance(srv, inst)
            srv.start()
            insts.append(inst)
            servers.append(srv)
        yield insts, servers
        for srv in servers:
            srv.stop()
        for inst in insts:
            inst.stop()
            inst.terminate()

    def test_rows_land_on_owning_host(self, two_hosts):
        insts, servers = two_hosts
        # find tokens owned by each process under the 2-way key hash
        tok0 = next(f"dev-{i}" for i in range(100)
                    if owning_process(f"dev-{i}", 2) == 0)
        tok1 = next(f"dev-{i}" for i in range(100)
                    if owning_process(f"dev-{i}", 2) == 1)
        for inst, tok in ((insts[0], tok0), (insts[1], tok1)):
            inst.device_management.create_device(token=tok,
                                                 device_type="sensor")
            inst.device_management.create_device_assignment(device=tok)

        jwt0 = insts[1].tokens.mint("admin", ["ROLE_ADMIN"])
        demux_to_1 = RpcDemux([servers[1].endpoint],
                              token_provider=lambda: jwt0)
        fwd = HostForwarder(
            insts[0].dispatcher, process_id=0,
            peer_demuxes={0: None, 1: demux_to_1},
            dead_letters=insts[0].dead_letters,
            deadline_ms=10.0)
        fwd.start()
        try:
            # one mixed payload arriving at host 0's frontend
            lines = []
            for tok in (tok0, tok1, tok0, tok1):
                lines.append(
                    b'{"deviceToken": "%s", "type": "Measurement",'
                    b' "request": {"name": "t", "value": 1,'
                    b' "eventDate": 1000}}' % tok.encode())
            accepted = fwd.ingest_payload(b"\n".join(lines))
            assert accepted == 2          # local rows only
            fwd.flush()
            deadline = time.time() + 10
            while time.time() < deadline and fwd.forwarded_rows < 2:
                time.sleep(0.05)
            assert fwd.forwarded_rows == 2
        finally:
            fwd.stop()
            demux_to_1.close()

        for inst in insts:
            inst.dispatcher.flush()
        d0 = insts[0].identity.device.lookup(tok0)
        d1 = insts[1].identity.device.lookup(tok1)
        insts[0].event_store.flush()
        insts[1].event_store.flush()
        assert len(insts[0].event_store.query(device_id=int(d0))) == 2
        assert len(insts[1].event_store.query(device_id=int(d1))) == 2
        # nothing dead-lettered, nothing misplaced
        assert fwd.dead_lettered == 0

    def test_forwarded_batch_trace_spans_both_hosts(self, two_hosts):
        """The DCN hop is traced end to end: a forwarded batch's
        client span (sender host) and server span (owning host) share
        one trace id — the cross-host half of the acceptance proof."""
        from sitewhere_tpu.runtime.tracing import Tracer

        insts, servers = two_hosts
        tok1 = next(f"dev-{i}" for i in range(100)
                    if owning_process(f"dev-{i}", 2) == 1)
        insts[1].device_management.create_device(token=tok1,
                                                 device_type="sensor")
        insts[1].device_management.create_device_assignment(device=tok1)

        jwt = insts[1].tokens.mint("admin", ["ROLE_ADMIN"])
        demux_to_1 = RpcDemux([servers[1].endpoint],
                              token_provider=lambda: jwt)
        fwd_tracer = Tracer(sample_rate=1.0)
        fwd = HostForwarder(
            insts[0].dispatcher, process_id=0,
            peer_demuxes={0: None, 1: demux_to_1},
            dead_letters=insts[0].dead_letters,
            deadline_ms=10.0, tracer=fwd_tracer)
        fwd.start()
        try:
            fwd.ingest_payload(
                b'{"deviceToken": "%s", "type": "Measurement",'
                b' "request": {"name": "t", "value": 1,'
                b' "eventDate": 1000}}' % tok1.encode())
            fwd.flush()
            deadline = time.time() + 10
            while time.time() < deadline and fwd.forwarded_rows < 1:
                time.sleep(0.05)
            assert fwd.forwarded_rows == 1
        finally:
            fwd.stop()
            demux_to_1.close()

        sent = [s for s in fwd_tracer.recent(200)
                if s["name"] == "rpc.client.events.ingest"]
        recv = [s for s in insts[1].tracer.recent(200)
                if s["name"] == "rpc.server.events.ingest"]
        assert sent and recv
        shared = {s["trace_id"] for s in sent} & {s["trace_id"] for s in recv}
        assert shared, "no trace id crossed the host boundary"
        # the DCN hop itself is a span (README: "forward.batch"), in the
        # same trace as the client/server legs
        hops = [s for s in fwd_tracer.recent(200)
                if s["name"] == "forward.batch"]
        assert hops and {s["trace_id"] for s in hops} & shared

    def test_config_driven_multihost_instances(self, tmp_path):
        """Two Instances from config alone (rpc.peers + shared jwt
        secret): a TCP protocol source on host 0 receives rows for BOTH
        hosts; each row lands on its owner, end to end."""
        import json as _json
        import socket as _socket
        import struct

        from sitewhere_tpu.ingest.decoders import JsonDecoder
        from sitewhere_tpu.ingest.sources import InboundEventSource, TcpReceiver

        # fixed ports so each peer list can be written before boot
        def free_port():
            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            return port

        ports = [free_port(), free_port()]
        peers = [f"127.0.0.1:{p}" for p in ports]
        insts = []
        for p in range(2):
            cfg = make_config(tmp_path / f"host{p}")
            cfg._tree["rpc"] = {
                "server": {"enabled": True, "host": "127.0.0.1",
                           "port": ports[p]},
                "process_id": p, "peers": peers,
                "forward_deadline_ms": 10.0,
            }
            cfg._tree["security"] = {"jwt_secret": "shared-test-secret"}
            inst = Instance(cfg)
            inst.start()
            inst.device_management.create_device_type(token="sensor",
                                                      name="S")
            insts.append(inst)
        assert insts[0].forwarder is not None

        tok0 = next(f"dev-{i}" for i in range(100)
                    if owning_process(f"dev-{i}", 2) == 0)
        tok1 = next(f"dev-{i}" for i in range(100)
                    if owning_process(f"dev-{i}", 2) == 1)
        for inst, tok in ((insts[0], tok0), (insts[1], tok1)):
            inst.device_management.create_device(token=tok,
                                                 device_type="sensor")
            inst.device_management.create_device_assignment(device=tok)

        src = insts[0].add_source(InboundEventSource(
            "tcp", [TcpReceiver(port=0)], JsonDecoder()))
        src.start()
        try:
            port = src.receivers[0].port
            with _socket.create_connection(("127.0.0.1", port)) as s:
                for tok, value in ((tok0, 1.0), (tok1, 2.0),
                                   (tok0, 3.0), (tok1, 4.0)):
                    payload = _json.dumps({
                        "deviceToken": tok, "type": "Measurement",
                        "request": {"name": "t", "value": value,
                                    "eventDate": 1000},
                    }).encode()
                    s.sendall(struct.pack(">I", len(payload)) + payload)
            deadline = time.time() + 15
            while time.time() < deadline:
                if insts[0].forwarder.forwarded_rows >= 2:
                    break
                insts[0].forwarder.flush(wait=True)
                time.sleep(0.05)
            assert insts[0].forwarder.forwarded_rows == 2
            for inst in insts:
                inst.dispatcher.flush()
                inst.event_store.flush()
            d0 = int(insts[0].identity.device.lookup(tok0))
            d1 = int(insts[1].identity.device.lookup(tok1))
            assert len(insts[0].event_store.query(device_id=d0)) == 2
            assert len(insts[1].event_store.query(device_id=d1)) == 2

            # federated search from host 0 sees BOTH hosts' events
            fed = insts[0].search_providers.get_provider("federated")
            all_events = fed.search()
            assert all_events.total == 4
            remote_only = fed.search(device_token=tok1)
            assert remote_only.total == 2   # rows that live on host 1
            # page_size 0 = unlimited sentinel, same as other providers
            from sitewhere_tpu.services.common import SearchCriteria
            unlimited = fed.search(SearchCriteria(page_size=0))
            assert len(unlimited.results) == unlimited.total == 4

            # cluster topology aggregates the peer over the fabric
            view = insts[0].cluster_topology()
            assert view["local"]["instance"] == "test-instance"
            assert view["peers"]["1"]["devices"] >= 1
            assert view["local"]["forwarding"]["forwarded_rows"] == 2

            # federated command invocation: REST on host 0 invokes a
            # command for host 1's device; the owner runs delivery
            import http.client as _http

            from sitewhere_tpu.web import WebServer

            insts[1].device_management.create_device_command(
                "sensor", token="ping", name="ping")
            a1 = insts[1].device_management.get_active_assignment(tok1)
            ws = WebServer(insts[0], port=0)
            ws.start()
            try:
                conn = _http.HTTPConnection("127.0.0.1", ws.port,
                                            timeout=10)
                jwt = insts[0].tokens.mint("admin", ["ROLE_ADMIN"])
                conn.request(
                    "POST", f"/api/assignments/{a1.token}/invocations",
                    body=_json.dumps({"commandToken": "ping"}),
                    headers={"Authorization": f"Bearer {jwt}"})
                resp = conn.getresponse()
                out = _json.loads(resp.read())
                conn.close()
                assert resp.status == 200 and out["queued"]
                insts[1].dispatcher.flush()
                insts[1].event_store.flush()
                from sitewhere_tpu.schema import EventType
                invs = insts[1].event_store.query(
                    device_id=d1,
                    event_type=int(EventType.COMMAND_INVOCATION))
                assert len(invs) == 1
            finally:
                ws.stop()
        finally:
            for inst in insts:
                inst.stop()
                inst.terminate()

    def test_multihost_requires_shared_secret(self, tmp_path):
        cfg = make_config(tmp_path)
        cfg._tree["rpc"] = {"server": {"enabled": True, "host": "127.0.0.1",
                                       "port": 0},
                            "process_id": 0,
                            "peers": ["127.0.0.1:1", "127.0.0.1:2"]}
        with pytest.raises(ValueError, match="jwt_secret"):
            Instance(cfg)

    def test_durable_spool_survives_restart_and_peer_outage(self, tmp_path):
        """With a data_dir the forwarder write-ahead-spools remote rows:
        an unreachable peer retains them on disk (no dead-letter), and a
        new forwarder over the same spool delivers them once the peer is
        back — the crash-recovery half of at-least-once for the DCN hop."""
        inst = Instance(make_config(tmp_path / "local"))
        inst.start()
        tok = next(f"dev-{i}" for i in range(100)
                   if owning_process(f"dev-{i}", 2) == 1)
        line = (b'{"deviceToken": "%s", "type": "Measurement",'
                b' "request": {"name": "t", "value": 7,'
                b' "eventDate": 1000}}' % tok.encode())
        spool_dir = str(tmp_path / "spool")
        try:
            # phase 1: peer down — rows spool, nothing dead-letters
            down = RpcDemux(["127.0.0.1:1"])
            fwd = HostForwarder(inst.dispatcher, 0, {0: None, 1: down},
                                dead_letters=inst.dead_letters,
                                deadline_ms=5.0, max_retries=1,
                                data_dir=spool_dir)
            assert fwd.durable
            fwd.ingest_payload(line)
            fwd.flush(wait=True)
            assert fwd.dead_lettered == 0
            assert fwd.metrics()["pending"] == 1
            fwd.stop()
            down.close()

            # phase 2: "restart" — peer now up; spool replays on start
            peer = Instance(make_config(tmp_path / "peer"))
            peer.start()
            peer.device_management.create_device_type(token="sensor",
                                                      name="S")
            peer.device_management.create_device(token=tok,
                                                 device_type="sensor")
            peer.device_management.create_device_assignment(device=tok)
            srv = RpcServer(port=0, tokens=peer.tokens)
            bind_instance(srv, peer)
            srv.start()
            jwt = peer.tokens.mint("system", ["ROLE_ADMIN"])
            up = RpcDemux([srv.endpoint], token_provider=lambda: jwt)
            fwd2 = HostForwarder(inst.dispatcher, 0, {0: None, 1: up},
                                 dead_letters=inst.dead_letters,
                                 deadline_ms=5.0, data_dir=spool_dir)
            fwd2.start()
            deadline = time.time() + 10
            while time.time() < deadline and fwd2.forwarded_rows < 1:
                time.sleep(0.05)
            assert fwd2.forwarded_rows == 1
            assert fwd2.metrics()["pending"] == 0
            peer.dispatcher.flush()
            peer.event_store.flush()
            d = int(peer.identity.device.lookup(tok))
            assert len(peer.event_store.query(device_id=d)) == 1
            fwd2.stop()
            up.close()
            srv.stop()
            peer.stop()
            peer.terminate()
        finally:
            inst.stop()
            inst.terminate()

    def test_peer_endpoint_live_reload(self, tmp_path):
        """A peer that moves to a new port picks up on config.reload()
        (Consul-watch analog) without restarting the local instance; a
        peer-COUNT change is rejected (ownership would shift)."""
        import json as _json
        import socket as _socket

        def free_port():
            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            return port

        p0 = free_port()
        # remote "host 1": one instance, server rebinds ports across the test
        remote = Instance(make_config(tmp_path / "remote"))
        remote.start()
        remote.device_management.create_device_type(token="sensor", name="S")
        tok1 = next(f"dev-{i}" for i in range(100)
                    if owning_process(f"dev-{i}", 2) == 1)
        remote.device_management.create_device(token=tok1,
                                               device_type="sensor")
        remote.device_management.create_device_assignment(device=tok1)
        srv_a = RpcServer(port=0, tokens=remote.tokens)
        bind_instance(srv_a, remote)
        srv_a.start()

        cfg_path = tmp_path / "host0.json"
        base = make_config(tmp_path / "local")._tree

        def write_cfg(peer_ep):
            base["rpc"] = {
                "server": {"enabled": True, "host": "127.0.0.1",
                           "port": p0},
                "process_id": 0,
                "peers": [f"127.0.0.1:{p0}", peer_ep],
                "forward_deadline_ms": 10.0,
            }
            base["security"] = {"jwt_secret": "reload-secret"}
            cfg_path.write_text(_json.dumps(base))

        write_cfg(srv_a.endpoint)
        from sitewhere_tpu.runtime.config import Config
        cfg = Config.load(str(cfg_path), apply_env=False)
        local = Instance(cfg)
        local.start()
        # remote verifies local's service JWTs: same shared secret
        remote.tokens._secret = local.tokens._secret

        line = (b'{"deviceToken": "%s", "type": "Measurement",'
                b' "request": {"name": "t", "value": 1,'
                b' "eventDate": 1000}}' % tok1.encode())
        try:
            local.forwarder.ingest_payload(line)
            local.forwarder.flush(wait=True)
            assert local.forwarder.forwarded_rows == 1

            # peer moves: new server, new port; config file follows
            srv_a.stop()
            srv_b = RpcServer(port=0, tokens=remote.tokens)
            bind_instance(srv_b, remote)
            srv_b.start()
            write_cfg(srv_b.endpoint)
            cfg.reload()
            assert local._peer_demuxes[1].endpoints == [srv_b.endpoint]

            local.forwarder.ingest_payload(line)
            deadline = time.time() + 10
            while (time.time() < deadline
                   and local.forwarder.forwarded_rows < 2):
                local.forwarder.flush(wait=True)
                time.sleep(0.05)
            assert local.forwarder.forwarded_rows == 2
            assert local.forwarder.dead_lettered == 0

            # count change is refused: endpoints stay as they were
            base["rpc"]["peers"] = [f"127.0.0.1:{p0}", srv_b.endpoint,
                                    "127.0.0.1:9999"]
            cfg_path.write_text(_json.dumps(base))
            cfg.reload()
            assert len(local._peer_demuxes) == 2
            assert local._peer_demuxes[1].endpoints == [srv_b.endpoint]
            # a pure swap is refused too: same endpoints, different
            # process-id binding = ownership shift
            base["rpc"]["peers"] = [srv_b.endpoint, f"127.0.0.1:{p0}"]
            cfg_path.write_text(_json.dumps(base))
            cfg.reload()
            assert local._peer_demuxes[1].endpoints == [srv_b.endpoint]
            srv_b.stop()
            # terminate deregisters the listener: a reload after
            # teardown must not touch the dead instance's demuxes
            assert local._on_peers_changed in cfg._listeners
        finally:
            local.stop()
            local.terminate()
            remote.stop()
            remote.terminate()
        assert local._on_peers_changed not in cfg._listeners

    def test_down_peer_does_not_accumulate_sender_threads(self, tmp_path):
        """One sender per owner at a time: a down peer being retried must
        not grow a thread pile-up as flush ticks arrive (durable mode
        retains rows, so the owner stays pending for the whole outage)."""
        tok = next(f"dev-{i}" for i in range(100)
                   if owning_process(f"dev-{i}", 2) == 1)
        down = RpcDemux(["127.0.0.1:1"])
        fwd = HostForwarder(None, 0, {0: None, 1: down},
                            deadline_ms=1.0, max_retries=2,
                            data_dir=str(tmp_path))
        try:
            fwd.ingest_payload(
                b'{"deviceToken": "%s", "type": "Measurement",'
                b' "request": {"name": "t", "value": 1}}' % tok.encode())
            for _ in range(50):
                fwd.flush()
            with fwd._lock:
                assert len(fwd._senders) <= 1
            # wait out the in-flight sender: mid-poll the reader position
            # sits past the record until the failure seeks back, so
            # pending only settles once no sender is running
            deadline = time.time() + 10
            while time.time() < deadline:
                with fwd._lock:
                    if not fwd._senders:
                        break
                time.sleep(0.05)
            assert fwd.metrics()["pending"] == 1   # retained, not lost
        finally:
            fwd.stop()
            down.close()

    def test_wrong_secret_peer_dead_letters_as_rejected(self, tmp_path):
        """A peer whose JWT secret doesn't match rejects the forward as
        unauthorized — a NON-retryable rejection, so rows dead-letter
        with the reason recorded instead of spooling forever."""
        peer = Instance(make_config(tmp_path / "peer"))
        peer.start()
        srv = RpcServer(port=0, tokens=peer.tokens)   # peer's own secret
        bind_instance(srv, peer)
        srv.start()
        local = Instance(make_config(tmp_path / "local"))
        local.start()
        try:
            # local mints with ITS secret; peer can't verify it
            jwt = local.tokens.mint("system", ["ROLE_ADMIN"])
            demux = RpcDemux([srv.endpoint], token_provider=lambda: jwt)
            fwd = HostForwarder(local.dispatcher, 0, {0: None, 1: demux},
                                dead_letters=local.dead_letters,
                                deadline_ms=5.0)
            tok = next(f"dev-{i}" for i in range(100)
                       if owning_process(f"dev-{i}", 2) == 1)
            fwd.ingest_payload(
                b'{"deviceToken": "%s", "type": "Measurement",'
                b' "request": {"name": "t", "value": 1}}' % tok.encode())
            fwd.flush(wait=True)
            assert fwd.dead_lettered == 1
            assert fwd.forwarded_rows == 0
            dead = [json.loads(p) for _, p in
                    local.dead_letters.scan(0)]
            rejected = [d for d in dead
                        if d.get("kind") == "undeliverable-forward"]
            assert rejected and "unauthorized" in rejected[0]["reason"]
        finally:
            fwd.stop()
            demux.close()
            srv.stop()
            local.stop()
            local.terminate()
            peer.stop()
            peer.terminate()

    def test_unreachable_peer_dead_letters(self, tmp_path):
        inst = Instance(make_config(tmp_path))
        inst.start()
        try:
            demux = RpcDemux(["127.0.0.1:1"])
            fwd = HostForwarder(
                inst.dispatcher, process_id=0,
                peer_demuxes={0: None, 1: demux},
                dead_letters=inst.dead_letters,
                deadline_ms=5.0, max_retries=1)
            tok = next(f"dev-{i}" for i in range(100)
                       if owning_process(f"dev-{i}", 2) == 1)
            fwd.ingest_payload(
                b'{"deviceToken": "%s", "type": "Measurement",'
                b' "request": {"name": "t", "value": 1}}'
                % tok.encode())
            fwd.flush(wait=True)
            assert fwd.dead_lettered >= 1
            demux.close()
        finally:
            inst.stop()
            inst.terminate()


# ---------------------------------------------------------------------------
# fleet health plane (rpc/health.py) + deadline propagation
# ---------------------------------------------------------------------------

from sitewhere_tpu.rpc import DeadlineExpired, PeerHealthTable, PeerState
from sitewhere_tpu.runtime import faults
from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.runtime.overload import OverloadShed, OverloadState


class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class TestPeerHealthTable:
    def _table(self, **kw):
        clock = _Clock()
        kw.setdefault("heartbeat_interval_s", 1.0)
        return PeerHealthTable([1], clock=clock, **kw), clock

    def test_silence_escalates_and_heartbeat_recovers(self):
        table, clock = self._table()   # suspect 3s, down 8s, dwell 2s
        assert table.state(1) == PeerState.ALIVE
        clock.advance(3.0)
        table.tick()
        assert table.state(1) == PeerState.SUSPECT
        clock.advance(5.0)
        table.tick()
        assert table.state(1) == PeerState.DOWN
        clock.advance(2.1)             # past the dwell
        table.observe_heartbeat(1)
        assert table.state(1) == PeerState.ALIVE

    def test_failure_streak_escalates_without_silence(self):
        """One-way partition: the peer's heartbeats still arrive but our
        sends fail — the send-failure streak must suspect it anyway,
        and an INCOMING beat must not paper over it (only an answered
        OUTBOUND call proves the path works again)."""
        table, clock = self._table(suspect_failures=3)
        for _ in range(2):
            table.observe_failure(1)
        assert table.state(1) == PeerState.ALIVE
        clock.advance(2.5)             # dwell satisfied
        table.observe_failure(1)
        assert table.state(1) == PeerState.SUSPECT
        # the peer's own beats keep arriving: still parked
        table.observe_heartbeat(1)
        clock.advance(2.5)
        table.tick()
        assert table.state(1) == PeerState.SUSPECT
        # an answered outbound call (a delivered probe) recovers it
        table.observe_alive(1)
        assert table.state(1) == PeerState.ALIVE

    def test_flapping_peer_never_oscillates_faster_than_hysteresis(self):
        """ISSUE acceptance: a peer flapping at the heartbeat period
        can change the table's verdict at most once per hysteresis
        dwell — no park/resume storms (fake clock, bit-exact)."""
        table, clock = self._table(hysteresis_s=2.0)
        transition_times = []
        last = table.state(1)
        # worst-case flap: one beat, then silence past suspect_after,
        # repeatedly, sampled every heartbeat period for 60 "seconds"
        for step in range(60):
            if step % 4 == 0:
                table.observe_heartbeat(1)
            clock.advance(1.0)
            table.tick()
            now_state = table.state(1)
            if now_state != last:
                transition_times.append(clock.t)
                last = now_state
        assert len(transition_times) >= 2      # it did flap
        gaps = [b - a for a, b in zip(transition_times,
                                      transition_times[1:])]
        assert min(gaps) >= 2.0, f"oscillated faster than dwell: {gaps}"
        snap = table.snapshot()["1"]
        assert snap["suppressed_flaps"] > 0    # hysteresis did real work

    def test_probe_pacing_claims_one_slot_per_interval(self):
        table, clock = self._table(probe_interval_s=2.0)
        table.observe_heartbeat(1, overload_state=int(OverloadState.SHEDDING),
                                retry_after_s=5.0)
        assert not table.can_drain(1)
        assert table.probe_due(1)
        assert not table.probe_due(1)          # slot claimed
        clock.advance(2.5)
        assert not table.probe_due(1)          # SHEDDING: retry-after (5s)
        clock.advance(3.0)                     # 5.5s > max(2, 5)
        assert table.probe_due(1)

    def test_owner_pressure_only_when_shedding(self):
        table, clock = self._table()
        assert table.owner_pressure(1) is None
        table.observe_heartbeat(1, overload_state=int(OverloadState.DEGRADED))
        assert table.owner_pressure(1) is None
        table.observe_heartbeat(1, overload_state=int(OverloadState.SHEDDING),
                                retry_after_s=2.0)
        assert table.owner_pressure(1) == (int(OverloadState.SHEDDING), 2.0)

    def test_piggyback_headers_update_overload(self):
        table, clock = self._table()
        table.observe_piggyback(1, {"x-overload": "2",
                                    "x-retry-after": "1.500"})
        assert table.overload_state(1) == 2
        assert table.retry_after(1) == 1.5
        assert not table.can_drain(1)
        table.observe_piggyback(1, {"x-overload": "0"})
        assert table.can_drain(1)

    def test_incarnation_change_is_recorded(self):
        table, clock = self._table()
        table.observe_heartbeat(1, incarnation=7)
        table.observe_heartbeat(1, incarnation=9)
        assert table.snapshot()["1"]["incarnation"] == 9

    def test_departed_peer_gauges_are_pruned(self):
        """Regression: ``forward.peer_state.<p>`` / ``.peer_overload.<p>``
        for a peer removed by set_peers (the apply_membership rebind
        path) used to linger forever — a fleet that churns membership
        accreted one gauge pair per peer that EVER existed, and the
        departed peer's frozen DOWN kept dashboards alerting."""
        registry = MetricsRegistry()
        clock = _Clock()
        table = PeerHealthTable([1, 2], clock=clock,
                                heartbeat_interval_s=1.0, metrics=registry)
        assert "forward.peer_state.2" in registry.names()
        table.set_peers([1, 3])
        names = registry.names()
        # peer 2 left: both its gauges unregister; peer 3 joined
        assert "forward.peer_state.2" not in names
        assert "forward.peer_overload.2" not in names
        assert "forward.peer_state.1" in names
        assert "forward.peer_state.3" in names
        # a full scrape after the churn carries no ghost peers
        from sitewhere_tpu.runtime.metrics import (
            parse_exposition,
            render_openmetrics,
        )

        families = parse_exposition(render_openmetrics(registry))
        assert "forward_peer_state_2" not in families

    def test_forward_metric_names_pass_the_lint(self):
        """Satellite: the forward.* family is a registered, linted
        metric surface — not a dict-only side channel."""
        from sitewhere_tpu.analysis.metric_names import lint_names

        registry = MetricsRegistry()
        fwd = HostForwarder(None, 0, {0: None, 1: RpcDemux(["127.0.0.1:1"])},
                            metrics=registry)
        names = [n for n in registry.names() if n.startswith("forward.")]
        assert "forward.pending_rows" in names
        assert "forward.peer_state.1" in names
        assert lint_names(names) == []


class TestDeadlinePropagation:
    def _server(self, fn):
        srv = RpcServer(port=0)
        srv.register("work.do", fn, auth_required=False)
        srv.start()
        return srv

    def test_expired_call_rejected_before_handler_runs(self):
        """ISSUE acceptance: injected fabric latency burns the budget in
        flight; the server answers deadline_expired WITHOUT executing
        the handler, and the rejection is retryable + distinct from
        peer-down."""
        ran = []
        srv = self._server(lambda c, b: ran.append(1) or {"ok": True})
        chan = RpcChannel(srv.endpoint)
        try:
            with faults.net_injected(srv.endpoint, latency_s=0.4):
                with pytest.raises(DeadlineExpired) as exc:
                    chan.call("work.do", {}, timeout_s=5.0, deadline_s=0.2)
            assert ran == []                       # handler never ran
            assert isinstance(exc.value, RpcError)  # retryable app error
            assert not isinstance(exc.value, ChannelUnavailable)
            # healthy fabric, fresh budget: the same call succeeds
            body, _ = chan.call("work.do", {}, deadline_s=5.0)
            assert body["ok"] and ran == [1]
        finally:
            chan.close()
            srv.stop()

    def test_budget_already_burned_fails_client_side(self):
        srv = self._server(lambda c, b: {"ok": True})
        chan = RpcChannel(srv.endpoint)
        try:
            with pytest.raises(DeadlineExpired):
                chan.call("work.do", {}, deadline_s=0.0)
            assert not chan.connected    # never even dialed
        finally:
            chan.close()
            srv.stop()

    def test_client_timeout_derives_from_budget(self):
        """A propagated 0.3s budget must cap the wait even when the
        caller passed a 30s transport timeout."""
        srv = self._server(lambda c, b: time.sleep(1.2) or {"ok": True})
        chan = RpcChannel(srv.endpoint)
        try:
            t0 = time.monotonic()
            with pytest.raises(ChannelUnavailable):
                chan.call("work.do", {}, timeout_s=30.0, deadline_s=0.3)
            assert time.monotonic() - t0 < 1.0
        finally:
            chan.close()
            srv.stop()

    def test_one_way_partition_executes_but_times_out(self):
        """The half-open link: the request is delivered (the handler
        runs!) but the reply is lost — the caller sees a transport
        fault, the distinct-from-deadline ambiguity a real network
        gives you."""
        ran = []
        srv = self._server(lambda c, b: ran.append(1) or {"ok": True})
        chan = RpcChannel(srv.endpoint)
        try:
            with faults.net_injected(srv.endpoint, drop=1.0, one_way=True):
                with pytest.raises(ChannelUnavailable):
                    chan.call("work.do", {}, timeout_s=0.4)
            deadline = time.time() + 5
            while time.time() < deadline and not ran:
                time.sleep(0.01)
            assert ran == [1]
        finally:
            chan.close()
            srv.stop()

    def test_response_piggyback_reaches_header_listener(self):
        seen = {}
        srv = self._server(lambda c, b: {"ok": True})
        srv.overload_provider = lambda: (2, 3.5)
        chan = RpcChannel(srv.endpoint, header_listener=seen.update)
        try:
            chan.call("work.do", {})
            assert seen["x-overload"] == "2"
            assert float(seen["x-retry-after"]) == 3.5
        finally:
            chan.close()
            srv.stop()


class _DownDemux:
    """Fake peer demux: every call is a transport failure."""

    def __init__(self):
        self.calls = 0
        self._lock = threading.Lock()

    def call(self, *a, **kw):
        with self._lock:
            self.calls += 1
        raise ChannelUnavailable("fake peer down")


class _ShedDemux:
    """Fake peer demux: admission refuses everything until healed."""

    def __init__(self):
        self.calls = 0
        self.accepted = []
        self.shedding = True
        self._lock = threading.Lock()

    def call(self, method, body=None, attachment=b"", **kw):
        with self._lock:
            self.calls += 1
            if self.shedding:
                raise RpcError("overloaded", "telemetry shed in SHEDDING",
                               {"x-overload": "2", "x-retry-after": "0.5"})
            lines = [l for l in attachment.split(b"\n") if l]
            self.accepted.extend(lines)
            return {"accepted": len(lines)}, b""


class _AcceptDemux:
    """Fake peer demux: accepts everything, records the lines."""

    def __init__(self):
        self.accepted = []
        self._lock = threading.Lock()

    def call(self, method, body=None, attachment=b"", **kw):
        if method != "events.ingest":
            return {}, b""
        with self._lock:
            lines = [l for l in attachment.split(b"\n") if l]
            self.accepted.extend(lines)
            return {"accepted": len(lines)}, b""


class _CollectorDispatcher:
    """Dispatcher stub: records locally-ingested wire lines."""

    def __init__(self):
        self.lines = []
        self._lock = threading.Lock()

    def ingest_wire_lines(self, payload, source_id="wire",
                          raise_on_decode_error=False):
        lines = [l for l in payload.split(b"\n") if l.strip()]
        with self._lock:
            self.lines.extend(lines)
        return len(lines)


def _wait_senders(fwd, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        with fwd._lock:
            if not fwd._senders:
                return
        time.sleep(0.01)
    raise AssertionError("senders did not quiesce")


def _line_for(owner, n_processes, value):
    tok = next(f"dev-{i}" for i in range(200)
               if owning_process(f"dev-{i}", n_processes) == owner)
    return (b'{"deviceToken": "%s", "type": "Measurement",'
            b' "request": {"name": "t", "value": %d,'
            b' "eventDate": 1000}}' % (tok.encode(), value))


class TestHealthGatedForwarding:
    def _health(self, clock, **kw):
        kw.setdefault("heartbeat_interval_s", 1.0)
        kw.setdefault("hysteresis_s", 0.0)
        kw.setdefault("suspect_failures", 1)
        return PeerHealthTable([1], clock=clock, **kw)

    def test_unhealthy_peer_probes_are_paced_not_a_retry_storm(
            self, tmp_path):
        """ISSUE acceptance: a SUSPECT peer's sender parks the spool and
        sends ONE paced probe per interval — send attempts stay bounded
        no matter how often the flusher ticks."""
        clock = _Clock()
        down = _DownDemux()
        fwd = HostForwarder(None, 0, {0: None, 1: down},
                            max_retries=1, data_dir=str(tmp_path),
                            metrics=MetricsRegistry(),
                            health=self._health(clock, probe_interval_s=2.0),
                            heartbeat_interval_s=0)
        try:
            fwd.ingest_payload(_line_for(1, 2, 1))
            fwd.flush()
            _wait_senders(fwd)
            assert fwd.health.state(1) == PeerState.SUSPECT
            after_first = down.calls
            assert after_first >= 1
            # flusher storm with the probe clock FROZEN: at most the one
            # already-claimed slot may still fire; no pile-up
            for _ in range(30):
                fwd.flush()
                _wait_senders(fwd)
            assert down.calls <= after_first + 1
            # clock advances past the probe interval: exactly one more
            clock.advance(2.5)
            mid = down.calls
            for _ in range(10):
                fwd.flush()
                _wait_senders(fwd)
            assert mid < down.calls <= mid + 1
            assert fwd.metrics()["pending"] == 1   # retained, never lost
            assert fwd.dead_lettered == 0
        finally:
            fwd.stop()

    def test_shed_peer_rows_park_then_drain_on_recovery(self, tmp_path):
        """An overloaded (SHEDDING) owner's rows park in the spool; the
        paced probe redelivers once it recovers and the spool drains —
        zero dead letters for rows the owner was always going to take."""
        clock = _Clock()
        shed = _ShedDemux()
        fwd = HostForwarder(None, 0, {0: None, 1: shed},
                            data_dir=str(tmp_path),
                            metrics=MetricsRegistry(),
                            health=self._health(clock, probe_interval_s=1.0),
                            heartbeat_interval_s=0)
        try:
            fwd.ingest_payload(_line_for(1, 2, 1))
            fwd.flush()
            _wait_senders(fwd)
            # the shed marked the peer's overload state off the error
            # frame's piggyback headers
            assert fwd.health.overload_state(1) == int(OverloadState.SHEDDING)
            assert not fwd.health.can_drain(1)
            assert fwd.metrics()["pending"] == 1
            assert fwd.dead_lettered == 0
            shed.shedding = False
            clock.advance(5.0)        # probe slot opens
            fwd.flush()
            _wait_senders(fwd)
            assert len(shed.accepted) == 1
            assert fwd.metrics()["pending"] == 0
            assert fwd.forwarded_rows == 1
        finally:
            fwd.stop()

    def test_memory_mode_shed_rows_buffer_then_forward_shed_kind(self):
        """Satellite: memory-mode overload-shed rows are NOT
        dead-lettered as 'peer unreachable' — they buffer under the
        retention bound, and a bound-forced drop dead-letters with the
        replayable forward-shed kind (hex payload, like intake-shed)."""
        from sitewhere_tpu.runtime.resilience import CollectingSink

        clock = _Clock()
        shed = _ShedDemux()
        sink = CollectingSink()
        dispatcher = _CollectorDispatcher()
        remote_line = _line_for(1, 2, 201)
        # retention bound fits exactly two remote lines; the third drops
        bound = 2 * (len(remote_line) + 1) + 4
        fwd = HostForwarder(dispatcher, 0, {0: None, 1: shed},
                            dead_letters=sink,
                            metrics=MetricsRegistry(),
                            health=self._health(clock, probe_interval_s=1.0),
                            heartbeat_interval_s=0,
                            max_retained_bytes=bound)
        # mixed local+remote payloads (the gateway-bulk shape): the edge
        # gate never fires, the remote share parks behind the shed owner
        fwd.ingest_payload(_line_for(0, 2, 101) + b"\n" + remote_line)
        fwd.flush()
        _wait_senders(fwd)
        assert fwd.metrics()["pending"] == 1      # retained, not dead
        assert len(sink) == 0
        # two more shed batches overflow the retention bound
        for v in (202, 203):
            clock.advance(5.0)
            fwd.ingest_payload(
                _line_for(0, 2, v - 100) + b"\n" + _line_for(1, 2, v))
            fwd.flush()
            _wait_senders(fwd)
        kinds = [d["kind"] for d in sink.records]
        assert kinds and set(kinds) == {"forward-shed"}
        dropped = sink.records[0]
        assert bytes.fromhex(dropped["payload"])  # replayable (hex) payload
        assert dropped["state"] == "SHEDDING"
        # every local row was ingested in place, every remote row is
        # either retained or audited as forward-shed: no silent loss
        assert len(dispatcher.lines) == 3
        retained = fwd.metrics()["pending"]
        dropped_rows = sum(
            bytes.fromhex(d["payload"]).count(b"\n") + 1
            for d in sink.records)
        assert retained + dropped_rows == 3
        # stop() in memory mode audits still-parked rows as replayable
        # forward-shed records — they die with the process, but never
        # silently
        fwd.stop()
        stop_rows = sum(
            bytes.fromhex(d["payload"]).count(b"\n") + 1
            for d in sink.records)
        assert stop_rows == 3
        assert {d["kind"] for d in sink.records} == {"forward-shed"}

    def test_stop_aborts_sender_backoff_promptly(self):
        """Satellite: sender retry backoff waits on the stop event —
        stop() returns promptly instead of waiting out ~2s sleeps."""
        down = _DownDemux()
        fwd = HostForwarder(None, 0, {0: None, 1: down},
                            max_retries=6,       # 0.1+0.2+...+2.0 ≈ 3.5s
                            metrics=MetricsRegistry(),
                            heartbeat_interval_s=0)
        fwd.start()
        fwd.ingest_payload(_line_for(1, 2, 1))
        fwd.flush()                     # sender enters its backoff loop
        time.sleep(0.15)
        t0 = time.monotonic()
        fwd.stop()
        assert time.monotonic() - t0 < 1.5

    def test_edge_refusal_reflects_remote_owner_overload(self):
        """ISSUE layer 3: a purely remote-owned telemetry payload whose
        owner advertises SHEDDING is refused with the OWNER's hint —
        the receiving transport turns that into 429 / 5.03 / pause."""
        clock = _Clock()
        fwd = HostForwarder(_CollectorDispatcher(), 0,
                            {0: None, 1: _AcceptDemux()},
                            metrics=MetricsRegistry(),
                            health=self._health(clock),
                            heartbeat_interval_s=0)
        fwd.health.observe_heartbeat(
            1, overload_state=int(OverloadState.SHEDDING), retry_after_s=4.0)
        with pytest.raises(OverloadShed) as exc:
            fwd.ingest_payload(_line_for(1, 2, 1))
        assert exc.value.retry_after_s == 4.0
        assert exc.value.state == OverloadState.SHEDDING
        assert fwd.metrics()["pending"] == 0      # nothing buffered
        # a CRITICAL-looking payload is never gated: the owner's own
        # admission decides (alerts are never shed)
        tok = next(f"dev-{i}" for i in range(200)
                   if owning_process(f"dev-{i}", 2) == 1)
        alert = (b'{"deviceToken": "%s", "type": "Alert", "request":'
                 b' {"type": "hot", "level": "warning", "eventDate": 1000}}'
                 % tok.encode())
        fwd.ingest_payload(alert)                 # no raise
        assert fwd.metrics()["pending"] == 1
        # mixed local+remote payloads forward too (spool absorbs)
        mixed = _line_for(0, 2, 7) + b"\n" + _line_for(1, 2, 8)
        fwd.ingest_payload(mixed)
        # recovery clears the gate
        fwd.health.observe_heartbeat(1, overload_state=0)
        fwd.ingest_payload(_line_for(1, 2, 9))
        fwd.stop()

    def test_heartbeat_learns_peer_overload_end_to_end(self, tmp_path):
        """The fleet.heartbeat loop against a real bound instance: the
        sender's table converges on the peer's forced overload state,
        then recovers."""
        inst = Instance(make_config(tmp_path))
        inst.start()
        srv = RpcServer(port=0, tokens=inst.tokens)
        bind_instance(srv, inst)
        srv.start()
        if inst.overload is not None:
            srv.overload_provider = lambda: (int(inst.overload.state),
                                             inst.overload.retry_after())
        jwt = inst.tokens.mint("system", ["ROLE_ADMIN"])
        demux = RpcDemux([srv.endpoint], token_provider=lambda: jwt)
        fwd = HostForwarder(None, 0, {0: None, 1: demux},
                            metrics=MetricsRegistry(),
                            heartbeat_interval_s=0.05)
        fwd.start()
        try:
            inst.overload.force(OverloadState.SHEDDING, reason="test")
            deadline = time.time() + 10
            while time.time() < deadline and fwd.health.overload_state(1) \
                    != int(OverloadState.SHEDDING):
                time.sleep(0.02)
            assert fwd.health.overload_state(1) == int(OverloadState.SHEDDING)
            assert fwd.health.state(1) == PeerState.ALIVE
            inst.overload.force(OverloadState.NORMAL, reason="test-done")
            deadline = time.time() + 10
            while time.time() < deadline and fwd.health.overload_state(1):
                time.sleep(0.02)
            assert fwd.health.overload_state(1) == 0
        finally:
            fwd.stop()
            demux.close()
            srv.stop()
            inst.stop()
            inst.terminate()


class TestMembershipUnderTraffic:
    def test_route_remote_rejects_stale_generation(self):
        fwd = HostForwarder(_CollectorDispatcher(), 0,
                            {0: None, 1: _AcceptDemux()},
                            metrics=MetricsRegistry(),
                            heartbeat_interval_s=0)
        with fwd._lock:
            gen = fwd._member_gen
        assert fwd._route_remote({}, gen)          # current gen: accepted
        with fwd._lock:
            fwd._member_gen += 1
        assert not fwd._route_remote({}, gen)      # stale: caller recomputes
        fwd.stop()

    def test_flapping_membership_under_concurrent_ingest_loses_nothing(
            self):
        """Satellite: apply_membership while ingest threads hammer the
        forwarder — every row lands at exactly one destination (no
        loss, no double-ownership), and rows ingested after the final
        map settle at their final owners."""
        dispatcher = _CollectorDispatcher()
        demux_a, demux_b, demux_c = (_AcceptDemux(), _AcceptDemux(),
                                     _AcceptDemux())
        maps = [
            {0: None, 1: demux_a, 2: demux_b},
            {0: None, 1: demux_a, 2: demux_b, 3: demux_c},
        ]
        fwd = HostForwarder(dispatcher, 0, dict(maps[0]),
                            metrics=MetricsRegistry(),
                            deadline_ms=2.0,
                            heartbeat_interval_s=0)
        fwd.start()
        n_threads, per_thread = 4, 40
        stop_flap = threading.Event()

        def ingest(tid):
            for i in range(per_thread):
                value = tid * 1000 + i
                # unique value marks the row across every destination
                fwd.ingest_payload(
                    b'{"deviceToken": "dev-%d", "type": "Measurement",'
                    b' "request": {"name": "t", "value": %d,'
                    b' "eventDate": 1000}}' % (value % 64, value))

        def flap():
            i = 0
            while not stop_flap.is_set():
                fwd.apply_membership(dict(maps[i % 2]))
                i += 1
            fwd.apply_membership(dict(maps[0]))    # final map: 3 processes

        threads = [threading.Thread(target=ingest, args=(t,))
                   for t in range(n_threads)]
        flapper = threading.Thread(target=flap)
        flapper.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        stop_flap.set()
        flapper.join(timeout=30)
        # rows ingested AFTER the final membership: ownership must be
        # computed under the final 3-process map, never a stale one
        tail_marker = 999_999
        fwd.ingest_payload(
            b'{"deviceToken": "dev-1", "type": "Measurement",'
            b' "request": {"name": "t", "value": %d,'
            b' "eventDate": 1000}}' % tail_marker)
        fwd.flush(wait=True)
        fwd.stop()

        import re as _re

        def values(lines):
            return [int(_re.search(rb'"value": (\d+)', l).group(1))
                    for l in lines]

        placed = {
            "local": values(dispatcher.lines),
            "a": values(demux_a.accepted),
            "b": values(demux_b.accepted),
            "c": values(demux_c.accepted),
        }
        want = {t * 1000 + i for t in range(n_threads)
                for i in range(per_thread)} | {tail_marker}
        got = [v for vs in placed.values() for v in vs]
        missing = want - set(got)
        assert not missing, f"lost rows: {sorted(missing)[:10]}"
        # exactly-once across DESTINATIONS: a row may never be accepted
        # by two different owners (memory-mode requeue is move, not copy)
        from collections import Counter as _Counter

        dup = {v for dest, vs in placed.items()
               for v in vs
               if sum(v in set(ovs) for ovs in placed.values()) > 1}
        assert not dup, f"double-owned rows: {sorted(dup)[:10]}"
        counts = _Counter(got)
        repeats = {v: c for v, c in counts.items() if c > 1}
        assert not repeats, f"duplicated rows: {list(repeats.items())[:10]}"
        # the tail row landed where the FINAL map says it belongs
        owner = owning_process("dev-1", 3)
        dest = {0: "local", 1: "a", 2: "b"}[owner]
        assert tail_marker in placed[dest]
