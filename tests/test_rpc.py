"""Cross-host RPC fabric: wire framing, channels, demux failover,
interceptors, domain services, and keyed event forwarding.

Reference behaviors pinned here: ApiDemux round-robin + failover +
waitForChannel backoff (ApiDemux.java:42-110), JWT/tenant interceptors
(JwtServerInterceptor, TenantTokenServerInterceptor.java:53-57), the
near-cached device lookups (CachedDeviceManagementApiChannel.java), and
Kafka's keyed-partition placement at the host boundary
(MicroserviceKafkaProducer.java:106) — two real Instances in one
process, rows crossing "DCN" (localhost TCP) to their owning host.
"""

import json
import socket
import threading
import time

import pytest

from sitewhere_tpu.rpc import (
    ChannelUnavailable,
    HostForwarder,
    RemoteDeviceManagement,
    RpcChannel,
    RpcDemux,
    RpcError,
    RpcServer,
    bind_instance,
    owning_process,
    split_lines,
    wire,
)
from sitewhere_tpu.security.jwt import TokenManagement


# ---------------------------------------------------------------------------
# wire framing
# ---------------------------------------------------------------------------

class TestWire:
    def test_round_trip(self):
        frame = wire.request_frame(
            7, "device.get", {"token": "dev-1"},
            {"authorization": "abc", "tenant": "t1"}, b"\x00\x01binary")
        a, b = socket.socketpair()
        try:
            a.sendall(wire.encode(frame))
            got = wire.read_frame(b)
        finally:
            a.close()
            b.close()
        assert got.request_id == 7
        assert got.method == "device.get"
        assert got.body == {"token": "dev-1"}
        assert got.headers["tenant"] == "t1"
        assert got.attachment == b"\x00\x01binary"
        assert not got.is_response and not got.is_error

    def test_response_and_error_flags(self):
        ok = wire.response_frame(1, {"x": 1})
        err = wire.response_frame(2, {"error": "boom"}, error=True)
        assert ok.is_response and not ok.is_error
        assert err.is_response and err.is_error

    def test_bad_magic_rejected(self):
        a, b = socket.socketpair()
        try:
            a.sendall(b"XXXX" + b"\x00" * 24)
            with pytest.raises(wire.WireError):
                wire.read_frame(b)
        finally:
            a.close()
            b.close()

    def test_undecodable_body_is_wire_error(self):
        # invalid JSON body must surface as WireError (protocol fault →
        # connection drop + failover), never escape as ValueError and
        # kill the reader thread silently
        import struct
        raw = (wire._HEADER.pack(wire.MAGIC, wire.FLAG_RESPONSE, 0, 1)
               + struct.pack(">H", 0)
               + struct.pack(">I", 2) + b"{}"
               + struct.pack(">I", 5) + b"{oops"
               + struct.pack(">I", 0))
        a, b = socket.socketpair()
        try:
            a.sendall(raw)
            with pytest.raises(wire.WireError):
                wire.read_frame(b)
        finally:
            a.close()
            b.close()

    def test_eof_mid_frame(self):
        a, b = socket.socketpair()
        try:
            a.sendall(wire.encode(wire.request_frame(1, "m", None))[:10])
            a.close()
            with pytest.raises(ConnectionError):
                wire.read_frame(b)
        finally:
            b.close()


# ---------------------------------------------------------------------------
# server + channel
# ---------------------------------------------------------------------------

@pytest.fixture()
def server():
    srv = RpcServer(port=0)
    srv.register("echo", lambda ctx, body: body, auth_required=False)
    srv.register("attach",
                 lambda ctx, body: ({"n": len(ctx.attachment)},
                                    ctx.attachment[::-1]),
                 auth_required=False)
    srv.start()
    yield srv
    srv.stop()


class TestServerChannel:
    def test_echo_and_attachment(self, server):
        chan = RpcChannel(server.endpoint)
        body, _ = chan.call("echo", {"hello": "world"})
        assert body == {"hello": "world"}
        body, attach = chan.call("attach", None, attachment=b"abc")
        assert body == {"n": 3}
        assert attach == b"cba"
        chan.close()

    def test_unknown_method_is_rpc_error(self, server):
        chan = RpcChannel(server.endpoint)
        with pytest.raises(RpcError) as exc:
            chan.call("nope", {})
        assert exc.value.error == "not_found"
        chan.close()

    def test_concurrent_calls_multiplex(self, server):
        chan = RpcChannel(server.endpoint)
        results = {}

        def worker(i):
            body, _ = chan.call("echo", {"i": i})
            results[i] = body["i"]

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == {i: i for i in range(16)}
        chan.close()

    def test_connection_refused_backoff(self):
        chan = RpcChannel("127.0.0.1:1")   # nothing listens on port 1
        with pytest.raises(ChannelUnavailable):
            chan.call("echo", {})
        # immediately retrying hits the backoff window, not the socket
        with pytest.raises(ChannelUnavailable) as exc:
            chan.call("echo", {})
        assert "backoff" in str(exc.value)
        chan.close()


# ---------------------------------------------------------------------------
# trace-context propagation across the RPC boundary
# ---------------------------------------------------------------------------

class TestTracePropagation:
    """The caller's trace context rides the frame headers; the server
    continues the SAME trace instead of starting a fresh one per call —
    the client/server tracing-interceptor pair."""

    def _server(self, tracer, fn=None):
        srv = RpcServer(port=0, tracer=tracer)
        srv.register("echo", fn or (lambda ctx, body: body),
                     auth_required=False)
        srv.start()
        return srv

    def test_same_trace_id_on_both_sides(self):
        from sitewhere_tpu.runtime.tracing import Tracer

        server_tracer = Tracer(sample_rate=1.0)
        srv = self._server(server_tracer)
        client_tracer = Tracer(sample_rate=1.0)
        try:
            chan = RpcChannel(srv.endpoint)
            trace = client_tracer.trace("forward.batch")
            body, _ = chan.call("echo", {"x": 1}, trace=trace)
            trace.end()
            chan.close()
            assert body == {"x": 1}
            client_spans = client_tracer.recent(10)
            server_spans = server_tracer.recent(10)
            assert [s["name"] for s in client_spans] == ["rpc.client.echo"]
            assert [s["name"] for s in server_spans] == ["rpc.server.echo"]
            # the acceptance criterion: one trace id across the boundary
            assert client_spans[0]["trace_id"] == server_spans[0]["trace_id"]
            # and the server span hangs off the client span
            assert server_spans[0]["parent_id"] == client_spans[0]["span_id"]
            assert server_tracer.joined == 1
        finally:
            srv.stop()

    def test_forced_error_retained_by_tail_sampler_on_both_sides(self):
        """A forced-error call with a 0% head rate: BOTH sides'
        tail samplers keep their half of the trace, same trace_id."""
        from sitewhere_tpu.runtime.tracing import Tracer

        def boom(ctx, body):
            raise ValueError("forced")

        server_tracer = Tracer(sample_rate=0.0, tail_errors=True)
        srv = self._server(server_tracer, fn=boom)
        client_tracer = Tracer(sample_rate=0.0, tail_errors=True)
        try:
            chan = RpcChannel(srv.endpoint)
            trace = client_tracer.trace("forward.batch")
            with pytest.raises(RpcError):
                chan.call("echo", {"x": 1}, trace=trace)
            trace.end()
            chan.close()
            assert server_tracer.retained_tail == 1
            assert client_tracer.retained_tail == 1
            client_spans = client_tracer.recent(10)
            server_spans = server_tracer.recent(10)
            assert client_spans[0]["trace_id"] == server_spans[0]["trace_id"]
            assert server_spans[0]["error"]
        finally:
            srv.stop()

    def test_no_trace_context_starts_fresh_server_trace(self):
        from sitewhere_tpu.runtime.tracing import Tracer

        server_tracer = Tracer(sample_rate=1.0)
        srv = self._server(server_tracer)
        try:
            chan = RpcChannel(srv.endpoint)
            chan.call("echo", {})
            chan.close()
            assert server_tracer.joined == 0
            assert server_tracer.sampled == 1
        finally:
            srv.stop()



class TestInterceptors:
    @pytest.fixture()
    def secured(self):
        tokens = TokenManagement()
        srv = RpcServer(port=0, tokens=tokens)
        srv.register("who", lambda ctx, body: {"user": ctx.username,
                                               "tenant": ctx.tenant})
        srv.register("admin.only", lambda ctx, body: {"ok": True},
                     authority="ROLE_ADMIN")
        srv.register("open", lambda ctx, body: {"ok": True},
                     auth_required=False)
        srv.start()
        yield srv, tokens
        srv.stop()

    def test_jwt_required(self, secured):
        srv, tokens = secured
        chan = RpcChannel(srv.endpoint)
        with pytest.raises(RpcError) as exc:
            chan.call("who", {})
        assert exc.value.error == "unauthorized"
        # open methods skip the interceptor (instance.ping analog)
        body, _ = chan.call("open", {})
        assert body == {"ok": True}
        chan.close()

    def test_jwt_and_tenant_headers_flow(self, secured):
        srv, tokens = secured
        jwt = tokens.mint("alice", ["ROLE_USER"])
        chan = RpcChannel(srv.endpoint, token_provider=lambda: jwt,
                          tenant="acme")
        body, _ = chan.call("who", {})
        assert body == {"user": "alice", "tenant": "acme"}
        chan.close()

    def test_authority_enforced(self, secured):
        srv, tokens = secured
        user = tokens.mint("bob", ["ROLE_USER"])
        admin = tokens.mint("root", ["ROLE_ADMIN"])
        chan = RpcChannel(srv.endpoint, token_provider=lambda: user)
        with pytest.raises(RpcError) as exc:
            chan.call("admin.only", {})
        assert exc.value.error == "forbidden"
        chan.close()
        chan = RpcChannel(srv.endpoint, token_provider=lambda: admin)
        body, _ = chan.call("admin.only", {})
        assert body == {"ok": True}
        chan.close()


# ---------------------------------------------------------------------------
# demux: round-robin, failover, recovery
# ---------------------------------------------------------------------------

class TestDemux:
    def _server(self, tag):
        srv = RpcServer(port=0)
        srv.register("which", lambda ctx, body: {"server": tag},
                     auth_required=False)
        srv.start()
        return srv

    def test_round_robin(self):
        a, b = self._server("a"), self._server("b")
        demux = RpcDemux([a.endpoint, b.endpoint])
        seen = {demux.call("which")[0]["server"] for _ in range(4)}
        assert seen == {"a", "b"}
        demux.close()
        a.stop()
        b.stop()

    def test_failover_when_replica_dies(self):
        a, b = self._server("a"), self._server("b")
        demux = RpcDemux([a.endpoint, b.endpoint])
        demux.call("which")   # connect both eventually
        a.stop()
        # every call still answers, from b
        for _ in range(4):
            assert demux.call("which")[0]["server"] == "b"
        demux.close()
        b.stop()

    def test_all_down_then_wait_for_channel(self):
        srv = self._server("a")
        endpoint = srv.endpoint
        srv.stop()
        demux = RpcDemux([endpoint])
        with pytest.raises(ChannelUnavailable):
            demux.call("which")
        # replica comes back on the same port; wait_for_channel reconnects
        host, port = endpoint.rsplit(":", 1)
        srv2 = RpcServer(host=host, port=int(port))
        srv2.register("which", lambda ctx, body: {"server": "a2"},
                      auth_required=False)
        srv2.start()
        demux.wait_for_channel(timeout_s=10)
        assert demux.call("which")[0]["server"] == "a2"
        demux.close()
        srv2.stop()

    def test_discovery_update_add_remove(self):
        a, b = self._server("a"), self._server("b")
        demux = RpcDemux([a.endpoint])
        assert demux.call("which")[0]["server"] == "a"
        demux.set_endpoints([b.endpoint])   # a removed, b added
        assert demux.endpoints == [b.endpoint]
        assert demux.call("which")[0]["server"] == "b"
        demux.close()
        a.stop()
        b.stop()


class TestWireLimits:
    def test_oversized_attachment_rejected_at_encode(self):
        frame = wire.request_frame(
            1, "m", None, attachment=b"x" * (wire.MAX_ATTACH + 1))
        with pytest.raises(wire.WireError):
            wire.encode(frame)

    def test_expired_token_unauthorized(self):
        tokens = TokenManagement()
        srv = RpcServer(port=0, tokens=tokens)
        srv.register("who", lambda ctx, body: {"user": ctx.username})
        srv.start()
        try:
            expired = tokens.mint("u", ["ROLE_USER"], expiration_min=-1)
            chan = RpcChannel(srv.endpoint,
                              token_provider=lambda: expired)
            with pytest.raises(RpcError) as exc:
                chan.call("who", {})
            assert exc.value.error == "unauthorized"
            chan.close()
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# domain services over the fabric + near-cache
# ---------------------------------------------------------------------------

from sitewhere_tpu.instance import Instance  # noqa: E402
from tests.test_instance import make_config, seed_device  # noqa: E402


@pytest.fixture()
def bound_instance(tmp_path):
    inst = Instance(make_config(tmp_path))
    inst.start()
    srv = RpcServer(port=0, tokens=inst.tokens, tracer=inst.tracer)
    bind_instance(srv, inst)
    srv.start()
    admin = inst.users.authenticate("admin", "password")
    jwt = inst.tokens.mint(admin.username, admin.authorities)
    yield inst, srv, jwt
    srv.stop()
    inst.stop()
    inst.terminate()


class TestDomainServices:
    def test_device_crud_and_events_over_fabric(self, bound_instance):
        inst, srv, jwt = bound_instance
        demux = RpcDemux([srv.endpoint], token_provider=lambda: jwt)
        demux.call("devicetype.create", {"token": "sensor", "name": "S"})
        demux.call("device.create", {"token": "dev-1",
                                     "device_type": "sensor"})
        demux.call("assignment.create", {"device": "dev-1"})
        body, _ = demux.call("device.get", {"token": "dev-1"})
        assert body["token"] == "dev-1"

        # event intake over the binary lane → owner's journaled wire path
        lines = b"\n".join(
            b'{"deviceToken": "dev-1", "type": "Measurement", "request":'
            b' {"name": "temp", "value": %d, "eventDate": 1000}}' % v
            for v in range(8))
        body, _ = demux.call("events.ingest", {"sourceId": "test"},
                             attachment=lines)
        assert body["accepted"] == 8
        inst.dispatcher.flush()
        body, _ = demux.call("events.query", {"deviceToken": "dev-1"})
        assert body["numResults"] == 8

        # state over the fabric
        body, _ = demux.call("state.get", {"deviceToken": "dev-1"})
        assert body["presence_missing"] in (True, False)
        demux.close()

    def test_mutations_need_admin(self, bound_instance):
        inst, srv, jwt = bound_instance
        inst.users.create_granted_authority("ROLE_USER")
        inst.users.create_user(username="viewer", password="pw",
                               authorities=["ROLE_USER"])
        weak = inst.tokens.mint("viewer", ["ROLE_USER"])
        demux = RpcDemux([srv.endpoint], token_provider=lambda: weak)
        with pytest.raises(RpcError) as exc:
            demux.call("device.create", {"token": "x",
                                         "device_type": "sensor"})
        assert exc.value.error == "forbidden"
        demux.close()

    def test_remote_device_management_cache(self, bound_instance):
        inst, srv, jwt = bound_instance
        seed_device(inst, "dev-c")
        demux = RpcDemux([srv.endpoint], token_provider=lambda: jwt)
        remote = RemoteDeviceManagement(demux, cache_ttl_s=60)
        first = remote.get_device("dev-c")
        again = remote.get_device("dev-c")
        assert first == again
        assert remote.hits == 1 and remote.misses == 1
        # write-through invalidation: update → next get refetches
        remote.update_device("dev-c", comments="updated")
        fresh = remote.get_device("dev-c")
        assert fresh["comments"] == "updated"
        assert remote.misses == 2
        # assignment near-cache
        a1 = remote.get_active_assignment("dev-c")
        a2 = remote.get_active_assignment("dev-c")
        assert a1 == a2 and remote.hits == 2
        demux.close()


# ---------------------------------------------------------------------------
# keyed cross-host forwarding (two Instances = two "hosts")
# ---------------------------------------------------------------------------

class TestForwarding:
    def test_owning_process_stable(self):
        assert owning_process("dev-1", 4) == owning_process("dev-1", 4)
        owners = {owning_process(f"dev-{i}", 4) for i in range(512)}
        assert owners == {0, 1, 2, 3}   # spreads over all processes

    def test_owning_process_rendezvous_elasticity(self):
        """Growing the fleet P -> P+1 remaps ~1/(P+1) of devices
        (rendezvous hashing; a modulo hash would remap ~P/(P+1)) and
        load stays balanced — including odd P, where a linear weight
        function (raw chained CRC32, the bug this test pins) skewed one
        process to 2× its share."""
        from collections import Counter

        tokens = [f"dev-{i}" for i in range(4000)]
        for P in (2, 3, 4, 5, 7, 8):
            counts = Counter(owning_process(t, P) for t in tokens)
            assert set(counts) == set(range(P))
            share = len(tokens) / P
            for p, n in counts.items():
                assert 0.8 * share < n < 1.2 * share, \
                    f"P={P}: process {p} holds {n} (fair share {share:.0f})"
            moved = sum(owning_process(t, P) != owning_process(t, P + 1)
                        for t in tokens)
            frac = moved / len(tokens)
            ideal = 1 / (P + 1)
            assert ideal / 1.5 < frac < ideal * 1.5, \
                f"P={P}: {frac:.2%} moved (ideal {ideal:.2%})"
            # devices that moved only ever move TO the new process
            for t in tokens:
                a, b = owning_process(t, P), owning_process(t, P + 1)
                assert a == b or b == P

    def test_split_lines_unparseable_stays_local(self):
        payload = (b'{"deviceToken": "d", "type": "Measurement"}\n'
                   b'not json at all\n'
                   b'{"noToken": 1}')
        by_owner = split_lines(payload, 2)
        locals_ = by_owner.get(-1, [])
        assert len(locals_) == 2   # bad line + tokenless line

    @pytest.fixture()
    def two_hosts(self, tmp_path):
        insts, servers = [], []
        for p in range(2):
            inst = Instance(make_config(tmp_path / f"host{p}"))
            inst.start()
            inst.device_management.create_device_type(token="sensor",
                                                      name="S")
            srv = RpcServer(port=0, tokens=inst.tokens, tracer=inst.tracer)
            bind_instance(srv, inst)
            srv.start()
            insts.append(inst)
            servers.append(srv)
        yield insts, servers
        for srv in servers:
            srv.stop()
        for inst in insts:
            inst.stop()
            inst.terminate()

    def test_rows_land_on_owning_host(self, two_hosts):
        insts, servers = two_hosts
        # find tokens owned by each process under the 2-way key hash
        tok0 = next(f"dev-{i}" for i in range(100)
                    if owning_process(f"dev-{i}", 2) == 0)
        tok1 = next(f"dev-{i}" for i in range(100)
                    if owning_process(f"dev-{i}", 2) == 1)
        for inst, tok in ((insts[0], tok0), (insts[1], tok1)):
            inst.device_management.create_device(token=tok,
                                                 device_type="sensor")
            inst.device_management.create_device_assignment(device=tok)

        jwt0 = insts[1].tokens.mint("admin", ["ROLE_ADMIN"])
        demux_to_1 = RpcDemux([servers[1].endpoint],
                              token_provider=lambda: jwt0)
        fwd = HostForwarder(
            insts[0].dispatcher, process_id=0,
            peer_demuxes={0: None, 1: demux_to_1},
            dead_letters=insts[0].dead_letters,
            deadline_ms=10.0)
        fwd.start()
        try:
            # one mixed payload arriving at host 0's frontend
            lines = []
            for tok in (tok0, tok1, tok0, tok1):
                lines.append(
                    b'{"deviceToken": "%s", "type": "Measurement",'
                    b' "request": {"name": "t", "value": 1,'
                    b' "eventDate": 1000}}' % tok.encode())
            accepted = fwd.ingest_payload(b"\n".join(lines))
            assert accepted == 2          # local rows only
            fwd.flush()
            deadline = time.time() + 10
            while time.time() < deadline and fwd.forwarded_rows < 2:
                time.sleep(0.05)
            assert fwd.forwarded_rows == 2
        finally:
            fwd.stop()
            demux_to_1.close()

        for inst in insts:
            inst.dispatcher.flush()
        d0 = insts[0].identity.device.lookup(tok0)
        d1 = insts[1].identity.device.lookup(tok1)
        insts[0].event_store.flush()
        insts[1].event_store.flush()
        assert len(insts[0].event_store.query(device_id=int(d0))) == 2
        assert len(insts[1].event_store.query(device_id=int(d1))) == 2
        # nothing dead-lettered, nothing misplaced
        assert fwd.dead_lettered == 0

    def test_forwarded_batch_trace_spans_both_hosts(self, two_hosts):
        """The DCN hop is traced end to end: a forwarded batch's
        client span (sender host) and server span (owning host) share
        one trace id — the cross-host half of the acceptance proof."""
        from sitewhere_tpu.runtime.tracing import Tracer

        insts, servers = two_hosts
        tok1 = next(f"dev-{i}" for i in range(100)
                    if owning_process(f"dev-{i}", 2) == 1)
        insts[1].device_management.create_device(token=tok1,
                                                 device_type="sensor")
        insts[1].device_management.create_device_assignment(device=tok1)

        jwt = insts[1].tokens.mint("admin", ["ROLE_ADMIN"])
        demux_to_1 = RpcDemux([servers[1].endpoint],
                              token_provider=lambda: jwt)
        fwd_tracer = Tracer(sample_rate=1.0)
        fwd = HostForwarder(
            insts[0].dispatcher, process_id=0,
            peer_demuxes={0: None, 1: demux_to_1},
            dead_letters=insts[0].dead_letters,
            deadline_ms=10.0, tracer=fwd_tracer)
        fwd.start()
        try:
            fwd.ingest_payload(
                b'{"deviceToken": "%s", "type": "Measurement",'
                b' "request": {"name": "t", "value": 1,'
                b' "eventDate": 1000}}' % tok1.encode())
            fwd.flush()
            deadline = time.time() + 10
            while time.time() < deadline and fwd.forwarded_rows < 1:
                time.sleep(0.05)
            assert fwd.forwarded_rows == 1
        finally:
            fwd.stop()
            demux_to_1.close()

        sent = [s for s in fwd_tracer.recent(200)
                if s["name"] == "rpc.client.events.ingest"]
        recv = [s for s in insts[1].tracer.recent(200)
                if s["name"] == "rpc.server.events.ingest"]
        assert sent and recv
        shared = {s["trace_id"] for s in sent} & {s["trace_id"] for s in recv}
        assert shared, "no trace id crossed the host boundary"
        # the DCN hop itself is a span (README: "forward.batch"), in the
        # same trace as the client/server legs
        hops = [s for s in fwd_tracer.recent(200)
                if s["name"] == "forward.batch"]
        assert hops and {s["trace_id"] for s in hops} & shared

    def test_config_driven_multihost_instances(self, tmp_path):
        """Two Instances from config alone (rpc.peers + shared jwt
        secret): a TCP protocol source on host 0 receives rows for BOTH
        hosts; each row lands on its owner, end to end."""
        import json as _json
        import socket as _socket
        import struct

        from sitewhere_tpu.ingest.decoders import JsonDecoder
        from sitewhere_tpu.ingest.sources import InboundEventSource, TcpReceiver

        # fixed ports so each peer list can be written before boot
        def free_port():
            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            return port

        ports = [free_port(), free_port()]
        peers = [f"127.0.0.1:{p}" for p in ports]
        insts = []
        for p in range(2):
            cfg = make_config(tmp_path / f"host{p}")
            cfg._tree["rpc"] = {
                "server": {"enabled": True, "host": "127.0.0.1",
                           "port": ports[p]},
                "process_id": p, "peers": peers,
                "forward_deadline_ms": 10.0,
            }
            cfg._tree["security"] = {"jwt_secret": "shared-test-secret"}
            inst = Instance(cfg)
            inst.start()
            inst.device_management.create_device_type(token="sensor",
                                                      name="S")
            insts.append(inst)
        assert insts[0].forwarder is not None

        tok0 = next(f"dev-{i}" for i in range(100)
                    if owning_process(f"dev-{i}", 2) == 0)
        tok1 = next(f"dev-{i}" for i in range(100)
                    if owning_process(f"dev-{i}", 2) == 1)
        for inst, tok in ((insts[0], tok0), (insts[1], tok1)):
            inst.device_management.create_device(token=tok,
                                                 device_type="sensor")
            inst.device_management.create_device_assignment(device=tok)

        src = insts[0].add_source(InboundEventSource(
            "tcp", [TcpReceiver(port=0)], JsonDecoder()))
        src.start()
        try:
            port = src.receivers[0].port
            with _socket.create_connection(("127.0.0.1", port)) as s:
                for tok, value in ((tok0, 1.0), (tok1, 2.0),
                                   (tok0, 3.0), (tok1, 4.0)):
                    payload = _json.dumps({
                        "deviceToken": tok, "type": "Measurement",
                        "request": {"name": "t", "value": value,
                                    "eventDate": 1000},
                    }).encode()
                    s.sendall(struct.pack(">I", len(payload)) + payload)
            deadline = time.time() + 15
            while time.time() < deadline:
                if insts[0].forwarder.forwarded_rows >= 2:
                    break
                insts[0].forwarder.flush(wait=True)
                time.sleep(0.05)
            assert insts[0].forwarder.forwarded_rows == 2
            for inst in insts:
                inst.dispatcher.flush()
                inst.event_store.flush()
            d0 = int(insts[0].identity.device.lookup(tok0))
            d1 = int(insts[1].identity.device.lookup(tok1))
            assert len(insts[0].event_store.query(device_id=d0)) == 2
            assert len(insts[1].event_store.query(device_id=d1)) == 2

            # federated search from host 0 sees BOTH hosts' events
            fed = insts[0].search_providers.get_provider("federated")
            all_events = fed.search()
            assert all_events.total == 4
            remote_only = fed.search(device_token=tok1)
            assert remote_only.total == 2   # rows that live on host 1
            # page_size 0 = unlimited sentinel, same as other providers
            from sitewhere_tpu.services.common import SearchCriteria
            unlimited = fed.search(SearchCriteria(page_size=0))
            assert len(unlimited.results) == unlimited.total == 4

            # cluster topology aggregates the peer over the fabric
            view = insts[0].cluster_topology()
            assert view["local"]["instance"] == "test-instance"
            assert view["peers"]["1"]["devices"] >= 1
            assert view["local"]["forwarding"]["forwarded_rows"] == 2

            # federated command invocation: REST on host 0 invokes a
            # command for host 1's device; the owner runs delivery
            import http.client as _http

            from sitewhere_tpu.web import WebServer

            insts[1].device_management.create_device_command(
                "sensor", token="ping", name="ping")
            a1 = insts[1].device_management.get_active_assignment(tok1)
            ws = WebServer(insts[0], port=0)
            ws.start()
            try:
                conn = _http.HTTPConnection("127.0.0.1", ws.port,
                                            timeout=10)
                jwt = insts[0].tokens.mint("admin", ["ROLE_ADMIN"])
                conn.request(
                    "POST", f"/api/assignments/{a1.token}/invocations",
                    body=_json.dumps({"commandToken": "ping"}),
                    headers={"Authorization": f"Bearer {jwt}"})
                resp = conn.getresponse()
                out = _json.loads(resp.read())
                conn.close()
                assert resp.status == 200 and out["queued"]
                insts[1].dispatcher.flush()
                insts[1].event_store.flush()
                from sitewhere_tpu.schema import EventType
                invs = insts[1].event_store.query(
                    device_id=d1,
                    event_type=int(EventType.COMMAND_INVOCATION))
                assert len(invs) == 1
            finally:
                ws.stop()
        finally:
            for inst in insts:
                inst.stop()
                inst.terminate()

    def test_multihost_requires_shared_secret(self, tmp_path):
        cfg = make_config(tmp_path)
        cfg._tree["rpc"] = {"server": {"enabled": True, "host": "127.0.0.1",
                                       "port": 0},
                            "process_id": 0,
                            "peers": ["127.0.0.1:1", "127.0.0.1:2"]}
        with pytest.raises(ValueError, match="jwt_secret"):
            Instance(cfg)

    def test_durable_spool_survives_restart_and_peer_outage(self, tmp_path):
        """With a data_dir the forwarder write-ahead-spools remote rows:
        an unreachable peer retains them on disk (no dead-letter), and a
        new forwarder over the same spool delivers them once the peer is
        back — the crash-recovery half of at-least-once for the DCN hop."""
        inst = Instance(make_config(tmp_path / "local"))
        inst.start()
        tok = next(f"dev-{i}" for i in range(100)
                   if owning_process(f"dev-{i}", 2) == 1)
        line = (b'{"deviceToken": "%s", "type": "Measurement",'
                b' "request": {"name": "t", "value": 7,'
                b' "eventDate": 1000}}' % tok.encode())
        spool_dir = str(tmp_path / "spool")
        try:
            # phase 1: peer down — rows spool, nothing dead-letters
            down = RpcDemux(["127.0.0.1:1"])
            fwd = HostForwarder(inst.dispatcher, 0, {0: None, 1: down},
                                dead_letters=inst.dead_letters,
                                deadline_ms=5.0, max_retries=1,
                                data_dir=spool_dir)
            assert fwd.durable
            fwd.ingest_payload(line)
            fwd.flush(wait=True)
            assert fwd.dead_lettered == 0
            assert fwd.metrics()["pending"] == 1
            fwd.stop()
            down.close()

            # phase 2: "restart" — peer now up; spool replays on start
            peer = Instance(make_config(tmp_path / "peer"))
            peer.start()
            peer.device_management.create_device_type(token="sensor",
                                                      name="S")
            peer.device_management.create_device(token=tok,
                                                 device_type="sensor")
            peer.device_management.create_device_assignment(device=tok)
            srv = RpcServer(port=0, tokens=peer.tokens)
            bind_instance(srv, peer)
            srv.start()
            jwt = peer.tokens.mint("system", ["ROLE_ADMIN"])
            up = RpcDemux([srv.endpoint], token_provider=lambda: jwt)
            fwd2 = HostForwarder(inst.dispatcher, 0, {0: None, 1: up},
                                 dead_letters=inst.dead_letters,
                                 deadline_ms=5.0, data_dir=spool_dir)
            fwd2.start()
            deadline = time.time() + 10
            while time.time() < deadline and fwd2.forwarded_rows < 1:
                time.sleep(0.05)
            assert fwd2.forwarded_rows == 1
            assert fwd2.metrics()["pending"] == 0
            peer.dispatcher.flush()
            peer.event_store.flush()
            d = int(peer.identity.device.lookup(tok))
            assert len(peer.event_store.query(device_id=d)) == 1
            fwd2.stop()
            up.close()
            srv.stop()
            peer.stop()
            peer.terminate()
        finally:
            inst.stop()
            inst.terminate()

    def test_peer_endpoint_live_reload(self, tmp_path):
        """A peer that moves to a new port picks up on config.reload()
        (Consul-watch analog) without restarting the local instance; a
        peer-COUNT change is rejected (ownership would shift)."""
        import json as _json
        import socket as _socket

        def free_port():
            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            return port

        p0 = free_port()
        # remote "host 1": one instance, server rebinds ports across the test
        remote = Instance(make_config(tmp_path / "remote"))
        remote.start()
        remote.device_management.create_device_type(token="sensor", name="S")
        tok1 = next(f"dev-{i}" for i in range(100)
                    if owning_process(f"dev-{i}", 2) == 1)
        remote.device_management.create_device(token=tok1,
                                               device_type="sensor")
        remote.device_management.create_device_assignment(device=tok1)
        srv_a = RpcServer(port=0, tokens=remote.tokens)
        bind_instance(srv_a, remote)
        srv_a.start()

        cfg_path = tmp_path / "host0.json"
        base = make_config(tmp_path / "local")._tree

        def write_cfg(peer_ep):
            base["rpc"] = {
                "server": {"enabled": True, "host": "127.0.0.1",
                           "port": p0},
                "process_id": 0,
                "peers": [f"127.0.0.1:{p0}", peer_ep],
                "forward_deadline_ms": 10.0,
            }
            base["security"] = {"jwt_secret": "reload-secret"}
            cfg_path.write_text(_json.dumps(base))

        write_cfg(srv_a.endpoint)
        from sitewhere_tpu.runtime.config import Config
        cfg = Config.load(str(cfg_path), apply_env=False)
        local = Instance(cfg)
        local.start()
        # remote verifies local's service JWTs: same shared secret
        remote.tokens._secret = local.tokens._secret

        line = (b'{"deviceToken": "%s", "type": "Measurement",'
                b' "request": {"name": "t", "value": 1,'
                b' "eventDate": 1000}}' % tok1.encode())
        try:
            local.forwarder.ingest_payload(line)
            local.forwarder.flush(wait=True)
            assert local.forwarder.forwarded_rows == 1

            # peer moves: new server, new port; config file follows
            srv_a.stop()
            srv_b = RpcServer(port=0, tokens=remote.tokens)
            bind_instance(srv_b, remote)
            srv_b.start()
            write_cfg(srv_b.endpoint)
            cfg.reload()
            assert local._peer_demuxes[1].endpoints == [srv_b.endpoint]

            local.forwarder.ingest_payload(line)
            deadline = time.time() + 10
            while (time.time() < deadline
                   and local.forwarder.forwarded_rows < 2):
                local.forwarder.flush(wait=True)
                time.sleep(0.05)
            assert local.forwarder.forwarded_rows == 2
            assert local.forwarder.dead_lettered == 0

            # count change is refused: endpoints stay as they were
            base["rpc"]["peers"] = [f"127.0.0.1:{p0}", srv_b.endpoint,
                                    "127.0.0.1:9999"]
            cfg_path.write_text(_json.dumps(base))
            cfg.reload()
            assert len(local._peer_demuxes) == 2
            assert local._peer_demuxes[1].endpoints == [srv_b.endpoint]
            # a pure swap is refused too: same endpoints, different
            # process-id binding = ownership shift
            base["rpc"]["peers"] = [srv_b.endpoint, f"127.0.0.1:{p0}"]
            cfg_path.write_text(_json.dumps(base))
            cfg.reload()
            assert local._peer_demuxes[1].endpoints == [srv_b.endpoint]
            srv_b.stop()
            # terminate deregisters the listener: a reload after
            # teardown must not touch the dead instance's demuxes
            assert local._on_peers_changed in cfg._listeners
        finally:
            local.stop()
            local.terminate()
            remote.stop()
            remote.terminate()
        assert local._on_peers_changed not in cfg._listeners

    def test_down_peer_does_not_accumulate_sender_threads(self, tmp_path):
        """One sender per owner at a time: a down peer being retried must
        not grow a thread pile-up as flush ticks arrive (durable mode
        retains rows, so the owner stays pending for the whole outage)."""
        tok = next(f"dev-{i}" for i in range(100)
                   if owning_process(f"dev-{i}", 2) == 1)
        down = RpcDemux(["127.0.0.1:1"])
        fwd = HostForwarder(None, 0, {0: None, 1: down},
                            deadline_ms=1.0, max_retries=2,
                            data_dir=str(tmp_path))
        try:
            fwd.ingest_payload(
                b'{"deviceToken": "%s", "type": "Measurement",'
                b' "request": {"name": "t", "value": 1}}' % tok.encode())
            for _ in range(50):
                fwd.flush()
            with fwd._lock:
                assert len(fwd._senders) <= 1
            # wait out the in-flight sender: mid-poll the reader position
            # sits past the record until the failure seeks back, so
            # pending only settles once no sender is running
            deadline = time.time() + 10
            while time.time() < deadline:
                with fwd._lock:
                    if not fwd._senders:
                        break
                time.sleep(0.05)
            assert fwd.metrics()["pending"] == 1   # retained, not lost
        finally:
            fwd.stop()
            down.close()

    def test_wrong_secret_peer_dead_letters_as_rejected(self, tmp_path):
        """A peer whose JWT secret doesn't match rejects the forward as
        unauthorized — a NON-retryable rejection, so rows dead-letter
        with the reason recorded instead of spooling forever."""
        peer = Instance(make_config(tmp_path / "peer"))
        peer.start()
        srv = RpcServer(port=0, tokens=peer.tokens)   # peer's own secret
        bind_instance(srv, peer)
        srv.start()
        local = Instance(make_config(tmp_path / "local"))
        local.start()
        try:
            # local mints with ITS secret; peer can't verify it
            jwt = local.tokens.mint("system", ["ROLE_ADMIN"])
            demux = RpcDemux([srv.endpoint], token_provider=lambda: jwt)
            fwd = HostForwarder(local.dispatcher, 0, {0: None, 1: demux},
                                dead_letters=local.dead_letters,
                                deadline_ms=5.0)
            tok = next(f"dev-{i}" for i in range(100)
                       if owning_process(f"dev-{i}", 2) == 1)
            fwd.ingest_payload(
                b'{"deviceToken": "%s", "type": "Measurement",'
                b' "request": {"name": "t", "value": 1}}' % tok.encode())
            fwd.flush(wait=True)
            assert fwd.dead_lettered == 1
            assert fwd.forwarded_rows == 0
            dead = [json.loads(p) for _, p in
                    local.dead_letters.scan(0)]
            rejected = [d for d in dead
                        if d.get("kind") == "undeliverable-forward"]
            assert rejected and "unauthorized" in rejected[0]["reason"]
        finally:
            fwd.stop()
            demux.close()
            srv.stop()
            local.stop()
            local.terminate()
            peer.stop()
            peer.terminate()

    def test_unreachable_peer_dead_letters(self, tmp_path):
        inst = Instance(make_config(tmp_path))
        inst.start()
        try:
            demux = RpcDemux(["127.0.0.1:1"])
            fwd = HostForwarder(
                inst.dispatcher, process_id=0,
                peer_demuxes={0: None, 1: demux},
                dead_letters=inst.dead_letters,
                deadline_ms=5.0, max_retries=1)
            tok = next(f"dev-{i}" for i in range(100)
                       if owning_process(f"dev-{i}", 2) == 1)
            fwd.ingest_payload(
                b'{"deviceToken": "%s", "type": "Measurement",'
                b' "request": {"name": "t", "value": 1}}'
                % tok.encode())
            fwd.flush(wait=True)
            assert fwd.dead_lettered >= 1
            demux.close()
        finally:
            inst.stop()
            inst.terminate()
