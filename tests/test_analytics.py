"""Analytics runner: windowed stats grid + anomaly detection + event tap.

Covers the sitewhere-spark capability (BASELINE.md config 3): batch jobs
over stored event history and the streaming tap bridge
(SiteWhereReceiver analog).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from sitewhere_tpu.analytics import (
    AnalyticsJob,
    EventTap,
    build_window_grid,
    detect_anomalies,
)


def _grid(device_id, window_idx, value, D, W):
    import jax.numpy as jnp

    n = len(value)
    return build_window_grid(
        jnp.asarray(np.asarray(device_id, np.int32)),
        jnp.asarray(np.asarray(window_idx, np.int32)),
        jnp.asarray(np.asarray(value, np.float32)),
        jnp.ones(n, bool),
        n_devices=D, n_windows=W,
    )


class TestWindowGrid:
    def test_scatter_stats(self):
        grid = _grid([0, 0, 1, 0], [0, 0, 2, 1], [1.0, 3.0, 5.0, 7.0], D=2, W=3)
        counts = np.asarray(grid.counts)
        means = np.asarray(grid.means)
        assert counts[0, 0] == 2 and means[0, 0] == 2.0
        assert counts[0, 1] == 1 and means[0, 1] == 7.0
        assert counts[1, 2] == 1 and means[1, 2] == 5.0
        assert counts.sum() == 4
        # variance of [1, 3] = 1.0
        assert np.asarray(grid.variances)[0, 0] == pytest.approx(1.0)

    def test_out_of_range_rows_dropped(self):
        grid = _grid([0, 5, -1, 0], [0, 0, 0, 9], [1.0] * 4, D=2, W=3)
        assert np.asarray(grid.counts).sum() == 1


class TestAnomalies:
    def test_spike_detected_after_baseline(self):
        rng = np.random.default_rng(0)
        W, D = 24, 3
        rows = []
        for w in range(W):
            for d in range(D):
                for _ in range(10):
                    base = 20.0 + d
                    # device 1 spikes at window 20
                    v = base + rng.normal(0, 0.5)
                    if d == 1 and w == 20:
                        v += 50.0
                    rows.append((d, w, v))
        dev, win, val = map(np.asarray, zip(*rows))
        grid = _grid(dev, win, val, D=D, W=W)
        anomalous, z = detect_anomalies(grid, baseline_windows=8,
                                        z_threshold=4.0)
        host = np.asarray(anomalous)
        assert host[1, 20]
        assert host.sum() == 1  # nothing else flagged
        assert abs(float(np.asarray(z)[1, 20])) > 4.0

    def test_cold_start_windows_not_flagged(self):
        # single early spike with no baseline yet → not flagged
        grid = _grid([0] * 3, [0, 0, 1], [1.0, 1.0, 99.0], D=1, W=4)
        anomalous, _ = detect_anomalies(grid, baseline_windows=4,
                                        min_baseline_count=8)
        assert not np.asarray(anomalous).any()


class TestJobOverStore:
    def test_end_to_end_over_event_store(self, tmp_path):
        from sitewhere_tpu.services.event_store import EventStore

        store = EventStore(str(tmp_path))
        store.start()
        rng = np.random.default_rng(1)
        t0 = 1_000_000
        for w in range(16):
            for d in range(4):
                for k in range(5):
                    value = 10.0 + rng.normal(0, 0.3)
                    if d == 2 and w == 12:
                        value += 30.0
                    store.add_event(
                        device_id=d, tenant_id=0, event_type=0,
                        ts_s=t0 + w * 3600 + k * 60, mtype_id=1, value=value,
                    )
        job = AnalyticsJob(window_s=3600, baseline_windows=6,
                           z_threshold=4.0, min_baseline_count=10)
        report = job.run(store, n_devices=4, mtype_id=1,
                         token_of=lambda d: f"dev-{d}")
        assert report["events"] == 16 * 4 * 5
        assert report["devices_seen"] == 4
        assert len(report["anomalies"]) == 1
        a = report["anomalies"][0]
        assert a.device_id == 2 and a.device_token == "dev-2"
        assert a.window == 12
        assert a.window_start_s == t0 + 12 * 3600
        store.stop()

    def test_empty_store(self, tmp_path):
        from sitewhere_tpu.services.event_store import EventStore

        store = EventStore(str(tmp_path))
        store.start()
        report = AnalyticsJob().run(store, n_devices=4)
        assert report["anomalies"] == [] and report["events"] == 0
        store.stop()


class TestEventTap:
    def test_tap_accumulates_outbound_batches(self):
        from sitewhere_tpu.outbound.manager import OutboundConnectorsManager

        tap = EventTap()
        mgr = OutboundConnectorsManager([tap.connector()])
        mgr.start()
        cols = {
            "device_id": np.arange(6, dtype=np.int32),
            "value": np.linspace(0, 5, 6).astype(np.float32),
            "event_type": np.zeros(6, np.int32),
        }
        mask = np.array([True, True, False, True, False, True])
        mgr.submit(cols, mask)
        mgr.drain()
        mgr.stop()
        out = tap.drain()
        assert len(out["device_id"]) == 4
        assert list(out["device_id"]) == [0, 1, 3, 5]
        assert tap.drain() == {}


class TestNumericalRobustness:
    def test_large_magnitude_variance_exact(self):
        """Two-pass variance avoids float32 cancellation: values ~1e5 with
        std ~1 must not report zero variance (regression)."""
        vals = np.array([1e5 - 1, 1e5 + 1, 1e5 - 1, 1e5 + 1], np.float32)
        grid = _grid([0, 0, 0, 0], [0, 0, 0, 0], vals, D=1, W=1)
        assert np.asarray(grid.variances)[0, 0] == pytest.approx(1.0, rel=1e-3)

    def test_quantized_baseline_not_flagged(self, tmp_path):
        """A constant baseline then a tiny quantization jitter must NOT be
        an anomaly (std floor scaled to the data, regression)."""
        from sitewhere_tpu.services.event_store import EventStore

        store = EventStore(str(tmp_path))
        store.start()
        t0 = 1_000_000
        for w in range(12):
            for k in range(10):
                # constant quantized baseline; final window has samples
                # bouncing between adjacent quantization steps
                value = 20.0 if w < 11 else (20.0 if k % 2 else 20.01)
                store.add_event(device_id=0, tenant_id=0, event_type=0,
                                ts_s=t0 + w * 3600 + k, mtype_id=1,
                                value=value)
        job = AnalyticsJob(window_s=3600, baseline_windows=8,
                           z_threshold=3.0, min_baseline_count=8)
        report = job.run(store, n_devices=1, mtype_id=1)
        assert report["anomalies"] == []
        store.stop()

    def test_large_offset_spike_still_detected(self, tmp_path):
        """Global centering keeps detection working at magnitude ~1e5."""
        from sitewhere_tpu.services.event_store import EventStore

        rng = np.random.default_rng(3)
        store = EventStore(str(tmp_path))
        store.start()
        t0 = 1_000_000
        for w in range(16):
            for k in range(10):
                value = 1e5 + rng.normal(0, 1.0)
                if w == 14:
                    value += 100.0
                store.add_event(device_id=0, tenant_id=0, event_type=0,
                                ts_s=t0 + w * 3600 + k, mtype_id=1,
                                value=value)
        job = AnalyticsJob(window_s=3600, baseline_windows=8,
                           z_threshold=5.0, min_baseline_count=10)
        report = job.run(store, n_devices=1, mtype_id=1)
        assert [a.window for a in report["anomalies"]] == [14]
        assert report["anomalies"][0].mean == pytest.approx(1e5 + 100, rel=1e-4)
        store.stop()


class TestShardedAnalytics:
    def test_sharded_grid_matches_unsharded(self, mesh8):
        import numpy as np

        from sitewhere_tpu.analytics.runner import (
            build_window_grid,
            build_window_grid_sharded,
        )
        import jax.numpy as jnp

        rng = np.random.default_rng(5)
        D, W, N = 64, 16, 5000
        dev = rng.integers(0, D, N).astype(np.int32)
        win = rng.integers(0, W, N).astype(np.int32)
        val = rng.normal(10.0, 2.0, N).astype(np.float32)

        ref = build_window_grid(
            jnp.asarray(dev), jnp.asarray(win), jnp.asarray(val),
            jnp.ones(N, bool), n_devices=D, n_windows=W)
        sharded = build_window_grid_sharded(
            mesh8, dev, win, val, n_devices=D, n_windows=W)
        np.testing.assert_array_equal(np.asarray(sharded.counts),
                                      np.asarray(ref.counts))
        np.testing.assert_allclose(np.asarray(sharded.means),
                                   np.asarray(ref.means), atol=1e-4)
        np.testing.assert_allclose(np.asarray(sharded.variances),
                                   np.asarray(ref.variances), atol=1e-3)
        # the result actually lives sharded across the mesh
        assert len(sharded.counts.sharding.device_set) == 8

    def test_job_runs_sharded_end_to_end(self, mesh8):
        import numpy as np

        from sitewhere_tpu.analytics import AnalyticsJob

        rng = np.random.default_rng(6)
        D, N = 64, 20_000
        dev = rng.integers(0, D, N).astype(np.int32)
        ts = (1_753_800_000 + rng.integers(0, 16 * 3600, N)).astype(np.int32)
        val = rng.normal(20.0, 1.0, N).astype(np.float32)
        # inject an obvious anomaly burst for device 3 in a late window
        burst = (dev == 3) & (ts > 1_753_800_000 + 14 * 3600)
        val[burst] += 50.0

        job = AnalyticsJob(window_s=3600)
        plain = job.run_columns(dev, ts, val, n_devices=D)
        sharded = job.run_columns(dev, ts, val, n_devices=D, mesh=mesh8)
        assert sharded["events"] == plain["events"]
        key = lambda a: (a.device_id, a.window)
        assert sorted(map(key, sharded["anomalies"])) == \
            sorted(map(key, plain["anomalies"]))
        assert any(a.device_id == 3 for a in sharded["anomalies"])


def test_window_sharded_anomalies_match_single_chip():
    """The ring-halo window-sharded flagger must agree bitwise with the
    local path: trailing baselines that cross a shard boundary read the
    left neighbor's tail via ppermute, and shard 0's zero halo equals the
    local empty-left-edge semantics."""
    import numpy as np

    from sitewhere_tpu.analytics import (
        build_window_grid,
        detect_anomalies,
        detect_anomalies_window_sharded,
    )
    from sitewhere_tpu.parallel.mesh import make_mesh

    D, W, N = 64, 32, 20_000
    rng = np.random.default_rng(3)
    dev = jnp.asarray(rng.integers(0, D, N).astype(np.int32))
    win = jnp.asarray(rng.integers(0, W, N).astype(np.int32))
    val = jnp.asarray(rng.normal(10.0, 1.0, N).astype(np.float32))
    # inject anomalies: device 7's window 20 runs hot
    hot = (np.asarray(dev) == 7) & (np.asarray(win) == 20)
    val = jnp.where(jnp.asarray(hot), val + 25.0, val)
    grid = build_window_grid(dev, win, val, jnp.ones(N, bool), D, W)

    mesh = make_mesh(8)
    a_ref, z_ref = detect_anomalies(grid, baseline_windows=4)
    a_sh, z_sh = detect_anomalies_window_sharded(
        mesh, grid, baseline_windows=4)
    assert bool(jnp.any(a_ref[7]))
    # z agrees up to f32 summation order (the sharded path prefix-sums
    # L + W/S windows per shard, not the whole history); flags can only
    # legitimately differ where |z| sits within that tolerance of the
    # threshold, so compare them away from the boundary
    zr, zs = np.asarray(z_ref), np.asarray(z_sh)
    np.testing.assert_allclose(zr, zs, rtol=2e-3, atol=1e-3)
    off_boundary = np.abs(np.abs(zr) - 3.0) > 1e-2
    np.testing.assert_array_equal(
        np.asarray(a_ref)[off_boundary], np.asarray(a_sh)[off_boundary])


def test_window_sharded_halo_depth_guard():
    import pytest as _pytest

    from sitewhere_tpu.analytics import (
        build_window_grid,
        detect_anomalies_window_sharded,
    )
    from sitewhere_tpu.parallel.mesh import make_mesh

    grid = build_window_grid(
        jnp.zeros(4, jnp.int32), jnp.zeros(4, jnp.int32),
        jnp.ones(4, jnp.float32), jnp.ones(4, bool), 8, 16)
    mesh = make_mesh(8)  # 2 windows per shard
    with _pytest.raises(ValueError):
        detect_anomalies_window_sharded(mesh, grid, baseline_windows=4)
