"""Pipeline-step behavior tests: validation, enrichment, rules, state.

These encode the reference semantics from SURVEY.md §3.2 — the same
behaviors the reference's live-driver tests exercised against a running
instance (EventSourceTests.java, MqttTests.java), but deterministic.
"""

import jax
import jax.numpy as jnp
import numpy as np

from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.pipeline import pipeline_step
from sitewhere_tpu.schema import (
    DeviceState,
    EventType,
    RuleTable,
    ZoneTable,
)

from helpers import (
    alert,
    location,
    make_batch,
    make_registry,
    measurement,
    square_zone,
    threshold_rule,
)


def run_step(batch, registry=None, state=None, rules=None, zones=None):
    registry = registry if registry is not None else make_registry()
    state = state if state is not None else DeviceState.empty(registry.capacity)
    rules = rules if rules is not None else RuleTable.empty(4)
    zones = zones if zones is not None else ZoneTable.empty(4)
    return jax.jit(pipeline_step)(registry, state, rules, zones, batch)


def test_accept_and_enrich():
    batch = make_batch([measurement(device=1, value=10.0)])
    _, out = run_step(batch)
    assert bool(out.accepted[0])
    assert int(out.area_id[0]) == 1
    assert int(out.customer_id[0]) == 2
    assert int(out.asset_id[0]) == 3
    assert int(out.assignment_id[0]) == 1
    assert int(out.metrics.accepted) == 1
    assert int(out.metrics.by_type[EventType.MEASUREMENT]) == 1


def test_unregistered_device_dead_letter():
    # Device 50 exists in no registry slot (inactive) — reference routes to
    # the unregistered-events topic (InboundPayloadProcessingLogic:228-233).
    batch = make_batch([measurement(device=50), measurement(device=-1)])
    _, out = run_step(batch)
    assert not bool(out.accepted.any())
    assert bool(out.unregistered.all())
    assert int(out.metrics.unregistered) == 2
    assert int(out.area_id[0]) == NULL_ID


def test_wrong_tenant_rejected():
    batch = make_batch([measurement(device=1, tenant=9)])
    _, out = run_step(batch)
    assert not bool(out.accepted[0])
    assert bool(out.unregistered[0])


def test_unassigned_device_dead_letter():
    reg = make_registry()
    reg = reg.replace(
        assignment_status=reg.assignment_status.at[2].set(0)  # NONE
    )
    batch = make_batch([measurement(device=2)])
    _, out = run_step(batch, registry=reg)
    assert not bool(out.accepted[0])
    assert bool(out.unassigned[0])
    assert int(out.metrics.unassigned) == 1


def test_padding_rows_ignored():
    batch = make_batch([measurement(device=1), {"valid": False}])
    _, out = run_step(batch)
    assert int(out.metrics.processed) == 1
    assert not bool(out.accepted[1])
    assert not bool(out.unregistered[1])


def test_threshold_rule_fires_and_derives_alert():
    rules = threshold_rule(RuleTable.empty(4), 0, mtype=3, op=0, threshold=50.0,
                           alert_code=200)
    batch = make_batch([
        measurement(device=0, mtype=3, value=75.0),   # fires (> 50)
        measurement(device=1, mtype=3, value=25.0),   # below
        measurement(device=2, mtype=1, value=99.0),   # wrong mtype
    ])
    _, out = run_step(batch, rules=rules)
    assert int(out.rule_id[0]) == 0
    assert int(out.rule_id[1]) == NULL_ID
    assert int(out.rule_id[2]) == NULL_ID
    assert int(out.metrics.threshold_alerts) == 1
    d = out.derived_alerts
    assert bool(d.valid[0]) and not bool(d.valid[1])
    assert int(d.alert_code[0]) == 200
    assert int(d.event_type[0]) == EventType.ALERT
    assert int(d.device_id[0]) == 0


def test_rule_tenant_scoping():
    rules = threshold_rule(RuleTable.empty(4), 0, mtype=3, op=0, threshold=50.0,
                           tenant=7)  # only tenant 7
    batch = make_batch([measurement(device=0, mtype=3, value=75.0, tenant=0)])
    _, out = run_step(batch, rules=rules)
    assert int(out.rule_id[0]) == NULL_ID


def test_geofence_inside_fires():
    zones = square_zone(ZoneTable.empty(4), 0, x0=0, y0=0, x1=10, y1=10,
                        alert_code=100)
    batch = make_batch([
        location(device=0, lon=5.0, lat=5.0),    # inside
        location(device=1, lon=15.0, lat=5.0),   # outside
        measurement(device=2, value=5.0),        # not a location
    ])
    _, out = run_step(batch, zones=zones)
    assert int(out.zone_id[0]) == 0
    assert int(out.zone_id[1]) == NULL_ID
    assert int(out.zone_id[2]) == NULL_ID
    assert int(out.metrics.zone_alerts) == 1
    assert int(out.derived_alerts.alert_code[0]) == 100


def test_geofence_alert_if_outside():
    zones = square_zone(ZoneTable.empty(4), 0, x0=0, y0=0, x1=10, y1=10,
                        condition=1, alert_code=101)
    batch = make_batch([
        location(device=0, lon=5.0, lat=5.0),    # inside -> no alert
        location(device=1, lon=15.0, lat=5.0),   # outside -> alert
    ])
    _, out = run_step(batch, zones=zones)
    assert int(out.zone_id[0]) == NULL_ID
    assert int(out.zone_id[1]) == 0


def test_geofence_area_scoping():
    # Zone bound to area 42; devices are enriched with area 1 -> no fire.
    zones = square_zone(ZoneTable.empty(4), 0, 0, 0, 10, 10, area=42)
    batch = make_batch([location(device=0, lon=5.0, lat=5.0)])
    _, out = run_step(batch, zones=zones)
    assert int(out.zone_id[0]) == NULL_ID


def test_state_updates_last_known():
    batch = make_batch([
        measurement(device=1, mtype=2, value=42.0, ts=1000),
        location(device=1, lat=1.5, lon=2.5, ts=1001),
        alert(device=3, code=9, ts=1002),
    ])
    state, out = run_step(batch)
    assert float(state.last_values[1, 2]) == 42.0
    assert float(state.last_lat[1]) == 1.5
    assert int(state.last_alert_code[3]) == 9
    assert int(state.last_event_ts_s[1]) == 1001
    assert int(state.last_event_type[1]) == EventType.LOCATION
    assert int(state.last_event_ts_s[3]) == 1002


def test_state_last_write_wins_out_of_order():
    # Two measurements for one device in one batch, older second — the
    # newer timestamp must win regardless of row order.
    batch = make_batch([
        measurement(device=1, mtype=0, value=99.0, ts=2000),
        measurement(device=1, mtype=0, value=11.0, ts=1500),
    ])
    state, _ = run_step(batch)
    assert float(state.last_values[1, 0]) == 99.0
    assert int(state.last_event_ts_s[1]) == 2000


def test_state_ns_tiebreak():
    batch = make_batch([
        measurement(device=1, mtype=0, value=1.0, ts=1000, ts_ns=100),
        measurement(device=1, mtype=0, value=2.0, ts=1000, ts_ns=900),
    ])
    state, _ = run_step(batch)
    assert int(state.last_event_ts_ns[1]) == 900
    assert int(state.last_event_type[1]) == EventType.MEASUREMENT


def test_rejected_events_do_not_touch_state():
    batch = make_batch([measurement(device=50, value=1.0, ts=1000)])
    state, out = run_step(batch)
    assert int(state.last_event_ts_s.max()) == 0
    assert not bool(out.accepted[0])


def test_presence_reset_on_event():
    reg = make_registry()
    st = DeviceState.empty(reg.capacity)
    st = st.replace(presence_missing=st.presence_missing.at[1].set(True)
                    .at[2].set(True))
    batch = make_batch([measurement(device=1, ts=1000)])
    state, _ = run_step(batch, registry=reg, state=st)
    assert not bool(state.presence_missing[1])  # came back
    assert bool(state.presence_missing[2])      # still missing


def test_metrics_accumulate():
    batch = make_batch([measurement(device=1), measurement(device=50)])
    _, out1 = run_step(batch)
    _, out2 = run_step(batch)
    total = out1.metrics + out2.metrics
    assert int(total.processed) == 4
    assert int(total.accepted) == 2
    assert int(total.unregistered) == 2


def test_step_is_jit_stable():
    """Same compiled step must serve different data (static shapes only)."""
    step = jax.jit(pipeline_step)
    reg = make_registry()
    st = DeviceState.empty(reg.capacity)
    rules, zones = RuleTable.empty(4), ZoneTable.empty(4)
    b1 = make_batch([measurement(device=1, value=1.0)])
    b2 = make_batch([location(device=2, lat=3.0, lon=4.0)])
    # Warm-up calls may compile more than once (host-resident vs
    # device-resident input layouts); steady state must not retrace.
    st, _ = step(reg, st, rules, zones, b1)
    st, _ = step(reg, st, rules, zones, b2)
    warm = step._cache_size()
    st, _ = step(reg, st, rules, zones, make_batch([measurement(device=3)]))
    st, _ = step(reg, st, rules, zones, make_batch([location(device=4)]))
    assert step._cache_size() == warm
    assert float(st.last_lat[2]) == 3.0


def test_unknown_mtype_does_not_clobber_slot0():
    b1 = make_batch([measurement(device=1, mtype=0, value=7.0, ts=1000)])
    state, _ = run_step(b1)
    b2 = make_batch([measurement(device=1, mtype=-1, value=999.0, ts=2000)])
    reg = make_registry()
    from sitewhere_tpu.schema import RuleTable, ZoneTable
    state, _ = jax.jit(pipeline_step)(
        reg, state, RuleTable.empty(4), ZoneTable.empty(4), b2
    )
    assert float(state.last_values[1, 0]) == 7.0


def test_location_ns_ordering_across_batches():
    reg = make_registry()
    st = DeviceState.empty(reg.capacity)
    from sitewhere_tpu.schema import RuleTable, ZoneTable
    step = jax.jit(pipeline_step)
    b_new = make_batch([location(device=1, lat=10.0, ts=1000, ts_ns=900)])
    b_old = make_batch([location(device=1, lat=-5.0, ts=1000, ts_ns=100)])
    st, _ = step(reg, st, RuleTable.empty(4), ZoneTable.empty(4), b_new)
    st, _ = step(reg, st, RuleTable.empty(4), ZoneTable.empty(4), b_old)
    assert float(st.last_lat[1]) == 10.0  # older ns must not regress state
