"""TP001: .item() inside a jitted function is a blocking host sync."""
import jax
import jax.numpy as jnp


@jax.jit
def bad_sum(x):
    total = jnp.sum(x)
    return total.item()
