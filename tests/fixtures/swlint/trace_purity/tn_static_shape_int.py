"""TN: int() of static shape data inside jit is trace-safe."""
import jax
import jax.numpy as jnp


@jax.jit
def padded(x):
    width = int(x.shape[0])
    op = int(3)
    return jnp.pad(x, (0, width % 8)), op
