"""TN: .item() in plain host code is fine — nothing is traced."""
import numpy as np


def summarize(arr):
    return np.asarray(arr).sum().item()
