"""TP001: print() inside a shard_map-ped function."""
from jax import shard_map


def local_step(block):
    print("step", block)
    return block * 2


def build(mesh):
    return shard_map(local_step, mesh=mesh, in_specs=None, out_specs=None)
