"""TP003: blocking D2H on the dispatch path without the counted
pipeline.host_syncs surface."""
import jax


def fetch_outputs(outputs):
    return jax.device_get(outputs)
