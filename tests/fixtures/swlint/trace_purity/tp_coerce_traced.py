"""TP002: float() of a jnp reduction concretizes the tracer."""
import jax
import jax.numpy as jnp


@jax.jit
def bad_mean(x):
    return float(jnp.mean(x)) * 2.0
