"""TN: a pure jnp jitted function with helpers."""
import jax
import jax.numpy as jnp


def helper(a, b):
    return jnp.where(a > b, a, b)


@jax.jit
def step(x, y):
    return helper(x, y) + jnp.sum(x)
