"""TN: a dispatch-path fetch that rides the counted host_syncs
surface (on_fetch hook) is exempt from TP003."""
import jax


def fetch_counted(outputs, on_fetch=None):
    if on_fetch is not None:
        on_fetch()   # wires pipeline.host_syncs
    return jax.device_get(outputs)
