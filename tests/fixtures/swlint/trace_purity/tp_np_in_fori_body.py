"""TP001: numpy.asarray inside a lax.fori_loop body breaks the trace."""
import jax
import numpy as np


def run(x):
    def body(i, carry):
        host = np.asarray(carry)
        return carry + host[0]

    return jax.lax.fori_loop(0, 4, body, x)
