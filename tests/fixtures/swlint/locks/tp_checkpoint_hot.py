"""LK005: Checkpointer.save invoked while a hot-path lock is held."""
import threading


class Hot:
    def __init__(self, checkpointer):
        self._lock = threading.Lock()
        self.checkpointer = checkpointer

    def commit_and_snapshot(self):
        with self._lock:
            self.checkpointer.save()
