"""TN: snapshot under the lock, transfer outside it — the fixed
DeviceStateManager pattern."""
import threading

import numpy as np


class Mgr:
    def __init__(self, state):
        self._lock = threading.Lock()
        self._state = state

    def snapshot(self):
        with self._lock:
            s = self._state
        return np.asarray(s)
