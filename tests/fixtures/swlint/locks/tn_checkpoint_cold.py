"""TN: the checkpoint save runs after the hot lock is released."""
import threading


class Hot:
    def __init__(self, checkpointer):
        self._lock = threading.Lock()
        self.checkpointer = checkpointer
        self.committed = 0

    def commit_and_snapshot(self):
        with self._lock:
            self.committed += 1
        self.checkpointer.save()
