"""TN: consistent a->b order everywhere — no inversion."""
import threading


class Ordered:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                return 1

    def two(self):
        with self._a:
            with self._b:
                return 2
