"""LK002: a plain Lock re-acquired through a call made under it."""
import threading


class Selfish:
    def __init__(self):
        self._lock = threading.Lock()

    def outer(self):
        with self._lock:
            return self.inner()

    def inner(self):
        with self._lock:
            return 1
