"""LK004: blocking D2H + H2D under a device-state manager's lock."""
import threading

import jax.numpy as jnp
import numpy as np


class Mgr:
    def __init__(self, state):
        self._lock = threading.Lock()
        self._state = state

    def snapshot(self):
        with self._lock:
            return np.asarray(self._state)

    def adopt(self, host_rows):
        with self._lock:
            self._state = jnp.asarray(host_rows)
