"""TN: RLock re-entry is its purpose — no self-deadlock."""
import threading


class Reentrant:
    def __init__(self):
        self._lock = threading.RLock()

    def outer(self):
        with self._lock:
            return self.inner()

    def inner(self):
        with self._lock:
            return 1
